"""Benchmark: flagship 3-client ResNet18 FedAvg hot loop on real hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N}

The hot loop is the jitted sharded epoch function — every client's
stochastic L-BFGS step (up to 4 inner iterations, Armijo line-search
probes included) on one lockstep minibatch per client. This is the same
work the reference does in `opt.step(closure)` x3 per minibatch
(reference src/federated_trio_resnet.py:320-338).

`vs_baseline` compares against the reference's measured throughput on this
host (torch CPU — the reference has no device code; see
`benchmarks/measure_reference.py`, result cached in
`benchmarks/reference_throughput.json`).
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    bench_device = os.environ.get("BENCH_DEVICE", "")
    if bench_device == "cpu":
        from federated_pytorch_test_tpu.utils import force_host_cpu

        force_host_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    k = 3
    batch = 32
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    # synthetic CIFAR-shaped data (identical compute to the real archive)
    src = synthetic_cifar(n_train=k * batch * max(steps, 8), n_test=64)
    cfg = get_preset(
        "fedavg_resnet",
        n_clients=k,
        batch=batch,
        check_results=False,
        # convs/matmuls in bf16 on the MXU when BENCH_DTYPE=bfloat16;
        # loss, norms and the L-BFGS math stay f32 either way
        compute_dtype=os.environ.get("BENCH_DTYPE", "float32"),
    )
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    epoch_fn, _, init_fn = tr._fns(gid)
    lstate, y, z, rho, extra = init_fn(tr.flat)
    flat, stats = tr.flat, tr.stats

    def run_epoch(flat, lstate, stats, idx):
        # epoch_fn donates (flat, lstate, stats): thread them through
        flat, lstate, stats, losses = epoch_fn(
            flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
            idx, tr.mean, tr.std, y, z, rho,
        )
        return flat, lstate, stats

    idx = tr._epoch_indices(0, gid, 0, 0)[:steps]
    # warmup / compile (same scan length as the timed run — scan length is
    # static, so a shorter warmup would compile a second program).
    # Synchronization is a SCALAR FETCH, not block_until_ready: on the
    # remote-tunnel PJRT runtime block_until_ready returns at dispatch-ack,
    # so only a device->host read is a true completion barrier. The timed
    # call's inputs differ from the warmup's (flat/lstate/stats are
    # threaded through), so no result caching can serve it.
    flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
    float(jnp.sum(flat[:, 0]))

    # best of 3: the tunneled chip is shared, so single-shot timings can
    # absorb other tenants' work — the minimum is the machine's number
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
        float(jnp.sum(flat[:, 0]))
        dt = min(dt, time.perf_counter() - t0)

    n_samples = steps * k * batch
    sps = n_samples / dt

    ref_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "reference_throughput.json",
    )
    vs_baseline = None
    if os.path.exists(ref_path):
        with open(ref_path) as f:
            ref = json.load(f)
        ref_sps = ref.get("samples_per_sec")
        if ref_sps:
            vs_baseline = sps / ref_sps

    print(
        json.dumps(
            {
                "metric": "fedavg_resnet18_3client_lbfgs_train_throughput",
                "value": round(sps, 2),
                "unit": "samples/sec",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
            }
        )
    )


if __name__ == "__main__":
    main()
