"""Benchmark: flagship 3-client ResNet18 FedAvg hot loop on real hardware.

The FINAL stdout line is ONE compact JSON headline (the driver parses
the last line of a bounded stdout tail, so it must stay short):
  {"metric": ..., "value": N, "unit": "samples/sec", "sps_p25": N,
   "sps_p75": N, "vs_baseline": N, "mfu": ..., "mxu_pct_peak": ...,
   "comm_bytes_per_round": N, "comm_savings_vs_full": N}
`value` is the MEDIAN of `BENCH_REPEATS` (default 5) timed runs with
its p25/p75 dispersion alongside — the chip is shared and single draws
range 160-2600 samples/s on the flagship (BASELINE.md), so a best-of-N
minimum would publish the luckiest draw as if it were typical.
The full record (roofline, sweep, MXU probe) is written to
`benchmarks/bench_full.json` (gitignored scratch — a per-round snapshot
`benchmarks/bench_full_r{N}.json` is committed so the docs' cited
evidence lives in the repo).

The hot loop is the jitted sharded epoch function — every client's
stochastic L-BFGS step (up to 4 inner iterations, Armijo line-search
probes included) on one lockstep minibatch per client. This is the same
work the reference does in `opt.step(closure)` x3 per minibatch
(reference src/federated_trio_resnet.py:320-338).

`vs_baseline` compares against the reference's measured throughput on this
host (torch CPU — the reference has no device code; see
`benchmarks/measure_reference.py`, result cached in
`benchmarks/reference_throughput.json`).

Chip-utilization accounting (the number samples/sec cannot give): the
compiled epoch program's exact FLOP and HBM-byte counts come from XLA's
cost model (`compiled.cost_analysis()` — the same counts the compiler
schedules against, so line-search probes, L-BFGS linear algebra, and
normalization are all included, not just the model matmuls), divided by
the measured wall-clock and the chip's peaks via the shared
`obs/roofline.py` accounting (`chip_peaks` + `roofline_record` — the
same helpers behind the trainer's and full_schedule_tpu.py's `roofline`
records); the headline carries `arithmetic_intensity` and
`achieved_hbm_frac` alongside `mfu`, and `health_overhead_s` gates the
in-run health engine's warm-round cost at ≈ 0 (obs/health.py does no
device work):

  mfu               = achieved FLOP/s / peak MXU FLOP/s (bf16 peak: the
                      MXU multiplies bf16 natively; f32-precision passes
                      run BELOW this peak, so mfu is conservative)
  hbm_util          = achieved bytes/s / peak HBM bandwidth
  arithmetic intensity vs the ridge point says which wall the workload
  is against — see BASELINE.md's roofline note.

The `eval_tail` block measures the eval-fold/async mechanisms on a cheap
net-model round: `eval_mode` (the engine default: `folded` — evals ride
inside the one fused dispatch; `async`/`sync` are the `--no-fold-eval` /
`--no-async-eval` fallbacks), `round_dispatches` (program launches per
folded check_results round — 2: round + round_init), and
`eval_overlap_saved_s` (wall saved per round vs the sync-eval path).
`BENCH_COMPILE_CACHE=DIR` points jax's persistent compilation cache at
DIR before anything compiles (the `--compile-cache` config knob's bench
analogue); the headline then carries `compile_s` (the probe's
compile-dominated warmup wall) and `recompile_count` (programs compiled
in-process) — rerun the bench with the same DIR and the cold-vs-warm
compile delta is the difference in `compile_s` between the two runs.

The `sweep` block (disable with BENCH_SWEEP=0) answers "can the chip
bind at all on this workload family?": the flagship config is inherently
overhead-bound (batch-32 CIFAR, BLAS1-heavy inner solver — inherited
from the reference, src/federated_trio_resnet.py:17), so the sweep
scales the two levers BASELINE.md names — batch size and model width —
and reports MFU per row. Rows: resnet18 at batch 32/128/512 (f32),
resnet18 batch-512 bf16, and net2 (the 2.5M-param CNN,
reference src/simple_models.py:83) at its reference batch 512.
"""

from __future__ import annotations

import json
import os
import time

# chip peak table + achieved-utilization accounting live in
# obs/roofline.py now (shared with the trainer's end-of-run `roofline`
# record and full_schedule_tpu.py); jax-free, so safe to import before
# the BENCH_DEVICE backend decision below
from federated_pytorch_test_tpu.obs import chip_peaks as _peaks
from federated_pytorch_test_tpu.obs import roofline_record as _roofline


def _measure(preset: str, model: str | None, batch: int, steps: int,
             dtype: str, peak_tflops, peak_gbps):
    """Build one config's epoch program, time it, return the row dict.

    Timing protocol (see memory: the tunneled chip lies to
    block_until_ready): `steps` lockstep minibatches inside ONE jitted
    scan amortize the ~0.1 s flat dispatch latency; a device->host
    scalar fetch is the completion barrier. The chip is SHARED, so a
    single draw ranges wildly (BASELINE.md: 160-2600 samples/s on the
    flagship) and a best-of-N minimum publishes the luckiest draw as if
    it were typical; instead the row reports the MEDIAN of
    `BENCH_REPEATS` (default 5) timed runs with its p25/p75 dispersion —
    the flash benches' v2 timing discipline. Derived utilization numbers
    (MFU, HBM, intensity) are computed from the median time.
    """
    import jax.numpy as jnp
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    k = 3
    src = synthetic_cifar(n_train=k * batch * max(steps, 8), n_test=64)
    over = dict(
        n_clients=k, batch=batch, check_results=False, compute_dtype=dtype,
        max_scan_steps=None,  # the timed scan IS one call; steps stays small
    )
    if model is not None:
        over["model"] = model
    cfg = get_preset(preset, **over)
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]

    # exact communication cost of the measured workload (obs/ledger.py):
    # bytes one consensus exchange of the measured group moves at full
    # participation, and how many times more the whole-model exchange
    # over one partition sweep would move — the paper's bandwidth claim
    # as a benchmark artifact, derived from the static Partition spec
    from federated_pytorch_test_tpu.obs import CommLedger

    ledger = CommLedger(
        tr.partition, k, dtype_bytes=int(jnp.dtype(tr.flat.dtype).itemsize)
    )
    comm_bytes_per_round = ledger.round_bytes(gid, k)
    comm_savings_vs_full = round(ledger.savings_vs_full(tr.group_order), 2)

    epoch_fn, _, init_fn = tr._fns(gid)
    lstate, y, z, rho, extra = init_fn(tr.flat)
    flat, stats = tr.flat, tr.stats

    def run_epoch(flat, lstate, stats, idx):
        # epoch_fn donates (flat, lstate, stats): thread them through
        flat, lstate, stats, losses = epoch_fn(
            flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
            idx, tr.mean, tr.std, y, z, rho,
        )
        return flat, lstate, stats

    idx = tr._epoch_indices(0, gid, 0, 0)[:steps]

    # exact FLOP / HBM-byte counts of the compiled epoch program; the
    # AOT executable then serves the timed calls (one compile per row)
    flops = hbm_bytes = None
    try:
        compiled = epoch_fn.lower(
            flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
            idx, tr.mean, tr.std, y, z, rho,
        ).compile()
        ca = compiled.cost_analysis()
        ca = ca if isinstance(ca, dict) else ca[0]
        flops = float(ca.get("flops", 0.0)) or None
        hbm_bytes = float(ca.get("bytes accessed", 0.0)) or None
        epoch_fn = compiled  # same call signature as the jitted fn
    except Exception:
        pass

    # warmup at the timed scan length (scan length is static in the
    # program); scalar fetch = the only true completion barrier here
    flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
    float(jnp.sum(flat[:, 0]))

    repeats = max(1, int(os.environ.get("BENCH_REPEATS", "5")))
    dts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
        float(jnp.sum(flat[:, 0]))
        dts.append(time.perf_counter() - t0)
    dt = float(np.median(dts))
    # dispersion in throughput space: the FAST quartile of times is the
    # p75 of samples/s and vice versa
    dt_p25, dt_p75 = float(np.percentile(dts, 25)), float(np.percentile(dts, 75))

    n_samples = steps * k * batch
    row = {
        "model": cfg.model,
        "batch": batch,
        "dtype": dtype,
        "steps": steps,
        "repeats": repeats,
        "samples_per_sec": round(n_samples / dt, 2),
        "sps_p25": round(n_samples / dt_p75, 2),
        "sps_p75": round(n_samples / dt_p25, 2),
        "epoch_time_s": round(dt, 4),
        "comm_bytes_per_round": comm_bytes_per_round,
        "comm_savings_vs_full": comm_savings_vs_full,
    }
    # the shared achieved-utilization accounting (obs/roofline.py); the
    # historical row keys are kept (hbm_util is achieved_hbm_frac's
    # pre-refactor name — committed BENCH_r0N artifacts use it)
    roof = _roofline(
        wall_s=dt, flops=flops, hbm_bytes=hbm_bytes,
        peak_tflops=peak_tflops, peak_hbm_gbps=peak_gbps, ndigits=4,
    )
    for key in ("achieved_tflops", "mfu", "achieved_hbm_gbps",
                "achieved_hbm_frac", "arithmetic_intensity"):
        if key in roof:
            row[key] = roof[key]
    if "achieved_hbm_frac" in roof:
        row["hbm_util"] = roof["achieved_hbm_frac"]

    # model-evaluation accounting (the reference's one built-in counter,
    # src/lbfgsnew.py:508-510): value_and_grad evals + Armijo line-search
    # probe evaluations per optimizer step, cumulative in the threaded
    # L-BFGS state over 1 warmup + the timed runs. The probe-ladder term
    # (LBFGSState.ls_evals, new with the multi-alpha fan) is what the
    # roofline argument is about — each probe re-streams the parameter
    # vector — and under `--linesearch-probes P` one widened fan charges
    # its full width, so the amortization is reported honestly: P=4
    # typically RAISES this number while the wall drops
    # (probe_batch_speedup).
    try:
        import jax

        fe = np.asarray(jax.tree.leaves(lstate.func_evals)[0]).reshape(-1)
        ls = np.asarray(jax.tree.leaves(lstate.ls_evals)[0]).reshape(-1)
        denom = (1 + repeats) * steps
        row["mean_func_evals_per_step"] = round(
            float((fe + ls).mean()) / denom, 2
        )
        row["mean_ls_probe_evals_per_step"] = round(float(ls.mean()) / denom, 2)
    except Exception:
        pass
    return row


def _probe_batch_probe():
    """Warm epoch wall with the multi-alpha probe fan vs the sequential
    line search (optim/linesearch.py, docs/PERF.md).

    The roofline probe behind `--linesearch-probes`: the sequential
    Armijo search walks its halving ladder one full forward pass per
    rung (mean ~4 per step on the flagship — each pass re-streams the
    parameter vector), while `P=4` evaluates 4 consecutive rungs in ONE
    widened vmapped pass and selects on device. Both configs pick the
    IDENTICAL alpha per step (the fan is the same ladder), so the timed
    delta is pure dispatch-shape: `probe_batch_speedup` = warm epoch
    wall at P=1 over P=4, medianized like every other probe. The honest
    cost side rides along: `mean_func_evals_per_step` per config
    (ls_evals included — P=4 charges its full fan width, so the number
    RISES while the wall drops).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    k, batch, steps = 3, 40, 8
    src = synthetic_cifar(n_train=k * batch * steps, n_test=60)
    out = {"linesearch_probes": 4}
    times, evals = {}, {}
    for p in (1, 4):
        cfg = get_preset(
            "fedavg", n_clients=k, batch=batch, check_results=False,
            synthetic_ok=True, max_scan_steps=None, linesearch_probes=p,
        )
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        epoch_fn, _, init_fn = tr._fns(gid)
        lstate, y, z, rho, extra = init_fn(tr.flat)
        flat, stats = tr.flat, tr.stats
        idx = tr._epoch_indices(0, gid, 0, 0)[:steps]

        def run(flat, lstate, stats):
            flat, lstate, stats, _ = epoch_fn(
                flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
                idx, tr.mean, tr.std, y, z, rho,
            )
            return flat, lstate, stats

        flat, lstate, stats = run(flat, lstate, stats)  # warmup/compile
        float(jnp.sum(flat[:, 0]))
        repeats = max(1, int(os.environ.get("BENCH_REPEATS", "5")))
        dts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            flat, lstate, stats = run(flat, lstate, stats)
            float(jnp.sum(flat[:, 0]))
            dts.append(time.perf_counter() - t0)
        times[p] = float(np.median(dts))
        fe = np.asarray(jax.tree.leaves(lstate.func_evals)[0]).reshape(-1)
        ls = np.asarray(jax.tree.leaves(lstate.ls_evals)[0]).reshape(-1)
        evals[p] = round(float((fe + ls).mean()) / ((1 + repeats) * steps), 2)
        tr.close()
    return {
        **out,
        "epoch_time_p1_s": round(times[1], 4),
        "epoch_time_p4_s": round(times[4], 4),
        # >= 1: the fan's amortization of the sequential per-rung
        # parameter streams (the acceptance target is >= 1.3x on the
        # line-search-enabled flagship config on real hardware)
        "probe_batch_speedup": round(times[1] / times[4], 3),
        "mean_func_evals_per_step_p1": evals[1],
        "mean_func_evals_per_step_p4": evals[4],
    }


def _widened_probe():
    """Warm fused-round wall: `--client-fold gemm` vs `vmap` at P=4.

    The widened-GEMM probe (docs/PERF.md §Widened GEMM): `vmap` compiles
    today's exact probe-fan programs — every probe carries its own full
    probe-batched parameter copy, so the MXU sees K·P skinny dots of
    M = B each — while `gemm` re-batches the fan at the tree level so
    probe-invariant layers run ONCE per fan and the active contraction
    widens to M (or N) = B·P. Both folds pick the IDENTICAL alpha per
    step (tests/test_widened.py asserts bitwise parity on CPU), so the
    timed delta is pure dispatch shape. Measured at B=32 (the flagship's
    skinny regime, where widening matters most per the roofline argument)
    and B=256 (already-wide rows — the speedup's expected decay curve).
    `effective_gemm_m` records the M the MXU sees at each point. On a
    CPU host the expected ratio is ~1x (no MXU to starve — docs/PERF.md
    §Re-measurement debt carries the >= 3x TPU target).
    """
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    k, probes = 3, 4
    out = {"linesearch_probes": probes}
    for batch in (32, 256):
        src = synthetic_cifar(n_train=k * batch * 2, n_test=60)
        times = {}
        for fold_mode in ("gemm", "vmap"):
            cfg = get_preset(
                "fedavg", n_clients=k, batch=batch, nloop=5, nadmm=3,
                max_groups=1, model="net", check_results=False,
                synthetic_ok=True, linesearch_probes=probes,
                client_fold=fold_mode,
            )
            tr = Trainer(cfg, verbose=False, source=src)
            gid = tr.group_order[0]
            tr.run_round(0, gid)  # warmup: compile-dominated
            dts = []
            for nloop in range(1, 4):
                t0 = time.perf_counter()
                tr.run_round(nloop, gid)
                dts.append(time.perf_counter() - t0)
            times[fold_mode] = float(np.median(dts))
            tr.close()
        out[f"round_time_gemm_b{batch}_s"] = round(times["gemm"], 4)
        out[f"round_time_vmap_b{batch}_s"] = round(times["vmap"], 4)
        # >= 1 where the widened fold pays: vmap wall over gemm wall
        out[f"widened_gemm_speedup_b{batch}"] = round(
            times["vmap"] / times["gemm"], 3
        )
        out[f"effective_gemm_m_b{batch}"] = k * probes * batch
    # the single headline convention: the skinny-regime point (B=32) is
    # where the fold's claim lives; B=256 rides along as the decay curve
    out["widened_gemm_speedup"] = out["widened_gemm_speedup_b32"]
    out["effective_gemm_m"] = out["effective_gemm_m_b32"]
    return out


def _exchange_probe(tr_partition, group_order, gid, k):
    """The codec zoo's ledger numbers for the measured workload
    (exchange/, obs/ledger.py): exact uplink bytes of one consensus
    exchange under every zoo member — bf16 (half the f32 row), topk at
    the default keep fraction (index+value pairs), q8 and q4 (scale
    header + packed levels) — and each member's partial+codec savings
    vs the naive full-model f32 exchange: the frontier's bytes axis as
    pure partition/codec arithmetic, no device time. The headline keeps
    the historical bf16 top-level rows; the zoo lands under "zoo".
    """
    from federated_pytorch_test_tpu.exchange import make_codec
    from federated_pytorch_test_tpu.obs import CommLedger

    out = {}
    zoo = {}
    for name, kw in (
        ("bf16", dict(exchange_dtype="bfloat16")),
        ("topk", dict(exchange_codec="topk")),
        ("q8", dict(exchange_codec="quant", quant_bits=8)),
        ("q4", dict(exchange_codec="quant", quant_bits=4)),
    ):
        codec = make_codec(**kw)
        ledger = CommLedger(
            tr_partition, k, dtype_bytes=4,
            exchange_dtype=kw.get("exchange_dtype", "float32"),
            codec=codec,
        )
        zoo[name] = {
            "label": codec.label(),
            "comm_bytes_per_round": ledger.round_bytes(gid, k),
            "comm_savings_vs_full": round(
                ledger.savings_vs_full(group_order), 2
            ),
        }
    out.update(
        {
            "exchange_dtype": "bfloat16",
            "comm_bytes_per_round": zoo["bf16"]["comm_bytes_per_round"],
            "comm_savings_vs_full": zoo["bf16"]["comm_savings_vs_full"],
            "zoo": zoo,
        }
    )
    return out


def _eval_tail_probe():
    """Measure the eval-fold/async mechanisms on a cheap net-model round.

    The flagship rows time the raw epoch program (check_results off); the
    eval tail is a property of the full `check_results` round, so this
    probe runs one: warm a tiny 3-client net round in `folded` mode (the
    engine default: evals inside the one fused dispatch) and in `sync`
    mode (`--no-fold-eval --no-async-eval`: standalone eval dispatches,
    each with a blocking host fetch), then times one warm round of each.
    The trajectory is bit-identical across modes (tests/test_fold_eval.py)
    so the wall delta is pure eval-tail overhead.
    """
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=300)
    base = dict(
        n_clients=3, batch=40, nloop=3, nadmm=3, max_groups=1, model="net",
        check_results=True, eval_batch=100, synthetic_ok=True,
    )
    probe = {"eval_mode": "folded"}  # the engine default this PR ships
    times = {}
    for mode, over in (
        ("folded", {}),
        ("sync", dict(fold_eval=False, async_eval=False)),
    ):
        cfg = get_preset("fedavg", **base, **over)
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        t0 = time.perf_counter()
        tr.run_round(0, gid)  # warmup: compile-dominated
        warm = time.perf_counter() - t0
        t0 = time.perf_counter()
        tr.run_round(1, gid)
        times[mode] = time.perf_counter() - t0
        if mode == "folded":
            d = tr.recorder.series["dispatch_count"][-1]["value"]
            probe["round_dispatches"] = int(d["total"])
            probe["recompile_count"] = int(
                sum(r["value"] for r in tr.recorder.series["recompile_count"])
            )
            # compile-dominated warmup wall: with BENCH_COMPILE_CACHE set,
            # rerunning the bench shows the persistent cache's warm-run
            # delta as the drop in this number
            probe["compile_s"] = round(warm, 3)
        tr.close()
    probe["round_time_folded_s"] = round(times["folded"], 4)
    probe["round_time_sync_eval_s"] = round(times["sync"], 4)
    probe["eval_overlap_saved_s"] = round(times["sync"] - times["folded"], 4)
    return probe


def _robust_probe():
    """Per-round overhead of the Byzantine-robust combiner vs the mean.

    Warms one tiny net fedavg round per combiner, then times THREE warm
    rounds of each and takes the per-combiner MEDIAN (the headline's
    medianized-timing discipline — a single-sample delta on a shared
    host is scheduler noise and can even read negative, i.e. claim the
    defense is free); the wall delta is the price of tolerating f
    corrupted clients per round without rollback (the order statistics
    pay an all_gather + per-coordinate sort the mean's psum avoids).
    `robust_agg` reports the engine default this build ships.

    The shared plan corrupts one client per round with scale x1.0 —
    bit-TRANSPARENT (apply_corruption's mode path selects the input
    verbatim), so both rounds include the full corruption machinery in
    their programs yet train the identical clean trajectory. A damaging
    strength would poison the mean run's parameters and the timed
    difference would measure data-dependent L-BFGS line-search
    divergence, not combiner cost.
    """
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import (
        ExperimentConfig,
        Trainer,
        get_preset,
    )

    import numpy as np

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=60)
    base = dict(
        n_clients=3, batch=40, nloop=5, nadmm=3, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
        fault_plan="seed=5,corrupt=1:scale:1",
    )
    times = {}
    for agg in ("mean", "trimmed"):
        cfg = get_preset("fedavg", robust_agg=agg, robust_f=1, **base)
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        tr.run_round(0, gid)  # warmup: compile-dominated
        dts = []
        for nloop in range(1, 4):
            t0 = time.perf_counter()
            tr.run_round(nloop, gid)
            dts.append(time.perf_counter() - t0)
        times[agg] = float(np.median(dts))
        tr.close()
    return {
        "robust_agg": ExperimentConfig().robust_agg,  # the engine default
        "round_time_mean_agg_s": round(times["mean"], 4),
        "round_time_trimmed_agg_s": round(times["trimmed"], 4),
        "robust_overhead_s": round(times["trimmed"] - times["mean"], 4),
    }


def _hetero_probe():
    """Simulated round wall with vs without a deadline, 3x straggler.

    The speed axis is SIMULATED time (fault/plan.py: one nominal inner
    step costs step_time_s seconds, a slow client slow_factor times
    that), so the probe prices the scheduling policy, not this host: the
    stall path's round wall is the slowest client's full-work time (the
    lockstep coordinator waits it out), the deadline path's is the
    deadline (the coordinator closes the round there and takes the
    partial updates). One 3x slow client per round with the deadline at
    the nominal full-work time gives the headline `deadline_speedup` —
    3.0 by construction for this fleet; the probe runs the REAL trainer
    (ragged budgets inside the one-dispatch round) and reads the
    recorded `client_time` series rather than asserting the arithmetic.
    """
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=60)
    total_steps = 2  # 80-sample shards at batch 40
    base = dict(
        n_clients=3, batch=40, nloop=2, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
        fault_plan="seed=5,slow=1:3",
    )
    walls = {}
    for mode, over in (
        ("stall", {}),
        ("deadline", dict(round_deadline=float(total_steps))),
    ):
        cfg = get_preset("fedavg", **base, **over)
        tr = Trainer(cfg, verbose=False, source=src)
        tr.run()
        rounds = [
            r["value"]["round"] for r in tr.recorder.series["client_time"]
        ]
        walls[mode] = float(sum(rounds) / len(rounds))
        tr.close()
    return {
        "round_sim_wall_stall_s": round(walls["stall"], 4),
        "round_sim_wall_deadline_s": round(walls["deadline"], 4),
        "deadline_speedup": round(walls["stall"] / walls["deadline"], 2),
    }


def _fleet_probe():
    """Auto-deadline vs a fixed-deadline sweep on a straggler fleet.

    The closed-loop claim (ROADMAP item 3): `--round-deadline auto`
    tracks the online client_time sketch, so it should match the BEST
    fixed deadline an operator could have picked — without the sweep —
    and beat the rest. The probe runs the REAL trainer over one 3x
    straggler fleet at three fixed deadlines (nominal, mid, slowest-
    client full-work: the operator's plausible picks) plus `auto`, and
    reads each point's mean simulated round wall (`client_time.round`)
    and final accuracy off the recorded series. The headline
    `auto_deadline_speedup` is the worst EQUAL-ACCURACY fixed point's
    wall over auto's (fixed points within 2 accuracy points of auto's;
    all of them when none is) — what the adaptive policy saves against
    a defensible-but-wrong constant. The full acceptance gate (churn +
    liars, Pareto dominance on the report frontier) is the slow-tier
    fleet test (tests/test_fleet.py) and the tier-2 fleet_smoke.
    """
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=60)
    total_steps = 2  # 80-sample shards at batch 40
    slow_factor = 3.0
    base = dict(
        n_clients=3, batch=40, nloop=5, nadmm=2, max_groups=1, model="net",
        check_results=True, eval_batch=60, synthetic_ok=True,
        # Bernoulli stragglers: MOST exchanges run at nominal speed, so
        # the sketch's median p95 settles near the nominal full-work
        # time and the post-warmup auto deadline keeps cutting the
        # occasional straggler (an every-exchange straggler would drag
        # the p95 signal up to the straggler's own time)
        fault_plan=f"seed=5,slow=0.15:{slow_factor:g}",
    )
    points = {}
    sweeps = {
        "fixed_nominal": float(total_steps),
        "fixed_mid": float(total_steps) * 2.0,
        "fixed_slowest": float(total_steps) * slow_factor,
        "auto": "auto",
    }
    for label, deadline in sweeps.items():
        cfg = get_preset("fedavg", **base, round_deadline=deadline)
        tr = Trainer(cfg, verbose=False, source=src)
        tr.run()
        rounds = [
            r["value"]["round"] for r in tr.recorder.series["client_time"]
        ]
        acc = tr.recorder.latest("test_accuracy")
        points[label] = {
            "deadline": deadline,
            "round_sim_wall_s": round(float(np.mean(rounds)), 4),
            "final_accuracy": round(float(np.mean(acc)), 4),
        }
        tr.close()
    auto = points["auto"]
    fixed = {k: v for k, v in points.items() if k != "auto"}
    equal = [
        v for v in fixed.values()
        if v["final_accuracy"] >= auto["final_accuracy"] - 0.02
    ] or list(fixed.values())
    worst = max(v["round_sim_wall_s"] for v in equal)
    return {
        "points": points,
        "auto_deadline_speedup": round(
            worst / auto["round_sim_wall_s"], 2
        ),
    }


def _cohort_probe():
    """Cohort-mode wall vs virtual-population size N at fixed cohort C.

    The cross-device scale claim (clients/, docs/SCALE.md) is that
    per-round cost depends on the COHORT, not the population: N virtual
    clients live in the host store and only C gathered rows ever touch a
    device, so the warm round wall at N=64 and N=1024 must match.
    `cohort_scaling` is the small-N/large-N median-round-time ratio —
    1.0 is perfectly flat, below ~0.9 means per-round cost is leaking an
    O(N) term (gather, sampler, or store bookkeeping). Medianized over
    three warm gather→round→scatter loops per row, same discipline as
    the other probes.
    """
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    c = 4
    src = synthetic_cifar(n_train=c * 40 * 2, n_test=60)
    times = {}
    for n_virtual in (64, 1024):
        cfg = get_preset(
            "fedavg", batch=40, nloop=4, nadmm=2, max_groups=1,
            model="net", check_results=False, synthetic_ok=True,
            virtual_clients=n_virtual, cohort=c, data_shards=c,
        )
        tr = Trainer(cfg, verbose=False, source=src)
        tr.run_loop(0)  # warmup: compile-dominated
        dts = []
        for nloop in range(1, 4):
            t0 = time.perf_counter()
            tr.run_loop(nloop)  # one gather -> round -> scatter cycle
            dts.append(time.perf_counter() - t0)
        times[n_virtual] = float(np.median(dts))
        tr.close()
    return {
        "cohort": c,
        "virtual_clients_small": 64,
        "virtual_clients_large": 1024,
        "round_time_n64_s": round(times[64], 4),
        "round_time_n1024_s": round(times[1024], 4),
        # ≈1.0 when per-round cost is flat in N (the scale contract)
        "cohort_scaling": round(times[64] / times[1024], 3),
    }


def _prefetch_probe():
    """Warm outer-loop wall with the pipelined cohort prefetch on vs
    off at N=10k/C=8, plus the spilled store's residency evidence.

    The prefetch claim (clients/prefetch.py, docs/SCALE.md §Prefetch
    lifecycle) is that the cohort gather — store chunk reads, the
    cohort's data-shard slices, their device puts — leaves the round
    wall: loop n+1's gather runs on a background thread while loop n
    trains, and adoption is bit-identical to a cold gather
    (tests/test_prefetch.py). `prefetch_overlap_saved_s` is the
    medianized warm gather→rounds→scatter loop wall with prefetch OFF
    minus ON — approximately the synchronous gather's wall, and > 0
    whenever the gather overlaps any compute at all (the acceptance
    gate on the CPU twin). The shard pool is sized so the per-loop
    data gather is tens of MB — a real gather, not a rounding error.

    The spilled-store rows ride along (the bounded-RSS story,
    ROADMAP item 4): one short run with `--store-resident-chunks`
    pinned low reports the post-run resident count and the evictions
    the budget forced — the fields the `memory_rss_peak_mb` headline
    needs next to it to mean "flat in N".
    """
    import shutil
    import tempfile

    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.obs import TraceRecorder

    c, n_virtual = 8, 10_000
    src = synthetic_cifar(n_train=c * 40 * 2, n_test=60)
    base = dict(
        batch=40, nloop=5, nadmm=1, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
        virtual_clients=n_virtual, cohort=c, data_shards=c,
    )
    # the signal lives in the cohort_gather SPAN, not the loop wall: on
    # the CPU twin the rounds are seconds of host compute while the
    # gather is milliseconds, so a wall-minus-wall delta is scheduler
    # noise. The span IS the claim — with prefetch off it is the
    # synchronous gather sitting on the wall; with prefetch on it is
    # the adoption cost (patch + bookkeeping), the background thread
    # having done the gather during the previous loop's rounds.
    gather_s, walls = {}, {}
    for on in (True, False):
        cfg = get_preset("fedavg", prefetch=on, **base)
        tr = Trainer(cfg, verbose=False, source=src)
        tr.recorder.tracer = TraceRecorder()
        tr.run_loop(0)  # warmup: compile-dominated
        dts = []
        for nloop in range(1, 5):
            t0 = time.perf_counter()
            tr.run_loop(nloop)  # one gather -> rounds -> scatter cycle
            dts.append(time.perf_counter() - t0)
        spans = [
            e["dur"] / 1e6
            for e in tr.recorder.tracer.events
            if e.get("name") == "cohort_gather"
            and e.get("args", {}).get("nloop", 0) >= 1  # warm loops only
        ]
        gather_s[on] = float(np.median(spans))
        walls[on] = float(np.median(dts))
        tr.close()
    out = {
        "virtual_clients": n_virtual,
        "cohort": c,
        "loop_time_prefetch_on_s": round(walls[True], 4),
        "loop_time_prefetch_off_s": round(walls[False], 4),
        "gather_span_prefetch_on_s": round(gather_s[True], 5),
        "gather_span_prefetch_off_s": round(gather_s[False], 5),
        # > 0: the gather span left the critical path (off-mode still
        # pays it synchronously on the wall; on-mode pays only adoption)
        "prefetch_overlap_saved_s": round(
            gather_s[False] - gather_s[True], 5
        ),
    }
    # spilled-store residency: a short bounded run through the real
    # checkpoint path (eviction spills need the manifest discipline)
    d = tempfile.mkdtemp(prefix="bench_spill_")
    try:
        cfg = get_preset(
            "fedavg", **{**base, "nloop": 3},
            store_chunk_clients=8, store_resident_chunks=2,
            save_model=True, checkpoint_dir=os.path.join(d, "ckpt"),
        )
        tr = Trainer(cfg, verbose=False, source=src)
        tr.run()
        res = tr.store.residency()
        out["store_resident_chunks"] = res["resident_chunks"]
        out["store_resident_budget"] = res["resident_budget"]
        out["store_evictions"] = res["evictions"]
        out["store_spill_bytes"] = res["spill_bytes"]
        # checksum overhead (storage-integrity PR, docs/FAULT.md
        # §Storage-integrity axis): the verify-on-read gate is one
        # crc32 pass over each spilled chunk's mmap before the view
        # parse — measured as the warm full-population gather wall,
        # checksums on minus off, over the spilled chunks the bounded
        # run just wrote. The acceptance gate is ≈ 0 (crc32 is
        # ~GB/s-scale on one core; the chunks here are a few MB);
        # scheduler noise can read slightly negative — reported as
        # measured. The mmap cache is cleared per rep so every rep
        # pays the full read path, not a cache hit.
        st = tr.store
        ids = np.arange(n_virtual)
        checksum_walls = {}
        for checks in (True, False):
            st.checksums = checks
            st._mmap_cache.clear()
            st.gather("flat", ids)  # warm: page cache + digest table
            reps = []
            for _ in range(5):
                st._mmap_cache.clear()
                t0 = time.perf_counter()
                st.gather("flat", ids)
                reps.append(time.perf_counter() - t0)
            checksum_walls[checks] = float(np.median(reps))
        st.checksums = True
        out["gather_wall_checksums_on_s"] = round(checksum_walls[True], 5)
        out["gather_wall_checksums_off_s"] = round(checksum_walls[False], 5)
        out["checksum_overhead_s"] = round(
            checksum_walls[True] - checksum_walls[False], 5
        )
        tr.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def _health_probe():
    """Warm-round wall with the in-run health engine on vs off.

    The health engine (obs/health.py) is pure host bookkeeping over
    values the trainer already fetched — P² sketch updates and windowed
    counters, zero device dispatches — so its per-round cost must be
    ≈ 0 (the ISSUE-10 gate). Two identical tiny net trainers, health on
    (the engine default) and off, each warmed one round then timed over
    three warm rounds; `health_overhead_s` is the median-round delta.
    On a shared host a delta within scheduler noise can read slightly
    negative — that IS the ≈ 0 verdict, reported as measured.
    """
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=60)
    base = dict(
        n_clients=3, batch=40, nloop=5, nadmm=3, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    times = {}
    for on in (True, False):
        cfg = get_preset("fedavg", health_monitor=on, **base)
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        tr.run_round(0, gid)  # warmup: compile-dominated
        dts = []
        for nloop in range(1, 4):
            t0 = time.perf_counter()
            tr.run_round(nloop, gid)
            dts.append(time.perf_counter() - t0)
        times[on] = float(np.median(dts))
        if on:
            n_health = len(tr.recorder.series.get("health", []))
        tr.close()
    return {
        "round_time_health_on_s": round(times[True], 4),
        "round_time_health_off_s": round(times[False], 4),
        "health_overhead_s": round(times[True] - times[False], 4),
        "health_records": n_health,
    }


def _flight_probe():
    """Warm-round wall with the flight recorder on vs off, plus the
    bench process's peak host RSS.

    The flight recorder (obs/flight.py) is a second sink on the metric
    stream: per streamed record one list append, per round one deque
    rotation — no device work, no extra I/O until an incident dumps —
    so its per-round cost must be ≈ 0 (the ISSUE-14 gate, the health
    probe's discipline: both trainers stream to a JSONL file, only the
    recorder flag differs, and a shared-host delta within scheduler
    noise can read slightly negative — that IS the ≈ 0 verdict).
    `memory_rss_peak_mb` rides along from obs/memory.py — the
    bounded-RSS evidence ROADMAP item 4's spilled-store gate will
    consume.
    """
    import shutil
    import tempfile

    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.obs import host_rss_peak_bytes

    src = synthetic_cifar(n_train=3 * 40 * 2, n_test=60)
    base = dict(
        n_clients=3, batch=40, nloop=5, nadmm=3, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    d = tempfile.mkdtemp(prefix="bench_flight_")
    times = {}
    try:
        for on in (True, False):
            cfg = get_preset(
                "fedavg",
                flight_recorder=on,
                metrics_stream=os.path.join(d, f"flight_{int(on)}.jsonl"),
                **base,
            )
            tr = Trainer(cfg, verbose=False, source=src)
            gid = tr.group_order[0]
            tr.run_round(0, gid)  # warmup: compile-dominated
            dts = []
            for nloop in range(1, 4):
                t0 = time.perf_counter()
                tr.run_round(nloop, gid)
                dts.append(time.perf_counter() - t0)
            times[on] = float(np.median(dts))
            tr.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    peak = host_rss_peak_bytes()
    return {
        "round_time_flight_on_s": round(times[True], 4),
        "round_time_flight_off_s": round(times[False], 4),
        "flight_recorder_overhead_s": round(times[True] - times[False], 4),
        "memory_rss_peak_mb": (
            round(peak / 2**20, 1) if peak is not None else None
        ),
    }


def main() -> None:
    bench_device = os.environ.get("BENCH_DEVICE", "")
    if bench_device == "cpu":
        from federated_pytorch_test_tpu.utils import force_host_cpu

        force_host_cpu()
    import jax

    compile_cache = os.environ.get("BENCH_COMPILE_CACHE")
    if compile_cache:
        os.makedirs(compile_cache, exist_ok=True)
        jax.config.update(
            "jax_compilation_cache_dir", os.path.abspath(compile_cache)
        )

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    # BENCH_MODEL swaps the flagship model (models.MODELS key) — the
    # trend smoke runs the tiny "net" CNN through the identical timing
    # path in seconds where the resnet18 L-BFGS epoch costs minutes on
    # the CPU twin. An overridden run is a DIFFERENT workload: the
    # headline metric is renamed to carry the model, so the row can
    # never append to (or judge) the resnet18 trajectory downstream,
    # and vs_baseline is omitted.
    model_override = os.environ.get("BENCH_MODEL") or None

    device_kind = jax.devices()[0].device_kind
    peak_tflops, peak_gbps = _peaks(device_kind)

    # ---- the flagship metric (reference workload, like for like) ----
    flag = _measure("fedavg_resnet", model_override, batch, steps, dtype,
                    peak_tflops, peak_gbps)

    ref_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "reference_throughput.json",
    )
    # the cached reference number is the batch-32 flagship workload; a
    # BENCH_BATCH override changes the workload, so the ratio would not
    # compare like for like — omit it rather than inflate it
    vs_baseline = None
    if model_override is None and batch == 32 and os.path.exists(ref_path):
        with open(ref_path) as f:
            ref = json.load(f)
        ref_sps = ref.get("samples_per_sec")
        if ref_sps:
            vs_baseline = flag["samples_per_sec"] / ref_sps

    # the provenance stamp (obs/provenance.py): every number this
    # process emits says where it came from — backend, chip, commit,
    # host, repeats. The trend layer keys its regression baselines on
    # the stamp's class, so a CPU-twin session can never masquerade as
    # a TPU measurement downstream.
    from federated_pytorch_test_tpu.obs.provenance import provenance_stamp

    stamp = provenance_stamp(repeats=flag.get("repeats"))

    out = {
        "metric": (
            f"fedavg_{model_override}_3client_lbfgs_train_throughput"
            if model_override
            else "fedavg_resnet18_3client_lbfgs_train_throughput"
        ),
        "value": flag["samples_per_sec"],
        "unit": "samples/sec",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
        "batch": batch,
        "n_clients": 3,
        "dtype": dtype,
        "provenance": stamp,
    }
    if "achieved_tflops" in flag:
        out["achieved_tflops"] = flag["achieved_tflops"]
    if "mfu" in flag:
        out["mfu"] = flag["mfu"]
    roof = {
        "device": device_kind,
        "epoch_time_s": flag["epoch_time_s"],
        "peak_tflops_bf16": peak_tflops,
        "peak_hbm_gbps": peak_gbps,
    }
    for key in ("achieved_hbm_gbps", "hbm_util", "achieved_hbm_frac",
                "arithmetic_intensity", "mean_func_evals_per_step"):
        if key in flag:
            roof[key] = flag[key]
    if peak_tflops and peak_gbps:
        roof["ridge_intensity"] = round(peak_tflops * 1e12 / (peak_gbps * 1e9), 1)
        if "arithmetic_intensity" in flag:
            roof["bound"] = (
                "memory"
                if flag["arithmetic_intensity"] < roof["ridge_intensity"]
                else "compute"
            )
    out["roofline"] = roof

    # BENCH_PROBES=0 skips the whole subsystem-probe suite (each is a
    # mini training run): the trend smoke in scripts/ci.sh needs only
    # the flagship headline, repeated, in seconds not minutes. Skipped
    # probes leave their keys absent — every headline read below is a
    # .get() and tolerates that.
    run_probes = os.environ.get("BENCH_PROBES", "1") != "0"

    if run_probes:
        # ---- the probe-batch probe: multi-alpha fan vs sequential search ----
        try:
            out["probe_batch"] = _probe_batch_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["probe_batch"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the widened-GEMM probe: --client-fold gemm vs vmap rounds ----
        try:
            out["widened"] = _widened_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["widened"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the exchange-codec ledger numbers for the flagship group ----
        try:
            from federated_pytorch_test_tpu.engine import (
                Trainer as _Tr,
                get_preset as _gp,
            )
            from federated_pytorch_test_tpu.data import synthetic_cifar as _syn

            _cfg = _gp("fedavg_resnet", n_clients=3, batch=32,
                       check_results=False, synthetic_ok=True)
            _tr = _Tr(_cfg, verbose=False,
                      source=_syn(n_train=3 * 32, n_test=32))
            out["exchange"] = _exchange_probe(
                _tr.partition, _tr.group_order, _tr.group_order[0], 3
            )
            _tr.close()
        except Exception as e:
            out["exchange"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the eval-tail probe: folded vs sync check_results rounds ----
        try:
            out["eval_tail"] = _eval_tail_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["eval_tail"] = {"error": f"{type(e).__name__}: {e}"[:200]}
        if compile_cache:
            out["eval_tail"]["compile_cache"] = os.path.abspath(compile_cache)

        # ---- the robust-aggregation probe: combiner overhead vs mean ----
        try:
            out["robust"] = _robust_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["robust"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the heterogeneity probe: deadline rounds vs the stall path ----
        try:
            out["hetero"] = _hetero_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["hetero"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the fleet probe: auto deadline vs the fixed-deadline sweep ----
        try:
            out["fleet"] = _fleet_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["fleet"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the cohort probe: round wall flat in virtual-population N ----
        try:
            out["cohort"] = _cohort_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["cohort"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the prefetch probe: cohort gather off the round wall ----
        try:
            out["prefetch"] = _prefetch_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["prefetch"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the health probe: sketch/monitor overhead per warm round ----
        try:
            out["health"] = _health_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["health"] = {"error": f"{type(e).__name__}: {e}"[:200]}

        # ---- the flight probe: recorder overhead + peak host RSS ----
        try:
            out["flight"] = _flight_probe()
        except Exception as e:  # a failed probe must not kill the bench
            out["flight"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # ---- the utilization sweep: batch and model-size levers ----
    # (round-2 VERDICT: "no row anywhere shows MFU climbing with batch or
    # model size"). Step counts shrink as batch grows so each row stays a
    # few seconds of device time while still amortizing dispatch. Skipped
    # in the BENCH_DEVICE=cpu escape hatch — the batch-512/2048 rows and
    # the 16k matmul probe are hours on a host core.
    run_sweep = (
        os.environ.get("BENCH_SWEEP", "1") != "0"
        and jax.devices()[0].platform != "cpu"  # incl. TPU-less fallback
    )
    if run_sweep:
        sweep_specs = [
            ("fedavg_resnet", None, 32, 20, "float32"),
            ("fedavg_resnet", None, 128, 10, "float32"),
            ("fedavg_resnet", None, 512, 5, "float32"),
            ("fedavg_resnet", None, 512, 5, "bfloat16"),
            ("fedavg_resnet", None, 2048, 3, "float32"),
            ("fedavg", "net2", 512, 5, "float32"),
        ]
        sweep = []
        for spec in sweep_specs:
            if spec[0] == "fedavg_resnet" and spec[2:] == (batch, steps, dtype):
                # the flagship row, already measured
                sweep.append(flag)
                continue
            try:
                sweep.append(_measure(*spec, peak_tflops, peak_gbps))
            except Exception as e:  # a failed row must not kill the bench
                sweep.append({
                    "model": spec[1] or "resnet18", "batch": spec[2],
                    "dtype": spec[4], "error": f"{type(e).__name__}: {e}"[:200],
                })
        out["sweep"] = sweep

    # ---- MXU saturation probe ----
    # the sweep shows the FLAGSHIP workload's utilization ceiling (the
    # inner solver's sequential chain binds before either roofline
    # wall). This probe shows the CHIP is not the limit: a DEPENDENT
    # chain of large bf16 matmuls, the shape XLA tiles perfectly onto
    # the MXU (dependence is what keeps the simplifier from collapsing
    # the chain — see the in-function comment). Its %-of-peak is the
    # denominator against which every workload row should be read.
    if run_sweep:
        import jax.numpy as jnp

        n, inner = 16384, 16
        a = jnp.ones((n, n), jnp.bfloat16)
        b = jnp.ones((n, n), jnp.bfloat16) * jnp.bfloat16(1e-4)

        def chain(a, b):
            # a DEPENDENT chain: each LHS is the previous product, so no
            # matmul can be CSE'd, hoisted, or algebraically collapsed.
            # Every cheaper formulation tried was silently destroyed by
            # the simplifier (all verified against cost_analysis):
            #   * `sum((s_i*a) @ b)` — scalar factors hoist out of the
            #     dot and the n identical matmuls CSE to ONE;
            #   * `sum(a @ b)` — rewritten as dot(colsum(a), rowsum(b)),
            #     O(n^2), no matmul at all (round 3's 177%-of-peak bug
            #     was the [:1,:1]-slice flavor of the same narrowing);
            #   * a fori_loop body is counted ONCE by cost_analysis,
            #     breaking the FLOP cross-check below.
            # The final reduction is sum of SQUARES — a plain sum would
            # let the last matmul collapse through the same rewrite.
            # inner=16 amortizes the tunneled runtime's ~0.14 s flat
            # dispatch+fetch latency (inner=4 reads ~62% for the same
            # chip state; 16 chained 16k matmuls measure ~89%).
            c = a
            for _ in range(inner):
                c = (c @ b) * jnp.bfloat16(1e-1)  # bound magnitudes
            cf = c.astype(jnp.float32)
            return jnp.sum(cf * cf)

        # FLOP numerator cross-checked against XLA's cost model of the
        # program actually compiled (verified equal to the analytic
        # 2n^3*inner for this chain): take the smaller so any future
        # compiler narrowing can only LOWER the reported utilization
        compiled_probe = jax.jit(chain).lower(a, b).compile()
        probe_flops = 2.0 * n * n * n * inner
        try:
            ca = compiled_probe.cost_analysis()
            ca = ca if isinstance(ca, dict) else ca[0]
            cm = float(ca.get("flops", 0.0))
            if cm > 0.0:
                probe_flops = min(probe_flops, cm)
        except Exception:
            pass
        float(compiled_probe(a, b))  # warmup; scalar fetch = true barrier
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(compiled_probe(a, b))
            best = min(best, time.perf_counter() - t0)
        probe_tflops = probe_flops / best / 1e12
        pct = round(100.0 * probe_tflops / peak_tflops, 1) if peak_tflops else None
        out["mxu_probe"] = {
            "shape": f"{n}x{n} bf16 matmul chain x{inner}",
            "achieved_tflops": round(probe_tflops, 1),
            "pct_peak": pct,
            # a >100% reading means the timing barrier or FLOP accounting
            # failed; say so in the artifact instead of publishing it
            "valid": bool(pct is None or pct <= 100.0),
        }

    # The full blob (sweep, roofline, probe) goes to a file; the FINAL
    # stdout line is a compact headline only. The driver keeps a bounded
    # tail of stdout and parses its last line — round 3's ~3KB line was
    # truncated mid-JSON and recorded as parsed:null.
    full_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "bench_full.json"
    )
    try:
        with open(full_path, "w") as f:
            json.dump(out, f, indent=1)
        print(f"full results -> {full_path}", flush=True)
    except OSError:
        print(json.dumps(out), flush=True)  # read-only checkout: keep data

    headline = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        # medianized timing (BASELINE.md: single draws range 160-2600 on
        # the shared chip): value is the median of BENCH_REPEATS runs,
        # p25/p75 say how noisy this measurement session was
        "sps_p25": flag.get("sps_p25"),
        "sps_p75": flag.get("sps_p75"),
        "repeats": flag.get("repeats"),
        "vs_baseline": out["vs_baseline"],
        "batch": out["batch"],
        "dtype": out["dtype"],
        "mfu": out.get("mfu"),
        # the roofline-telemetry facts (obs/roofline.py): where the
        # flagship epoch sits against the chip's two walls — the
        # intensity-vs-ridge verdict ROADMAP item 2's honest note needs
        "arithmetic_intensity": flag.get("arithmetic_intensity"),
        "achieved_hbm_frac": flag.get("achieved_hbm_frac"),
        "epoch_time_s": out["roofline"]["epoch_time_s"],
        # the communication ledger's two headline facts (obs/ledger.py):
        # exact bytes one consensus exchange of the measured group moves,
        # and the partial-vs-full-model exchange savings over a partition
        # sweep — the quantity the source paper's bandwidth claim is about
        "comm_bytes_per_round": flag.get("comm_bytes_per_round"),
        "comm_savings_vs_full": flag.get("comm_savings_vs_full"),
        # the roofline probe facts (multi-alpha fan + bf16 codec PR,
        # docs/PERF.md): honest per-step model-eval count (line-search
        # probes included), the fan width the speedup row measures, warm
        # epoch wall P=1/P=4 ratio, and the bf16 codec's halved uplink
        "mean_func_evals_per_step": flag.get("mean_func_evals_per_step"),
        "linesearch_probes": out.get("probe_batch", {}).get(
            "linesearch_probes"
        ),
        "probe_batch_speedup": out.get("probe_batch", {}).get(
            "probe_batch_speedup"
        ),
        # the widened-GEMM facts (ISSUE-17, docs/PERF.md §Widened GEMM):
        # warm fused-round wall vmap/gemm at the flagship's skinny B=32
        # (the headline claim; >= 3x is the TPU target, ~1x expected on
        # CPU hosts), the already-wide B=256 decay point, and the M the
        # MXU actually sees through the fold
        "widened_gemm_speedup": out.get("widened", {}).get(
            "widened_gemm_speedup"
        ),
        "widened_gemm_speedup_b256": out.get("widened", {}).get(
            "widened_gemm_speedup_b256"
        ),
        "effective_gemm_m": out.get("widened", {}).get("effective_gemm_m"),
        "exchange_dtype": out.get("exchange", {}).get("exchange_dtype"),
        "bf16_comm_bytes_per_round": out.get("exchange", {}).get(
            "comm_bytes_per_round"
        ),
        # the provenance stamp (obs/provenance.py): the headline's
        # backend/chip/commit identity — what the trend layer's
        # class-isolated regression sentinel keys on
        "provenance": stamp,
    }
    # the eval-tail facts (fold/async eval PR): which eval mode the
    # engine defaults to, how many program launches a folded
    # check_results round costs, and the per-round wall the fold saves
    # over the sync-eval path; recompile_count/compile_s track the
    # persistent compile cache (BENCH_COMPILE_CACHE) across reruns
    et = out.get("eval_tail", {})
    for key in ("eval_mode", "round_dispatches", "eval_overlap_saved_s",
                "recompile_count", "compile_s"):
        headline[key] = et.get(key)
    # the robust-aggregation facts (Byzantine PR): the engine's default
    # combiner and the per-round wall a trimmed-mean defense costs over it
    rb = out.get("robust", {})
    for key in ("robust_agg", "robust_overhead_s"):
        headline[key] = rb.get(key)
    # the heterogeneity fact (deadline-rounds PR): simulated round wall
    # saved by closing rounds at the deadline instead of stalling for a
    # 3x straggler (partial updates ride the participation machinery)
    headline["deadline_speedup"] = out.get("hetero", {}).get(
        "deadline_speedup"
    )
    # the closed-loop fact (auto-deadline PR): simulated round wall the
    # adaptive policy saves against the worst equal-accuracy fixed
    # deadline of the sweep (>= 1.0 means auto matched the best pick)
    headline["auto_deadline_speedup"] = out.get("fleet", {}).get(
        "auto_deadline_speedup"
    )
    # the cross-device scale fact (virtual-client cohort PR): warm
    # gather→round→scatter wall ratio at N=64 vs N=1024 with C fixed —
    # ≈1.0 means per-round cost depends on the cohort, not the
    # virtual-population size (clients/, docs/SCALE.md)
    headline["cohort_scaling"] = out.get("cohort", {}).get("cohort_scaling")
    # the health-engine fact (in-run health PR): per-warm-round wall the
    # always-on sketches/monitor cost — the ≈ 0 gate (obs/health.py does
    # no device work; scheduler noise can read slightly negative)
    headline["health_overhead_s"] = out.get("health", {}).get(
        "health_overhead_s"
    )
    # the flight-recorder facts (obs/flight.py PR): per-warm-round wall
    # the always-on incident ring costs — the ≈ 0 gate, measured with
    # the stream sink live on both sides — and the bench process's peak
    # host RSS (obs/memory.py), ROADMAP item 4's bounded-RSS evidence
    headline["flight_recorder_overhead_s"] = out.get("flight", {}).get(
        "flight_recorder_overhead_s"
    )
    headline["memory_rss_peak_mb"] = out.get("flight", {}).get(
        "memory_rss_peak_mb"
    )
    # the scale-out facts (pipelined prefetch + spilled store PR,
    # docs/SCALE.md): warm loop wall the background cohort gather takes
    # off the critical path (> 0 = the gather span overlapped compute),
    # and the bounded store's residency evidence riding next to the
    # peak-RSS row — resident chunks held vs the evictions the budget
    # forced (the flat-in-N story needs both numbers together)
    # ...and the storage-integrity tax riding the same probe: warm
    # gather wall with the verify-on-read checksums on minus off —
    # the ≈ 0 evidence that durability is not a throughput knob
    for key in ("prefetch_overlap_saved_s", "store_resident_chunks",
                "store_evictions", "checksum_overhead_s"):
        headline[key] = out.get("prefetch", {}).get(key)
    if "mxu_probe" in out:
        headline["mxu_pct_peak"] = out["mxu_probe"]["pct_peak"]
        headline["mxu_probe_valid"] = out["mxu_probe"]["valid"]
    # tracked secondary headline (round-4 VERDICT item 5): the measured
    # best throughput configuration — bf16 batch-512 — so the win region
    # beyond the reference's batch-32 workload is a recorded series, not
    # a one-off sweep row
    for row in out.get("sweep", []):
        if (row.get("model"), row.get("batch"), row.get("dtype")) == (
            "resnet18", 512, "bfloat16",
        ) and "samples_per_sec" in row:
            headline["bf16_512_sps"] = row["samples_per_sec"]
            headline["bf16_512_mfu"] = row.get("mfu")
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
