"""Benchmark: flagship 3-client ResNet18 FedAvg hot loop on real hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N,
   "mfu": ..., "achieved_tflops": ..., "roofline": {...}}

The hot loop is the jitted sharded epoch function — every client's
stochastic L-BFGS step (up to 4 inner iterations, Armijo line-search
probes included) on one lockstep minibatch per client. This is the same
work the reference does in `opt.step(closure)` x3 per minibatch
(reference src/federated_trio_resnet.py:320-338).

`vs_baseline` compares against the reference's measured throughput on this
host (torch CPU — the reference has no device code; see
`benchmarks/measure_reference.py`, result cached in
`benchmarks/reference_throughput.json`).

Chip-utilization accounting (the number samples/sec cannot give): the
compiled epoch program's exact FLOP and HBM-byte counts come from XLA's
cost model (`compiled.cost_analysis()` — the same counts the compiler
schedules against, so line-search probes, L-BFGS linear algebra, and
normalization are all included, not just the model matmuls), divided by
the measured wall-clock and the chip's peaks:

  mfu               = achieved FLOP/s / peak MXU FLOP/s (bf16 peak: the
                      MXU multiplies bf16 natively; f32-precision passes
                      run BELOW this peak, so mfu is conservative)
  hbm_util          = achieved bytes/s / peak HBM bandwidth
  arithmetic intensity vs the ridge point says which wall the workload
  is against — see BASELINE.md's roofline note.
"""

from __future__ import annotations

import json
import os
import time

# (peak dense MXU TFLOP/s in bf16, peak HBM GB/s) per device_kind prefix.
# Public spec-sheet numbers; 'TPU v5 lite' == v5e.
_CHIP_PEAKS = {
    "TPU v5 lite": (197.0, 819.0),
    "TPU v5e": (197.0, 819.0),
    "TPU v5p": (459.0, 2765.0),
    "TPU v4": (275.0, 1228.0),
    "TPU v6 lite": (918.0, 1640.0),
    "TPU v6e": (918.0, 1640.0),
}


def _peaks(device_kind: str):
    for prefix, peaks in _CHIP_PEAKS.items():
        if device_kind.startswith(prefix):
            return peaks
    return None, None


def main() -> None:
    bench_device = os.environ.get("BENCH_DEVICE", "")
    if bench_device == "cpu":
        from federated_pytorch_test_tpu.utils import force_host_cpu

        force_host_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    k = 3
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    # synthetic CIFAR-shaped data (identical compute to the real archive)
    src = synthetic_cifar(n_train=k * batch * max(steps, 8), n_test=64)
    cfg = get_preset(
        "fedavg_resnet",
        n_clients=k,
        batch=batch,
        check_results=False,
        # convs/matmuls in bf16 on the MXU when BENCH_DTYPE=bfloat16;
        # loss, norms and the L-BFGS math stay f32 either way
        compute_dtype=os.environ.get("BENCH_DTYPE", "float32"),
    )
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    epoch_fn, _, init_fn = tr._fns(gid)
    lstate, y, z, rho, extra = init_fn(tr.flat)
    flat, stats = tr.flat, tr.stats

    def run_epoch(flat, lstate, stats, idx):
        # epoch_fn donates (flat, lstate, stats): thread them through
        flat, lstate, stats, losses = epoch_fn(
            flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
            idx, tr.mean, tr.std, y, z, rho,
        )
        return flat, lstate, stats

    idx = tr._epoch_indices(0, gid, 0, 0)[:steps]

    # exact FLOP / HBM-byte counts of the compiled epoch program (XLA's
    # cost model over the optimized HLO — includes every line-search
    # probe and all L-BFGS linear algebra, not just the model matmuls).
    # The AOT executable then SERVES the warmup/timed calls below, so the
    # epoch program is compiled exactly once per run.
    flops = hbm_bytes = None
    try:
        compiled = epoch_fn.lower(
            flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
            idx, tr.mean, tr.std, y, z, rho,
        ).compile()
        ca = compiled.cost_analysis()
        ca = ca if isinstance(ca, dict) else ca[0]
        flops = float(ca.get("flops", 0.0)) or None
        hbm_bytes = float(ca.get("bytes accessed", 0.0)) or None
        epoch_fn = compiled  # same call signature as the jitted fn
    except Exception:
        pass

    # warmup / compile (same scan length as the timed run — scan length is
    # static, so a shorter warmup would compile a second program).
    # Synchronization is a SCALAR FETCH, not block_until_ready: on the
    # remote-tunnel PJRT runtime block_until_ready returns at dispatch-ack,
    # so only a device->host read is a true completion barrier. The timed
    # call's inputs differ from the warmup's (flat/lstate/stats are
    # threaded through), so no result caching can serve it.
    flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
    float(jnp.sum(flat[:, 0]))

    # best of 3: the tunneled chip is shared, so single-shot timings can
    # absorb other tenants' work — the minimum is the machine's number
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        flat, lstate, stats = run_epoch(flat, lstate, stats, idx)
        float(jnp.sum(flat[:, 0]))
        dt = min(dt, time.perf_counter() - t0)

    n_samples = steps * k * batch
    sps = n_samples / dt

    # closure-evaluation accounting (the reference's one built-in counter,
    # src/lbfgsnew.py:508-510): value_and_grad evals per optimizer step,
    # cumulative in the threaded L-BFGS state
    func_evals = None
    try:
        fe = np.asarray(jax.tree.leaves(lstate.func_evals)[0]).reshape(-1)
        # state was threaded through 1 warmup + 3 timed epochs of `steps`
        func_evals = float(fe.mean()) / (4 * steps)
    except Exception:
        pass

    ref_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "reference_throughput.json",
    )
    # the cached reference number is the batch-32 flagship workload; a
    # BENCH_BATCH override changes the workload, so the ratio would not
    # compare like for like — omit it rather than inflate it
    vs_baseline = None
    if batch == 32 and os.path.exists(ref_path):
        with open(ref_path) as f:
            ref = json.load(f)
        ref_sps = ref.get("samples_per_sec")
        if ref_sps:
            vs_baseline = sps / ref_sps

    out = {
        "metric": "fedavg_resnet18_3client_lbfgs_train_throughput",
        "value": round(sps, 2),
        "unit": "samples/sec",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
        "batch": batch,
        "n_clients": k,
        "dtype": cfg.compute_dtype,
    }

    device_kind = jax.devices()[0].device_kind
    peak_tflops, peak_gbps = _peaks(device_kind)
    if flops:
        achieved_tflops = flops / dt / 1e12
        out["achieved_tflops"] = round(achieved_tflops, 3)
        if peak_tflops:
            out["mfu"] = round(achieved_tflops / peak_tflops, 4)
    if hbm_bytes:
        achieved_gbps = hbm_bytes / dt / 1e9
        roof = {
            "device": device_kind,
            "epoch_time_s": round(dt, 4),
            "flops_per_epoch": flops,
            "hbm_bytes_per_epoch": hbm_bytes,
            "achieved_hbm_gbps": round(achieved_gbps, 1),
            "peak_tflops_bf16": peak_tflops,
            "peak_hbm_gbps": peak_gbps,
            "mean_func_evals_per_step": (
                round(func_evals, 2) if func_evals else None
            ),
        }
        if flops:
            ai = flops / hbm_bytes
            roof["arithmetic_intensity"] = round(ai, 1)
            if peak_tflops and peak_gbps:
                roof["ridge_intensity"] = round(
                    peak_tflops * 1e12 / (peak_gbps * 1e9), 1
                )
                roof["hbm_util"] = round(achieved_gbps / peak_gbps, 4)
                roof["bound"] = (
                    "memory" if ai < roof["ridge_intensity"] else "compute"
                )
        out["roofline"] = roof

    print(json.dumps(out))


if __name__ == "__main__":
    main()
