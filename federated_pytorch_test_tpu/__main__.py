"""CLI for the framework: `python -m federated_pytorch_test_tpu`.

The reference has no CLI at all — experiments are run by executing one of
the five driver scripts after hand-editing its module constants (reference
src/federated_trio.py:17-34; SURVEY.md §5 config system). Here the five
scripts are presets and every constant is a flag:

    python -m federated_pytorch_test_tpu --preset fedavg
    python -m federated_pytorch_test_tpu --preset admm --nloop 2 --no-bb-update
    python -m federated_pytorch_test_tpu --list-presets

Rounds run FUSED by default — each partition group's full averaging
round (every epoch + consensus exchange) is one jitted dispatch
(engine/steps.py build_round_fn); `--no-fuse-rounds` restores the
per-epoch dispatch path (bit-identical trajectory, more dispatch
latency).

Chaos runs (fault/, docs/FAULT.md) ride the same config surface:
`--fault-plan "seed=1,dropout=0.3,crash=0:1:2"` (or a FaultPlan JSON
path) injects replayable dropout/straggler/crash faults, and
`--resume auto --save-model` makes a crashed run recover from the latest
readable checkpoint on restart. An injected crash exits non-zero with
the InjectedCrash message; rerunning the identical command resumes.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from federated_pytorch_test_tpu.engine import (
    PRESETS,
    ExperimentConfig,
    get_preset,
    run_experiment,
)


def _add_config_flags(parser: argparse.ArgumentParser) -> None:
    """One flag per `ExperimentConfig` field (booleans get --x/--no-x)."""
    for f in dataclasses.fields(ExperimentConfig):
        flag = "--" + f.name.replace("_", "-")
        if f.type in ("bool", bool):
            parser.add_argument(
                flag,
                dest=f.name,
                action=argparse.BooleanOptionalAction,
                default=None,
            )
        else:
            ts = str(f.type)
            typ = {"int": int, "float": float}.get(ts, str)
            if "int | None" in ts:
                typ = int  # flag absent => None; given => parsed as int
            parser.add_argument(flag, dest=f.name, type=typ, default=None)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu",
        description="TPU-native federated / consensus optimization experiments",
    )
    parser.add_argument(
        "--preset",
        default="fedavg",
        choices=sorted(PRESETS),
        help="base experiment (one of the five reference drivers)",
    )
    parser.add_argument("--list-presets", action="store_true")
    parser.add_argument(
        "--metrics-out", default=None, help="write metric series JSON here"
    )
    parser.add_argument("--quiet", action="store_true")
    _add_config_flags(parser)
    args = parser.parse_args(argv)

    if args.list_presets:
        for name, cfg in sorted(PRESETS.items()):
            print(
                f"{name:16s} model={cfg.model:9s} strategy={cfg.strategy:7s} "
                f"batch={cfg.batch} nloop={cfg.nloop} nadmm={cfg.nadmm}"
            )
        return 0

    overrides = {
        f.name: getattr(args, f.name)
        for f in dataclasses.fields(ExperimentConfig)
        if getattr(args, f.name) is not None
    }
    cfg = get_preset(args.preset, **overrides)
    print(f"# running preset={args.preset} cfg={cfg}")
    recorder = run_experiment(cfg, verbose=not args.quiet)
    if args.metrics_out:
        recorder.save(args.metrics_out)
        print(f"# metrics written to {args.metrics_out}")
    final = recorder.latest("test_accuracy")
    if final is not None:
        print("# final per-client accuracy: " + json.dumps(final))
    return 0


if __name__ == "__main__":
    sys.exit(main())
