"""CLI for the framework: `python -m federated_pytorch_test_tpu`.

The reference has no CLI at all — experiments are run by executing one of
the five driver scripts after hand-editing its module constants (reference
src/federated_trio.py:17-34; SURVEY.md §5 config system). Here the five
scripts are presets and every constant is a flag:

    python -m federated_pytorch_test_tpu --preset fedavg
    python -m federated_pytorch_test_tpu --preset admm --nloop 2 --no-bb-update
    python -m federated_pytorch_test_tpu --list-presets

Rounds run FUSED by default — each partition group's full averaging
round (every epoch + consensus exchange + the `check_results` eval
sweeps) is one jitted dispatch (engine/steps.py build_round_fn);
`--no-fuse-rounds` restores the per-epoch dispatch path and
`--no-fold-eval` moves the evals back outside the round program (both
bit-identical trajectories, more dispatch latency). Evals outside a
fused program are enqueued asynchronously and harvested at round
boundaries (`--no-async-eval` restores the blocking per-eval fetch).
`--compile-cache DIR` persists XLA executables so warm reruns skip
backend compilation.

Roofline levers (docs/PERF.md): `--linesearch-probes P` batches the
L-BFGS Armijo search's sequential halving ladder into widened P-rung
probe fans (P=1, the default, is bitwise the sequential search; P>1
selects the identical step sizes while amortizing the per-probe
parameter streams), and `--exchange-dtype bfloat16` ships every
consensus uplink as bf16 — exactly half the ledger bytes; robust
combiners and quarantine operate on the decoded f32 views.

The communication codec zoo + layer-group scheduler (exchange/,
docs/PERF.md §Codec zoo) moves the bytes frontier further:
`--exchange-codec topk --topk-fraction f` ships each client's top
`ceil(f*n)` magnitudes as index+value pairs (~20% of the f32 uplink at
f=0.1), `--exchange-codec quant --quant-bits 8|4` ships one scale plus
8/4 bits per value (~25% / ~12.5%), `--error-feedback` carries each
(client, group)'s compression residual into its next encode, and
`--group-schedule adaptive` picks WHICH partition group each round
exchanges from the streamed post-round drift signal —
`--group-skip-frac F` lets drift-quiet slots send NOTHING at all. The
ledger records every codec's exact bytes; `report` labels each run's
frontier point with its codec+scheduler config and sums
`bytes_saved_by_skipping`. All of these are trajectory-changing knobs
and live in the metrics-stream tag.

Chaos runs (fault/, docs/FAULT.md) ride the same config surface:
`--fault-plan "seed=1,dropout=0.3,crash=0:1:2,corrupt=1:scale:10"` (or
a FaultPlan JSON path, parsed strictly) injects replayable dropout/
straggler/crash/update-corruption faults, and `--resume auto
--save-model` makes a crashed run recover from the latest readable
checkpoint on restart. An injected crash exits non-zero with the
InjectedCrash message; rerunning the identical command resumes.
Byzantine defense: `--robust-agg median|trimmed|clip` (+ `--robust-f`)
makes the consensus exchange tolerate corrupted updates instead of
averaging them in, and `--quarantine-z Z` auto-quarantines update-norm
outliers for the rest of their round; the end-of-run summary gains a
`# faults injected:` scoreboard and a quarantine-waste comm line.
System heterogeneity: a plan's `slow=<k-or-p>[:factor]` axis models
clients with slower compute, and `--round-deadline S` makes rounds
deadline-based — each client runs the ragged inner-step budget it can
afford (inside the same one-dispatch round program), deadline misses
contribute partial updates instead of stalling the cohort, and
tail-latency percentiles land in the `client_time` series
(docs/FAULT.md §Heterogeneity). The CLOSED LOOP: `--round-deadline
auto[:pXX]` tracks the online client_time percentile sketch instead of
a constant (decisions streamed as the `deadline` series, replayed from
the stream on resume), a plan's `churn=<p>[:mean_absence]` axis churns
virtual clients out of the sampler's available pool per outer loop,
and `--cohort-weighting telemetry` steers sampling by each virtual
client's observed speed / deadline-miss / dropout / quarantine history
accumulated in the client store.

Cross-device scale (clients/, docs/SCALE.md): `--virtual-clients N
--cohort C` models a population of N virtual clients in a host-side
chunked store; each outer loop a seeded replayable cohort of C clients
(`--cohort-seed`, `--cohort-weighting uniform|samples|identity`) is
gathered into the same one-dispatch round program and scattered back,
with `--data-shards S` mapping the population onto S disjoint data
shards. Fault schedules stay keyed by virtual-client id, checkpoints
write only dirty store chunks (O(C) per loop), and crash recovery
replays the identical cohort sequence. The NEXT loop's cohort gather is
prefetched on a background thread while the current loop trains
(`--no-prefetch` is the bitwise-identical fallback), and
`--store-resident-chunks R` LRU-bounds the store chunks held in RAM —
clean chunks evict and memory-map back in on demand, dirty ones spill
to the checkpoint dir first — so host RSS is O(R + cohort), flat in N
(docs/SCALE.md §Spilled store: the million-virtual-client shape).

Observability (obs/, docs/OBSERVABILITY.md) rides it too:
`--metrics-stream run.jsonl` streams every metric record to a crash-safe
JSONL file that `--resume auto` continues seamlessly, `--trace-out
run.trace.json` writes the host loop nest as Chrome trace-event JSON
(open in https://ui.perfetto.dev), `--diagnostics-every N` samples the
cross-client `group_distance` diagnostic, the in-run health engine
(`--no-health-monitor` to disable, `--health-window N` for the anomaly
window) distills every round into a `health` record plus `health:*`
trace instants, and every run ends with a summary table: per-series
record counts, exact communicated bytes vs the full-model-exchange and
ship-the-data baselines, dispatch and recompile counts, and the health
verdict.

Self-monitoring ops (obs/flight.py, obs/memory.py — the flight-recorder
PR): with `--metrics-stream` set, a bounded flight ring mirrors the last
`--flight-window` rounds of the stream and dumps a self-contained
`incident-<nloop>-<round>.json` bundle into `<stream>.incidents/`
whenever the health engine fires (loss explosion/plateau, rollback,
quarantine burst, deadline-miss spike) or the run dies mid-flight
(`--no-flight-recorder` to disable); every round records host RSS +
per-device allocator stats as the process-local `memory` series
(`--no-memory-telemetry`); and `--profile-on-anomaly DIR` runs the round
after a health alert under a jax.profiler trace window, bounded by
`--profile-budget N` captures — profiling that costs nothing until
something is wrong.

Cross-run analysis and live ops are their own verbs (obs/registry.py,
obs/console.py — pure host-side file analysis, no accelerator backend
init, so they run on any host):

    python -m federated_pytorch_test_tpu report runs/ --json report.json
    python -m federated_pytorch_test_tpu watch runs/ [--once] [--interval S]
    python -m federated_pytorch_test_tpu scrub ckpt/ [--repair]
    python -m federated_pytorch_test_tpu trend . benchmarks/ [--store F]
    python -m federated_pytorch_test_tpu debt [--script remeasure.sh]
    python -m federated_pytorch_test_tpu chaos [--budget-s S | --cases N]
                                               [--seed S] [--repro FILE]

`report` ingests a directory of `--metrics-stream` files (validating
each header like resume does, refusing foreign streams), aligns the
runs on round index, and emits comparison tables plus the
convergence-vs-bytes frontier (accuracy vs cumulative `comm_bytes` per
run) as JSON and markdown — a codec/combiner/deadline sweep becomes one
command; `--incidents` adds the cross-run incident-bundle table.
`watch` tails the same streams through the same validated ingestion and
renders a refreshing terminal dashboard — sparklines, health, comm,
fleet counters, memory, incidents. `scrub` (fault/scrub.py) walks a
store/checkpoint directory, verifies every spilled-chunk checksum
against its manifest, and reports (exit 1, naming each corrupt file) or
`--repair`s via the store's ladder: adopt an intact prior chunk version,
else drop the chunk so its rows re-initialize pristine. The storage
fault axis itself rides the plan string — `storage=<p>:<bitrot|torn|
ioerror|enospc>[:strength]` chaos-injects the store/checkpoint/stream
byte paths, survived by checksum-verified reads with bounded retry
(docs/FAULT.md §Storage-integrity axis). `trend` (obs/benchdb.py)
ingests BENCH_*.json wrappers and benchmark artifacts into an
append-only trend store keyed by (metric, provenance class) and runs
the noise-aware regression sentinel — CPU-twin baselines never judge
TPU numbers; `debt` (obs/debt.py) lists DEBT.json's open
re-measurement entries and emits the runnable script that pays them.
`chaos` (fault/chaos.py) soaks the engine under a seeded fuzzer that
composes random fault-plan axes with random config knobs, checks every
drawn case against the crash+resume invariant oracle, shrinks any
violating plan to a 1-minimal repro bundle (exit 2), and replays
bundles with `--repro FILE` — it forces the host-CPU backend itself,
so the soak runs on any machine.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def _add_config_flags(parser: argparse.ArgumentParser) -> None:
    """One flag per `ExperimentConfig` field (booleans get --x/--no-x)."""
    from federated_pytorch_test_tpu.engine import ExperimentConfig

    for f in dataclasses.fields(ExperimentConfig):
        flag = "--" + f.name.replace("_", "-")
        if f.type in ("bool", bool):
            parser.add_argument(
                flag,
                dest=f.name,
                action=argparse.BooleanOptionalAction,
                default=None,
            )
        else:
            ts = str(f.type)
            typ = {"int": int, "float": float}.get(ts, str)
            if "int | None" in ts:
                typ = int  # flag absent => None; given => parsed as int
            elif "float | None" in ts:
                typ = float  # same contract (e.g. --quarantine-z)
            parser.add_argument(flag, dest=f.name, type=typ, default=None)


def _print_summary(recorder, cfg) -> None:
    """End-of-run observability summary (one `#`-prefixed line each)."""
    counts = ", ".join(
        f"{name}={len(recs)}" for name, recs in sorted(recorder.series.items())
    )
    print(f"# series: {counts}")
    comm = recorder.latest("comm_summary")
    if comm and comm.get("rounds"):
        line = (
            f"# comm: {comm['bytes_total']:,} B uplink over "
            f"{comm['rounds']} consensus rounds "
            f"({comm['bytes_per_round_mean']:,.0f} B/round); "
            f"full-model exchange would be {comm['bytes_full_exchange']:,} B"
        )
        if comm.get("savings_vs_full") is not None:
            # None when total uplink is zero (every round fully dropped)
            line += f" (savings x{comm['savings_vs_full']})"
        if comm.get("data_floor_bytes"):
            line += (
                f"; ship-the-data floor {comm['data_floor_bytes']:,} B "
                f"(uplink/floor {comm['vs_data_floor']})"
            )
        print(line)
    part = recorder.latest("cohort_participation")
    if part is not None:
        print(
            f"# cohort: {part['cohort']} of {part['n_virtual']} virtual "
            f"clients per loop over {part['loops']} loops; "
            f"{part['sampled_ever']} ever sampled "
            f"(per-client min={part['min']} max={part['max']} "
            f"mean={part['mean']})"
        )
    st = recorder.latest("store_summary")
    if st is not None:
        # the spilled-store digest (clients/store.py residency): how
        # bounded the host side actually stayed
        budget = st.get("resident_budget")
        line = (
            f"# store: {st['chunks_materialized']} resident chunk(s)"
            + (f" (budget {budget})" if budget is not None else "")
            + f", {st.get('on_disk_chunks', 0)} on disk"
        )
        if st.get("evictions"):
            line += (
                f"; {st['evictions']} eviction(s), "
                f"{st.get('spill_bytes', 0):,} B spilled"
            )
        if st.get("spill_reads"):
            line += f", {st['spill_reads']} spill read(s)"
        print(line)
    inj = recorder.latest("injected_faults")
    if inj is not None:
        # the chaos scoreboard: scheduled kinds come from the pure plan
        # (fault/injector.py injected_summary — a resumed run prints the
        # same totals); the quarantine count is a detection and survives
        # resume only via a replayed --metrics-stream
        order = (
            "drops", "stragglers", "crashes", "corruptions",
            "deadline_misses", "capped_stalls", "churned", "quarantines",
            "storage_faults",
        )
        print(
            "# faults injected: "
            + ", ".join(f"{k}={inj[k]}" for k in order if k in inj)
        )
    if comm and comm.get("bytes_quarantined_wasted"):
        print(
            f"# quarantine waste: {comm['bytes_quarantined_wasted']:,} B "
            "uplink transmitted by quarantined clients and discarded"
        )
    disp: dict = {}
    for r in recorder.series.get("dispatch_count", []):
        for k, v in r["value"].items():
            disp[k] = disp.get(k, 0) + v
    recompiles = sum(
        r["value"] for r in recorder.series.get("recompile_count", [])
    )
    if disp:
        per_cat = ", ".join(
            f"{k}={v}" for k, v in sorted(disp.items()) if k != "total"
        )
        print(
            f"# dispatches: {disp.get('total', 0)} ({per_cat}); "
            f"compiled programs: {recompiles}"
        )
    health = recorder.series.get("health", [])
    if health:
        anomalies = sum(len(r["value"].get("anomalies", ())) for r in health)
        last = health[-1]["value"]
        line = (
            f"# health: {len(health)} rounds monitored, "
            f"{anomalies} anomalies"
        )
        tl = last.get("train_loss")
        if tl:
            line += f"; loss p50={tl['p50']:g} p95={tl['p95']:g}"
        ct = last.get("client_time")
        if ct:
            # the online tail estimate item 4's learned deadlines consume
            line += f"; client_time p95~{ct['p50']:g}s"
        print(line)
    mem = recorder.latest("memory")
    if mem is not None and mem.get("rss_bytes"):
        line = f"# memory: rss {mem['rss_bytes'] / 2**20:,.0f} MiB"
        if mem.get("peak_rss_bytes"):
            line += f" (peak {mem['peak_rss_bytes'] / 2**20:,.0f} MiB)"
        devs = [
            f"dev{i}={d['bytes_in_use'] / 2**20:,.0f} MiB"
            for i, d in enumerate(mem.get("devices") or [])
            if d and d.get("bytes_in_use") is not None
        ]
        if devs:
            line += "; " + ", ".join(devs)
        print(line)
    incidents = recorder.series.get("incident", [])
    if incidents:
        kinds = sorted(
            {k for r in incidents for k in r["value"].get("kinds", ())}
        )
        bundles = ", ".join(r["value"]["bundle"] for r in incidents)
        print(
            f"# incidents: {len(incidents)} bundle(s) "
            f"[{','.join(kinds)}] -> {bundles} "
            f"(under {cfg.metrics_stream}.incidents/)"
        )
    captures = recorder.series.get("profile_capture", [])
    if captures:
        print(
            f"# profiler: {len(captures)} anomaly-triggered capture(s) "
            f"under {cfg.profile_on_anomaly}"
        )
    roof = recorder.latest("roofline")
    if roof is not None:
        line = f"# roofline: wall {roof['wall_s']}s/round"
        if "client_fold" in roof:
            line += f", fold {roof['client_fold']}"
        if "effective_gemm_m" in roof:
            line += f", GEMM M {roof['effective_gemm_m']}"
        if "arithmetic_intensity" in roof:
            line += f", intensity {roof['arithmetic_intensity']}"
        if "mfu" in roof:
            line += f", MFU {roof['mfu']}"
        if "achieved_hbm_frac" in roof:
            line += f", HBM {roof['achieved_hbm_frac']} of peak"
        if "bound" in roof:
            line += f" ({roof['bound']}-bound)"
        print(line)
    if cfg.metrics_stream:
        print(f"# metric stream: {cfg.metrics_stream}")
    if cfg.trace_out:
        print(
            f"# trace: {cfg.trace_out} (open in https://ui.perfetto.dev "
            "or chrome://tracing)"
        )
    if recorder.first_nonfinite is not None:
        print(f"# FIRST NON-FINITE at {recorder.first_nonfinite}")


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        # the cross-run registry verb (obs/registry.py): dispatched
        # before the engine import chain so `report` never initializes
        # an accelerator backend — it runs on hosts whose TPU runtime
        # is absent or would block on init
        from federated_pytorch_test_tpu.obs.registry import report_main

        return report_main(argv[1:])
    if argv and argv[0] == "watch":
        # the live console verb (obs/console.py): same backend-free
        # dispatch rule as `report` — a dashboard must never block on
        # accelerator init while tailing someone else's run
        from federated_pytorch_test_tpu.obs.console import watch_main

        return watch_main(argv[1:])
    if argv and argv[0] == "scrub":
        # the storage-integrity verb (fault/scrub.py): walk a store /
        # checkpoint dir, verify every chunk checksum, report or
        # --repair — backend-free like report/watch, so a dead host's
        # store can be scrubbed from anywhere
        from federated_pytorch_test_tpu.fault.scrub import scrub_main

        return scrub_main(argv[1:])
    if argv and argv[0] == "trend":
        # the perf-trend verb (obs/benchdb.py): ingest BENCH wrappers /
        # benchmark artifacts into the append-only trend store and run
        # the provenance-class-isolated regression sentinel —
        # backend-free like report/watch/scrub (pure file analysis)
        from federated_pytorch_test_tpu.obs.benchdb import trend_main

        return trend_main(argv[1:])
    if argv and argv[0] == "debt":
        # the re-measurement debt verb (obs/debt.py): list DEBT.json's
        # open entries and emit the ready-to-run payment script for the
        # first session with the owed backend — backend-free too
        from federated_pytorch_test_tpu.obs.debt import debt_main

        return debt_main(argv[1:])
    if argv and argv[0] == "chaos":
        # the chaos-harness verb (fault/chaos.py): seeded fuzzer over
        # composed fault plans x knob lattice, invariant oracle with
        # crash+resume twins, failing-plan shrinker, repro replay —
        # dispatched engine-import-free like report/scrub; it pins the
        # backend to host CPU itself before touching the Trainer
        from federated_pytorch_test_tpu.fault.chaos import chaos_main

        return chaos_main(argv[1:])

    from federated_pytorch_test_tpu.engine import (
        PRESETS,
        ExperimentConfig,
        get_preset,
        run_experiment,
    )

    parser = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu",
        description="TPU-native federated / consensus optimization experiments",
    )
    parser.add_argument(
        "--preset",
        default="fedavg",
        choices=sorted(PRESETS),
        help="base experiment (one of the five reference drivers)",
    )
    parser.add_argument("--list-presets", action="store_true")
    parser.add_argument(
        "--metrics-out",
        default=None,
        help="write the final metrics JSON here (atomic write; envelope "
        '{"series": ..., "first_nonfinite": ...}). For an incremental '
        "stream that survives crashes, use --metrics-stream instead.",
    )
    parser.add_argument("--quiet", action="store_true")
    _add_config_flags(parser)
    args = parser.parse_args(argv)

    if args.list_presets:
        for name, cfg in sorted(PRESETS.items()):
            print(
                f"{name:16s} model={cfg.model:9s} strategy={cfg.strategy:7s} "
                f"batch={cfg.batch} nloop={cfg.nloop} nadmm={cfg.nadmm}"
            )
        return 0

    overrides = {
        f.name: getattr(args, f.name)
        for f in dataclasses.fields(ExperimentConfig)
        if getattr(args, f.name) is not None
    }
    cfg = get_preset(args.preset, **overrides)
    print(f"# running preset={args.preset} cfg={cfg}")
    recorder = run_experiment(cfg, verbose=not args.quiet)
    if args.metrics_out:
        recorder.save(args.metrics_out)
        print(f"# metrics written to {args.metrics_out}")
    _print_summary(recorder, cfg)
    final = recorder.latest("test_accuracy")
    if final is not None:
        print("# final per-client accuracy: " + json.dumps(final))
    return 0


if __name__ == "__main__":
    sys.exit(main())
