"""Static partition specs over the raveled parameter vector.

The reference trains one "layer" (weight+bias pair, reference
src/federated_trio.py:120-126) or one ResNet block-range (reference
src/federated_trio_resnet.py:189-203, `upidx` table :174-178) per outer
round, and only that group's parameters are averaged. Here a `Partition`
captures that grouping statically: each group is a tuple of `(start, size)`
segments into the flat vector. `extract`/`insert` are pure functions with
shapes fixed at trace time, so each group's training round compiles to a
fixed-size program and the consensus collectives move exactly
`group_size(gid)` floats across the mesh — the bandwidth-saving contract of
reference README.md:2.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.partition.flat import leaf_offsets, total_size

PyTree = Any


@dataclasses.dataclass(frozen=True)
class Segment:
    """A contiguous [start, start+size) span of the flat parameter vector."""

    start: int
    size: int


@dataclasses.dataclass(frozen=True)
class Partition:
    """A static decomposition of the flat parameter vector into groups.

    Attributes:
      groups: per-group tuple of `Segment`s (merged / contiguous where
        possible). Group ids follow the model's layer numbering, matching
        the reference's `train_order_layer_ids` universe
        (reference src/simple_models.py:38-39,78-79,130-131).
      total: length of the full flat vector.
      linear_group_ids: groups carrying L1/L2 regularization (the
        reference's `linear_layer_ids`, src/simple_models.py:29-30).
      train_order: default group visit order per outer loop.
    """

    groups: Tuple[Tuple[Segment, ...], ...]
    total: int
    linear_group_ids: Tuple[int, ...] = ()
    train_order: Tuple[int, ...] = ()

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    def group_size(self, gid: int) -> int:
        return sum(s.size for s in self.groups[gid])

    def extract(self, flat: jnp.ndarray, gid: int) -> jnp.ndarray:
        """Pure function: flat vector -> the group's coordinates (static shape)."""
        segs = self.groups[gid]
        parts = [jax.lax.slice(flat, (s.start,), (s.start + s.size,)) for s in segs]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def insert(self, flat: jnp.ndarray, gid: int, vec: jnp.ndarray) -> jnp.ndarray:
        """Pure function: write the group's coordinates back into the flat vector."""
        segs = self.groups[gid]
        off = 0
        for s in segs:
            flat = jax.lax.dynamic_update_slice(
                flat, jax.lax.slice(vec, (off,), (off + s.size,)), (s.start,)
            )
            off += s.size
        return flat

    def mask(self, gid: int) -> jnp.ndarray:
        """Boolean mask over the flat vector for one group (diagnostics)."""
        m = jnp.zeros((self.total,), dtype=bool)
        for s in self.groups[gid]:
            m = m.at[s.start : s.start + s.size].set(True)
        return m

    def validate(self) -> None:
        """Check groups tile the flat vector exactly once (no overlap, no gap)."""
        spans = sorted(
            (s.start, s.size) for segs in self.groups for s in segs
        )
        cursor = 0
        for start, size in spans:
            if start != cursor:
                raise ValueError(
                    f"partition groups do not tile flat vector: gap/overlap at {start} (expected {cursor})"
                )
            cursor += size
        if cursor != self.total:
            raise ValueError(f"partition covers {cursor} of {self.total} parameters")


def _merge_segments(spans: Sequence[Tuple[int, int]]) -> Tuple[Segment, ...]:
    """Merge sorted (start, size) spans into maximal contiguous segments."""
    merged = []
    for start, size in sorted(spans):
        if merged and merged[-1][0] + merged[-1][1] == start:
            merged[-1][1] += size
        else:
            merged.append([start, size])
    return tuple(Segment(s, n) for s, n in merged)


def build_partition(
    template: PyTree,
    group_paths: Sequence[Sequence[Tuple[str, ...]]],
    linear_group_ids: Sequence[int] = (),
    train_order: Sequence[int] = (),
) -> Partition:
    """Build a `Partition` from a params template and per-group path prefixes.

    `group_paths[g]` is a list of path prefixes (tuples of string keys);
    every leaf whose path starts with one of them belongs to group `g`.
    Every leaf must belong to exactly one group.
    """
    offsets = leaf_offsets(template)
    groups = []
    claimed: dict[Tuple[str, ...], int] = {}
    for g, prefixes in enumerate(group_paths):
        spans = []
        for path, start, size in offsets:
            if any(path[: len(p)] == tuple(p) for p in prefixes):
                if path in claimed:
                    raise ValueError(
                        f"leaf {path} claimed by groups {claimed[path]} and {g}"
                    )
                claimed[path] = g
                spans.append((start, size))
        if not spans:
            raise ValueError(f"group {g} with prefixes {prefixes} matched no leaves")
        groups.append(_merge_segments(spans))
    unclaimed = [path for path, _, _ in offsets if path not in claimed]
    if unclaimed:
        raise ValueError(f"leaves not claimed by any group: {unclaimed}")
    part = Partition(
        groups=tuple(groups),
        total=total_size(template),
        linear_group_ids=tuple(linear_group_ids),
        train_order=tuple(train_order) if train_order else tuple(range(len(groups))),
    )
    part.validate()
    return part
