"""Parameter-partition machinery.

TPU-native replacement for the reference's freeze/flat-vector machinery
(reference src/federated_trio.py:120-196 `unfreeze_one_layer`,
`get_trainable_values`, `put_trainable_values`; block-range variant
src/federated_trio_resnet.py:189-243). Instead of mutating `requires_grad`
flags on a stateful module, a `Partition` is a static description of how the
raveled parameter vector decomposes into layer/block groups; extracting and
inserting a group's flat vector are pure, jit-compatible functions with
static shapes, so XLA sees fixed-size slices and the consensus collectives
only ever move the active group's coordinates.
"""

from federated_pytorch_test_tpu.partition.flat import flatten_params, unflatten_like
from federated_pytorch_test_tpu.partition.spec import Partition, Segment, build_partition

__all__ = [
    "Partition",
    "Segment",
    "build_partition",
    "flatten_params",
    "unflatten_like",
]
