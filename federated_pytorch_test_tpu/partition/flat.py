"""Pytree <-> flat-vector codec.

Equivalent capability to the reference's `get_trainable_values` /
`put_trainable_values` (reference src/federated_trio.py:133-161) and the
optimizer-internal `_gather_flat_grad` / `_copy_params_out/in`
(reference src/lbfgsnew.py:81-121), built on `jax.flatten_util.ravel_pytree`
so the flat view is a pure function of the pytree rather than an in-place
copy loop. All downstream consensus math operates on these flat vectors.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

PyTree = Any


def flatten_params(params: PyTree) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], PyTree]]:
    """Ravel a parameter pytree to a 1-D vector.

    Returns `(flat, unravel)` where `unravel(flat) == params`. The leaf
    order is jax's canonical tree-flatten order (sorted dict keys); all
    partition offsets in this package are computed in the same order, so a
    `Partition` built from a template is valid for any pytree with the same
    structure.
    """
    return ravel_pytree(params)


def unflatten_like(template: PyTree) -> Callable[[jnp.ndarray], PyTree]:
    """Return an unravel function for pytrees shaped like `template`."""
    _, unravel = ravel_pytree(template)
    return unravel


def leaf_offsets(template: PyTree):
    """Offsets of each leaf inside the raveled vector.

    Returns a list of `(path, start, size)` tuples in ravel order, where
    `path` is a tuple of string keys (dict keys / attribute names). This is
    the ground truth used by `build_partition` to map a model's named
    layers/blocks to contiguous flat segments.
    """
    leaves = jax.tree_util.tree_flatten_with_path(template)[0]
    out = []
    start = 0
    for path, leaf in leaves:
        size = int(jnp.size(leaf))
        out.append((_path_keys(path), start, size))
        start += size
    return out


def total_size(template: PyTree) -> int:
    return sum(int(jnp.size(l)) for l in jax.tree_util.tree_leaves(template))


def _path_keys(path) -> Tuple[str, ...]:
    keys = []
    for entry in path:
        if hasattr(entry, "key"):
            keys.append(str(entry.key))
        elif hasattr(entry, "name"):
            keys.append(str(entry.name))
        elif hasattr(entry, "idx"):
            keys.append(str(entry.idx))
        else:  # pragma: no cover - future jax key types
            keys.append(str(entry))
    return tuple(keys)
