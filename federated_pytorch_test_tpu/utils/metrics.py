"""Training observability: the reference's five metric series, structured.

The reference's observability is `print()` lines with grep-able formats
plus documented shell pipelines to extract series from logs (reference
src/consensus_admm_trio.py:392,517,548-552). The capability contract
(SURVEY.md §5) is five series: per-client per-batch loss, per-round primal
and dual residuals, mean rho, and per-client test accuracy. Here every
observation lands in a structured in-memory store (JSON-serializable) AND
is printed in a format close to the reference's, so the same shell recipes
still work.

The store is extended by the `obs/` layer (docs/OBSERVABILITY.md):

* **sinks** — every `log()` record is forwarded to pluggable sinks
  (`obs/sinks.py JsonlSink` is the crash-safe streaming one); `flush()` /
  `commit_loop()` are the trainer's per-round and per-checkpoint
  durability barriers, and `add_sink(..., replay=...)` seeds the
  in-memory series from a resumed stream so a crash+resume run's series
  is continuous;
* **deferred records** — a record's value may be a `Deferred` (a thunk,
  typically closing over a `jax.Array` whose device->host fetch is the
  expensive part): the record takes its place in the series immediately,
  but the value is materialized lazily — harvested in batch at the
  trainer's round boundaries (`flush`) and ALWAYS before a
  `commit_loop()` marker reaches the sinks, so the crash-safety contract
  (everything before an `nloop_complete` marker is durable and complete)
  holds with async evals exactly as with sync ones. While a deferred
  record is pending, subsequent streamed records queue behind it, so the
  sink stream stays record-for-record in logging order;
* **tracer** — `phase()` is the ONE enter/exit context manager shared by
  the wall-clock `step_time` records and the Chrome-trace span recorder
  (`obs/trace.py`), so the timing series and the exported trace can never
  disagree about what was measured.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class Deferred:
    """A lazily-materialized metric value.

    Wraps a zero-arg thunk whose call is postponed until the record is
    harvested (round boundary / commit / serialization). The thunk runs
    at most once; `resolve()` returns the cached value afterwards. The
    intended payload is a device array already ENQUEUED on the
    accelerator — the dispatch happened at log time, only the blocking
    device->host fetch is deferred — so rollback/late mutation of the
    live training state cannot change what a deferred record reports.
    """

    __slots__ = ("_fn", "_value", "_resolved")

    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn
        self._value = None
        self._resolved = False

    def resolve(self) -> Any:
        if not self._resolved:
            self._value = self._fn()
            self._fn = None  # drop the closure (and its device arrays)
            self._resolved = True
        return self._value


@dataclasses.dataclass
class MetricsRecorder:
    """Append-only metric series, keyed by name.

    Each record is a dict with a `step` context (nloop/group/nadmm/...)
    plus the value. `print_fn` mirrors each record to stdout in a
    reference-style grep-able line.
    """

    series: Dict[str, List[dict]] = dataclasses.field(default_factory=dict)
    verbose: bool = True
    # cursor of the FIRST non-finite loss/residual observed, or None while
    # the run is healthy (see _flag_nonfinite). Frozen once set: the first
    # poisoned round is the diagnostic one, everything after is fallout.
    first_nonfinite: Optional[dict] = None
    # streaming sinks (obs/sinks.py protocol: record/flush/commit/close)
    # and the optional trace-span recorder (obs/trace.py TraceRecorder)
    sinks: List[Any] = dataclasses.field(default_factory=list)
    # synchronous observers (obs/health.py HealthEngine protocol:
    # observe(name, rec)): called at LOG time for every STREAMED record —
    # the exact record set (and order) the sinks persist, which is what
    # lets a resumed observer rebuild identical state from a stream
    # replay. Unlike sinks, observers see a deferred record BEFORE its
    # value is materialized (they must ignore Deferred-valued series).
    observers: List[Any] = dataclasses.field(default_factory=list)
    tracer: Optional[Any] = None
    _t0: float = dataclasses.field(default_factory=time.perf_counter)
    # streamed records not yet forwarded to the sinks: a `Deferred` value
    # holds its slot here until harvested, and every later streamed
    # record queues BEHIND it so the sink stream preserves logging order
    _pending: List[Tuple[str, dict]] = dataclasses.field(default_factory=list)

    def log(self, name: str, value: Any, *, stream: bool = True, **context) -> None:
        """Append one record; `stream=False` keeps it OUT of the sinks —
        for series that are facts about THIS PROCESS rather than the run's
        trajectory (`recompile_count`: a resumed process recompiles
        programs the crashed one had warm, so streaming it would break the
        crash/resume stream-continuity contract).

        `value` may be a `Deferred`: the record enters the series now and
        is materialized + forwarded to the sinks at the next harvest
        (`flush`/`commit_loop`/serialization)."""
        rec = {"t": time.perf_counter() - self._t0, "value": value, **context}
        self.series.setdefault(name, []).append(rec)
        if stream:
            for ob in self.observers:
                ob.observe(name, rec)
            if self._pending or isinstance(value, Deferred):
                self._pending.append((name, rec))
            else:
                for s in self.sinks:
                    s.record(name, rec)

    def _harvest(self) -> None:
        """Materialize every pending deferred value and forward the queued
        records to the sinks, in logging order."""
        pending, self._pending = self._pending, []
        for name, rec in pending:
            if isinstance(rec["value"], Deferred):
                rec["value"] = rec["value"].resolve()
            for s in self.sinks:
                s.record(name, rec)

    def _materialize(self) -> None:
        """Resolve every deferred value in the store IN PLACE (no sink
        forwarding — pending records keep their queue slots and reach the
        sinks, already resolved, at the next harvest)."""
        for recs in self.series.values():
            for rec in recs:
                if isinstance(rec["value"], Deferred):
                    rec["value"] = rec["value"].resolve()

    def discard_pending(self, name: str) -> None:
        """Drop the not-yet-harvested records of one series — from both
        the sink queue and the in-memory store. The trainer's rollback
        path uses this: a poisoned round is discarded wholesale, and its
        enqueued (deferred) evals go with it — they never reach the
        stream, in ANY eval mode (docs/FAULT.md §Rollback mode)."""
        dropped = [rec for n, rec in self._pending if n == name]
        self._pending = [(n, r) for n, r in self._pending if n != name]
        if dropped and name in self.series:
            drop_ids = {id(r) for r in dropped}
            self.series[name] = [
                r for r in self.series[name] if id(r) not in drop_ids
            ]
            if not self.series[name]:
                del self.series[name]

    # ------------------------------------------------------ sinks & tracing

    def add_sink(self, sink, replay=()) -> None:
        """Attach a sink, optionally seeding the store from its replayed
        records (a resumed JSONL stream): replayed records enter `series`
        directly — NOT re-forwarded to the sink, which already holds them
        — and a replayed `nonfinite_flag` restores the poisoned cursor."""
        for name, rec in replay:
            self.series.setdefault(name, []).append(rec)
            if name == "nonfinite_flag" and self.first_nonfinite is None:
                self.first_nonfinite = dict(rec["value"])
        self.sinks.append(sink)

    def flush(self) -> None:
        """Per-round durability: harvest pending deferred records, then
        push buffered sink writes to the OS."""
        self._harvest()
        for s in self.sinks:
            s.flush()

    def commit_loop(self, nloop: int) -> None:
        """Checkpoint-boundary durability: marker + fsync in every sink.
        Pending deferred records are ALWAYS resolved and written first —
        the marker's contract (everything before it is durable and
        complete) must hold for async evals too, or a crash+resume stream
        would diverge from an uninterrupted one. The JSONL resume path
        truncates to these markers (obs/sinks.py)."""
        self._harvest()
        for s in self.sinks:
            s.commit(nloop)

    def close(self) -> None:
        self._harvest()
        for s in self.sinks:
            s.close()

    @contextlib.contextmanager
    def phase(self, phase: str, *, record: bool = True, **context):
        """Time one phase: a tracer span plus (optionally) a `step_time`
        record — the shared enter/exit point of the timing series and the
        Chrome trace (obs/trace.py). `record=False` emits the span only,
        keeping the `step_time` series exactly its pre-obs phase set
        (epoch / consensus / fused_round / straggler_wait)."""
        t0 = time.perf_counter()
        cm = (
            self.tracer.span(phase, **context)
            if self.tracer is not None
            else contextlib.nullcontext()
        )
        with cm:
            yield
        if record:
            self.step_time(phase, time.perf_counter() - t0, **context)

    def _flag_nonfinite(self, name: str, values, context: dict) -> None:
        """Flag the FIRST NaN/Inf observation with its loop cursor.

        The reference lets a poisoned loss print as `nan` and scroll away
        (its only guards live inside the optimizer, src/lbfgsnew.py:542);
        here the first non-finite loss/residual pins the exact
        (loop, group, round) cursor in `first_nonfinite` and a
        `nonfinite_flag` series record, instead of propagating silently
        through the remaining rounds.
        """
        if self.first_nonfinite is not None:
            return
        if any(not math.isfinite(v) for v in values):
            self.first_nonfinite = {"series": name, **context}
            self.log("nonfinite_flag", {"series": name, **context})
            if self.verbose:
                ctx = " ".join(f"{k}={v}" for k, v in context.items())
                print(f"NONFINITE first non-finite {name} at {ctx}")

    def batch_losses(self, losses, *, nloop, group, nadmm, epoch, minibatch) -> None:
        """Per-client training losses for one lockstep minibatch.

        Reference line: `layer=%d %d minibatch=%d epoch=%d losses %e,%e,%e`
        (src/federated_trio.py:352).
        """
        vals = [float(v) for v in losses]
        ctx = dict(
            nloop=nloop, group=group, nadmm=nadmm, epoch=epoch,
            minibatch=minibatch,
        )
        self._flag_nonfinite("train_loss", vals, ctx)
        self.log("train_loss", vals, **ctx)
        if self.verbose:
            print(
                f"layer={group} {nloop} minibatch={minibatch} epoch={epoch} "
                "losses " + ",".join(f"{v:e}" for v in vals)
            )

    def residuals(
        self, primal, dual, mean_rho, *, nloop, group, nadmm, group_size
    ) -> None:
        """Consensus residuals for one averaging/ADMM round.

        Reference line: `layer=%d(%d,%f) ADMM=%d primal=%e dual=%e`
        (src/consensus_admm_trio.py:517); FedAvg prints only the dual
        (src/federated_trio.py:359).
        """
        ctx = dict(nloop=nloop, group=group, nadmm=nadmm)
        self._flag_nonfinite(
            "residuals",
            [float(v) for v in (dual, primal) if v is not None],
            ctx,
        )
        self.log("dual_residual", float(dual), **ctx)
        if primal is not None:
            self.log("primal_residual", float(primal), **ctx)
        if mean_rho is not None:
            self.log("mean_rho", float(mean_rho), **ctx)
        if self.verbose:
            p = f" primal={float(primal):e}" if primal is not None else ""
            r = f",{float(mean_rho):f}" if mean_rho is not None else ""
            print(
                f"layer={group}({group_size}{r}) ADMM={nadmm}{p} "
                f"dual={float(dual):e}"
            )

    def accuracies(
        self, accs, *, nloop, group, nadmm, epoch=None, minibatch=None
    ) -> None:
        """Per-client top-1 test accuracy (fractions in [0,1]).

        Reference: `verification_error_check` prints per-client percentages
        (src/federated_trio.py:199-223). `epoch`/`minibatch` are set on the
        per-batch cadence (`eval_every_batch`, the reference's
        check_results=True telemetry, src/no_consensus_trio.py:266-267).

        `accs` may be a `Deferred` (the trainer's async eval path): the
        record is logged now and materialized — including the verbose
        per-client print, which then appears at harvest time instead of
        inline — when the round's deferred records are harvested.
        """
        ctx = dict(nloop=nloop, group=group, nadmm=nadmm)
        if epoch is not None:
            ctx["epoch"] = epoch
        if minibatch is not None:
            ctx["minibatch"] = minibatch

        def emit(raw):
            vals = [float(a) for a in raw]
            if self.verbose:
                for k, a in enumerate(vals):
                    print(
                        f"Accuracy of client {k + 1} on the test images: "
                        f"{100.0 * a:.2f} %"
                    )
            return vals

        if isinstance(accs, Deferred):
            self.log(
                "test_accuracy", Deferred(lambda: emit(accs.resolve())), **ctx
            )
        else:
            self.log("test_accuracy", emit(accs), **ctx)

    def step_time(self, phase: str, seconds: float, **context) -> None:
        """Wall-clock duration of one phase (epoch / consensus / eval).

        The tracing series the reference's dead `start_time = time.time()`
        never produced (reference src/no_consensus_trio.py:6,175).
        """
        self.log("step_time", {"phase": phase, "seconds": seconds}, **context)
        if self.verbose:
            ctx = " ".join(f"{k}={v}" for k, v in context.items())
            print(f"step_time phase={phase} {ctx} seconds={seconds:.4f}")

    def participation(self, survivors: int, k: int, **context) -> None:
        """Surviving-client count of one masked consensus round.

        Only recorded when a fault plan is active (engine/trainer.py), so
        no-chaos runs keep their pre-fault metric series byte-identical.
        """
        self.log("participation", {"survivors": survivors, "clients": k}, **context)
        if self.verbose:
            ctx = " ".join(f"{k_}={v}" for k_, v in context.items())
            print(f"participation {survivors}/{k} {ctx}")

    def fault(self, kind: str, clients, **context) -> None:
        """A detected client fault (non-finite loss/params).

        The failure-detection series the reference lacks entirely
        (SURVEY.md §5: NaN guards exist only inside the optimizer).
        """
        ids = [int(c) for c in clients]
        self.log("fault", {"kind": kind, "clients": ids}, **context)
        if self.tracer is not None:
            self.tracer.instant(f"fault:{kind}", clients=ids, **context)
        if self.verbose:
            ctx = " ".join(f"{k}={v}" for k, v in context.items())
            print(f"FAULT kind={kind} clients={ids} {ctx}")

    def update_norms(self, norms, *, nloop, group, nadmm) -> None:
        """Per-client update norms of one consensus exchange (`[K]`).

        The auto-quarantine evidence series (consensus/robust.py
        `update_suspects`): `‖x_k − z‖` for every alive client; a
        non-finite norm (nan-burst-corrupted sender) records as `null` —
        a bare NaN token would make the JSONL stream invalid RFC-8259
        (jq and strict parsers abort mid-stream even though Python's
        json.loads tolerates it). Only recorded when `quarantine_z` is
        configured, so pre-quarantine runs keep their series byte-
        identical. Deliberately NOT fed to the first-nonfinite cursor —
        a corrupt update here is a DETECTED corruption, not a
        training-health event.
        """
        vals = [
            float(v) if math.isfinite(float(v)) else None for v in norms
        ]
        self.log("update_norm", vals, nloop=nloop, group=group, nadmm=nadmm)
        if self.verbose:
            print(
                f"update_norm nloop={nloop} group={group} nadmm={nadmm} "
                + ",".join("nonfinite" if v is None else f"{v:e}" for v in vals)
            )

    def quarantine(self, clients, *, nloop, group, nadmm) -> None:
        """Clients auto-quarantined at one consensus exchange.

        Flagged by their update-norm z-score (or a non-finite update) and
        excluded from the REST OF THE ROUND's exchanges — the suspect
        mask ANDs into the participation mask (docs/FAULT.md). Mirrors
        `fault` (trace instant + grep-able line) but is its own series:
        a quarantine is the DEFENSE acting, not a failure observed.
        """
        ids = [int(c) for c in clients]
        self.log(
            "quarantine", {"clients": ids}, nloop=nloop, group=group,
            nadmm=nadmm,
        )
        if self.tracer is not None:
            self.tracer.instant(
                "fault:quarantine", clients=ids, nloop=nloop, group=group,
                nadmm=nadmm,
            )
        if self.verbose:
            print(
                f"QUARANTINE clients={ids} nloop={nloop} group={group} "
                f"nadmm={nadmm}"
            )

    def client_times(self, pct: dict, *, nloop, group, nadmm) -> None:
        """Simulated client-time tail of one consensus round's local work.

        `pct` carries the per-client time percentiles (`p50`/`p95`/`p99`,
        seconds of SIMULATED compute: steps × step_time × speed —
        fault/plan.py's speed axis), the slowest client (`max`) and the
        round's simulated wall `round` — `min(max, deadline)` when
        deadline rounds are on, since the coordinator closes the round
        at the deadline instead of waiting out the tail. Recorded only
        for heterogeneous or deadline runs, so homogeneous streams stay
        byte-identical (engine/trainer.py `_hetero_enabled`).
        """
        vals = {k: float(v) for k, v in pct.items()}
        self.log("client_time", vals, nloop=nloop, group=group, nadmm=nadmm)
        if self.verbose:
            print(
                f"client_time nloop={nloop} group={group} nadmm={nadmm} "
                + " ".join(f"{k}={v:.3f}" for k, v in vals.items())
            )

    def step_budgets(self, budgets, *, nloop, group, nadmm) -> None:
        """Per-client inner-step budgets of one deadline round (`[K]`).

        What each client could afford before the round deadline
        (fault/injector.py `step_budgets_for_round`); a value below the
        lockstep step count is a deadline miss, zero means the client's
        report never arrived. Only recorded under `--round-deadline`.
        """
        vals = [int(b) for b in budgets]
        self.log("step_budget", vals, nloop=nloop, group=group, nadmm=nadmm)
        if self.verbose:
            print(
                f"step_budget nloop={nloop} group={group} nadmm={nadmm} "
                + ",".join(str(v) for v in vals)
            )

    def deadline_miss(self, clients, *, nloop, group, nadmm) -> None:
        """Clients whose step budget fell short of the full lockstep
        count at one exchange — they contributed a PARTIAL update (or,
        at budget zero, none at all). Mirrors `quarantine` (trace
        instant + grep-able line) but is its own series: a miss is
        graceful degradation, not a failure or a defense.
        """
        ids = [int(c) for c in clients]
        self.log(
            "deadline_miss", {"clients": ids}, nloop=nloop, group=group,
            nadmm=nadmm,
        )
        if self.tracer is not None:
            self.tracer.instant(
                "fault:deadline_miss", clients=ids, nloop=nloop, group=group,
                nadmm=nadmm,
            )
        if self.verbose:
            print(
                f"DEADLINE_MISS clients={ids} nloop={nloop} group={group} "
                f"nadmm={nadmm}"
            )

    def cohort(self, ids, *, nloop) -> None:
        """The virtual-client cohort gathered for one outer loop (`[C]`
        ascending virtual ids — clients/cohort.py).

        Slot `s` of every other per-client series of the loop
        (train_loss columns, update_norm, step_budget, fault/quarantine
        client lists) refers to virtual client `ids[s]`: this record is
        the slot→virtual-id key. Only recorded in cohort mode, so
        legacy-mode streams stay byte-identical (and the identity-
        sampling bitwise bridge compares trajectories, not this series).
        """
        vals = [int(i) for i in ids]
        self.log("cohort", {"clients": vals}, nloop=nloop)
        if self.verbose:
            ids_s = ",".join(str(v) for v in vals)
            print(f"cohort nloop={nloop} clients={ids_s}")

    def group_distance(self, dists, *, nloop, group) -> None:
        """Per-group distance-from-mean diagnostic (`[num_groups]`).

        The series `parallel/diagnostics.py group_distances` feeds when
        the trainer's `--diagnostics-every N` cadence is on — the
        reference defines the equivalent `distance_of_layers` but never
        calls it (reference src/federated_trio.py:170-186).
        """
        vals = [float(v) for v in dists]
        self.log("group_distance", vals, nloop=nloop, group=group)
        if self.verbose:
            print(
                f"group_distance nloop={nloop} group={group} "
                + ",".join(f"{v:e}" for v in vals)
            )

    def latest(self, name: str):
        if not self.series.get(name):
            return None
        rec = self.series[name][-1]
        if isinstance(rec["value"], Deferred):
            rec["value"] = rec["value"].resolve()
        return rec["value"]

    def to_json(self) -> str:
        """The full store as JSON: `{"series": ..., "first_nonfinite": ...}`.

        The envelope carries the poisoned-round cursor alongside the
        series — a bare-series dump would lose exactly the record a
        post-mortem of a `--metrics-out` file needs. Deferred values are
        materialized first (a thunk is not JSON).
        """
        self._materialize()
        return json.dumps(
            {"series": self.series, "first_nonfinite": self.first_nonfinite}
        )

    def save(self, path: str) -> None:
        """Atomic write (tmp + rename, the `utils/checkpoint.py` pattern):
        a crash mid-write replaces the file completely or not at all,
        never with torn JSON."""
        path = os.path.abspath(path)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
