"""Force jax onto XLA's host (CPU) platform with a virtual device mesh.

The ambient environment may register a real-TPU PJRT plugin ("axon") at
interpreter start and pin `JAX_PLATFORMS` to it; initializing that backend
dials a tunnel and can block indefinitely, and the plugin registration
overrides a `JAX_PLATFORMS=cpu` environment variable. Tests, benchmarks on
CPU, and the multi-chip dry run all need the same counter-dance: drop the
plugin factory, force the platform back to cpu, and (optionally) raise the
virtual host device count. This module is that dance's single home.

Must run before any jax backend is instantiated (importing jax is fine;
creating arrays / calling `jax.devices()` is not).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def set_host_device_count(n: int) -> None:
    """Ensure `XLA_FLAGS` requests at least `n` virtual host devices.

    Replaces an existing smaller `--xla_force_host_platform_device_count`
    value rather than silently keeping it.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n}")
    else:
        return
    os.environ["XLA_FLAGS"] = flags


def force_host_cpu(min_devices: int | None = None):
    """Pin jax to the cpu platform; return the jax module.

    With `min_devices`, also guarantees that many virtual host devices (or
    raises RuntimeError if a backend was already initialized with fewer).
    """
    if min_devices is not None:
        set_host_device_count(min_devices)

    import jax
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

    if min_devices is not None and jax.local_device_count() < min_devices:
        raise RuntimeError(
            f"need {min_devices} host devices, have {jax.local_device_count()} "
            f"on platform {jax.default_backend()!r}; a jax backend was "
            f"initialized before force_host_cpu could raise the count"
        )
    return jax


def compile_cache_dir() -> str:
    """The repo-level persistent XLA compile-cache directory.

    One definition for every consumer — the test conftest, the multichip
    dryrun, and the fresh-interpreter subprocesses tests spawn (CLI,
    examples, multiprocess workers) all point jax at this path (config
    key `jax_compilation_cache_dir` / env `JAX_COMPILATION_CACHE_DIR`);
    a second copy of the path would silently drift and cost every
    compile again.
    """
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".cache", "xla")
