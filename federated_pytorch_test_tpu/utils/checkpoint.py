"""Checkpoint/resume of the FULL algorithm state.

The reference saves per-client torch files `./s1.model`... holding
`{model_state_dict, epoch, optimizer_state_dict, running_loss}`
(reference src/federated_trio.py:372-390) but on resume restores only the
model weights — optimizer state is written yet never loaded, and the ADMM
y/z/rho state is not checkpointed at all (reference
src/federated_trio.py:103-112; SURVEY.md §5). Here one orbax checkpoint
holds the whole algorithm state tree AT AN OUTER-LOOP BOUNDARY: stacked
client params, BatchNorm statistics, the loop cursor, and the
per-(group, client) ADMM rho store. That IS the complete state there —
L-BFGS history and the consensus y/z duals are re-initialized fresh at
every partition round by construction (the reference builds a fresh
optimizer and zeroed duals per round, src/federated_trio.py:273-275,
src/consensus_admm_trio.py:281-288), rho is the ONE consensus quantity
that outlives a round (allocated once outside the reference's loops,
src/consensus_admm_trio.py:263, hence `Trainer._rho_store` and its slot
in the checkpoint), and epoch shuffles are a pure function of
(seed, loop indices) — so a resumed run replays the exact trajectory it
would have taken. That invariant extends to injected faults: a FaultPlan's
dropout masks and straggler stalls are pure functions of (plan seed, round
cursor) too (fault/plan.py), so a chaos run resumed after a crash replays
the same masked-aggregation trajectory the uninterrupted run takes
(docs/FAULT.md). Writes are atomic — staged under `.tmp_step_N`, then
os.replace'd — and the loader falls back past unreadable checkpoints, so
a crash can interrupt any instant of a run without wedging its resume.
"""

from __future__ import annotations

import os
import shutil
import warnings
from typing import Any

import jax
import numpy as np

from federated_pytorch_test_tpu.fault.io import retry_io

PyTree = Any


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, step: int) -> str:
    """The ONE place that knows the `directory/step_N` layout."""
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _list_steps(root: str) -> list[int]:
    # hidden ".tmp_step_N" staging dirs are invisible here by construction
    return sorted(
        int(d.split("_", 1)[1])
        for d in (os.listdir(root) if os.path.isdir(root) else [])
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    )


def save_checkpoint(
    directory: str, state: PyTree, *, step: int, storage_io=None
) -> str:
    """ATOMICALLY write `state` (a pytree of arrays) under `directory/step_N`.

    The tree is first materialized under the hidden staging path
    `directory/.tmp_step_N` — which `load_checkpoint` never considers —
    then `os.replace`d into its final name, so a crash mid-write can never
    leave a torn `step_N` for the resume path to trip on: either the
    rename happened (complete checkpoint) or it didn't (no checkpoint; the
    loader falls back to the previous one). An existing checkpoint at the
    same step is overwritten (the reference likewise clobbers
    `./sK.model`); the brief gap while the stale tree is cleared is
    likewise covered by the loader's fall-back-to-next-newest.

    `storage_io` is the optional fault/io.py StorageFaultShim: a plan's
    write-side storage faults (ioerror/enospc) fire before the staging
    write, survived by the shared bounded retry — the checkpoint writer
    is a disk-facing byte path like the store and the metric stream.

    Returns the final checkpoint path.
    """
    root = os.path.abspath(directory)
    path = checkpoint_path(directory, step)
    tmp = os.path.join(root, f".tmp_step_{step}")
    state = jax.tree.map(np.asarray, state)
    os.makedirs(root, exist_ok=True)

    def write():
        if storage_io is not None:
            storage_io.before_write(f"checkpoint step_{step}")
        if os.path.exists(tmp):  # leftover staging from a crashed writer
            shutil.rmtree(tmp)
        _checkpointer().save(tmp, state, force=True)

    retry_io(write, what=f"checkpoint write (step_{step})")
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)
    return path


def load_checkpoint(directory: str, *, step: int | None = None) -> PyTree:
    """Load the checkpoint at `step`, or the newest READABLE one if None.

    With `step=None`, unreadable/incomplete checkpoints (torn writes from
    a crash predating the atomic writer, half-deleted trees, bad metadata)
    are skipped with a warning and the next-newest is tried — a chaos run
    resumes from the latest checkpoint that actually restores. With an
    explicit `step`, failures propagate: the caller named a specific
    checkpoint and silently substituting another would be worse.

    Raises FileNotFoundError when no (readable) checkpoint exists.
    """
    root = os.path.abspath(directory)
    if step is not None:
        path = checkpoint_path(directory, step)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        return _checkpointer().restore(path)
    steps = _list_steps(root)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {root}")
    for s in reversed(steps):
        path = checkpoint_path(directory, s)
        try:
            return _checkpointer().restore(path)
        except Exception as e:  # orbax raises several types on torn trees
            warnings.warn(
                f"skipping unreadable checkpoint {path}: {type(e).__name__}: "
                f"{e}; falling back to the next-newest"
            )
    raise FileNotFoundError(
        f"no readable checkpoint under {root} (tried steps {steps})"
    )
