"""Checkpoint/resume of the FULL algorithm state.

The reference saves per-client torch files `./s1.model`... holding
`{model_state_dict, epoch, optimizer_state_dict, running_loss}`
(reference src/federated_trio.py:372-390) but on resume restores only the
model weights — optimizer state is written yet never loaded, and the ADMM
y/z/rho state is not checkpointed at all (reference
src/federated_trio.py:103-112; SURVEY.md §5). Here one orbax checkpoint
holds the whole algorithm state tree AT AN OUTER-LOOP BOUNDARY: stacked
client params, BatchNorm statistics, the loop cursor, and the
per-(group, client) ADMM rho store. That IS the complete state there —
L-BFGS history and the consensus y/z duals are re-initialized fresh at
every partition round by construction (the reference builds a fresh
optimizer and zeroed duals per round, src/federated_trio.py:273-275,
src/consensus_admm_trio.py:281-288), rho is the ONE consensus quantity
that outlives a round (allocated once outside the reference's loops,
src/consensus_admm_trio.py:263, hence `Trainer._rho_store` and its slot
in the checkpoint), and epoch shuffles are a pure function of
(seed, loop indices) — so a resumed run replays the exact trajectory it
would have taken.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

PyTree = Any


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(directory: str, state: PyTree, *, step: int) -> str:
    """Write `state` (any pytree of arrays/scalars) under `directory/step_N`.

    Returns the checkpoint path. Existing checkpoint at the same step is
    overwritten (the reference likewise clobbers `./sK.model`).
    """
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    state = jax.tree.map(np.asarray, state)
    _checkpointer().save(path, state, force=True)
    return path


def load_checkpoint(directory: str, *, step: int | None = None) -> PyTree:
    """Load the checkpoint at `step`, or the latest one if `step` is None.

    Raises FileNotFoundError when no checkpoint exists.
    """
    root = os.path.abspath(directory)
    if step is None:
        steps = sorted(
            int(d.split("_", 1)[1])
            for d in (os.listdir(root) if os.path.isdir(root) else [])
            if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        )
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {root}")
        step = steps[-1]
    path = os.path.join(root, f"step_{step}")
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return _checkpointer().restore(path)
