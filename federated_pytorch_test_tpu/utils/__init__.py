"""Cross-cutting utilities: metrics, checkpointing, profiling."""

from federated_pytorch_test_tpu.utils.metrics import Deferred, MetricsRecorder
from federated_pytorch_test_tpu.utils.checkpoint import (
    checkpoint_path,
    load_checkpoint,
    save_checkpoint,
)
from federated_pytorch_test_tpu.utils.hostcpu import (
    compile_cache_dir,
    force_host_cpu,
    set_host_device_count,
)

__all__ = [
    "compile_cache_dir",
    "Deferred",
    "MetricsRecorder",
    "checkpoint_path",
    "load_checkpoint",
    "save_checkpoint",
    "force_host_cpu",
    "set_host_device_count",
]
