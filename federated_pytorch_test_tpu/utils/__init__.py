"""Cross-cutting utilities: metrics, checkpointing, profiling."""

from federated_pytorch_test_tpu.utils.metrics import MetricsRecorder
from federated_pytorch_test_tpu.utils.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)

__all__ = ["MetricsRecorder", "load_checkpoint", "save_checkpoint"]
