"""Virtual clients: the cross-device scale layer (docs/SCALE.md).

Turns the engine's cross-*silo* shape (every configured client trains
every round as device-resident `[K]` state) into the cross-*device* one
(a host-side store of N ≫ K virtual clients, a seeded replayable cohort
of C gathered into the unchanged one-dispatch round program each outer
loop, survivors scattered back):

* `ClientStore` (store.py) — chunked, lazily-materialized host state
  with O(C)-per-loop dirty-chunk checkpointing and an LRU-bounded
  resident set (clean-chunk eviction + memory-mapped spill reads, so
  host RSS is flat in N — docs/SCALE.md §Spilled store);
* `CohortSampler` (cohort.py) — the participation schedule, pure in
  `(seed, nloop)` like a `fault.FaultPlan`, riding the shared
  SEED_FOLDS registry;
* `CohortPrefetcher` (prefetch.py) — double-buffers the next loop's
  cohort gather on a background thread so store I/O leaves the round
  wall (`--no-prefetch` is the bitwise fallback).

The engine wires both in `engine/trainer.py` (`--virtual-clients N
--cohort C`); fault schedules stay keyed by VIRTUAL client id, so a
client's chaos identity follows it across cohorts (docs/FAULT.md).
"""

from federated_pytorch_test_tpu.clients.cohort import (
    WEIGHTINGS,
    CohortSampler,
)
from federated_pytorch_test_tpu.clients.prefetch import CohortPrefetcher
from federated_pytorch_test_tpu.clients.store import ClientStore

__all__ = [
    "ClientStore",
    "CohortPrefetcher",
    "CohortSampler",
    "WEIGHTINGS",
]
