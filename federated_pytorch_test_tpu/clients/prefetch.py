"""Pipelined cohort prefetch: loop n+1's gather overlaps loop n's rounds.

Cohort mode's per-loop wall is `gather → rounds → scatter` (docs/
SCALE.md). The scatter already overlaps device compute (its device→host
copies are enqueued asynchronously right after the last round's
dispatch), but the GATHER — store chunk reads, the cohort's data-shard
slices, and their device_puts — was synchronous host I/O sitting on the
round wall. This module double-buffers it: while loop n trains, a
background thread assembles loop n+1's cohort, and `_begin_loop_cohort`
adopts the finished buffers instead of gathering cold.

**Decision points** (the prefetch lifecycle, docs/SCALE.md §Prefetch
lifecycle). A gather can only start once the cohort is DECIDED, and the
decision must read exactly the state the synchronous path would:

* `uniform` / `samples` / `identity` weighting — the draw is pure in
  `(cohort_seed, nloop)` (clients/cohort.py), so loop n+1's cohort is
  known the moment loop n begins: the trainer launches at the end of
  loop n's own gather, and the prefetch overlaps the loop's entire
  round schedule. Churn availability composes — the pool mask is pure
  in the fault plan's seed.
* `telemetry` weighting — the draw reads the store's reliability
  counters, which loop n updates at scatter time: the decision is
  pinned at loop n's SCATTER-FINALIZE (the weights' natural
  availability point), and the launched gather overlaps the loop's
  commit tail (stream marker, checkpoint write) — still ahead of loop
  n+1's first dispatch. The early draw lands in the sampler's history
  exactly where the synchronous draw would (first call of the loop),
  so `cohort_weight` records and resume replay are unchanged.

**Staleness rule.** A prefetch launched before loop n's scatter reads
PRE-scatter store rows. Scatter only writes loop n's own cohort, so the
only rows that can go stale are the overlap `cohort(n) ∩ cohort(n+1)`
— known at launch. When the overlap is empty (the common case at
N ≫ C) the worker device_puts everything and adoption is free; when it
isn't, the worker keeps host arrays and `_begin_loop_cohort` re-gathers
just the overlap rows after scatter n lands, patches, and puts — the
adopted values are bit-for-bit what the synchronous gather would have
produced (`--no-prefetch` is the always-available bitwise fallback,
tests/test_prefetch.py). Store fields registered DURING loop n (a
group's first rho/ef scatter) are gathered synchronously at adoption —
they were unknown at launch. Scatter-before-next-gather ordering is
therefore preserved *semantically*: the bytes adopted for any row a
scatter touched are post-scatter bytes.

**Failure rule.** A prefetch is an optimization, never a dependency:
transient I/O failures (OSError, checksum IntegrityError — flaky or
chaos-injected disks, fault/io.py) get the bounded retry/backoff every
disk-facing path shares BEFORE the worker gives up; a worker exception
that survives it is stashed and adoption falls back to the synchronous
gather with a warning that names the failing chunk file when the error
carries one. A crash mid-prefetch just loses the daemon thread with
the process, and the resumed run gathers cold — stream and store
identity are untouched (the crash/resume contract rides the unchanged
commit ordering).
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Callable, Optional

import numpy as np

from federated_pytorch_test_tpu.fault.io import IntegrityError, retry_io


class CohortPrefetcher:
    """One in-flight prefetched cohort, at most.

    `worker(nloop, ids, known_dirty)` runs on the background thread and
    returns an opaque payload the trainer adopts; the prefetcher itself
    is deliberately ignorant of jax and the store — it owns only the
    thread lifecycle and the match-or-discard rule.
    """

    def __init__(
        self,
        worker: Callable[[int, np.ndarray, np.ndarray], Any],
        io_retries: int = 3,
    ):
        self._worker = worker
        # transient-I/O retry budget for one worker run (module
        # docstring Failure rule); deterministic worker errors fail
        # fast — only OSError/IntegrityError are worth a re-run
        self._io_retries = int(io_retries)
        self._pending: Optional[dict] = None

    @property
    def in_flight(self) -> Optional[int]:
        """The loop index of the pending prefetch, or None."""
        return self._pending["nloop"] if self._pending else None

    def launch(
        self, nloop: int, ids: np.ndarray, known_dirty: np.ndarray
    ) -> None:
        """Start assembling loop `nloop`'s cohort `ids` in the
        background. `known_dirty` are the virtual ids the CURRENT loop
        will scatter before adoption — the worker must leave their rows
        patchable (host-side) or prove the overlap empty. A second
        launch replaces an untaken pending one (out-of-order benchmark
        drivers); the superseded thread finishes into the void."""
        box = {"payload": None, "error": None}
        ids = np.asarray(ids, np.int64)
        known_dirty = np.asarray(known_dirty, np.int64)

        def run():
            try:
                box["payload"] = retry_io(
                    lambda: self._worker(nloop, ids, known_dirty),
                    what=f"cohort prefetch worker (loop {nloop})",
                    attempts=self._io_retries,
                    retry_on=(OSError, IntegrityError),
                )
            except BaseException as e:  # stash; adoption falls back
                box["error"] = e

        t = threading.Thread(
            target=run, name=f"cohort-prefetch-{nloop}", daemon=True
        )
        self._pending = {"nloop": int(nloop), "ids": ids, "box": box,
                         "thread": t}
        t.start()

    def take(self, nloop: int, ids: np.ndarray) -> Optional[Any]:
        """The finished payload for loop `nloop` with cohort `ids`, or
        None (nothing pending, a mismatched target, or a failed worker
        — all of which mean: gather synchronously). Blocks until the
        in-flight work completes; by adoption time that work has been
        overlapping the previous loop's rounds, so the wait is at most
        what the synchronous gather would have cost anyway."""
        p, self._pending = self._pending, None
        if p is None:
            return None
        if p["nloop"] != int(nloop) or not np.array_equal(
            p["ids"], np.asarray(ids, np.int64)
        ):
            # a replayed/out-of-order loop: the prefetched cohort is not
            # this one — discard (the thread finishes into the void)
            return None
        p["thread"].join()
        err = p["box"]["error"]
        if err is not None:
            detail = f"{type(err).__name__}: {err}"
            if isinstance(err, IntegrityError) and err.path:
                # the operator's first question is WHICH file — surface
                # the chunk path even when the message got wrapped
                detail += f" [chunk file: {err.path}]"
            warnings.warn(
                f"cohort prefetch for loop {nloop} failed "
                f"({detail}); gathering synchronously"
            )
            return None
        return p["box"]["payload"]

    def cancel(self) -> None:
        """Drop any pending prefetch (end of run / close): the daemon
        thread finishes into the void and its buffers are released."""
        self._pending = None
