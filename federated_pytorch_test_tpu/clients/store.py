"""Host-side virtual-client state store: N ≫ K clients, O(C) round cost.

The pre-cohort engine holds every configured client's state as `[K]`
device arrays — cross-*silo* simulation, where K is bounded by HBM and
`benchmarks/client_scaling_tpu.json` shows per-client efficiency
collapsing as K grows on one device. Cross-*device* federated learning
inverts the shape: a server keeps state for thousands-to-millions of
mostly-idle virtual clients on the HOST, and each round only the sampled
cohort's rows ever touch a device (clients/cohort.py, engine/trainer.py
gather → fused round → scatter).

`ClientStore` is that host side. Four properties drive the design:

* **Lazy chunks.** Client rows live in fixed-size chunks
  (`chunk_clients` ids per chunk). A chunk is PRISTINE — represented by
  nothing at all — until some row of it is first written; gathers from a
  pristine chunk broadcast the per-field init row (cohort mode requires
  the common-seed init, engine/config.py, so every virtual client starts
  from the same row). Memory and checkpoint cost therefore scale with
  the clients ever *touched*, not with N: a 1M-client store that has run
  ten C=64 cohorts holds ≤ 640 materialized rows.

* **Spilled residency** (`resident_chunks`, docs/SCALE.md §Spilled
  store). Even "touched only" grows without bound over a long run, so
  the RESIDENT set — chunks held in RAM — is LRU-bounded when a budget
  is set. A CLEAN chunk (its current version is on disk) evicts for
  free: the dict entry is dropped and later gathers read the needed
  rows straight off a memory-mapped view of its `.npz` file (rows are
  copied out; the file is never held open past the call). A DIRTY chunk
  spills first — written as the next `chunk_<cid>_v<seq>.npz` version
  through exactly the `save` path, so the following manifest simply
  references the already-written file. Host RSS is therefore
  O(resident budget + cohort), flat in N; with no budget the store
  keeps the legacy keep-everything behavior bit for bit.

* **Dirty-chunk checkpointing.** `save(dir, step)` writes ONLY the
  chunks dirtied since the last save (one `.npz` per chunk, tmp+rename
  like utils/checkpoint.py) plus a small JSON manifest mapping every
  materialized chunk to its current file. The manifest write is the
  atomic commit point: a crash mid-save leaves at worst orphaned chunk
  files that the next save garbage-collects, never a torn snapshot —
  the previous manifest still references the previous versions. Per-loop
  checkpoint delta is O(C) (tests/test_clients.py asserts it), while a
  naive store-in-the-orbax-tree design would rewrite O(N) every loop.
  An eviction-spilled version written between saves is the same story:
  committed only when a manifest names it, orphaned (and GC'd) when the
  run crashes first — spilling never widens the crash window.

* **Field registry.** A row is a set of named fields — `flat` (the
  client's parameter vector), one per batch-stats leaf, one per
  partition group's persistent ADMM rho (`rho/<gid>`, registered lazily
  the first time that group's round completes; see
  engine/trainer.py `_rho_store`), one per group's error-feedback
  residual under a lossy exchange codec (`ef/<gid>`, zero fill —
  `--error-feedback`, exchange/, docs/PERF.md: the compression error a
  client's last encode lost follows the VIRTUAL client into its next
  cohort), and the telemetry reliability counters (`telem/*`,
  docs/SCALE.md). L-BFGS history and the consensus
  y/z duals are deliberately NOT stored: the engine re-initializes them
  fresh at every partition round by construction (utils/checkpoint.py
  module docstring), so persisting them would be dead weight per client.

Static per-client metadata (data-shard assignment, per-shard sample
counts) is computed once at construction and never checkpointed — it is
a pure function of (N, n_shards, shard sizes), the same purity contract
the cohort sampler and fault plans ride.

Thread-safety: the cohort prefetcher (clients/prefetch.py) gathers loop
n+1's rows on a background thread while the trainer's main thread may
scatter loop n's, save a checkpoint, or evict under the residency
budget. One re-entrant lock serializes every public operation — the
critical sections are O(C) row copies or one chunk's file I/O, so the
background gather still overlaps all of the round's device compute.

Storage integrity (docs/SCALE.md §Durability, docs/FAULT.md §Storage):
with `checksums` on (the default) every chunk write stamps a digest
(fault/io.py `checksum`) that is recorded in the manifest and verified
on EVERY read — mmap or full — before any row can reach a gather, and
the manifest itself carries a self-CRC. A failed verification retries
(bounded, exponential backoff — transient rot/injected faults heal on a
clean re-read), then walks the repair ladder: adopt the newest intact
PRIOR version of the chunk (versions are never overwritten, so older
snapshots survive); else re-initialize the chunk pristine by
construction and count it (`repairs_reinit`, surfaced into the
telemetry-weighting penalties); else — with `repair=False`, the strict
resume/scrub stance — refuse loudly naming the chunk. Legacy v1
manifests (no digests) restore read-only-accepted: their chunks simply
go unverified until the next save rewrites them under v2. The optional
`storage_io` shim (fault/io.py StorageFaultShim) routes every chunk
read/write through the chaos schedule of the plan's `storage` axis.
"""

from __future__ import annotations

import ast
import contextlib
import io as _io
import json
import mmap
import os
import struct
import threading
import warnings
import zipfile
from typing import Dict, Optional

import numpy as np

from federated_pytorch_test_tpu.fault.io import (
    CHECKSUM_ALG,
    IntegrityError,
    checksum,
    retry_io,
    stamp_crc,
    verify_crc,
    verify_digest,
)

# version 2 adds per-chunk digests + the manifest self-CRC; version 1
# (pre-integrity) manifests are still restorable — legacy chunks are
# accepted read-only/unverified (module docstring)
_MANIFEST_VERSION = 2


def _manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"manifest_step_{step}.json")


def _npz_views(buf, zf: zipfile.ZipFile) -> Dict[str, np.ndarray]:
    """Read-only array views into an uncompressed `.npz`'s byte buffer.

    np.savez STORES members uncompressed, so each `<name>.npy` payload
    is a contiguous byte range of the archive: parse each member's
    local header + npy header and view the payload in place — `buf` may
    be an mmap (the zero-copy spilled-gather path) or a verified bytes
    object (the checksummed/shimmed path). Raises on anything
    unexpected; the wrappers below fall back to a full `np.load`.
    """
    out: Dict[str, np.ndarray] = {}
    for info in zf.infolist():
        if info.compress_type != zipfile.ZIP_STORED:
            raise ValueError("compressed npz member")
        if not info.filename.endswith(".npy"):
            continue
        ho = info.header_offset
        # local file header: magic(4) .. name_len@26 extra_len@28
        if buf[ho : ho + 4] != b"PK\x03\x04":
            raise ValueError("unexpected local header")
        name_len, extra_len = struct.unpack_from("<HH", buf, ho + 26)
        o = ho + 30 + name_len + extra_len
        if buf[o : o + 6] != b"\x93NUMPY":
            raise ValueError("not an npy member")
        major = buf[o + 6]
        if major == 1:
            (hlen,) = struct.unpack_from("<H", buf, o + 8)
            data = o + 10 + hlen
            header = bytes(buf[o + 10 : o + 10 + hlen])
        else:
            (hlen,) = struct.unpack_from("<I", buf, o + 8)
            data = o + 12 + hlen
            header = bytes(buf[o + 12 : o + 12 + hlen])
        meta = ast.literal_eval(header.decode("latin1"))
        if meta.get("fortran_order") or not isinstance(
            meta.get("descr"), str
        ):
            raise ValueError("non-C-contiguous or structured npy")
        dtype = np.dtype(meta["descr"])
        shape = tuple(meta["shape"])
        arr = np.ndarray(shape, dtype, buffer=buf, offset=data)
        if arr.flags.writeable:
            arr.flags.writeable = False
        out[info.filename[:-4]] = arr
    return out


def _mmap_npz(path: str) -> Dict[str, np.ndarray]:
    """Read-only array views into an uncompressed `.npz`, one shared mmap.

    `np.load(..., mmap_mode=...)` silently ignores the mode for zip
    archives (every member would be decompressed into RAM), which is
    exactly the O(chunk) copy a spilled gather exists to avoid: map the
    file once and view each member's payload in place (`_npz_views`).
    A gather then copies only the rows it needs.

    Falls back to a full `np.load` read (same values, more RAM for the
    duration of the call) on anything unexpected — compressed members,
    Fortran order, a dtype whose descr isn't a plain string — rather
    than ever failing a restore over an optimization.
    """
    try:
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        with zipfile.ZipFile(path) as zf:
            return _npz_views(mm, zf)
    except (OSError, ValueError, KeyError, SyntaxError, struct.error):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}


def _npz_from_bytes(data: bytes, path: str) -> Dict[str, np.ndarray]:
    """`_mmap_npz`'s equivalent over an in-memory byte buffer (the
    shimmed read path holds the — possibly chaos-corrupted — bytes, not
    the file). Unparseable data raises `IntegrityError` naming the file:
    by the time this runs the buffer either passed its checksum or has
    none to check, so a parse failure IS corruption, and the caller's
    retry/repair ladder must see it as such rather than a crash."""
    try:
        try:
            with zipfile.ZipFile(_io.BytesIO(data)) as zf:
                return _npz_views(data, zf)
        except (ValueError, KeyError, SyntaxError, struct.error,
                zipfile.BadZipFile):
            with np.load(_io.BytesIO(data), allow_pickle=False) as z:
                return {k: z[k] for k in z.files}
    except Exception as e:
        raise IntegrityError(
            f"cannot parse chunk file {path}: {e}", path=path
        ) from e


class ClientStore:
    """Chunked, lazily-materialized `[N, ...]` per-field client state."""

    def __init__(
        self,
        n_virtual: int,
        shard_ids: np.ndarray,
        sample_counts: np.ndarray,
        chunk_clients: int = 256,
        resident_chunks: Optional[int] = None,
        spill_dir: Optional[str] = None,
        checksums: bool = True,
        storage_io=None,
        io_retries: int = 3,
        repair: bool = True,
    ):
        """`resident_chunks` bounds the chunks held in RAM (None = keep
        everything, the legacy behavior); eviction of a dirty chunk
        spills it under `spill_dir` (the same directory later `save`
        calls must use — asserted there), so a budget REQUIRES one.

        `checksums` stamps/verifies per-chunk digests (module
        docstring); `storage_io` is an optional fault/io.py
        StorageFaultShim routing chunk reads/writes through the storage
        chaos axis; `io_retries` bounds the read/write retry;
        `repair=False` makes an unrepairable chunk refuse loudly
        (IntegrityError naming it) instead of re-initializing pristine."""
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        if chunk_clients < 1:
            raise ValueError(
                f"chunk_clients must be >= 1, got {chunk_clients}"
            )
        if resident_chunks is not None:
            if resident_chunks < 1:
                raise ValueError(
                    f"resident_chunks must be >= 1, got {resident_chunks}"
                )
            if spill_dir is None:
                raise ValueError(
                    "a resident-chunk budget needs a spill_dir: evicting "
                    "a dirty chunk must write its bytes somewhere"
                )
        self.n_virtual = int(n_virtual)
        self.chunk_clients = int(chunk_clients)
        self.resident_chunks = (
            int(resident_chunks) if resident_chunks is not None else None
        )
        self._spill_dir = os.path.abspath(spill_dir) if spill_dir else None
        self.shard_ids = np.asarray(shard_ids, np.int64).reshape(-1)
        self.sample_counts = np.asarray(sample_counts, np.int64).reshape(-1)
        if self.shard_ids.shape[0] != n_virtual:
            raise ValueError(
                f"shard_ids has {self.shard_ids.shape[0]} entries for "
                f"n_virtual={n_virtual}"
            )
        if self.sample_counts.shape[0] != n_virtual:
            raise ValueError(
                f"sample_counts has {self.sample_counts.shape[0]} entries "
                f"for n_virtual={n_virtual}"
            )
        # field name -> [*(row shape)] init row (the pristine value of
        # every client's row of that field)
        self._fills: Dict[str, np.ndarray] = {}
        # chunk id -> {field name -> [rows_in_chunk, *(row shape)]};
        # a chunk dict may lack fields registered after it materialized —
        # those fall back to the fill row on gather. Insertion order IS
        # the LRU order: touches reinsert at the end, eviction pops the
        # front.
        self._chunks: Dict[int, Dict[str, np.ndarray]] = {}
        self._dirty: set = set()
        self._files: Dict[int, str] = {}  # chunk id -> current filename
        self._seq = 0  # monotone version counter for chunk filenames
        # field metadata of a restored manifest: fields saved by the
        # crashed run but not yet re-registered by this one (lazy rho
        # fields) — validated at re-registration time
        self._saved_fields: Dict[str, dict] = {}
        # spilled-store telemetry (obs: `store_summary` / the `memory`
        # record's store block): evictions under the residency budget,
        # bytes the dirty-spill path wrote, chunk-file reads gathers
        # served off disk (cache misses — see _read_chunk)
        self.evictions = 0
        self.spill_bytes = 0
        self.spill_reads = 0
        # host-side row traffic: surfaced via traffic() in the status
        # sidecar's store block so the chaos oracle (and `watch`) can
        # see the cohort data path moving rows
        self.gather_calls = 0
        self.gather_rows = 0
        self.scatter_calls = 0
        self.scatter_rows = 0
        # storage integrity (module docstring): per-file digests the
        # manifest records, the chaos shim, and the detect/heal/repair
        # counters the `integrity` record + scrub report surface
        self.checksums = bool(checksums)
        self._io = storage_io
        self.io_retries = int(io_retries)
        self.repair = bool(repair)
        self._digests: Dict[str, dict] = {}
        self.verified_reads = 0
        self.integrity_failures = 0
        self.retry_heals = 0
        self.repairs_prior = 0
        self.repairs_reinit = 0
        # per-virtual-client repair counts since the last drain
        # (take_repaired): the trainer scatters them into the
        # `telem/repairs` reliability field so telemetry weighting can
        # demote clients whose rows were rebuilt
        self._repaired: Dict[int, int] = {}
        # chunk-file versions some retained MANIFEST references: a
        # spill may delete the version it supersedes only when no
        # manifest names it (resume must reach every retained
        # snapshot); maintained by save()'s GC scan and load()
        self._protected: set = set()
        # parsed mmap views per chunk FILE (versions are immutable, so
        # entries never go stale): one zip parse serves every field of
        # a gather batch instead of fields × chunks parses. Small FIFO
        # bound — mappings are virtual memory, but the handles are not
        # free. Guarded by _lock like everything else.
        self._mmap_cache: Dict[str, Dict[str, np.ndarray]] = {}
        self._mmap_cache_max = 8
        # batched_writes() defers residency enforcement across a
        # multi-field scatter (one eviction sweep per loop, not one per
        # field — re-spilling the same chunk per field would multiply
        # the spill I/O by the field count)
        self._defer_budget = False
        # one lock for every public operation: the cohort prefetcher
        # gathers on a background thread (module docstring)
        self._lock = threading.RLock()

    # ------------------------------------------------------------- fields

    def register_field(self, name: str, fill_row: np.ndarray) -> None:
        """Declare field `name` with its pristine per-client row.

        Idempotent for an identical fill (re-registration happens on
        resume); a *different* fill for an existing name is a caller bug
        and raises — silently changing what pristine clients hold would
        corrupt every never-sampled client.
        """
        row = np.asarray(fill_row)
        with self._lock:
            if name in self._fills:
                if (
                    self._fills[name].shape != row.shape
                    or self._fills[name].dtype != row.dtype
                    or not np.array_equal(
                        self._fills[name], row, equal_nan=True
                    )
                ):
                    raise ValueError(
                        f"field {name!r} re-registered with a different "
                        "fill row (shape/dtype/value mismatch)"
                    )
                return
            saved = self._saved_fields.get(name)
            if saved is not None and (
                list(row.shape) != list(saved["shape"])
                or str(row.dtype) != saved["dtype"]
            ):
                raise ValueError(
                    f"client-store field {name!r} was saved with shape "
                    f"{saved['shape']} dtype {saved['dtype']} but this run "
                    f"registers shape {list(row.shape)} dtype {row.dtype}"
                )
            self._fills[name] = row.copy()

    def has_field(self, name: str) -> bool:
        with self._lock:
            return name in self._fills

    @property
    def fields(self):
        with self._lock:  # the prefetch thread snapshots this while
            # the main thread may be registering a group's first rho/ef
            return tuple(sorted(self._fills))

    @property
    def saved_fields(self) -> Dict[str, dict]:
        """Field metadata a restored manifest recorded (`{name: {shape,
        dtype}}`): what the crashed run had registered at its last save.
        The trainer re-registers its lazy fields (per-group rho) from
        this so restored chunks holding them stay addressable before the
        group's first round of the resumed run."""
        return dict(self._saved_fields)

    # ------------------------------------------------------- gather/scatter

    def _chunk_of(self, vid: int) -> int:
        return int(vid) // self.chunk_clients

    def _chunk_rows(self, cid: int) -> int:
        lo = cid * self.chunk_clients
        return min(self.chunk_clients, self.n_virtual - lo)

    def _check_ids(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_virtual):
            raise IndexError(
                f"virtual-client ids out of range [0, {self.n_virtual}): "
                f"min={ids.min()}, max={ids.max()}"
            )
        return ids

    def _by_chunk(self, ids: np.ndarray):
        """`(cid, positions, local_rows)` groups of a checked id vector —
        one entry per touched chunk, positions indexing the caller's
        id/row order (the vectorized replacement for a per-id loop)."""
        cids = ids // self.chunk_clients
        out = []
        for cid in np.unique(cids):
            pos = np.nonzero(cids == cid)[0]
            out.append(
                (int(cid), pos, ids[pos] - int(cid) * self.chunk_clients)
            )
        return out

    def _touch(self, cid: int) -> None:
        """Move a resident chunk to the LRU tail (most recently used)."""
        self._chunks[cid] = self._chunks.pop(cid)

    def gather(self, name: str, ids: np.ndarray) -> np.ndarray:
        """Rows of field `name` for `ids`, as a fresh `[len(ids), ...]`
        array (never a view into the store — the caller device_puts and
        possibly donates it). Non-resident chunks with an on-disk
        version serve their rows off a memory-mapped read without
        rejoining the resident set — a gather never costs RAM beyond
        its own output."""
        with self._lock:
            ids = self._check_ids(ids)
            self.gather_calls += 1
            self.gather_rows += int(ids.size)
            fill = self._fills[name]
            out = np.empty((ids.size,) + fill.shape, fill.dtype)
            for cid, pos, rows in self._by_chunk(ids):
                chunk = self._chunks.get(cid)
                if chunk is not None:
                    self._touch(cid)
                    if name in chunk:
                        out[pos] = chunk[name][rows]
                    else:
                        out[pos] = fill
                elif cid in self._files:
                    arrs = self._read_chunk(cid)
                    if name in arrs:
                        out[pos] = arrs[name][rows]
                    else:
                        # field registered after this version was written
                        out[pos] = fill
                else:
                    out[pos] = fill
            return out

    def scatter(self, name: str, ids: np.ndarray, rows: np.ndarray) -> None:
        """Write `rows[i]` into client `ids[i]`'s slot of field `name`,
        materializing (init-filled or disk-reloaded) chunks as needed
        and marking every touched chunk dirty for the next `save`. The
        residency budget is enforced AFTER the whole scatter — mid-
        operation the resident set may exceed it by up to the cohort's
        chunks (RSS stays O(resident + cohort))."""
        with self._lock:
            ids = self._check_ids(ids)
            self.scatter_calls += 1
            self.scatter_rows += int(ids.size)
            rows = np.asarray(rows)
            fill = self._fills[name]
            if rows.shape != (ids.size,) + fill.shape:
                raise ValueError(
                    f"scatter of field {name!r}: rows shape {rows.shape} "
                    f"!= {(ids.size,) + fill.shape}"
                )
            if rows.dtype != fill.dtype:
                raise ValueError(
                    f"scatter of field {name!r}: dtype {rows.dtype} != "
                    f"registered {fill.dtype} (an implicit cast here would "
                    "silently change restored state)"
                )
            for cid, pos, local in self._by_chunk(ids):
                chunk = self._chunks.get(cid)
                if chunk is None:
                    chunk = self._materialize(cid)
                else:
                    self._touch(cid)
                if name not in chunk:
                    chunk[name] = np.broadcast_to(
                        fill, (self._chunk_rows(cid),) + fill.shape
                    ).copy()
                chunk[name][local] = rows[pos]
                self._dirty.add(cid)
            self._ensure_budget()

    def _read_chunk(self, cid: int) -> Dict[str, np.ndarray]:
        """Read-only array views of chunk `cid`'s current on-disk
        version, through the per-file cache (versions are immutable):
        one zip parse serves every field of a gather batch.
        `spill_reads` counts the cache MISSES — actual file opens.
        A read that fails verification past the retry walks the repair
        ladder (`_repair_chunk`), which may re-point `_files[cid]` at a
        prior version or delete the entry entirely (pristine re-init —
        the returned dict is then empty and every field falls back to
        its fill row)."""
        fname = self._files[cid]
        arrs = self._mmap_cache.get(fname)
        if arrs is not None:
            return arrs
        self.spill_reads += 1
        try:
            arrs = self._load_verified(fname)
        except (OSError, IntegrityError) as e:
            return self._repair_chunk(cid, fname, e)
        self._cache_views(fname, arrs)
        return arrs

    def _cache_views(self, fname: str, arrs: Dict[str, np.ndarray]) -> None:
        self._mmap_cache[fname] = arrs
        while len(self._mmap_cache) > self._mmap_cache_max:
            self._mmap_cache.pop(next(iter(self._mmap_cache)))

    def _load_verified(self, fname: str) -> Dict[str, np.ndarray]:
        """One chunk file -> array views, checksum-verified BEFORE any
        row can reach a gather, with bounded retry (transient injected
        faults — and real flaky disks — heal on a clean re-read, which
        `retry_heals` counts). Raises OSError/IntegrityError when every
        attempt fails; the caller decides repair vs refusal."""
        path = self._chunk_path(fname)
        digest = self._digests.get(fname) if self.checksums else None
        if self._io is None and digest is None:
            # fast path: no chaos shim, nothing to verify (checksums
            # off, or a legacy/unmanifested version) — the pre-integrity
            # zero-copy mmap read, bit for bit
            return _mmap_npz(path)
        fails = [0]

        def attempt() -> Dict[str, np.ndarray]:
            try:
                if self._io is not None:
                    data = self._io.read_bytes(path)
                    if not verify_digest(data, digest):
                        raise IntegrityError(
                            f"client-store chunk {fname} failed checksum "
                            f"verification at {path}",
                            path=path,
                        )
                    if digest is not None:
                        self.verified_reads += 1
                    return _npz_from_bytes(data, path)
                # no shim: verify over a throwaway mapping (page-cache
                # warm for the view parse that follows)
                with open(path, "rb") as f:
                    mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                    try:
                        ok = verify_digest(mm, digest)
                    finally:
                        mm.close()
                if not ok:
                    raise IntegrityError(
                        f"client-store chunk {fname} failed checksum "
                        f"verification at {path}",
                        path=path,
                    )
                self.verified_reads += 1
                return _mmap_npz(path)
            except (OSError, IntegrityError) as e:
                fails[0] += 1
                if isinstance(e, IntegrityError):
                    self.integrity_failures += 1
                raise

        out = retry_io(
            attempt,
            what=f"client-store chunk read ({fname})",
            attempts=self.io_retries,
            retry_on=(OSError, IntegrityError),
        )
        if fails[0]:
            self.retry_heals += 1
        return out

    def _retained_digests(self, root: str) -> Dict[str, dict]:
        """Chunk digests every retained manifest records (the repair
        ladder verifies PRIOR versions against the manifest that
        committed them, not just the live map's digests)."""
        out: Dict[str, dict] = {}
        try:
            entries = sorted(os.listdir(root))
        except OSError:
            return out
        for entry in entries:
            if not (
                entry.startswith("manifest_step_")
                and entry.endswith(".json")
            ):
                continue
            try:
                with open(os.path.join(root, entry)) as f:
                    out.update(json.load(f).get("digests", {}))
            except (OSError, ValueError):
                continue
        return out

    def _repair_chunk(
        self, cid: int, fname: str, err: Exception
    ) -> Dict[str, np.ndarray]:
        """The repair ladder for a chunk whose current version failed
        past the retry (module docstring): newest intact prior version;
        else pristine re-init by construction, counted; else — repair
        disabled — refuse loudly naming the chunk."""
        root = self._root(self._save_dir)
        if not self.repair:
            raise IntegrityError(
                f"client-store chunk {fname} is corrupt and repair is "
                f"disabled: {err}",
                path=self._chunk_path(fname),
            )
        for f, d in self._retained_digests(root).items():
            self._digests.setdefault(f, d)
        prefix = f"chunk_{cid:06d}_v"
        try:
            priors = sorted(
                (
                    e
                    for e in os.listdir(root)
                    if e.startswith(prefix)
                    and e.endswith(".npz")
                    and e != fname
                ),
                reverse=True,  # newest version first
            )
        except OSError:
            priors = []
        for prior in priors:
            try:
                arrs = self._load_verified(prior)
            except (OSError, IntegrityError):
                continue
            self._files[cid] = prior
            self.repairs_prior += 1
            self._count_repairs(cid)
            self._cache_views(prior, arrs)
            warnings.warn(
                f"client-store chunk {cid} repaired: adopted prior "
                f"intact version {prior} (current {fname} failed: {err})"
            )
            return arrs
        # no intact version anywhere: the chunk reverts to pristine —
        # correct BY CONSTRUCTION (every field falls back to its
        # registered fill row, the same state a never-touched chunk
        # holds) — and the loss is counted, per-client, for the
        # telemetry penalties
        del self._files[cid]
        self._dirty.discard(cid)
        self.repairs_reinit += 1
        self._count_repairs(cid)
        warnings.warn(
            f"client-store chunk {cid} has no intact version "
            f"(current {fname} failed: {err}); re-initialized pristine"
        )
        return {}

    def _count_repairs(self, cid: int) -> None:
        lo = cid * self.chunk_clients
        for vid in range(lo, lo + self._chunk_rows(cid)):
            self._repaired[vid] = self._repaired.get(vid, 0) + 1

    def take_repaired(self) -> Dict[int, int]:
        """Drain the per-client repair counts accumulated since the
        last call (`{vid: repairs}`) — the trainer folds them into the
        `telem/repairs` reliability field each loop."""
        with self._lock:
            out = self._repaired
            self._repaired = {}
            return out

    def verify_all(self) -> dict:
        """Verify every manifest-referenced chunk file's checksum —
        no adoption, no repair: the resume-time gate (and scrub's
        report pass). Raises IntegrityError naming the first chunk that
        fails past the retry; legacy files without a digest are skipped
        (read-only accepted by the format contract). Returns
        `{"verified": n, "chunks": total}`."""
        with self._lock:
            checked = 0
            for cid in sorted(self._files):
                fname = self._files[cid]
                digest = (
                    self._digests.get(fname) if self.checksums else None
                )
                if digest is None:
                    continue
                path = self._chunk_path(fname)

                def attempt(path=path, fname=fname, digest=digest):
                    if self._io is not None:
                        data = self._io.read_bytes(path)
                    else:
                        with open(path, "rb") as f:
                            data = f.read()
                    if not verify_digest(data, digest):
                        self.integrity_failures += 1
                        raise IntegrityError(
                            f"client-store chunk {fname} failed checksum "
                            f"verification at {path}",
                            path=path,
                        )

                retry_io(
                    attempt,
                    what=f"client-store chunk verify ({fname})",
                    attempts=self.io_retries,
                    retry_on=(OSError, IntegrityError),
                )
                self.verified_reads += 1
                checked += 1
            return {"verified": checked, "chunks": len(self._files)}

    def integrity_digest(self) -> dict:
        """The small integrity digest the trainer logs as the
        `integrity` record and stamps into the status sidecar
        (docs/OBSERVABILITY.md): checksum config + the
        detect/heal/repair counters."""
        with self._lock:
            return {
                "checksums": self.checksums,
                "alg": CHECKSUM_ALG,
                "verified_reads": int(self.verified_reads),
                "failures": int(self.integrity_failures),
                "retry_heals": int(self.retry_heals),
                "repairs_prior": int(self.repairs_prior),
                "repairs_reinit": int(self.repairs_reinit),
            }

    def _materialize(self, cid: int) -> Dict[str, np.ndarray]:
        """Bring chunk `cid` into the resident set for writing: a full
        (writable) copy of its on-disk version when one exists, else an
        empty dict whose fields fill lazily."""
        if cid in self._files:
            chunk = {
                k: np.array(v)  # writable copies off the shared views
                for k, v in self._read_chunk(cid).items()
            }
        else:
            chunk = {}
        self._chunks[cid] = chunk
        return chunk

    def touched_chunks(self, ids: np.ndarray) -> set:
        """Chunk ids a scatter of `ids` dirties (the O(C) bound of one
        loop's checkpoint delta: ≤ len(ids) chunks + the manifest)."""
        return {self._chunk_of(v) for v in self._check_ids(ids)}

    # --------------------------------------------------------- residency

    @contextlib.contextmanager
    def batched_writes(self):
        """Defer residency enforcement to the end of a multi-field
        write batch (the trainer's cohort scatter: one scatter call per
        field over the same chunks). Without this, each field's scatter
        would spill the over-budget chunks and the next field's would
        reload them — full chunk I/O multiplied by the field count.
        Inside the batch the resident set may exceed the budget by the
        cohort's chunks, the same O(resident + cohort) transient the
        per-call rule allows. No-op without a budget."""
        with self._lock:
            self._defer_budget = True
        try:
            yield
        finally:
            with self._lock:
                self._defer_budget = False
                self._ensure_budget()

    def _ensure_budget(self) -> None:
        """Evict LRU chunks until the resident set fits the budget.

        Clean chunks (current version on disk) drop for free; dirty
        ones spill — written as the next version through the same
        tmp+fsync+rename path `save` uses, so the following manifest
        just references the file. Invariant: every clean materialized
        chunk HAS a file (chunks materialize dirty and only become
        clean via save/spill, or arrive clean from a load), so eviction
        never loses the only copy. A spill deletes the version it
        supersedes when NO retained manifest references it
        (`_protected`) — otherwise a long run without checkpoints
        would accumulate one full dead chunk file per eviction, and
        only `save`'s GC (which such a run never reaches) could
        reclaim them.
        """
        if self.resident_chunks is None or self._defer_budget:
            return
        while len(self._chunks) > self.resident_chunks:
            cid = next(iter(self._chunks))  # LRU head
            if cid in self._dirty or cid not in self._files:
                old = self._files.get(cid)
                self.spill_bytes += self._write_chunk(cid, self._spill_dir)
                self._dirty.discard(cid)
                if old is not None and old not in self._protected:
                    self._mmap_cache.pop(old, None)
                    try:
                        os.remove(self._chunk_path(old))
                    except OSError:
                        pass  # best-effort, like save's GC
            del self._chunks[cid]
            self.evictions += 1

    def _root(self, directory: str) -> str:
        return os.path.abspath(os.path.join(directory, "client_store"))

    def _chunk_path(self, fname: str) -> str:
        # chunk files live under the spill/save root; the two are
        # asserted identical in save()
        return os.path.join(self._root(self._save_dir), fname)

    # the directory chunk files are read back from: the spill dir until
    # a save/load names one (they must agree — see save)
    @property
    def _save_dir(self) -> str:
        if self._dir is not None:
            return self._dir
        if self._spill_dir is not None:
            return self._spill_dir
        raise RuntimeError(
            "no chunk directory known yet (no save/load happened and no "
            "spill_dir was configured)"
        )

    _dir: Optional[str] = None

    def _write_chunk(self, cid: int, directory: str) -> int:
        """One chunk -> its next versioned `.npz` (tmp+fsync+rename);
        updates `_files` and returns the bytes written. THE one chunk
        writer — `save` and the dirty-spill eviction share it, so the
        on-disk format and the GC's filename rules cannot drift. The
        payload is serialized once up front so its digest covers
        exactly the bytes that land, and transient write faults
        (injected ioerror/enospc, real flaky disks) are absorbed by the
        bounded retry — the chaos shim refuses BEFORE any bytes move,
        so a retried write never half-lands."""
        root = self._root(directory)
        os.makedirs(root, exist_ok=True)
        self._seq += 1
        fname = f"chunk_{cid:06d}_v{self._seq:08d}.npz"
        tmp = os.path.join(root, f".tmp_{fname}")
        buf = _io.BytesIO()
        np.savez(buf, **self._chunks[cid])
        payload = buf.getvalue()

        def write():
            if self._io is not None:
                self._io.before_write(f"client-store chunk {fname}")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())

        retry_io(
            write,
            what=f"client-store chunk write ({fname})",
            attempts=self.io_retries,
        )
        os.replace(tmp, os.path.join(root, fname))
        if self.checksums:
            self._digests[fname] = checksum(payload)
        self._files[cid] = fname
        return len(payload)

    # --------------------------------------------------------- checkpointing

    # manifests retained per save: the newest one plus enough history to
    # cover the crash window between a store save and its checkpoint's
    # orbax commit (resume then falls back exactly one step). Retaining
    # N manifests bounds disk at O(population touched) + N*O(C) chunk
    # versions; without pruning, every superseded chunk version would
    # stay referenced by some historical manifest forever.
    keep_manifests: int = 2

    def save(self, directory: str, step: int) -> str:
        """Write the dirty chunks + the step manifest; return its path.

        Called by `Trainer.save` BEFORE the orbax checkpoint of the same
        step is committed: a crash between the two leaves this manifest
        dangling (no checkpoint names it), and resume falls back to the
        previous checkpoint + its manifest — both still intact, because
        chunk files are versioned (`chunk_<cid>_v<seq>.npz`), never
        overwritten in place. After the manifest commit, manifests older
        than the newest `keep_manifests` are pruned and chunk files no
        retained manifest references (superseded versions, crashed-save
        orphans, eviction spills the crashed run never committed, stale
        `.tmp_` staging files) are garbage-collected — resume therefore
        reaches the newest `keep_manifests` snapshots; falling back
        further (multiple consecutive torn checkpoints) fails loudly in
        `load` rather than restoring silently-wrong rows. Under a
        residency budget the now-all-clean resident set is shed back to
        the budget before returning.
        """
        with self._lock:
            if self._spill_dir is not None and os.path.abspath(
                directory
            ) != self._spill_dir:
                raise ValueError(
                    f"save directory {directory!r} != configured spill "
                    f"dir {self._spill_dir!r}: eviction-spilled chunk "
                    "versions would be invisible to this manifest"
                )
            self._dir = os.path.abspath(directory)
            root = self._root(directory)
            os.makedirs(root, exist_ok=True)
            for cid in sorted(self._dirty):
                self._write_chunk(cid, directory)
            self._dirty.clear()
            manifest = {
                "version": _MANIFEST_VERSION,
                "step": int(step),
                "n_virtual": self.n_virtual,
                "chunk_clients": self.chunk_clients,
                "seq": self._seq,
                "chunks": {
                    str(c): f for c, f in sorted(self._files.items())
                },
                "fields": {
                    name: {
                        "shape": list(row.shape),
                        "dtype": str(row.dtype),
                    }
                    for name, row in sorted(self._fills.items())
                },
                # per-chunk-file digests, verified on every read before
                # a row can reach a gather (module docstring); a file
                # without one (checksums off when it was written) stays
                # read-only accepted like a v1 legacy chunk
                "digests": {
                    f: self._digests[f]
                    for f in sorted(set(self._files.values()))
                    if f in self._digests
                },
            }
            # the manifest carries its own CRC (fault/io.py stamp_crc):
            # a bit-rotted-but-parsable manifest must not restore —
            # it indexes every chunk of the snapshot
            text = stamp_crc(manifest)
            path = _manifest_path(root, step)
            tmp = path + ".tmp"

            def write_manifest():
                if self._io is not None:
                    self._io.before_write(
                        f"client-store manifest step {step}"
                    )
                with open(tmp, "w") as f:
                    f.write(text)
                    f.flush()
                    os.fsync(f.fileno())

            retry_io(
                write_manifest,
                what=f"client-store manifest write (step {step})",
                attempts=self.io_retries,
            )
            os.replace(tmp, path)
            self._gc(root)
            self._ensure_budget()
            return path

    def _gc(self, root: str) -> None:
        """Prune old manifests, then delete unreferenced files.

        Best-effort: any OS error leaves files behind for the next save
        to reclaim, never fails the checkpoint. A torn (unparseable)
        retained manifest aborts chunk GC entirely — its references are
        unknowable, and deleting a chunk it might name would turn a
        recoverable situation into data loss. Files named by the LIVE
        `_files` map are always kept: an eviction-spilled version
        written since the manifest above is the only copy of a clean
        evicted chunk's current state.
        """
        def is_manifest(entry: str) -> bool:
            # committed manifests only: a crashed writer's staging file
            # (`manifest_step_N.json.tmp`) is never authoritative — it
            # is deleted below, not parsed, so it can't wedge GC forever
            return entry.startswith("manifest_step_") and entry.endswith(
                ".json"
            )

        steps = []
        for entry in os.listdir(root):
            if is_manifest(entry):
                try:
                    steps.append(int(entry[len("manifest_step_"):-5]))
                except ValueError:
                    continue
        for s in sorted(steps)[: -self.keep_manifests]:
            try:
                os.remove(_manifest_path(root, s))
            except OSError:
                pass
        manifest_refs = set()
        for entry in os.listdir(root):
            if not is_manifest(entry):
                continue
            try:
                with open(os.path.join(root, entry)) as f:
                    manifest_refs.update(
                        json.load(f).get("chunks", {}).values()
                    )
            except (OSError, ValueError):
                # torn retained manifest: references unknowable — keep
                # everything (spills must then protect the live map too)
                self._protected |= set(self._files.values())
                return
        # what eviction spills must never delete: every retained
        # manifest's versions (resume reaches any of those snapshots)
        self._protected = set(manifest_refs)
        referenced = manifest_refs | set(self._files.values())
        self._digests = {
            f: d for f, d in self._digests.items() if f in referenced
        }
        for entry in os.listdir(root):
            stale = entry.startswith("chunk_") and entry not in referenced
            if stale or entry.startswith(".tmp_") or entry.endswith(
                ".json.tmp"
            ):
                try:
                    os.remove(os.path.join(root, entry))
                except OSError:
                    pass

    def load(self, directory: str, step: int) -> None:
        """Restore the snapshot `save(directory, step)` committed.

        Chunks named by the manifest become addressable (their files are
        stat-checked now so a half-deleted store fails at restore, not
        mid-run) but are NOT read into RAM: gathers serve rows off the
        memory-mapped files and scatters materialize on demand — a
        restored million-client store costs no more resident memory
        than a fresh one. Everything the manifest doesn't name reverts
        to pristine. Field fills are NOT restored from disk — the caller
        re-registers them from the same deterministic init it built them
        with (common-seed model init), and the manifest's recorded
        shapes/dtypes are cross-checked against that registration so a
        config drift (different model, different rho shape) fails loudly
        instead of broadcasting the wrong fill under restored chunks.
        """
        with self._lock:
            if self._spill_dir is not None and os.path.abspath(
                directory
            ) != self._spill_dir:
                raise ValueError(
                    f"load directory {directory!r} != configured spill "
                    f"dir {self._spill_dir!r}"
                )
            root = self._root(directory)
            path = _manifest_path(root, step)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"no client-store manifest for step {step} under "
                    f"{root} (the checkpoint was written without cohort "
                    "mode, or the store snapshot was deleted)"
                )
            with open(path) as f:
                manifest = json.load(f)
            version = manifest.get("version")
            if version not in (1, _MANIFEST_VERSION):
                raise ValueError(
                    f"client-store manifest version "
                    f"{version} != supported "
                    f"{_MANIFEST_VERSION}"
                )
            if version >= 2 and not verify_crc(manifest):
                # a v2 manifest ALWAYS carries a self-CRC; a parsable
                # document that fails it is bit rot, and it indexes the
                # whole snapshot — refuse so the trainer's restore loop
                # falls back to the previous intact checkpoint
                raise IntegrityError(
                    f"client-store manifest for step {step} failed its "
                    f"self-checksum at {path}",
                    path=path,
                )
            for key, mine in (
                ("n_virtual", self.n_virtual),
                ("chunk_clients", self.chunk_clients),
            ):
                if int(manifest[key]) != mine:
                    raise ValueError(
                        f"client-store manifest {key}={manifest[key]} but "
                        f"this run configured {mine}: the snapshot indexes "
                        "a different virtual population and cannot be "
                        "restored onto it"
                    )
            for name, meta in manifest.get("fields", {}).items():
                if name in self._fills:
                    row = self._fills[name]
                    if (
                        list(row.shape) != list(meta["shape"])
                        or str(row.dtype) != meta["dtype"]
                    ):
                        raise ValueError(
                            f"client-store field {name!r} was saved with "
                            f"shape {meta['shape']} dtype {meta['dtype']} "
                            f"but this run registered shape "
                            f"{list(row.shape)} dtype {row.dtype}"
                        )
            files = {
                int(c): fname for c, fname in manifest["chunks"].items()
            }
            missing = [
                f
                for f in files.values()
                if not os.path.exists(os.path.join(root, f))
            ]
            if missing:
                raise FileNotFoundError(
                    f"client-store manifest step {step} names chunk "
                    f"file(s) that do not exist under {root}: "
                    f"{sorted(missing)[:4]}"
                )
            self._dir = os.path.abspath(directory)
            self._chunks.clear()
            self._dirty.clear()
            self._mmap_cache.clear()
            self._files = files
            # v1 manifests carry no digests: their chunks restore
            # read-only accepted/unverified until the next save rewrites
            # them under v2 (the legacy-migration path, docs/SCALE.md)
            self._digests = dict(manifest.get("digests", {}))
            # conservative: this manifest's versions are committed (and
            # a sibling retained manifest may reference more — the next
            # save's GC scan refines the set); spills must not delete
            # any of them
            self._protected |= set(files.values())
            self._seq = int(manifest.get("seq", 0))
            self._saved_fields = dict(manifest.get("fields", {}))

    # ------------------------------------------------------------- summary

    def materialized_chunks(self) -> int:
        return len(self._chunks)

    def residency(self) -> dict:
        """The small live digest the trainer folds into each round's
        `memory` record and the `watch` status sidecar (docs/SCALE.md
        §Spilled store): resident/on-disk chunk counts, the budget, and
        the eviction/spill counters."""
        with self._lock:
            return {
                "resident_chunks": len(self._chunks),
                "resident_budget": self.resident_chunks,
                "on_disk_chunks": len(self._files),
                "evictions": int(self.evictions),
                "spill_bytes": int(self.spill_bytes),
                "spill_reads": int(self.spill_reads),
            }

    def traffic(self) -> dict:
        """Cumulative host-side row traffic: how many rows every gather
        and scatter has moved since construction. Process-local (like
        the storage-fault counter, a resumed run restarts from zero);
        the chaos oracle reads it off the status sidecar to assert the
        cohort data path actually moved rows in cohort mode."""
        with self._lock:
            return {
                "gather_calls": int(self.gather_calls),
                "gather_rows": int(self.gather_rows),
                "scatter_calls": int(self.scatter_calls),
                "scatter_rows": int(self.scatter_rows),
            }

    def summary(self) -> dict:
        """Small host-memory/occupancy digest for the end-of-run log."""
        with self._lock:
            rows = sum(
                next(iter(c.values())).shape[0] if c else 0
                for c in self._chunks.values()
            )
            nbytes = sum(
                a.nbytes for c in self._chunks.values() for a in c.values()
            )
            return {
                "n_virtual": self.n_virtual,
                "chunk_clients": self.chunk_clients,
                "chunks_total": -(-self.n_virtual // self.chunk_clients),
                "chunks_materialized": len(self._chunks),
                "rows_materialized": int(rows),
                "host_bytes": int(nbytes),
                "fields": list(self.fields),
                **self.residency(),
            }
