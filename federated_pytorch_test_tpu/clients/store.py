"""Host-side virtual-client state store: N ≫ K clients, O(C) round cost.

The pre-cohort engine holds every configured client's state as `[K]`
device arrays — cross-*silo* simulation, where K is bounded by HBM and
`benchmarks/client_scaling_tpu.json` shows per-client efficiency
collapsing as K grows on one device. Cross-*device* federated learning
inverts the shape: a server keeps state for thousands-to-millions of
mostly-idle virtual clients on the HOST, and each round only the sampled
cohort's rows ever touch a device (clients/cohort.py, engine/trainer.py
gather → fused round → scatter).

`ClientStore` is that host side. Three properties drive the design:

* **Lazy chunks.** Client rows live in fixed-size chunks
  (`chunk_clients` ids per chunk). A chunk is PRISTINE — represented by
  nothing at all — until some row of it is first written; gathers from a
  pristine chunk broadcast the per-field init row (cohort mode requires
  the common-seed init, engine/config.py, so every virtual client starts
  from the same row). Memory and checkpoint cost therefore scale with
  the clients ever *touched*, not with N: a 1M-client store that has run
  ten C=64 cohorts holds ≤ 640 materialized rows.

* **Dirty-chunk checkpointing.** `save(dir, step)` writes ONLY the
  chunks dirtied since the last save (one `.npz` per chunk, tmp+rename
  like utils/checkpoint.py) plus a small JSON manifest mapping every
  materialized chunk to its current file. The manifest write is the
  atomic commit point: a crash mid-save leaves at worst orphaned chunk
  files that the next save garbage-collects, never a torn snapshot —
  the previous manifest still references the previous versions. Per-loop
  checkpoint delta is O(C) (tests/test_clients.py asserts it), while a
  naive store-in-the-orbax-tree design would rewrite O(N) every loop.

* **Field registry.** A row is a set of named fields — `flat` (the
  client's parameter vector), one per batch-stats leaf, one per
  partition group's persistent ADMM rho (`rho/<gid>`, registered lazily
  the first time that group's round completes; see
  engine/trainer.py `_rho_store`), one per group's error-feedback
  residual under a lossy exchange codec (`ef/<gid>`, zero fill —
  `--error-feedback`, exchange/, docs/PERF.md: the compression error a
  client's last encode lost follows the VIRTUAL client into its next
  cohort), and the telemetry reliability counters (`telem/*`,
  docs/SCALE.md). L-BFGS history and the consensus
  y/z duals are deliberately NOT stored: the engine re-initializes them
  fresh at every partition round by construction (utils/checkpoint.py
  module docstring), so persisting them would be dead weight per client.

Static per-client metadata (data-shard assignment, per-shard sample
counts) is computed once at construction and never checkpointed — it is
a pure function of (N, n_shards, shard sizes), the same purity contract
the cohort sampler and fault plans ride.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

_MANIFEST_VERSION = 1


def _manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"manifest_step_{step}.json")


class ClientStore:
    """Chunked, lazily-materialized `[N, ...]` per-field client state."""

    def __init__(
        self,
        n_virtual: int,
        shard_ids: np.ndarray,
        sample_counts: np.ndarray,
        chunk_clients: int = 256,
    ):
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        if chunk_clients < 1:
            raise ValueError(
                f"chunk_clients must be >= 1, got {chunk_clients}"
            )
        self.n_virtual = int(n_virtual)
        self.chunk_clients = int(chunk_clients)
        self.shard_ids = np.asarray(shard_ids, np.int64).reshape(-1)
        self.sample_counts = np.asarray(sample_counts, np.int64).reshape(-1)
        if self.shard_ids.shape[0] != n_virtual:
            raise ValueError(
                f"shard_ids has {self.shard_ids.shape[0]} entries for "
                f"n_virtual={n_virtual}"
            )
        if self.sample_counts.shape[0] != n_virtual:
            raise ValueError(
                f"sample_counts has {self.sample_counts.shape[0]} entries "
                f"for n_virtual={n_virtual}"
            )
        # field name -> [*(row shape)] init row (the pristine value of
        # every client's row of that field)
        self._fills: Dict[str, np.ndarray] = {}
        # chunk id -> {field name -> [rows_in_chunk, *(row shape)]};
        # a chunk dict may lack fields registered after it materialized —
        # those fall back to the fill row on gather
        self._chunks: Dict[int, Dict[str, np.ndarray]] = {}
        self._dirty: set = set()
        self._files: Dict[int, str] = {}  # chunk id -> current filename
        self._seq = 0  # monotone version counter for chunk filenames
        # field metadata of a restored manifest: fields saved by the
        # crashed run but not yet re-registered by this one (lazy rho
        # fields) — validated at re-registration time
        self._saved_fields: Dict[str, dict] = {}

    # ------------------------------------------------------------- fields

    def register_field(self, name: str, fill_row: np.ndarray) -> None:
        """Declare field `name` with its pristine per-client row.

        Idempotent for an identical fill (re-registration happens on
        resume); a *different* fill for an existing name is a caller bug
        and raises — silently changing what pristine clients hold would
        corrupt every never-sampled client.
        """
        row = np.asarray(fill_row)
        if name in self._fills:
            if (
                self._fills[name].shape != row.shape
                or self._fills[name].dtype != row.dtype
                or not np.array_equal(
                    self._fills[name], row, equal_nan=True
                )
            ):
                raise ValueError(
                    f"field {name!r} re-registered with a different fill "
                    "row (shape/dtype/value mismatch)"
                )
            return
        saved = self._saved_fields.get(name)
        if saved is not None and (
            list(row.shape) != list(saved["shape"])
            or str(row.dtype) != saved["dtype"]
        ):
            raise ValueError(
                f"client-store field {name!r} was saved with shape "
                f"{saved['shape']} dtype {saved['dtype']} but this run "
                f"registers shape {list(row.shape)} dtype {row.dtype}"
            )
        self._fills[name] = row.copy()

    def has_field(self, name: str) -> bool:
        return name in self._fills

    @property
    def fields(self):
        return tuple(sorted(self._fills))

    @property
    def saved_fields(self) -> Dict[str, dict]:
        """Field metadata a restored manifest recorded (`{name: {shape,
        dtype}}`): what the crashed run had registered at its last save.
        The trainer re-registers its lazy fields (per-group rho) from
        this so restored chunks holding them stay addressable before the
        group's first round of the resumed run."""
        return dict(self._saved_fields)

    # ------------------------------------------------------- gather/scatter

    def _chunk_of(self, vid: int) -> int:
        return int(vid) // self.chunk_clients

    def _chunk_rows(self, cid: int) -> int:
        lo = cid * self.chunk_clients
        return min(self.chunk_clients, self.n_virtual - lo)

    def _check_ids(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_virtual):
            raise IndexError(
                f"virtual-client ids out of range [0, {self.n_virtual}): "
                f"min={ids.min()}, max={ids.max()}"
            )
        return ids

    def gather(self, name: str, ids: np.ndarray) -> np.ndarray:
        """Rows of field `name` for `ids`, as a fresh `[len(ids), ...]`
        array (never a view into the store — the caller device_puts and
        possibly donates it)."""
        ids = self._check_ids(ids)
        fill = self._fills[name]
        out = np.empty((ids.size,) + fill.shape, fill.dtype)
        for pos, vid in enumerate(ids):
            cid = self._chunk_of(vid)
            chunk = self._chunks.get(cid)
            if chunk is None or name not in chunk:
                out[pos] = fill
            else:
                out[pos] = chunk[name][int(vid) - cid * self.chunk_clients]
        return out

    def scatter(self, name: str, ids: np.ndarray, rows: np.ndarray) -> None:
        """Write `rows[i]` into client `ids[i]`'s slot of field `name`,
        materializing (init-filled) chunks as needed and marking every
        touched chunk dirty for the next `save`."""
        ids = self._check_ids(ids)
        rows = np.asarray(rows)
        fill = self._fills[name]
        if rows.shape != (ids.size,) + fill.shape:
            raise ValueError(
                f"scatter of field {name!r}: rows shape {rows.shape} != "
                f"{(ids.size,) + fill.shape}"
            )
        if rows.dtype != fill.dtype:
            raise ValueError(
                f"scatter of field {name!r}: dtype {rows.dtype} != "
                f"registered {fill.dtype} (an implicit cast here would "
                "silently change restored state)"
            )
        for pos, vid in enumerate(ids):
            cid = self._chunk_of(vid)
            chunk = self._chunks.setdefault(cid, {})
            if name not in chunk:
                chunk[name] = np.broadcast_to(
                    fill, (self._chunk_rows(cid),) + fill.shape
                ).copy()
            chunk[name][int(vid) - cid * self.chunk_clients] = rows[pos]
            self._dirty.add(cid)

    def touched_chunks(self, ids: np.ndarray) -> set:
        """Chunk ids a scatter of `ids` dirties (the O(C) bound of one
        loop's checkpoint delta: ≤ len(ids) chunks + the manifest)."""
        return {self._chunk_of(v) for v in self._check_ids(ids)}

    # --------------------------------------------------------- checkpointing

    # manifests retained per save: the newest one plus enough history to
    # cover the crash window between a store save and its checkpoint's
    # orbax commit (resume then falls back exactly one step). Retaining
    # N manifests bounds disk at O(population touched) + N*O(C) chunk
    # versions; without pruning, every superseded chunk version would
    # stay referenced by some historical manifest forever.
    keep_manifests: int = 2

    def save(self, directory: str, step: int) -> str:
        """Write the dirty chunks + the step manifest; return its path.

        Called by `Trainer.save` BEFORE the orbax checkpoint of the same
        step is committed: a crash between the two leaves this manifest
        dangling (no checkpoint names it), and resume falls back to the
        previous checkpoint + its manifest — both still intact, because
        chunk files are versioned (`chunk_<cid>_v<seq>.npz`), never
        overwritten in place. After the manifest commit, manifests older
        than the newest `keep_manifests` are pruned and chunk files no
        retained manifest references (superseded versions, crashed-save
        orphans, stale `.tmp_` staging files) are garbage-collected —
        resume therefore reaches the newest `keep_manifests` snapshots;
        falling back further (multiple consecutive torn checkpoints)
        fails loudly in `load` rather than restoring silently-wrong
        rows.
        """
        root = os.path.abspath(os.path.join(directory, "client_store"))
        os.makedirs(root, exist_ok=True)
        for cid in sorted(self._dirty):
            self._seq += 1
            fname = f"chunk_{cid:06d}_v{self._seq:08d}.npz"
            tmp = os.path.join(root, f".tmp_{fname}")
            with open(tmp, "wb") as f:
                np.savez(f, **self._chunks[cid])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(root, fname))
            self._files[cid] = fname
        self._dirty.clear()
        manifest = {
            "version": _MANIFEST_VERSION,
            "step": int(step),
            "n_virtual": self.n_virtual,
            "chunk_clients": self.chunk_clients,
            "seq": self._seq,
            "chunks": {str(c): f for c, f in sorted(self._files.items())},
            "fields": {
                name: {
                    "shape": list(row.shape),
                    "dtype": str(row.dtype),
                }
                for name, row in sorted(self._fills.items())
            },
        }
        path = _manifest_path(root, step)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._gc(root)
        return path

    def _gc(self, root: str) -> None:
        """Prune old manifests, then delete unreferenced files.

        Best-effort: any OS error leaves files behind for the next save
        to reclaim, never fails the checkpoint. A torn (unparseable)
        retained manifest aborts chunk GC entirely — its references are
        unknowable, and deleting a chunk it might name would turn a
        recoverable situation into data loss.
        """
        def is_manifest(entry: str) -> bool:
            # committed manifests only: a crashed writer's staging file
            # (`manifest_step_N.json.tmp`) is never authoritative — it
            # is deleted below, not parsed, so it can't wedge GC forever
            return entry.startswith("manifest_step_") and entry.endswith(
                ".json"
            )

        steps = []
        for entry in os.listdir(root):
            if is_manifest(entry):
                try:
                    steps.append(int(entry[len("manifest_step_"):-5]))
                except ValueError:
                    continue
        for s in sorted(steps)[: -self.keep_manifests]:
            try:
                os.remove(_manifest_path(root, s))
            except OSError:
                pass
        referenced = set()
        for entry in os.listdir(root):
            if not is_manifest(entry):
                continue
            try:
                with open(os.path.join(root, entry)) as f:
                    referenced.update(json.load(f).get("chunks", {}).values())
            except (OSError, ValueError):
                return  # torn retained manifest: references unknowable
        for entry in os.listdir(root):
            stale = entry.startswith("chunk_") and entry not in referenced
            if stale or entry.startswith(".tmp_") or entry.endswith(
                ".json.tmp"
            ):
                try:
                    os.remove(os.path.join(root, entry))
                except OSError:
                    pass

    def load(self, directory: str, step: int) -> None:
        """Restore the snapshot `save(directory, step)` committed.

        Chunks named by the manifest are loaded; everything else reverts
        to pristine. Field fills are NOT restored from disk — the caller
        re-registers them from the same deterministic init it built them
        with (common-seed model init), and the manifest's recorded
        shapes/dtypes are cross-checked against that registration so a
        config drift (different model, different rho shape) fails loudly
        instead of broadcasting the wrong fill under restored chunks.
        """
        root = os.path.abspath(os.path.join(directory, "client_store"))
        path = _manifest_path(root, step)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no client-store manifest for step {step} under {root} "
                "(the checkpoint was written without cohort mode, or the "
                "store snapshot was deleted)"
            )
        with open(path) as f:
            manifest = json.load(f)
        if manifest.get("version") != _MANIFEST_VERSION:
            raise ValueError(
                f"client-store manifest version {manifest.get('version')} "
                f"!= supported {_MANIFEST_VERSION}"
            )
        for key, mine in (
            ("n_virtual", self.n_virtual),
            ("chunk_clients", self.chunk_clients),
        ):
            if int(manifest[key]) != mine:
                raise ValueError(
                    f"client-store manifest {key}={manifest[key]} but this "
                    f"run configured {mine}: the snapshot indexes a "
                    "different virtual population and cannot be restored "
                    "onto it"
                )
        for name, meta in manifest.get("fields", {}).items():
            if name in self._fills:
                row = self._fills[name]
                if (
                    list(row.shape) != list(meta["shape"])
                    or str(row.dtype) != meta["dtype"]
                ):
                    raise ValueError(
                        f"client-store field {name!r} was saved with "
                        f"shape {meta['shape']} dtype {meta['dtype']} but "
                        f"this run registered shape {list(row.shape)} "
                        f"dtype {row.dtype}"
                    )
        self._chunks.clear()
        self._dirty.clear()
        self._files = {
            int(c): fname for c, fname in manifest["chunks"].items()
        }
        self._seq = int(manifest.get("seq", 0))
        self._saved_fields = dict(manifest.get("fields", {}))
        for cid, fname in self._files.items():
            with np.load(os.path.join(root, fname)) as z:
                self._chunks[cid] = {k: z[k] for k in z.files}

    # ------------------------------------------------------------- summary

    def materialized_chunks(self) -> int:
        return len(self._chunks)

    def summary(self) -> dict:
        """Small host-memory/occupancy digest for the end-of-run log."""
        rows = sum(
            next(iter(c.values())).shape[0] if c else 0
            for c in self._chunks.values()
        )
        nbytes = sum(
            a.nbytes for c in self._chunks.values() for a in c.values()
        )
        return {
            "n_virtual": self.n_virtual,
            "chunk_clients": self.chunk_clients,
            "chunks_total": -(-self.n_virtual // self.chunk_clients),
            "chunks_materialized": len(self._chunks),
            "rows_materialized": int(rows),
            "host_bytes": int(nbytes),
            "fields": list(self.fields),
        }
