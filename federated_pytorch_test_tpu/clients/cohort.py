"""Seeded, replayable cohort sampling over a virtual-client population.

Cross-*device* federated learning is partial participation by
construction: a server holds state for N mostly-idle virtual clients and
each round only a small cohort of C actually trains — TAMUNA
(arXiv:2302.09832) is the algorithmic anchor for this regime, and FedADMM
(arXiv:2204.03529) shows the ADMM consensus the engine already runs
tolerates exactly this kind of partial, heterogeneous participation (the
fault layer's participation masks supply the aggregation-under-absence
semantics).

A `CohortSampler` is the *schedule* of that participation and nothing
else, designed with the same purity contract as `fault.FaultPlan`: the
cohort of outer loop `nloop` is a pure function of `(seed, nloop)` alone
— no execution history, no RNG object threaded across calls — so a
crashed-and-resumed run re-derives every historical cohort exactly, the
trainer's resume path can reconstruct skipped loops' communication
totals, and fused/unfused/restarted runs all train the identical cohort
sequence. The sampler claims the "cohort" slot of the shared seed-fold
registry (fault/plan.py SEED_FOLDS): even an operator who points
`--cohort-seed` and the fault plan's seed at the same value gets
independent cohort and dropout draws.

Cohort SLOT ORDER is ascending virtual-client id. The engine's compiled
round program is slot-indexed (a `[C]`-leading client axis sharded over
the mesh — parallel/mesh.py), so some canonical id→slot order is needed;
ascending order makes gather/scatter locality best-case for the chunked
store and keeps the mapping independent of the draw algorithm.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from federated_pytorch_test_tpu.fault.plan import fold_seed

WEIGHTINGS = ("uniform", "samples", "identity")


class CohortSampler:
    """Draw the cohort of each outer loop, purely in `(seed, nloop)`.

    * `uniform`  — C of N without replacement, equal probability;
    * `samples`  — C of N without replacement, probability proportional
      to each virtual client's sample count (clients holding more data
      are seen more often — the weighting FedAvg's convergence analysis
      assumes when shards are unbalanced);
    * `identity` — the degenerate full-participation schedule
      (requires C == N): every loop trains `arange(N)`. This is the
      bitwise bridge to the pre-cohort engine — N=K, C=K, identity
      reproduces the legacy every-client-every-round trajectory exactly
      (tests/test_clients.py).
    """

    def __init__(
        self,
        n_virtual: int,
        cohort: int,
        seed: int = 0,
        weighting: str = "uniform",
        sample_counts: Optional[np.ndarray] = None,
    ):
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        if not 1 <= cohort <= n_virtual:
            raise ValueError(
                f"cohort must be in [1, n_virtual={n_virtual}], got {cohort}"
            )
        if weighting not in WEIGHTINGS:
            raise ValueError(
                f"weighting must be one of {WEIGHTINGS}, got {weighting!r}"
            )
        if weighting == "identity" and cohort != n_virtual:
            raise ValueError(
                "identity weighting is full participation: cohort "
                f"({cohort}) must equal n_virtual ({n_virtual})"
            )
        self.n_virtual = int(n_virtual)
        self.cohort_size = int(cohort)
        self.seed = int(seed)
        self.weighting = weighting
        self._p = None
        if weighting == "samples":
            if sample_counts is None:
                raise ValueError(
                    "weighting='samples' needs per-virtual-client "
                    "sample_counts"
                )
            counts = np.asarray(sample_counts, np.float64).reshape(-1)
            if counts.shape[0] != n_virtual:
                raise ValueError(
                    f"sample_counts has {counts.shape[0]} entries for "
                    f"n_virtual={n_virtual}"
                )
            if not (np.isfinite(counts).all() and (counts > 0).all()):
                raise ValueError(
                    "sample_counts must be finite and positive (a "
                    "zero-sample client could never be drawn, which is a "
                    "store-construction bug, not a sampling policy)"
                )
            self._p = counts / counts.sum()

    def _rng(self, nloop: int) -> np.random.Generator:
        # the reserved "cohort" fold of the shared registry — see module
        # docstring; same SeedSequence style as FaultPlan._rng
        return np.random.default_rng([fold_seed(self.seed, "cohort"), nloop])

    def cohort(self, nloop: int) -> np.ndarray:
        """`[C]` int64 virtual-client ids of outer loop `nloop`, ascending.

        Pure in `(seed, nloop)`: two calls — in different processes,
        before and after a crash, with any interleaving — return the
        identical array. The last loop's draw is memoized (purity makes
        the cache transparent): the trainer re-derives the cohort at
        every fault-schedule projection of the loop. Callers must treat
        the returned array as read-only.
        """
        cached = getattr(self, "_memo", None)
        if cached is not None and cached[0] == nloop:
            return cached[1]
        ids = self._draw(nloop)
        self._memo = (nloop, ids)
        return ids

    def _draw(self, nloop: int) -> np.ndarray:
        if self.weighting == "identity":
            return np.arange(self.n_virtual, dtype=np.int64)
        rng = self._rng(nloop)
        ids = rng.choice(
            self.n_virtual,
            size=self.cohort_size,
            replace=False,
            p=self._p,
            # the default (True) would permute all N ids per draw; at
            # N ≫ C that is the sampler's whole cost. Floyd's algorithm
            # draws C of N in O(C). Selection DISTRIBUTION per id is
            # unchanged for uniform draws; the draw order differs, which
            # the ascending slot order erases anyway.
            shuffle=False,
        )
        return np.sort(ids.astype(np.int64))

    def participation_counts(self, nloops: int) -> np.ndarray:
        """`[N]` int64: how often each virtual client was sampled over
        `nloops` outer loops — pure in (seed, nloops), so a resumed run
        reports the same end-of-run participation summary as an
        uninterrupted one (engine/trainer.py logs it as the
        `cohort_participation` record)."""
        counts = np.zeros(self.n_virtual, np.int64)
        for nloop in range(nloops):
            counts[self.cohort(nloop)] += 1
        return counts
