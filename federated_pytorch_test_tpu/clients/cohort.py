"""Seeded, replayable cohort sampling over a virtual-client population.

Cross-*device* federated learning is partial participation by
construction: a server holds state for N mostly-idle virtual clients and
each round only a small cohort of C actually trains — TAMUNA
(arXiv:2302.09832) is the algorithmic anchor for this regime, and FedADMM
(arXiv:2204.03529) shows the ADMM consensus the engine already runs
tolerates exactly this kind of partial, heterogeneous participation (the
fault layer's participation masks supply the aggregation-under-absence
semantics).

A `CohortSampler` is the *schedule* of that participation and nothing
else, designed with the same purity contract as `fault.FaultPlan`: the
cohort of outer loop `nloop` is a pure function of `(seed, nloop)` and
— for the closed-loop pieces — the RECORDED history alone: the churn
axis's availability pool is pure in the fault plan's seed
(fault/plan.py `availability`), and the 'telemetry' weighting reads
per-virtual-client reliability state whose every update is committed
with the loop that produced it (engine/trainer.py, docs/SCALE.md). No
RNG object is threaded across calls, every draw lands in a per-loop
history (checkpointed by the trainer), and so a crashed-and-resumed run
re-derives — or replays — every historical cohort exactly: the
trainer's resume path can reconstruct skipped loops' communication
totals, and fused/unfused/restarted runs all train the identical cohort
sequence. The sampler claims the "cohort" slot of the shared seed-fold
registry (fault/plan.py SEED_FOLDS): even an operator who points
`--cohort-seed` and the fault plan's seed at the same value gets
independent cohort and dropout draws.

Cohort SLOT ORDER is ascending virtual-client id. The engine's compiled
round program is slot-indexed (a `[C]`-leading client axis sharded over
the mesh — parallel/mesh.py), so some canonical id→slot order is needed;
ascending order makes gather/scatter locality best-case for the chunked
store and keeps the mapping independent of the draw algorithm.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from federated_pytorch_test_tpu.fault.plan import fold_seed

WEIGHTINGS = ("uniform", "samples", "identity", "telemetry")


class CohortSampler:
    """Draw the cohort of each outer loop, purely in `(seed, nloop,
    recorded history)`.

    * `uniform`  — C of N without replacement, equal probability;
    * `samples`  — C of N without replacement, probability proportional
      to each virtual client's sample count (clients holding more data
      are seen more often — the weighting FedAvg's convergence analysis
      assumes when shards are unbalanced);
    * `identity` — the degenerate full-participation schedule
      (requires C == N): every loop trains `arange(N)`. This is the
      bitwise bridge to the pre-cohort engine — N=K, C=K, identity
      reproduces the legacy every-client-every-round trajectory exactly
      (tests/test_clients.py);
    * `telemetry` — probability from OBSERVED per-virtual-client
      reliability (`telemetry_weights`: a provider returning `[N]`
      positive weights from the client store's accumulated speed /
      deadline-miss / dropout / quarantine history — engine/trainer.py
      `_telemetry_weights`). History-dependent by design: the draw of
      loop `nloop` is pure given the committed history through loop
      `nloop - 1`, and the trainer checkpoints the draw history so a
      resumed run REPLAYS past cohorts (`seed_history`) instead of
      re-drawing them from restored state.

    `availability` (optional) is the churn hook (fault/plan.py): a
    callable `nloop -> [N] mask or None` restricting each loop's draw
    to the available pool. When fewer than C clients are available, the
    whole pool trains and the REMAINDER is recalled from the absent
    pool by the same loop rng — the compiled client axis is static, so
    a short cohort is not an option, and a deterministic recall keeps
    the schedule pure.
    """

    def __init__(
        self,
        n_virtual: int,
        cohort: int,
        seed: int = 0,
        weighting: str = "uniform",
        sample_counts: Optional[np.ndarray] = None,
        telemetry_weights=None,
        availability=None,
    ):
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        if not 1 <= cohort <= n_virtual:
            raise ValueError(
                f"cohort must be in [1, n_virtual={n_virtual}], got {cohort}"
            )
        if weighting not in WEIGHTINGS:
            raise ValueError(
                f"weighting must be one of {WEIGHTINGS}, got {weighting!r}"
            )
        if weighting == "identity" and cohort != n_virtual:
            raise ValueError(
                "identity weighting is full participation: cohort "
                f"({cohort}) must equal n_virtual ({n_virtual})"
            )
        if weighting == "telemetry" and telemetry_weights is None:
            raise ValueError(
                "weighting='telemetry' needs a telemetry_weights "
                "provider (per-virtual-client reliability state)"
            )
        if weighting == "identity" and availability is not None:
            # tolerated only as a no-op hook: the trainer passes its
            # lazy availability closure unconditionally, and identity
            # runs never schedule churn (engine/trainer.py rejects the
            # combination) — a RESTRICTED identity draw would be a
            # contradiction, caught at draw time below
            pass
        self.n_virtual = int(n_virtual)
        self.cohort_size = int(cohort)
        self.seed = int(seed)
        self.weighting = weighting
        self._telemetry_weights = telemetry_weights
        self._availability = availability
        # nloop -> [C] draw history: a transparent cache for the pure
        # weightings (re-derivation matches), the REPLAY substrate for
        # the history-dependent one (trainer checkpoints + re-seeds it)
        self._history: dict = {}
        self._p = None
        if weighting == "samples":
            if sample_counts is None:
                raise ValueError(
                    "weighting='samples' needs per-virtual-client "
                    "sample_counts"
                )
            counts = np.asarray(sample_counts, np.float64).reshape(-1)
            if counts.shape[0] != n_virtual:
                raise ValueError(
                    f"sample_counts has {counts.shape[0]} entries for "
                    f"n_virtual={n_virtual}"
                )
            if not (np.isfinite(counts).all() and (counts > 0).all()):
                raise ValueError(
                    "sample_counts must be finite and positive (a "
                    "zero-sample client could never be drawn, which is a "
                    "store-construction bug, not a sampling policy)"
                )
            self._p = counts / counts.sum()

    def _rng(self, nloop: int) -> np.random.Generator:
        # the reserved "cohort" fold of the shared registry — see module
        # docstring; same SeedSequence style as FaultPlan._rng
        return np.random.default_rng([fold_seed(self.seed, "cohort"), nloop])

    def cohort(self, nloop: int) -> np.ndarray:
        """`[C]` int64 virtual-client ids of outer loop `nloop`, ascending.

        For the pure weightings, two calls — in different processes,
        before and after a crash, with any interleaving — return the
        identical array; the per-loop history is a transparent cache
        (the trainer re-derives the cohort at every fault-schedule
        projection of the loop). For 'telemetry' the first call of a
        loop IS the draw (from the reliability state as of that
        moment); later calls replay it from history — which resume
        re-seeds from the checkpoint (`seed_history`), never re-draws.
        Callers must treat the returned array as read-only.
        """
        cached = self._history.get(int(nloop))
        if cached is not None:
            return cached
        ids = self._draw(nloop)
        self._history[int(nloop)] = ids
        return ids

    def seed_history(self, nloop: int, ids) -> None:
        """Install a checkpointed draw for loop `nloop` (resume path):
        history-dependent weightings must REPLAY completed loops'
        cohorts, not re-draw them from restored state."""
        ids = np.sort(np.asarray(ids, np.int64).reshape(-1))
        if ids.shape[0] != self.cohort_size:
            raise ValueError(
                f"seeded cohort for loop {nloop} has {ids.shape[0]} "
                f"members, expected {self.cohort_size}"
            )
        self._history[int(nloop)] = ids

    def _weights(self) -> Optional[np.ndarray]:
        """The draw's `[N]` probability vector (summing to 1), or None
        for uniform draws."""
        if self.weighting == "samples":
            return self._p
        if self.weighting == "telemetry":
            w = np.asarray(
                self._telemetry_weights(), np.float64
            ).reshape(-1)
            if w.shape[0] != self.n_virtual or not (
                np.isfinite(w).all() and (w > 0).all()
            ):
                raise ValueError(
                    "telemetry_weights must return [n_virtual] finite "
                    "positive weights (a zero weight would starve a "
                    "client forever on early evidence)"
                )
            return w / w.sum()
        return None

    def draw_weights(self, nloop: int):
        """The normalized `[N]` probability vector loop `nloop`'s draw
        used (None for uniform draws) — memoized by the draw itself, so
        the trainer's `cohort_weight` record costs no second
        full-population telemetry gather. Only valid for the most
        recent draw (history-replayed loops never re-derive weights)."""
        cached = getattr(self, "_last_weights", None)
        if cached is not None and cached[0] == int(nloop):
            return cached[1]
        return self._weights()

    def _draw(self, nloop: int) -> np.ndarray:
        avail = (
            self._availability(nloop)
            if self._availability is not None
            else None
        )
        if avail is not None:
            avail = np.asarray(avail).reshape(-1) > 0
            if avail.shape[0] != self.n_virtual:
                raise ValueError(
                    f"availability mask has {avail.shape[0]} entries "
                    f"for n_virtual={self.n_virtual}"
                )
            if avail.all():
                avail = None  # unrestricted pool: the common case
        if self.weighting == "identity":
            if avail is not None:
                raise ValueError(
                    "identity weighting (full participation) cannot "
                    "draw from a churned pool"
                )
            return np.arange(self.n_virtual, dtype=np.int64)
        rng = self._rng(nloop)
        p = self._weights()
        self._last_weights = (int(nloop), p)

        def choice(pool: np.ndarray, size: int) -> np.ndarray:
            pp = None
            if p is not None:
                if pool.shape[0] == self.n_virtual:
                    pp = p  # full pool: skip the renormalization (its
                    # float division would perturb the legacy draws)
                else:
                    pp = p[pool]
                    pp = pp / pp.sum()
            return pool[
                rng.choice(
                    pool.shape[0],
                    size=size,
                    replace=False,
                    p=pp,
                    # the default (True) would permute the whole pool
                    # per draw; at N ≫ C that is the sampler's whole
                    # cost. Floyd's algorithm draws C of N in O(C).
                    # Selection DISTRIBUTION per id is unchanged for
                    # uniform draws; the draw order differs, which the
                    # ascending slot order erases anyway.
                    shuffle=False,
                )
            ]

        if avail is None:
            ids = choice(
                np.arange(self.n_virtual, dtype=np.int64),
                self.cohort_size,
            )
        else:
            pool = np.nonzero(avail)[0]
            if pool.shape[0] >= self.cohort_size:
                ids = choice(pool, self.cohort_size)
            else:
                # RECALL rule (docstring): the whole available pool
                # trains, and the remainder is drawn from the absent
                # pool by the same loop rng — deterministic, and the
                # compiled client axis keeps its static width
                absent = np.nonzero(~avail)[0]
                extra = choice(
                    absent, self.cohort_size - pool.shape[0]
                )
                ids = np.concatenate([pool, extra])
        return np.sort(ids.astype(np.int64))

    def participation_counts(self, nloops: int) -> np.ndarray:
        """`[N]` int64: how often each virtual client was sampled over
        `nloops` outer loops — pure in (seed, nloops), so a resumed run
        reports the same end-of-run participation summary as an
        uninterrupted one (engine/trainer.py logs it as the
        `cohort_participation` record)."""
        counts = np.zeros(self.n_virtual, np.int64)
        for nloop in range(nloops):
            counts[self.cohort(nloop)] += 1
        return counts
