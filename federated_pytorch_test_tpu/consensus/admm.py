"""ADMM consensus with optional Barzilai-Borwein adaptive penalty.

The reference's three-step ADMM (src/consensus_admm_trio.py:375-513):

  x-update: each client minimizes `loss + y·(x−z) + ρ/2‖x−z‖²` with the
            inner L-BFGS (closures :343-373) — here `admm_penalty` is the
            augmented-Lagrangian term added to the per-client loss;
  z-update: `znew = Σ_k (y_k + ρ_k x_k) / Σ_k ρ_k` (:502) — a weighted
            psum over the clients axis;
  y-update: `y_k += ρ_k (x_k − znew)` (:511-513).

Residuals (:503,514): dual `‖z − znew‖/N`, primal `Σ_k ‖x_k − znew‖/(K·N)`.

The BB spectral penalty adaptation (src/consensus_admm_trio.py:399-498,
hyper-params :37-44) runs every `bb_period` ADMM iterations (not the
first): with `ŷ = y + ρ(x−z)` (OLD rho), `Δy = ŷ − ŷ⁰`, `Δx = x − x⁰`,
inner products d11=Δy·Δy, d12=Δy·Δx, d22=Δx·Δx gate the update
(all > ε, |d12| > ε); the correlation `α = d12/√(d11·d22)`, steepest-
descent `αSD = d11/d12` and minimum-gradient `αMG = d12/d22` steps combine
into the hybrid `α̂ = αMG if 2αMG > αSD else αSD − αMG/2`, accepted iff
`α ≥ corr_min ∧ α̂ < ρ_max`. The z-update then uses the NEW rho while ŷ
was formed with the old one — reference ordering (:407 before :502),
preserved. Reference quirks kept: `ŷ⁰` initializes to the partition's
starting parameter values, not zeros (:299-302); `x⁰` and `ŷ⁰` are
(re)stored at nadmm==0 and at every DUE BB step — whether or not the
proposal was accepted (:401-405,494-498).

Everything is per-client elementwise math except the z-update's weighted
psum, so the whole round is one SPMD function over the local client block.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.consensus.penalties import soft_threshold
from federated_pytorch_test_tpu.consensus.robust import robust_combine
from federated_pytorch_test_tpu.parallel import client_count, client_sum, weighted_client_mean


@dataclasses.dataclass(frozen=True)
class ADMMConfig:
    """Hyper-parameters (reference src/consensus_admm_trio.py:23,37-44)."""

    rho0: float = 0.001
    bb_update: bool = False
    bb_period: int = 2
    bb_alphacorrmin: float = 0.2
    bb_epsilon: float = 1e-3
    bb_rhomax: float = 0.1
    # elastic-net consensus: soft-threshold znew with this value (> 0
    # enables). The reference ships this disabled (commented out,
    # src/consensus_admm_trio_resnet.py:416-419) but keeps the
    # `sthreshold` helper; here it is a first-class option.
    z_soft_threshold: float = 0.0


class ADMMState(NamedTuple):
    y: jnp.ndarray  # [K_loc, N] scaled duals, client-local
    z: jnp.ndarray  # [N] consensus vector, replicated
    rho: jnp.ndarray  # [K_loc, 1] per-client penalty
    yhat0: jnp.ndarray  # [K_loc, N] BB: previous y-hat
    x0: jnp.ndarray  # [K_loc, N] BB: previous x


def admm_init(x_local: jnp.ndarray, config: ADMMConfig) -> ADMMState:
    """Fresh per-partition state from the group's starting coordinates.

    y and z start at zero (reference src/consensus_admm_trio.py:281-288);
    ŷ⁰ starts at the current parameter values (:299-302, quirk preserved).

    Per-client leaves are derived from `x_local` (zeros as `x*0`) so that,
    under `shard_map`, they carry the client axis's varying-manual-axes tag
    and a `lax.scan` over `admm_round` has matching carry types; `z` is a
    plain constant, matching the axis-invariant output of the z-update's
    psum.
    """
    n = x_local.shape[-1]
    zero = x_local * 0
    return ADMMState(
        y=zero,
        z=jnp.zeros((n,), x_local.dtype),
        rho=zero[:, :1] + jnp.asarray(config.rho0, x_local.dtype),
        yhat0=x_local,
        x0=zero,
    )


def admm_penalty(
    x: jnp.ndarray, y: jnp.ndarray, z: jnp.ndarray, rho: jnp.ndarray
) -> jnp.ndarray:
    """Augmented-Lagrangian term `y·(x−z) + ρ/2·‖x−z‖²` for ONE client.

    Added to the client's data loss inside the x-update closure (reference
    src/consensus_admm_trio.py:343). vmap over the local client block.
    """
    diff = x - z
    return jnp.dot(y, diff) + 0.5 * jnp.squeeze(rho) * jnp.dot(diff, diff)


def _bb_new_rho(
    rho: jnp.ndarray,
    yhat: jnp.ndarray,
    yhat0: jnp.ndarray,
    x: jnp.ndarray,
    x0: jnp.ndarray,
    config: ADMMConfig,
) -> jnp.ndarray:
    """One client's BB spectral rho proposal (reference
    src/consensus_admm_trio.py:407-429). All branches are computed with
    safe denominators and selected by masks (XLA evaluates both sides of a
    `where`)."""
    dy = yhat - yhat0
    dx = x - x0
    d11 = jnp.dot(dy, dy)
    d12 = jnp.dot(dy, dx)  # can be negative
    d22 = jnp.dot(dx, dx)
    eps = config.bb_epsilon
    well_posed = (jnp.abs(d12) > eps) & (d11 > eps) & (d22 > eps)

    d12s = jnp.where(jnp.abs(d12) > eps, d12, 1.0)
    prod = jnp.where(well_posed, d11 * d22, 1.0)
    alpha = d12s / jnp.sqrt(prod)
    alpha_sd = d11 / d12s
    alpha_mg = d12s / jnp.where(d22 > eps, d22, 1.0)
    alpha_hat = jnp.where(2.0 * alpha_mg > alpha_sd, alpha_mg, alpha_sd - 0.5 * alpha_mg)

    accept = well_posed & (alpha >= config.bb_alphacorrmin) & (alpha_hat < config.bb_rhomax)
    return jnp.where(accept, alpha_hat, jnp.squeeze(rho))[None]


class ADMMMetrics(NamedTuple):
    primal_residual: jnp.ndarray
    dual_residual: jnp.ndarray
    mean_rho: jnp.ndarray
    survivors: jnp.ndarray


def admm_round(
    x_local: jnp.ndarray,
    state: ADMMState,
    nadmm: jnp.ndarray,
    config: ADMMConfig,
    mask: Optional[jnp.ndarray] = None,
    x_agg: Optional[jnp.ndarray] = None,
    combine: str = "mean",
    robust_f: int = 0,
) -> Tuple[ADMMState, ADMMMetrics]:
    """BB adaptation (if due) + z-update + y-update for one ADMM iteration.

    `x_local` is the local client block `[K_loc, N]` after the x-update
    (the inner L-BFGS round); `nadmm` is the (traced) ADMM iteration index
    within the current partition round.

    `mask` is the `[K_loc]` participation vector (1 = this client's
    x-update arrived, 0 = dropped; fault/plan.py). A dropped client's
    contribution is excluded from the z-update's weighted psum, its dual
    y and BB carry stores (rho, x0, yhat0) are frozen — its x never
    arrived, so adapting against it would adapt against stale state — and
    the primal residual averages over survivors only. A degenerate
    all-dropped round keeps z (and every y) unchanged. With the all-ones
    mask every select picks the unmasked operand and every product is a
    multiplication by 1.0, so the result is BIT-IDENTICAL to the unmasked
    path (tests/test_fault.py).

    `x_agg` is the aggregation's VIEW of each client's x — what the
    exchange received, which under an injected corruption fault differs
    from what the client holds (fault/plan.py: corruption is in transit).
    Only the z-update consumes it; the client-local math (BB adaptation,
    y-update, primal residual) keeps the true `x_local` — a Byzantine
    client lies to the server, not to itself. Defaults to `x_local`
    (identical graph, so clean runs are untouched).

    `combine` selects the z-update: 'mean' (the reference's ρ-weighted
    psum, untouched) or a robust order statistic over `v = y/ρ + x`
    ('median' / 'trimmed' with `robust_f` per side / 'clip';
    consensus/robust.py — unweighted across survivors, a documented
    deviation from the ρ-weighting).
    """
    n = x_local.shape[-1]
    k = client_count(x_local)
    if mask is None:
        part = None
        survivors = k
    else:
        part = mask.astype(x_local.dtype)[:, None] > 0  # [K_loc, 1] bool
        survivors = client_sum(mask.astype(x_local.dtype))

    if config.bb_update:
        is_first = nadmm == 0
        due = (nadmm > 0) & (nadmm % config.bb_period == 0)
        yhat = state.y + state.rho * (x_local - state.z)  # OLD rho
        rho_prop = jax.vmap(_bb_new_rho, in_axes=(0, 0, 0, 0, 0, None))(
            state.rho, yhat, state.yhat0, x_local, state.x0, config
        )
        if part is not None:
            due_k = due & part  # dropped clients freeze their BB state
            first_k = is_first | due_k
        else:
            due_k, first_k = due, is_first | due
        rho = jnp.where(due_k, rho_prop, state.rho)
        x0 = jnp.where(first_k, x_local, state.x0)
        yhat0 = jnp.where(due_k, yhat, state.yhat0)
    else:
        rho, x0, yhat0 = state.rho, state.x0, state.yhat0

    # z-update: weighted mean with v = y/rho + x, w = rho so that
    # sum(v*w)/sum(w) == sum(y + rho*x)/sum(rho) (reference :502); under a
    # mask the weight becomes rho*m — surviving clients only. The update
    # entering the exchange is the RECEIVED one (x_agg — corrupted in
    # transit under a corruption fault); everything client-local above
    # and below uses the true x_local.
    xz = x_local if x_agg is None else x_agg
    if combine == "mean":
        if part is None:
            znew = weighted_client_mean(state.y / rho + xz, rho)
        else:
            w = rho * part.astype(x_local.dtype)
            num = client_sum((state.y / rho + xz) * w)
            den = client_sum(w)
            znew = num / jnp.where(den > 0, den, 1.0)
    else:
        m = (
            mask
            if mask is not None
            else jnp.ones((x_local.shape[0],), x_local.dtype)
        ).astype(x_local.dtype)
        znew, usable = robust_combine(
            state.y / rho + xz, m, combine, trim_f=robust_f, prev=state.z
        )
    if config.z_soft_threshold > 0.0:
        znew = soft_threshold(znew, config.z_soft_threshold)
    if combine != "mean":
        # per-coordinate keep-previous AFTER the soft threshold — an
        # unusable coordinate keeps z exactly (consensus/robust.py)
        znew = jnp.where(usable, znew, state.z)
    if part is not None or combine != "mean":
        znew = jnp.where(survivors > 0, znew, state.z)
    dual = jnp.linalg.norm(state.z - znew) / n

    # y-update (reference :511-513); dropped clients keep their duals —
    # they neither saw znew nor contributed an x
    if part is None:
        y = state.y + rho * (x_local - znew)
    else:
        y = jnp.where(part, state.y + rho * (x_local - znew), state.y)

    if part is None:
        primal = client_sum(jnp.linalg.norm(x_local - znew, axis=-1)) / (k * n)
    else:
        resid = jnp.linalg.norm(x_local - znew, axis=-1)
        primal = client_sum(mask.astype(x_local.dtype) * resid) / (
            jnp.where(survivors > 0, survivors, 1.0) * n
        )
    mean_rho = client_sum(jnp.sum(rho, axis=-1)) / k

    new_state = ADMMState(y=y, z=znew, rho=rho, yhat0=yhat0, x0=x0)
    return new_state, ADMMMetrics(primal, dual, mean_rho, survivors)
