"""Regularization helpers: elastic net on linear partitions, soft threshold.

Reference src/federated_trio.py:303-333 adds
`λ1‖v‖₁ + λ2‖v‖₂²` to the loss when the active partition is a linear
layer (`ci in net.linear_layer_ids()`); `sthreshold` (reference
src/federated_trio.py:188-196, a torch Softshrink) is the proximal
operator kept for the commented-out elastic-net z-update variant
(reference src/consensus_admm_trio_resnet.py:416-419).
"""

from __future__ import annotations

import jax.numpy as jnp


def elastic_net(v: jnp.ndarray, lambda1: float, lambda2: float) -> jnp.ndarray:
    """`λ1‖v‖₁ + λ2‖v‖₂²` (reference src/federated_trio.py:309-310)."""
    return lambda1 * jnp.sum(jnp.abs(v)) + lambda2 * jnp.sum(v * v)


def soft_threshold(z: jnp.ndarray, sval: float) -> jnp.ndarray:
    """Soft shrinkage `sign(z)·max(|z|−sval, 0)` (reference
    src/federated_trio.py:188-196)."""
    return jnp.sign(z) * jnp.maximum(jnp.abs(z) - sval, 0.0)
