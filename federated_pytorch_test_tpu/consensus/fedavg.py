"""Partial-parameter federated averaging.

Reference src/federated_trio.py:353-363: after each inner-optimization
round, the active partition group's coordinates are averaged across
clients, `znew = (x_1 + x_2 + x_3)/3`, the dual residual `‖z − znew‖/N` is
reported (z starts at 0, so the first residual is just `‖znew‖/N` — a
reference quirk preserved here), and znew is broadcast back into every
client's network.

SPMD form: `fedavg_round` runs inside `shard_map`; the average is one
`psum` over the clients axis on the masked group vector, and the returned
`z` is replicated, so "broadcast back" is a local `Partition.insert`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from federated_pytorch_test_tpu.consensus.penalties import soft_threshold
from federated_pytorch_test_tpu.consensus.robust import robust_combine
from federated_pytorch_test_tpu.parallel import client_count, client_mean, client_sum


class FedAvgState(NamedTuple):
    z: jnp.ndarray  # [N] consensus vector, replicated across devices


def fedavg_init(n: int, dtype=jnp.float32) -> FedAvgState:
    """z starts at zero (reference src/federated_trio.py:266-268)."""
    return FedAvgState(z=jnp.zeros((n,), dtype))


def fedavg_round(
    x_local: jnp.ndarray,
    state: FedAvgState,
    z_soft_threshold: float = 0.0,
    mask: Optional[jnp.ndarray] = None,
    combine: str = "mean",
    robust_f: int = 0,
) -> Tuple[FedAvgState, dict]:
    """One averaging round over the local client block `[K_loc, N]`.

    Returns the new state (z = cross-client mean) and the dual residual
    `‖z − znew‖/N` (reference src/federated_trio.py:357-358).

    `z_soft_threshold > 0` applies the elastic-net proximal soft shrinkage
    to znew — the reference ships this disabled but keeps the helper
    (reference src/federated_trio.py:188-196).

    `mask` is the `[K_loc]` participation vector of the local client block
    (1 = the client's contribution arrived this round, 0 = dropped; see
    fault/plan.py): the mean is mask-weighted over surviving clients only.
    A degenerate all-dropped round keeps the previous consensus state and
    reports `survivors == 0`. With the all-ones mask every operation is
    multiplication by 1.0 and division by the identical psum'd K, so the
    result is BIT-IDENTICAL to the unmasked path (tests/test_fault.py).

    `combine` selects the aggregation: 'mean' (the reference's, above —
    its code path is untouched so no-chaos runs stay bit-identical) or a
    Byzantine-robust order statistic from consensus/robust.py ('median',
    'trimmed' with `robust_f` trimmed per side, 'clip') that tolerates
    corrupted updates instead of averaging them in (docs/FAULT.md).
    """
    n = x_local.shape[-1]
    if combine == "mean":
        if mask is None:
            znew = client_mean(x_local)
            survivors = client_count(x_local)
        else:
            m = mask.astype(x_local.dtype)
            survivors = client_sum(m)
            safe = jnp.where(survivors > 0, survivors, 1.0)
            znew = client_sum(x_local * m[:, None]) / safe
    else:
        m = (
            mask
            if mask is not None
            else jnp.ones((x_local.shape[0],), x_local.dtype)
        ).astype(x_local.dtype)
        survivors = client_sum(m)
        znew, usable = robust_combine(
            x_local, m, combine, trim_f=robust_f, prev=state.z
        )
    if z_soft_threshold > 0.0:
        znew = soft_threshold(znew, z_soft_threshold)
    if combine != "mean":
        # per-coordinate keep-previous AFTER the soft threshold: an
        # unusable coordinate (every survivor non-finite) keeps z
        # EXACTLY, not a shrunk copy — the all-dropped invariant's
        # corruption mirror (consensus/robust.py)
        znew = jnp.where(usable, znew, state.z)
    if mask is not None or combine != "mean":
        znew = jnp.where(survivors > 0, znew, state.z)
    dual = jnp.linalg.norm(state.z - znew) / n
    return FedAvgState(z=znew), {"dual_residual": dual, "survivors": survivors}
