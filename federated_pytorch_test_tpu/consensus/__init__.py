"""Consensus strategies: FedAvg, ADMM (+ Barzilai-Borwein adaptive rho).

The reference inlines these algorithms into each driver script (SURVEY.md
§1 L5); here they are pure SPMD functions designed to run INSIDE a
`shard_map` over the `clients` mesh axis, operating on the local client
block `[K_loc, N]` of the active partition group's flat coordinates. Their
only cross-client communication is the weighted-psum collectives of
`federated_pytorch_test_tpu.parallel` — exactly one masked-group vector
crosses the interconnect per round (reference README.md:2's bandwidth
contract).
"""

from federated_pytorch_test_tpu.consensus.admm import (
    ADMMConfig,
    ADMMState,
    admm_init,
    admm_penalty,
    admm_round,
)
from federated_pytorch_test_tpu.consensus.fedavg import (
    FedAvgState,
    fedavg_init,
    fedavg_round,
)
from federated_pytorch_test_tpu.consensus.penalties import elastic_net, soft_threshold
from federated_pytorch_test_tpu.consensus.robust import (
    ROBUST_METHODS,
    apply_corruption,
    quarantine_release_2f,
    robust_combine,
    update_suspects,
)

__all__ = [
    "ADMMConfig",
    "ADMMState",
    "FedAvgState",
    "ROBUST_METHODS",
    "admm_init",
    "admm_penalty",
    "admm_round",
    "apply_corruption",
    "quarantine_release_2f",
    "elastic_net",
    "fedavg_init",
    "fedavg_round",
    "robust_combine",
    "soft_threshold",
    "update_suspects",
]
