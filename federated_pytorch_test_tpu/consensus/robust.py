"""Byzantine-robust aggregation: corruption model, robust combiners,
auto-quarantine statistics.

The participation mask (fault/plan.py) defends against *absent* clients;
this module defends against *present but lying* ones. FedADMM
(arXiv:2204.03529) argues consensus aggregation should absorb client
misbehavior when the combiner is robust, and TAMUNA (arXiv:2302.09832)
treats partial participation as an algorithmic regime — the same applies
to partial *trust*: tolerate up to `f` corrupted updates per round
instead of poisoning the consensus variable or sacrificing the whole
round to the rollback machinery.

Three pieces, all pure SPMD functions over the local client block (the
same calling convention as consensus/fedavg.py, consensus/admm.py):

* `apply_corruption` — the fault model's on-device half: given the
  plan's `[K]` mode/strength/seed rows (fault/plan.py `corruption`),
  corrupt the chosen clients' updates IN TRANSIT. Mode 0 selects the
  input bits verbatim, so a corruption-capable program with an all-clean
  row is bit-identical to the clean program.
* `robust_combine` — masked coordinate-wise **median**, **trimmed-mean
  (f per side)**, and **norm-clipping** combiners with the same
  shape contract as the masked mean (`[K_loc, N]` + `[K_loc]` mask ->
  `[N]`). Order statistics need every client's value per coordinate, so
  these pay one `all_gather` over the clients axis — the one place the
  bandwidth contract is deliberately spent on integrity (mean keeps its
  psum).
* `update_suspects` — the auto-quarantine statistic: per-client update
  norms `‖x_k − z‖` and their cross-client z-scores; a non-finite or
  outlying update flags its sender as suspect, and the trainer ANDs the
  accumulated suspect mask into the NEXT exchange's participation mask
  (quarantine is round-scoped — a persistently Byzantine client is
  re-detected each partition round from the same deterministic
  evidence).

Robustness contract of the order-statistic combiners: a NON-FINITE value
is self-evident corruption and is excluded per coordinate BEFORE the
order statistics (a NaN needs no voting to reject — and counting it as
a cohort member would bias the trim window onto the wrong finite value:
with 3 survivors and one NaN burst, trimmed(1) would otherwise
systematically pick the larger honest value instead of their middle).
The combiners always consume the exchange codec's DECODED f32 views
(exchange/, engine/steps.py `_consensus_local`) — bf16 widening, topk's
sparse scatter, quantized levels, error-feedback-compensated sends all
look like plain f32 vectors here, and the non-finite exclusion is
exactly what keeps a nan_burst liar visible through every lossy member
(the topk encoder ranks non-finite magnitudes above everything for the
same reason: the evidence must reach this code).
The trim then guards against the plausible-but-wrong values — `trimmed`
tolerates up to `f` arbitrarily scaled/flipped survivors per round,
`median` just under half; an exchange whose every update is non-finite
keeps the previous consensus state. The rollback machinery stays the
last resort, not the only defense.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.parallel import CLIENT_AXIS
from federated_pytorch_test_tpu.parallel.collectives import (
    all_clients,
    client_sum,
)

ROBUST_METHODS = ("mean", "median", "trimmed", "clip")


def quarantine_release_2f(method: str, trim_f: int) -> int | None:
    """The quarantine-release threshold for one combiner, or None.

    With `trimmed(f)`, an exchange whose quarantine-trusted cohort
    shrinks to <= 2f cannot trim meaningfully — trimmed(1)-of-2 trims
    every coordinate and keeps z (the documented PR-9 ~40-point K=3
    collapse) — so such an exchange RELEASES the quarantine mask and
    lets the trim itself defend (docs/FAULT.md §Quarantine). THE one
    definition on purpose: it gates the compiled program's in-scan
    release (engine/steps.py build_round_fn) AND the host replay of
    both trainer paths + the comm ledger's wasted-uplink attribution
    (engine/trainer.py) — a drifted copy would let the program's
    combine disagree with the ledger. Release is trimmed-scoped:
    median/clip/mean keep the original exclusion semantics.
    """
    if method == "trimmed" and trim_f > 0:
        return 2 * trim_f
    return None


# ------------------------------------------------------- corruption model


def apply_corruption(
    x_local: jnp.ndarray,
    modes: jnp.ndarray,
    strengths: jnp.ndarray,
    seeds: jnp.ndarray,
    gauss: bool = True,
) -> jnp.ndarray:
    """Corrupt chosen clients' updates in transit (`[K_loc, N]` -> same).

    `modes [K_loc]` i32 uses fault/plan.py's CORRUPT_MODES codes
    (0 = clean — selects the input bits verbatim, so an all-clean row is
    bit-transparent); `strengths [K_loc]` is λ for scale, σ for gauss;
    `seeds [K_loc]` i32 feed the gauss mode's deterministic on-device
    noise draw (pure in the plan seed + round cursor, so fused and
    unfused chaos runs corrupt identically).

    `gauss` is a STATIC build flag: under vmap the batched-predicate
    switch lowers to computing every branch and selecting, so a plan
    that never schedules gauss (a single `corrupt_mode` per plan) should
    pass False and compile the PRNG draw out of the hot program instead
    of paying a per-client `[N]` normal draw every exchange.
    """

    def one(xk, mk, sk, seedk):
        branches = [
            lambda _: xk,  # 0: clean
            lambda _: xk * sk,  # 1: scale ×λ
            lambda _: -xk,  # 2: signflip
            lambda _: jnp.full_like(xk, jnp.nan),  # 3: nan_burst
            (
                (
                    lambda _: xk
                    + sk
                    * jax.random.normal(  # 4: gauss σ·N(0,1)
                        jax.random.PRNGKey(seedk), xk.shape, xk.dtype
                    )
                )
                if gauss
                else (lambda _: xk)  # mode 4 unreachable in this plan
            ),
        ]
        return lax.switch(jnp.clip(mk, 0, len(branches) - 1), branches, 0)

    return jax.vmap(one)(x_local, modes, strengths, seeds)


# -------------------------------------------------------- robust combiners


def _sorted_finite_survivors(v_local, m, axis_name):
    """All-gathered `[K, N]` values sorted ascending per coordinate, with
    dropped clients AND non-finite entries pushed to +inf, plus the
    per-coordinate finite-survivor count `[N]`. The usable cohort
    occupies the sorted prefix — non-finite values are self-evident
    corruption, excluded before any order statistic (module docstring)."""
    all_v = all_clients(v_local, axis_name)
    all_m = all_clients(m, axis_name)
    ok = (all_m[:, None] > 0) & jnp.isfinite(all_v)  # [K, N]
    vals = jnp.where(ok, all_v, jnp.inf)
    return jnp.sort(vals, axis=0), jnp.sum(ok.astype(jnp.int32), axis=0)


def _prefix_median(sv, cnt):
    """Coordinate-wise median of each column's first `cnt[j]` sorted rows."""
    lo = jnp.maximum(cnt - 1, 0) // 2  # [N]
    hi = jnp.maximum(cnt, 1) // 2
    take = lambda i: jnp.take_along_axis(sv, i[None, :], axis=0)[0]
    return 0.5 * (take(lo) + take(hi))


def robust_combine(
    v_local: jnp.ndarray,
    mask: jnp.ndarray,
    method: str,
    *,
    trim_f: int = 0,
    prev: jnp.ndarray | None = None,
    axis_name: str = CLIENT_AXIS,
):
    """Masked robust cross-client combine: `[K_loc, N]` ->
    `(combined [N], usable [N] bool)`.

    `usable` marks coordinates with at least one finite surviving value;
    where it is False, `combined` already holds `prev` — but callers
    must ALSO re-select `prev` on `~usable` after any downstream
    transform (fedavg_round/admm_round apply it after the soft
    threshold), or an all-unusable exchange would shrink the kept
    consensus state instead of keeping it exactly, breaking the
    all-dropped-round invariant's corruption mirror.

    * `median` — coordinate-wise median over the finite survivors.
    * `trimmed` — drop the `trim_f` largest and smallest values per
      coordinate among the finite survivors, mean the rest; falls back
      to the median where `finite survivors <= 2*trim_f` leaves nothing
      to average.
    * `clip` — norm-clipping around `prev`: each survivor's update
      `v_k − prev` is shrunk onto the ball of radius τ = median of the
      finite survivors' update norms, then averaged; non-finite updates
      are excluded entirely (a NaN cannot be clipped back to honesty).

    ADMM note: the mean z-update weights clients by ρ_k; the robust
    combiners are unweighted order statistics (a Byzantine client could
    inflate its own weight otherwise), which is a documented deviation —
    with uniform ρ the two coincide.
    """
    if method not in ROBUST_METHODS or method == "mean":
        raise ValueError(
            f"robust_combine handles {[m for m in ROBUST_METHODS if m != 'mean']}, "
            f"got {method!r} (the mean lives in fedavg_round/admm_round)"
        )
    m = mask.astype(v_local.dtype)

    if method in ("median", "trimmed"):
        assert prev is not None, "order statistics need the fallback vector"
        sv, cnt = _sorted_finite_survivors(v_local, m, axis_name)
        median = _prefix_median(sv, cnt)
        if method == "median":
            combined = median
        else:
            idx = jnp.arange(sv.shape[0], dtype=jnp.int32)
            # per-coordinate trim window over the finite prefix
            keep = (idx[:, None] >= trim_f) & (idx[:, None] < cnt[None, :] - trim_f)
            # where-guard BEFORE the multiply: the excluded slots hold
            # +infs (dropped / non-finite), and inf*0 would poison the
            # sum the trim exists to protect
            kept = jnp.where(keep, sv, 0.0)
            denom = jnp.maximum(cnt - 2 * trim_f, 1).astype(v_local.dtype)
            trimmed = jnp.sum(kept, axis=0) / denom
            combined = jnp.where(cnt > 2 * trim_f, trimmed, median)
        # a coordinate with NO usable value (every survivor non-finite)
        # keeps the previous consensus state
        usable = cnt > 0
        return jnp.where(usable, combined, prev), usable

    # norm-clipping around the previous consensus state
    assert prev is not None, "clip needs the previous consensus vector"
    d = v_local - prev[None, :]
    norms = jnp.sqrt(jnp.sum(d * d, axis=-1))  # [K_loc]
    ok = m * jnp.isfinite(norms).astype(v_local.dtype)
    n_ok = client_sum(ok, axis_name=axis_name)
    all_n = all_clients(norms, axis_name)
    all_ok = all_clients(ok, axis_name)
    sn = jnp.sort(jnp.where(all_ok > 0, all_n, jnp.inf))
    tau = _prefix_median(sn[:, None], n_ok.astype(jnp.int32)[None])[0]
    factor = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-30))
    clipped = prev[None, :] + d * factor[:, None]
    contrib = jnp.where(ok[:, None] > 0, clipped, 0.0)
    combined = client_sum(contrib, axis_name=axis_name) / jnp.maximum(n_ok, 1.0)
    usable = jnp.broadcast_to(n_ok > 0, combined.shape)
    return jnp.where(usable, combined, prev), usable


# --------------------------------------------------------- auto-quarantine


def update_suspects(
    v_local: jnp.ndarray,
    prev: jnp.ndarray,
    mask: jnp.ndarray,
    z_thresh,
    axis_name: str = CLIENT_AXIS,
):
    """Per-client update norms + outlier flags: `([K_loc], [K_loc])`.

    `u_k = ‖v_k − prev‖` is the magnitude of the update client k sent
    this exchange; its z-score is computed over the alive, finite-update
    cohort. Suspect iff alive AND (non-finite update, OR
    `|u_k − mean| > z_thresh·std + ε` with a finite-update COHORT of at
    least 3 — the judged client included — to define the statistic; in a
    smaller cohort an "outlier" is unidentifiable and nobody is flagged
    on norm evidence alone).

    Small-cohort note: the z-score uses the population std (÷N), under
    which a single outlier among K alive clients cannot exceed `√(K−1)`
    (≈1.41 at K=3 — exactly attained when the honest cohort agrees), so
    thresholds near 1.0 — not the folkloric 2.5–3 — are the operating
    range for trio-sized experiments. `z_thresh = 0` is the hair
    trigger: any deviation from the cohort mean is suspect (the
    all-quarantined degenerate case the tests pin).
    """
    d = v_local - prev[None, :]
    u = jnp.sqrt(jnp.sum(d * d, axis=-1))  # [K_loc]
    m = mask.astype(u.dtype)
    finite = jnp.isfinite(u)
    ok = m * finite.astype(u.dtype)
    n_ok = client_sum(ok, axis_name=axis_name)
    safe = jnp.maximum(n_ok, 1.0)
    uz = jnp.where(ok > 0, u, 0.0)
    mean = client_sum(uz, axis_name=axis_name) / safe
    var = (
        client_sum(jnp.where(ok > 0, (u - mean) ** 2, 0.0), axis_name=axis_name)
        / safe
    )
    std = jnp.sqrt(var)
    # ε floors keep an all-equal cohort (std == 0) from flagging ulp noise
    outlier = jnp.abs(u - mean) > (
        z_thresh * std + 1e-12 + 1e-6 * jnp.abs(mean)
    )
    suspect = m * jnp.where(
        (~finite) | (outlier & (n_ok >= 3.0)), 1.0, 0.0
    ).astype(u.dtype)
    return u, suspect
