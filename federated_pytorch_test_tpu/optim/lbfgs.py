"""Jittable stochastic L-BFGS with trust-region damping and line searches.

Capability parity with the reference's `LBFGSNew` optimizer
(reference src/lbfgsnew.py:9-743), re-designed for XLA:

* The reference is a stateful torch `Optimizer` whose `step(closure)`
  re-invokes a Python closure between in-place parameter mutations
  (reference src/lbfgsnew.py:485-743). Here the optimizer is a pure
  transform `lbfgs_step(loss_fn, x, state) -> (x', state', aux)` over a
  flat parameter vector: the bounded inner iteration is a
  `lax.while_loop`, the two-loop recursion runs over fixed-size circular
  history buffers, and every line-search probe's forward pass is traced
  into the same XLA program — one device computation per optimizer step,
  no host round-trips.
* History is a pair of `[m, N]` buffers + a count instead of Python lists
  (reference src/lbfgsnew.py:598-605 uses `list.pop(0)/append`); invalid
  slots are masked inside the recursion so shapes stay static.
* All of the reference's stochastic-mode machinery is preserved:
  trust-region damping `y += lm0 * s` (reference src/lbfgsnew.py:572-573),
  the online inter-batch gradient mean/variance estimate feeding the
  maximum step `alphabar = 1/(1 + var/((n-1)·‖g‖))` (reference
  src/lbfgsnew.py:578-591), the curvature-acceptance guard
  `ys > 1e-10·‖s‖²` with history updates suppressed on batch boundaries
  (reference src/lbfgsnew.py:596-608), and the NaN guards on the gradient
  norm, step size, and re-evaluated gradient (reference
  src/lbfgsnew.py:542,659-663,679-681,697-699).

Deliberately reproduced quirks (SURVEY.md §3.3): the gradient norm used in
the loop guard and the alphabar formula is frozen at its step-entry value
(reference src/lbfgsnew.py:541,589 never update `grad_nrm` inside the
loop), and the Welford count for the inter-batch variance is the *global*
iteration counter, which advances `max_iter` per step though the estimate
updates once per step (reference src/lbfgsnew.py:585-589 uses
`state['n_iter']`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.optim.compact import compact_direction
from federated_pytorch_test_tpu.optim.linesearch import (
    backtracking_armijo_aux,
    backtracking_armijo_probes_aux,
    vma_zero,
    backtracking_armijo,
    cubic_linesearch,
)


def _pallas_direction(g, s_hist, y_hist, count, h_diag):
    # lazy import: pay the jax.experimental.pallas import cost only when
    # the 'pallas' backend is actually selected
    from federated_pytorch_test_tpu.ops import compact_direction_pallas

    return compact_direction_pallas(g, s_hist, y_hist, count, h_diag)

LossFn = Callable[[jnp.ndarray], jnp.ndarray]  # flat params -> scalar loss


@dataclasses.dataclass(frozen=True)
class LBFGSConfig:
    """Hyper-parameters, mirroring the reference's constructor defaults
    (reference src/lbfgsnew.py:59-71)."""

    lr: float = 1.0
    max_iter: int = 10
    max_eval: int | None = None  # defaults to max_iter * 5 // 4
    tolerance_grad: float = 1e-5
    tolerance_change: float = 1e-9
    history_size: int = 7
    line_search: bool = False
    batch_mode: bool = False
    # trust-region damping coefficient in batch mode (reference
    # src/lbfgsnew.py:538 `lm0=1e-6`)
    lm0: float = 1e-6
    # 'compact': Byrd–Nocedal compact representation — the same H·g as the
    #   two-loop recursion, restructured into MXU-tileable [m,N] matmuls
    #   (see optim/compact.py). 'two_loop': the masked sequential recursion.
    # 'pallas': the compact form with its history traffic fused into two
    #   Pallas kernels — one HBM pass for all four Gram/projection
    #   contractions, one for the direction assembly (see
    #   ops/compact_pallas.py; interpret mode off-TPU).
    direction: str = "compact"
    # batched multi-alpha Armijo fan width (batch-mode line search only,
    # linesearch.backtracking_armijo_probes_aux): each line-search loop
    # iteration evaluates this many halving-ladder rungs in ONE widened
    # vmapped pass and selects the first Armijo-satisfying rung on
    # device. 1 = the sequential search, DISPATCHED to the unchanged
    # `backtracking_armijo_aux` so the trajectory is bitwise-identical to
    # pre-probe builds; > 1 selects the same ladder rung (up to
    # ulp-boundary Armijo ties under batched reduction) while amortizing
    # the sequential per-probe parameter re-streams into fans
    # (docs/PERF.md).
    ls_probes: int = 1

    def __post_init__(self):
        if self.direction not in ("compact", "two_loop", "pallas"):
            raise ValueError(
                "direction must be 'compact', 'two_loop' or 'pallas', "
                f"got {self.direction!r}"
            )
        if self.ls_probes < 1:
            raise ValueError(
                f"ls_probes must be >= 1, got {self.ls_probes}"
            )

    @property
    def resolved_max_eval(self) -> int:
        return self.max_eval if self.max_eval is not None else self.max_iter * 5 // 4


class LBFGSState(NamedTuple):
    """Persistent optimizer state (the reference's `self.state` dict,
    src/lbfgsnew.py:727-740), as fixed-shape arrays."""

    s_hist: jnp.ndarray  # [m, N] past steps s_k = t * d
    y_hist: jnp.ndarray  # [m, N] past (damped) gradient differences
    hist_count: jnp.ndarray  # i32, number of valid (s, y) pairs
    h_diag: jnp.ndarray  # f32, initial inverse-Hessian scale
    d: jnp.ndarray  # [N] last search direction
    t: jnp.ndarray  # f32, last step size
    prev_grad: jnp.ndarray  # [N]
    prev_loss: jnp.ndarray  # f32
    n_iter: jnp.ndarray  # i32, global iteration counter
    func_evals: jnp.ndarray  # i32
    running_avg: jnp.ndarray  # [N] inter-batch gradient mean (batch mode)
    running_avg_sq: jnp.ndarray  # [N] inter-batch second-moment accumulator
    # i32, cumulative Armijo line-search probe evaluations (batch-mode
    # line search only; the cubic search and fixed-step mode contribute
    # 0). Separate from `func_evals` on purpose: func_evals keeps its
    # historical meaning (entry + re-evaluations — the quantity the
    # `max_eval` budget is charged against), while this counter makes the
    # line search's forward passes visible — the roofline quantity
    # bench.py's `mean_func_evals_per_step` reports (func_evals +
    # ls_evals per step). Under `ls_probes > 1` one widened fan charges
    # its full fan width: the amortization is honest, not hidden.
    ls_evals: jnp.ndarray


class LBFGSAux(NamedTuple):
    """Per-step diagnostics (the reference's return value + counters)."""

    loss: jnp.ndarray  # loss at step entry (reference returns `orig_loss`)
    step_size: jnp.ndarray  # last accepted step size
    n_inner: jnp.ndarray  # inner iterations executed this step
    func_evals: jnp.ndarray  # closure-equivalent evaluations this step
    # `has_aux=True` only: the user aux of the evaluation AT THE FINAL
    # PARAMETERS (the accepted line-search point or the re-evaluation,
    # whichever saw final x last; () otherwise), and whether it is valid
    # — False only on the rare NaN-step-size fallback whose final point
    # was never evaluated (see lbfgs_step)
    aux: Any = ()
    aux_ok: jnp.ndarray | bool = True
    # `has_aux=True` only: the user aux of the ENTRY evaluation (at the
    # step's starting parameters; () otherwise). Always valid — the entry
    # point is evaluated unconditionally — so it is what callers fall
    # back to when `aux_ok` is False: the same KIND of quantity as `aux`
    # (e.g. the engine's penalty-free data loss), one step earlier,
    # instead of a different quantity entirely (`loss` is the total
    # objective, penalties included).
    entry_aux: Any = ()
    # Armijo line-search probe evaluations this step (see
    # LBFGSState.ls_evals — this is the per-step delta)
    ls_evals: jnp.ndarray | int = 0


def lbfgs_init(x0: jnp.ndarray, config: LBFGSConfig) -> LBFGSState:
    """Fresh state for a parameter vector like `x0`.

    The reference creates a fresh optimizer per partition round
    (reference src/federated_trio.py:273-275); this is the equivalent —
    cheap enough to call inside a jitted round because it is just zeros.
    """
    n = x0.shape[0]
    m = config.history_size
    dt = x0.dtype
    z = jnp.zeros((n,), dt)
    return LBFGSState(
        s_hist=jnp.zeros((m, n), dt),
        y_hist=jnp.zeros((m, n), dt),
        hist_count=jnp.int32(0),
        h_diag=jnp.asarray(1.0, dt),
        d=z,
        t=jnp.asarray(config.lr, dt),
        prev_grad=z,
        prev_loss=jnp.asarray(0.0, dt),
        n_iter=jnp.int32(0),
        func_evals=jnp.int32(0),
        running_avg=z,
        running_avg_sq=z,
        ls_evals=jnp.int32(0),
    )


def _two_loop_direction(
    g: jnp.ndarray,
    s_hist: jnp.ndarray,
    y_hist: jnp.ndarray,
    count: jnp.ndarray,
    h_diag: jnp.ndarray,
) -> jnp.ndarray:
    """Masked two-loop recursion: -H·g over the valid history slots.

    Reference src/lbfgsnew.py:615-637, with the Python lists replaced by
    `[m, N]` buffers; slots `i >= count` contribute nothing because their
    `al`/`be` coefficients are forced to zero.
    """
    m = s_hist.shape[0]

    ys_all = jnp.einsum("in,in->i", y_hist, s_hist)  # y_i . s_i per slot
    valid = jnp.arange(m) < count
    # safe reciprocal: invalid or degenerate slots get rho = 0
    ro = jnp.where(valid & (ys_all != 0.0), 1.0 / jnp.where(ys_all != 0.0, ys_all, 1.0), 0.0)

    def backward(i_rev, carry):
        q, al = carry
        i = m - 1 - i_rev
        a = jnp.dot(s_hist[i], q) * ro[i]
        q = q - a * y_hist[i]
        return q, al.at[i].set(a)

    q0 = -g
    q, al = lax.fori_loop(0, m, backward, (q0, jnp.zeros((m,), g.dtype)))

    def forward(i, r):
        b = jnp.dot(y_hist[i], r) * ro[i]
        return r + (al[i] - b) * s_hist[i]

    r = q * h_diag
    return lax.fori_loop(0, m, forward, r)


def _push_history(
    s_hist: jnp.ndarray,
    y_hist: jnp.ndarray,
    count: jnp.ndarray,
    s: jnp.ndarray,
    y: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Append (s, y), evicting the oldest pair when full.

    Reference src/lbfgsnew.py:598-605 (`pop(0)` + `append`); here a roll
    keeps slots in chronological order so the recursion's masked loops
    stay index-ordered.
    """
    m = s_hist.shape[0]
    full = count == m
    s_hist = jnp.where(full, jnp.roll(s_hist, -1, axis=0), s_hist)
    y_hist = jnp.where(full, jnp.roll(y_hist, -1, axis=0), y_hist)
    idx = jnp.where(full, m - 1, count).astype(jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    s_hist = lax.dynamic_update_slice(s_hist, s[None], (idx, zero))
    y_hist = lax.dynamic_update_slice(y_hist, y[None], (idx, zero))
    return s_hist, y_hist, jnp.minimum(count + 1, m)


class _Carry(NamedTuple):
    x: jnp.ndarray
    loss: jnp.ndarray
    g: jnp.ndarray
    abs_grad_sum: jnp.ndarray
    d: jnp.ndarray
    t: jnp.ndarray
    s_hist: jnp.ndarray
    y_hist: jnp.ndarray
    hist_count: jnp.ndarray
    h_diag: jnp.ndarray
    prev_grad: jnp.ndarray
    prev_loss: jnp.ndarray
    n_global: jnp.ndarray
    evals: jnp.ndarray
    n_inner: jnp.ndarray
    alphabar: jnp.ndarray
    running_avg: jnp.ndarray
    running_avg_sq: jnp.ndarray
    done: jnp.ndarray
    aux: Any  # user aux of the last evaluation at the carry's x
    aux_ok: jnp.ndarray  # False while x was produced by the NaN fallback
    ls_evals: jnp.ndarray  # i32, Armijo probe evaluations this step


def lbfgs_step(
    loss_fn: LossFn,
    x: jnp.ndarray,
    state: LBFGSState,
    config: LBFGSConfig,
    has_aux: bool = False,
    fan_fn=None,
) -> Tuple[jnp.ndarray, LBFGSState, LBFGSAux]:
    """One optimizer step: up to `max_iter` L-BFGS iterations with line search.

    `loss_fn` must be a pure function of the flat parameter vector (close
    over the batch before calling). The whole body — direction updates,
    history pushes, line-search probes — is jit-compatible; the equivalent
    of the reference's `step(closure)` (src/lbfgsnew.py:485-743).

    With `has_aux=True`, `loss_fn` returns `(loss, aux)` and the returned
    `LBFGSAux.aux` is the user aux of the evaluation AT THE FINAL
    PARAMETERS — every loss evaluation already computes it, so exporting
    it is free, and it is what lets the engine fold its per-batch
    diagnostic forward (BN batch statistics + raw data loss) into the
    accepted line-search evaluation instead of paying an extra model
    pass (engine/steps.py). Only the batch-mode Armijo path threads aux
    (the accepted alpha there is provably the last one evaluated); the
    cubic search accepts points it probed earlier, so `has_aux` requires
    `batch_mode` + `line_search`. `LBFGSAux.aux_ok` is False only when
    the final x came from the NaN-step-size fallback AND was never
    re-evaluated — callers must keep their previous aux then.

    `fan_fn`, when given, is the widened probe-fan evaluator
    `fan_fn(x, d, alphas) -> (losses, auxs)` handed to the multi-alpha
    Armijo search as its `fan_phi` (linesearch.py) — it must compute the
    same values as `vmap(phi_aux)` over the fan, only batched
    differently (the `--client-fold gemm` hook, engine/steps.py). Only
    consulted when `ls_probes > 1`; `None` compiles today's exact
    programs byte-for-byte.
    """
    if has_aux and not (config.batch_mode and config.line_search):
        raise ValueError(
            "has_aux requires batch_mode line search: only the Armijo "
            "path's accepted step is guaranteed to be its last-evaluated "
            "point, which is what makes the carried aux belong to the "
            "returned parameters"
        )
    max_eval = config.resolved_max_eval
    tol_grad = config.tolerance_grad
    tol_change = config.tolerance_change
    lr = jnp.asarray(config.lr, x.dtype)

    loss_fn_aux = loss_fn if has_aux else (lambda xx: (loss_fn(xx), ()))
    value_and_grad = jax.value_and_grad(loss_fn_aux, has_aux=True)
    (loss0, aux0), g0 = value_and_grad(x)
    abs_grad_sum0 = jnp.sum(jnp.abs(g0))
    # Frozen at entry for both the loop guard and alphabar (see module
    # docstring on reproduced quirks).
    grad_nrm = jnp.linalg.norm(g0)

    def cond(c: _Carry):
        return (c.n_inner < config.max_iter) & (~c.done) & (~jnp.isnan(grad_nrm))

    def body(c: _Carry):
        n_inner = c.n_inner + 1
        n_global = c.n_global + 1
        first_ever = n_global == 1

        # a varying scalar zero (the gradient is always varying under
        # shard_map): added to scalar cond outputs below so both branches
        # produce identical varying-mesh-axis types under vma checking,
        # with any axis name (this module is mesh-agnostic and cannot
        # pvary by name) — see linesearch.vma_zero
        vzero = vma_zero(c.g[0])

        def fresh_direction(c: _Carry):
            # reference src/lbfgsnew.py:550-557: steepest descent, reset
            # history and running statistics.
            return (
                -c.g,
                jnp.zeros_like(c.s_hist) + vzero,
                jnp.zeros_like(c.y_hist) + vzero,
                jnp.int32(0) + vzero.astype(jnp.int32),
                jnp.asarray(1.0, c.x.dtype) + vzero,
                c.alphabar + vzero,
                jnp.zeros_like(c.running_avg) + vzero,
                jnp.zeros_like(c.running_avg_sq) + vzero,
            )

        def update_direction(c: _Carry):
            y = c.g - c.prev_grad
            s = c.d * c.t
            if config.batch_mode:
                y = y + config.lm0 * s  # trust-region damping
            ys = jnp.dot(y, s)
            ss = jnp.dot(s, s)

            if config.batch_mode:
                # First inner iteration of a new step = new mini-batch:
                # update the inter-batch gradient statistics instead of the
                # curvature history (reference src/lbfgsnew.py:578-591).
                batch_changed = (n_inner == 1) & (n_global > 1)
                g_minus_old = c.g - c.running_avg
                ravg_new = c.running_avg + g_minus_old / n_global.astype(c.x.dtype)
                ravgsq_new = c.running_avg_sq + (c.g - ravg_new) * g_minus_old
                ravg = jnp.where(batch_changed, ravg_new, c.running_avg)
                ravgsq = jnp.where(batch_changed, ravgsq_new, c.running_avg_sq)
                var_term = jnp.sum(ravgsq) / (
                    (n_global - 1).astype(c.x.dtype) * grad_nrm
                )
                alphabar = jnp.where(
                    batch_changed, 1.0 / (1.0 + var_term), c.alphabar
                )
            else:
                batch_changed = jnp.bool_(False)
                ravg, ravgsq, alphabar = c.running_avg, c.running_avg_sq, c.alphabar

            accept = (ys > 1e-10 * ss) & (~batch_changed)

            def push(args):
                sh, yh, cnt = args
                return _push_history(sh, yh, cnt, s, y)

            s_hist, y_hist, hist_count = lax.cond(
                accept, push, lambda a: a, (c.s_hist, c.y_hist, c.hist_count)
            )
            yy = jnp.dot(y, y)
            h_new = jnp.where(yy != 0.0, ys / jnp.where(yy != 0.0, yy, 1.0), c.h_diag)
            h_diag = jnp.where(accept, h_new, c.h_diag)
            # NaN H_diag is carried through with only a warning in the
            # reference (src/lbfgsnew.py:610-611); same here implicitly.
            direction_fn = {
                "compact": compact_direction,
                "two_loop": _two_loop_direction,
                "pallas": _pallas_direction,
            }[config.direction]
            d = direction_fn(c.g, s_hist, y_hist, hist_count, h_diag)
            return (
                d,
                s_hist + vzero,
                y_hist + vzero,
                hist_count + vzero.astype(jnp.int32),
                h_diag + vzero,
                alphabar + vzero,
                ravg + vzero,
                ravgsq + vzero,
            )

        (d, s_hist, y_hist, hist_count, h_diag, alphabar, ravg, ravgsq) = lax.cond(
            first_ever, fresh_direction, update_direction, c
        )

        prev_grad = c.g
        prev_loss = c.loss

        # step-size seed (reference src/lbfgsnew.py:651-654)
        t = jnp.where(
            first_ever, jnp.minimum(1.0, 1.0 / c.abs_grad_sum) * lr, lr
        ).astype(c.x.dtype)

        gtd = jnp.dot(c.g, d)

        aux_new = c.aux
        aux_ok_new = c.aux_ok
        ls_evals = c.ls_evals
        if config.line_search:
            x_cur = c.x

            def phi_aux(alpha):
                return loss_fn_aux(x_cur + alpha * d)

            if config.batch_mode:
                # static dispatch on the fan width: ls_probes == 1 keeps
                # the UNCHANGED sequential search — the bitwise fallback —
                # while > 1 evaluates fans of consecutive halving rungs
                # in one widened pass (same accepted alpha, amortized
                # parameter streaming; linesearch.py)
                if config.ls_probes > 1:
                    t_ls, ls_ev, aux_ls = backtracking_armijo_probes_aux(
                        phi_aux, c.loss, gtd, alphabar,
                        probes=config.ls_probes,
                        fan_phi=(
                            (lambda alphas: fan_fn(x_cur, d, alphas))
                            if fan_fn is not None else None
                        ),
                    )
                else:
                    t_ls, ls_ev, aux_ls = backtracking_armijo_aux(
                        phi_aux, c.loss, gtd, alphabar
                    )
                ls_evals = c.ls_evals + ls_ev
                aux_new = aux_ls
                # a NaN step size falls back to lr below: the point
                # x + lr*d was never evaluated, so the carried aux does
                # not belong to it (restored if the re-evaluation runs)
                aux_ok_new = ~jnp.isnan(t_ls)
            else:
                t_ls = cubic_linesearch(
                    lambda a: phi_aux(a)[0], c.loss, config.lr
                )
            t = jnp.where(jnp.isnan(t_ls), lr, t_ls).astype(c.x.dtype)

        x = c.x + t * d

        # termination tests not needing a re-evaluation
        # (reference src/lbfgsnew.py:709-724)
        stop_now = (
            (n_inner >= config.max_iter)
            | (c.evals >= max_eval)
            | (gtd > -tol_change)
            | (jnp.sum(jnp.abs(t * d)) <= tol_change)
        )

        def reeval(_):
            (l, aux_r), gg = value_and_grad(x)
            # the re-evaluation IS at x, whatever step-size fallback
            # produced it — aux becomes valid again (| True keeps
            # aux_ok_new's varying-mesh-axis type under vma checking)
            return l, gg, jnp.sum(jnp.abs(gg)), c.evals + 1, aux_r, (
                aux_ok_new | True
            )

        def keep(_):
            return c.loss, c.g, c.abs_grad_sum, c.evals, aux_new, aux_ok_new

        loss, g, abs_grad_sum, evals, aux_new, aux_ok_new = lax.cond(
            stop_now, keep, reeval, None
        )

        done = (
            stop_now
            | jnp.isnan(abs_grad_sum)
            | (abs_grad_sum <= tol_grad)
            | (jnp.abs(loss - prev_loss) < tol_change)
        )

        return _Carry(
            x=x,
            loss=loss,
            g=g,
            abs_grad_sum=abs_grad_sum,
            d=d,
            t=t,
            s_hist=s_hist,
            y_hist=y_hist,
            hist_count=hist_count,
            h_diag=h_diag,
            prev_grad=prev_grad,
            prev_loss=prev_loss,
            n_global=n_global,
            evals=evals,
            n_inner=n_inner,
            alphabar=alphabar,
            running_avg=ravg,
            running_avg_sq=ravgsq,
            done=done,
            aux=aux_new,
            aux_ok=aux_ok_new,
            ls_evals=ls_evals,
        )

    # Exact zeros carrying the loss's varying-mesh-axis type. Under
    # shard_map with vma checking the while_loop's carry must enter with
    # the vma its body produces; `state` may arrive as unvarying constants
    # (lbfgs_init) while the body mixes in the (always-varying) loss and
    # gradient. Seeding every field costs nothing numerically — see
    # linesearch.vma_zero on the inf/NaN safety.
    vz = vma_zero(loss0)
    iz = vz.astype(jnp.int32)
    init = _Carry(
        x=x,
        loss=loss0,
        g=g0,
        abs_grad_sum=abs_grad_sum0,
        d=state.d + vz,
        t=state.t + vz,
        s_hist=state.s_hist + vz,
        y_hist=state.y_hist + vz,
        hist_count=state.hist_count + iz,
        h_diag=state.h_diag + vz,
        prev_grad=state.prev_grad + vz,
        prev_loss=state.prev_loss + vz,
        n_global=state.n_iter + iz,
        evals=jnp.int32(1) + iz,
        n_inner=jnp.int32(0) + iz,
        alphabar=lr + vz,
        running_avg=state.running_avg + vz,
        running_avg_sq=state.running_avg_sq + vz,
        done=abs_grad_sum0 <= tol_grad,
        # entry evaluation is at x: if no iteration runs, final x == x
        # and aux0 is exactly its aux
        aux=aux0,
        aux_ok=vz == 0,
        ls_evals=jnp.int32(0) + iz,
    )

    def masked_body(c: _Carry) -> _Carry:
        # vmap-safety: under `jax.vmap` the while body runs for every
        # client while ANY client's condition holds; a client that already
        # terminated must keep its carry frozen or its params would take
        # extra L-BFGS iterations its siblings are still running. The NaN
        # clause mirrors the loop guard: a client entering with a NaN
        # gradient must keep its params untouched (reference
        # src/lbfgsnew.py:541-542), not absorb a NaN step from the batched
        # body.
        new = body(c)
        frozen = c.done | jnp.isnan(grad_nrm)
        return jax.tree.map(lambda n, o: jnp.where(frozen, o, n), new, c)

    final = lax.while_loop(cond, masked_body, init)

    new_state = LBFGSState(
        s_hist=final.s_hist,
        y_hist=final.y_hist,
        hist_count=final.hist_count,
        h_diag=final.h_diag,
        d=final.d,
        t=final.t,
        prev_grad=final.prev_grad,
        prev_loss=final.prev_loss,
        n_iter=final.n_global,
        func_evals=state.func_evals + final.evals,
        running_avg=final.running_avg,
        running_avg_sq=final.running_avg_sq,
        ls_evals=state.ls_evals + final.ls_evals,
    )
    aux = LBFGSAux(
        loss=loss0,
        step_size=final.t,
        n_inner=final.n_inner,
        func_evals=final.evals,
        aux=final.aux,
        aux_ok=final.aux_ok,
        # aux0 rides along untouched by the loop; unused leaves (e.g. the
        # engine's entry BN stats) are dead code XLA eliminates
        entry_aux=aux0,
        ls_evals=final.ls_evals,
    )
    return final.x, new_state, aux
