"""Compact-representation L-BFGS direction: the two-loop recursion as matmuls.

The classic two-loop recursion (reference src/lbfgsnew.py:615-637, our
`lbfgs._two_loop_direction`) is 2m sequentially-dependent BLAS1 passes over
the [N] parameter vector — each history slot's dot product must finish
before the next slot can start, so on TPU it runs on the VPU with 2m round
trips to HBM and the MXU idle.

The Byrd–Nocedal–Schnabel compact representation (SIAM J. Num. An. 1994,
"Representations of quasi-Newton matrices and their use in limited memory
methods") writes the SAME inverse-Hessian product in closed form:

    H g = γ g + [S  γY] · [[ R⁻ᵀ(D + γ YᵀY) R⁻¹,  −R⁻ᵀ ],
                           [ −R⁻¹,                 0    ]] · [Sᵀg; γ Yᵀg]

with S,Y the [m,N] step/grad-difference history, R the upper triangle of
S Yᵀ (slot-chronological), D its diagonal, and γ the initial Hessian scale
(`h_diag`). The heavy work becomes four [m,N]-shaped matmuls (Sᵀg, Yᵀg,
then S·w, Y·u) plus an m×m Gram matrix — all MXU-tileable, one HBM pass
over the history per phase — and two m×m triangular solves that are
negligible at m=10. The result is algebraically identical to the two-loop
recursion's direction (equal up to floating-point roundoff — reduction
order differs; see tests/test_lbfgs.py equivalence tests).

Invalid history slots (`i >= count`, or degenerate `yᵢ·sᵢ = 0`) are masked
by zeroing their rows and pinning the corresponding diagonal of R to 1 so
the triangular solves stay non-singular while the slot's contribution
vanishes exactly.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular


def compact_direction(
    g: jnp.ndarray,
    s_hist: jnp.ndarray,
    y_hist: jnp.ndarray,
    count: jnp.ndarray,
    h_diag: jnp.ndarray,
) -> jnp.ndarray:
    """-H·g via the compact representation over the valid history slots.

    Drop-in replacement for `lbfgs._two_loop_direction` (same signature,
    same result); `s_hist`/`y_hist` are [m, N] chronological buffers of
    which the first `count` rows are valid.
    """
    m = s_hist.shape[0]
    dt = g.dtype

    valid = jnp.arange(m) < count
    s = jnp.where(valid[:, None], s_hist, 0.0)
    y = jnp.where(valid[:, None], y_hist, 0.0)

    # m x m Gram blocks; one [m,N] @ [N,m] pass each (MXU)
    sy = s @ y.T  # sy[i, j] = s_i . y_j
    d_diag = jnp.diagonal(sy)
    # guard: treat slots with degenerate curvature as invalid too
    ok = valid & (d_diag != 0.0)
    s = jnp.where(ok[:, None], s, 0.0)
    y = jnp.where(ok[:, None], y, 0.0)
    sy = jnp.where(ok[:, None] & ok[None, :], sy, 0.0)
    d_diag = jnp.diagonal(sy)

    # R = upper triangle of S Yᵀ, with invalid diagonals pinned to 1 so the
    # triangular solves are non-singular (their rhs entries are 0 there)
    r = jnp.triu(sy) + jnp.diag(jnp.where(ok, 0.0, 1.0).astype(dt))

    p = s @ g  # Sᵀg  [m]
    q = y @ g  # Yᵀg  [m]

    u = solve_triangular(r, p, lower=False)  # R⁻¹ Sᵀg
    # (YᵀY)u contracted as Y(uᵀY): reuses uy and avoids the [m,N]@[N,m]
    # Gram pass — (yy @ u)[i] = y_i · Σ_j u_j y_j = (y @ uy)[i]
    uy = u @ y  # [N]
    w = solve_triangular(
        r, d_diag * u + h_diag * (y @ uy) - h_diag * q, lower=False, trans=1
    )  # R⁻ᵀ((D + γ YᵀY) u − γ Yᵀg)

    hg = h_diag * g + w @ s - h_diag * uy
    return -hg
