"""Compact-representation L-BFGS direction: the two-loop recursion as matmuls.

The classic two-loop recursion (reference src/lbfgsnew.py:615-637, our
`lbfgs._two_loop_direction`) is 2m sequentially-dependent BLAS1 passes over
the [N] parameter vector — each history slot's dot product must finish
before the next slot can start, so on TPU it runs on the VPU with 2m round
trips to HBM and the MXU idle.

The Byrd–Nocedal–Schnabel compact representation (SIAM J. Num. An. 1994,
"Representations of quasi-Newton matrices and their use in limited memory
methods") writes the SAME inverse-Hessian product in closed form:

    H g = γ g + [S  γY] · [[ R⁻ᵀ(D + γ YᵀY) R⁻¹,  −R⁻ᵀ ],
                           [ −R⁻¹,                 0    ]] · [Sᵀg; γ Yᵀg]

with S,Y the [m,N] step/grad-difference history, R the upper triangle of
S Yᵀ (slot-chronological), D its diagonal, and γ the initial Hessian scale
(`h_diag`). The heavy work becomes a handful of [m,N]-shaped matmuls — all
MXU-tileable — and two m×m triangular solves that are negligible at m=10.
The result is algebraically identical to the two-loop recursion's
direction (equal up to floating-point roundoff — reduction order differs;
see tests/test_lbfgs.py equivalence tests).

Invalid history slots (`i >= count`, or degenerate `yᵢ·sᵢ = 0`) are masked
by zeroing their rows and pinning the corresponding diagonal of R to 1 so
the triangular solves stay non-singular while the slot's contribution
vanishes exactly. That masking + solve sequence lives in `compact_solves`,
shared with the fused Pallas backend (ops/compact_pallas.py) so the two
backends cannot drift.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax.numpy as jnp
from jax.lax import Precision
from jax.scipy.linalg import solve_triangular

# full-f32 MXU passes for the heavy [m, N] contractions: they are HBM-
# bandwidth-bound, so this costs nothing and matches the Pallas backend's
# fidelity instead of drifting with single-bf16-pass MXU defaults on TPU
_HI = Precision.HIGHEST


def compact_solves(
    sy: jnp.ndarray,
    p: jnp.ndarray,
    q: jnp.ndarray,
    valid: jnp.ndarray,
    h_diag: jnp.ndarray,
    yyu: Callable[[jnp.ndarray], Tuple[jnp.ndarray, object]],
):
    """The middle section shared by both compact backends.

    Given the Gram/projection contractions `sy = S Yᵀ` [m,m], `p = Sᵀg`,
    `q = Yᵀg` [m] (computed over `valid`-masked rows), masks
    degenerate-curvature slots, builds R, and runs the two triangular
    solves. `yyu(u)` must return `((YᵀY) u, aux)` — the pure-JAX backend
    contracts it as `Y (u @ Y)` reusing `uy` as aux; the Pallas backend
    has the m×m `Y Yᵀ` from its fused pass and uses `yy @ u`.

    Returns `(u, w, ok, aux)` with `u = R⁻¹Sᵀg`,
    `w = R⁻ᵀ((D + γ YᵀY)u − γ Yᵀg)`, both exactly zero at non-`ok` slots.
    """
    dt = sy.dtype
    d_diag = jnp.diagonal(sy)
    # guard: treat slots with degenerate curvature as invalid too
    ok = valid & (d_diag != 0.0)
    pair = ok[:, None] & ok[None, :]
    sy = jnp.where(pair, sy, 0.0)
    p = jnp.where(ok, p, 0.0)
    q = jnp.where(ok, q, 0.0)
    d_diag = jnp.diagonal(sy)

    # R = upper triangle of S Yᵀ, with invalid diagonals pinned to 1 so the
    # triangular solves are non-singular (their rhs entries are 0 there —
    # hence u, w are exactly 0 at those slots and the explicit re-masking
    # below is belt-and-braces for NaN-contaminated invalid slots)
    r = jnp.triu(sy) + jnp.diag(jnp.where(ok, 0.0, 1.0).astype(dt))

    u = solve_triangular(r, p, lower=False)  # R⁻¹ Sᵀg
    u = jnp.where(ok, u, 0.0)
    yyu_vec, aux = yyu(u)
    w = solve_triangular(
        r, d_diag * u + h_diag * yyu_vec - h_diag * q, lower=False, trans=1
    )  # R⁻ᵀ((D + γ YᵀY) u − γ Yᵀg)
    w = jnp.where(ok, w, 0.0)
    return u, w, ok, aux


def compact_direction(
    g: jnp.ndarray,
    s_hist: jnp.ndarray,
    y_hist: jnp.ndarray,
    count: jnp.ndarray,
    h_diag: jnp.ndarray,
) -> jnp.ndarray:
    """-H·g via the compact representation over the valid history slots.

    Drop-in replacement for `lbfgs._two_loop_direction` (same signature,
    same result); `s_hist`/`y_hist` are [m, N] chronological buffers of
    which the first `count` rows are valid.
    """
    m = s_hist.shape[0]

    valid = jnp.arange(m) < count
    s = jnp.where(valid[:, None], s_hist, 0.0)
    y = jnp.where(valid[:, None], y_hist, 0.0)

    # the heavy contractions: [m,N] @ [N,m] / [m,N] @ [N] passes (MXU)
    sy = jnp.matmul(s, y.T, precision=_HI)  # sy[i, j] = s_i . y_j
    p = jnp.matmul(s, g, precision=_HI)  # Sᵀg  [m]
    q = jnp.matmul(y, g, precision=_HI)  # Yᵀg  [m]

    def yyu(u):
        # (YᵀY)u contracted as Y(uᵀY): (yy @ u)[i] = y_i · Σ_j u_j y_j =
        # (y @ uy)[i]; avoids an [m,N]@[N,m] Gram pass and `uy` is reused
        # in the final assembly
        uy = jnp.matmul(u, y, precision=_HI)  # [N]
        return jnp.matmul(y, uy, precision=_HI), uy

    u, w, _, uy = compact_solves(sy, p, q, valid, h_diag, yyu)

    hg = h_diag * g + jnp.matmul(w, s, precision=_HI) - h_diag * uy
    return -hg
