"""Inner optimizers.

The centerpiece is a fully jittable stochastic L-BFGS: the TPU-native
re-design of the reference's closure-based `LBFGSNew`
(reference src/lbfgsnew.py:9-743). Instead of a stateful torch Optimizer
that mutates `p.data` between Python-side closure calls, `lbfgs_step` is a
pure `(loss_fn, x, state) -> (x, state, aux)` transform whose bounded inner
iteration, two-loop recursion, and line searches all run inside one XLA
program (`lax.while_loop` / `lax.fori_loop` / `lax.cond`) — so a whole
optimizer step, including every line-search probe's forward pass, is a
single fused device computation with no host round-trips.
"""

from federated_pytorch_test_tpu.optim.compact import compact_direction
from federated_pytorch_test_tpu.optim.linesearch import (
    backtracking_armijo_probes_aux,
    vma_zero,
)
from federated_pytorch_test_tpu.optim.lbfgs import (
    LBFGSConfig,
    LBFGSState,
    lbfgs_init,
    lbfgs_step,
)

__all__ = [
    "vma_zero",
    "LBFGSConfig",
    "LBFGSState",
    "backtracking_armijo_probes_aux",
    "compact_direction",
    "lbfgs_init",
    "lbfgs_step",
]
