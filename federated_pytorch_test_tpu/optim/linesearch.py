"""Jittable line searches for the stochastic L-BFGS.

Two strategies, mirroring the reference's pair
(reference src/lbfgsnew.py:124-174 backtracking, :179-482 cubic/zoom):

* `backtracking_armijo` — stochastic (batch) mode: halve the step from
  `alphabar` until the Armijo condition holds, at most 35 times.
* `cubic_linesearch` — full-batch mode: Fletcher bracketing with cubic
  interpolation and a zoom stage; directional derivatives of the 1-D
  restriction are taken by central differences of the loss function, as in
  the reference (src/lbfgsnew.py:209-217), because the restriction's value
  is all the closure protocol exposes there. All loops are bounded
  `lax.while_loop`s so every probe's forward pass stays on device.

Deliberate deviation (documented per SURVEY.md §2.2 quirks): the
reference's `_cubic_interpolate` computes the minimizer `z0` in step units
but probes the loss at `a + z0*(b-a)` (src/lbfgsnew.py:363-366), mixing
parameterizations. Here the probe is at `z0` itself — the consistent
interpretation — which only changes which of {a, b, z0} wins the final
three-way minimum in rare cases.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Scalar = jnp.ndarray
PhiFn = Callable[[Scalar], Scalar]  # alpha -> loss(x + alpha * d)


def vma_zero(ref):
    """Exact scalar zero carrying `ref`'s varying-mesh-axis type.

    Loop carries under shard_map's vma checking must enter with the vma
    their body produces; constants (jnp.int32(0), lr, ...) are unvarying,
    so they are seeded by adding this zero derived from an always-varying
    value (a loss or gradient element). nan_to_num keeps the zero exact
    even when `ref` is inf/NaN — a divergent client must reach the
    NaN-freeze paths with its carry unpoisoned, not absorb inf*0 = NaN.
    """
    return jnp.nan_to_num(ref, nan=0.0, posinf=0.0, neginf=0.0) * 0


def _freeze(pred, new, old):
    """Keep `old` carry entries where `pred` holds (vmap-safety).

    Under `jax.vmap` a `while_loop` body runs for every batch element while
    ANY element's condition holds; an element that already terminated must
    return its carry unchanged. Apply to the whole carry so a future field
    can't forget its mask.
    """
    return jax.tree.map(lambda n, o: jnp.where(pred, o, n), new, old)


def backtracking_armijo_aux(
    phi_aux,
    f_old: Scalar,
    gtd: Scalar,
    alphabar: Scalar,
    c1: float = 1e-4,
    max_iters: int = 35,
):
    """Armijo backtracking from max step `alphabar`, carrying eval aux.

    Reference src/lbfgsnew.py:124-174: start at `alphabar`, halve while
    `f(x + a d) > f_old + a * c1 * g.d`, up to `max_iters` halvings; the
    last step is returned even if the condition never held.

    `phi_aux(alpha) -> (loss, aux)`. The loop carries the aux of the
    LAST evaluated alpha, and that alpha IS the accepted one (the loop
    exits when the current pair satisfies the condition or exhausts the
    budget, and the vmap freeze keeps (alpha, loss, aux) triples
    consistent) — so the returned aux belongs to the returned step.
    This is what lets the engine fold its per-batch diagnostic forward
    into the accepted evaluation: `aux` carries the BN batch statistics
    and the raw data loss that the forward at the accepted point already
    computed (engine/steps.py).

    Returns `(alpha, n_evals, aux)`.

    vmap-safe: under `jax.vmap` a `while_loop` body runs for every batch
    element while ANY element's condition holds, so the halving is masked
    per element — a client whose Armijo condition already holds keeps its
    step unchanged while siblings continue backtracking.
    """
    prod = c1 * gtd

    def cond(carry):
        ci, alpha, f_new, _ = carry
        return jnp.logical_and(ci < max_iters, f_new > f_old + alpha * prod)

    def body(carry):
        ci, alpha, f_new, aux = carry
        active = (f_new > f_old + alpha * prod) & (ci < max_iters)
        alpha_half = 0.5 * alpha
        f_half, aux_half = phi_aux(alpha_half)
        return _freeze(
            ~active, (ci + 1, alpha_half, f_half, aux_half), carry
        )

    f1, aux1 = phi_aux(alphabar)
    vz = vma_zero(f_old)
    iz = vz.astype(jnp.int32)
    ci, alpha, _, aux = lax.while_loop(
        cond, body, (jnp.int32(0) + iz, alphabar + vz, f1 + vz, aux1)
    )
    return alpha, ci + 1, aux


def backtracking_armijo(
    phi: PhiFn,
    f_old: Scalar,
    gtd: Scalar,
    alphabar: Scalar,
    c1: float = 1e-4,
    max_iters: int = 35,
) -> Tuple[Scalar, Scalar]:
    """`backtracking_armijo_aux` without an aux payload; same contract."""
    alpha, evals, _ = backtracking_armijo_aux(
        lambda a: (phi(a), ()), f_old, gtd, alphabar, c1, max_iters
    )
    return alpha, evals


def backtracking_armijo_probes_aux(
    phi_aux,
    f_old: Scalar,
    gtd: Scalar,
    alphabar: Scalar,
    c1: float = 1e-4,
    max_iters: int = 35,
    probes: int = 4,
    fan_phi=None,
):
    """Batched multi-alpha Armijo: `probes` candidate steps per widened pass.

    The sequential search (`backtracking_armijo_aux`) walks the halving
    ladder `alphabar * 2^-j`, j = 0..max_iters, one full forward pass per
    probe — on the memory-bound L-BFGS roofline each pass re-streams the
    whole parameter vector from HBM (docs/PERF.md). Here each loop
    iteration evaluates a FAN of `probes` consecutive ladder rungs in ONE
    `jax.vmap`ped pass (the alpha axis stacks onto whatever batching the
    caller already runs — in the engine, the K-client vmap) and selects
    the first Armijo-satisfying rung on device.

    The SELECTED alpha matches the sequential search's: both accept the
    first rung j with
    `f(alphabar·2^-j) <= f_old + alphabar·2^-j · c1·gtd`, falling back to
    rung `max_iters` when none satisfies (exact for any `probes` when
    `phi_aux` is deterministic scalar code, the unit-proven property).
    One caveat in the widened engine pass: the fan evaluates `phi_aux` as
    a `[P·K]` batch, so XLA reduction order can move a loss by an ulp,
    and a rung sitting exactly on the Armijo threshold may flip its
    accept — same ladder, same rule, identical up to ulp-boundary ties.
    That (plus the batched-reduction ulps in the carried loss/aux) is why
    `probes == 1` callers must use `backtracking_armijo_aux` itself (the
    engine dispatches on the static `LBFGSConfig.ls_probes`) — that path
    is the bitwise fallback, this one is the amortized fan — and why
    `ls_probes` is a stream-tagged trajectory-changing knob.

    Returns `(alpha, n_evals, aux)` where `n_evals` counts EVERY ladder
    rung actually evaluated (`probes` per executed fan, minus rungs past
    `max_iters` masked out of the final fan) — the honest amortization
    accounting behind bench.py's `mean_func_evals_per_step`. The aux
    belongs to the returned alpha, as in the sequential search.

    vmap-safe like the sequential loop: a client whose fan already
    accepted keeps its carry frozen while siblings keep fanning.

    `fan_phi`, when given, replaces the default widened evaluation
    `jax.vmap(phi_aux)(alphas)` with `fan_phi(alphas) -> (losses, auxs)`
    over the `[P]` alpha fan. It MUST compute the same values as the
    default (same objective, same aux structure) — only the batching
    structure may differ. This is the widened-GEMM hook
    (`--client-fold gemm`, engine/steps.py): the engine's fan keeps the
    frozen partition groups' parameters UNBATCHED along the probe axis,
    so XLA's vmap batching rules fold the P axis into the matmul M
    dimension instead of emitting P skinny per-probe dots. `None`
    compiles today's exact fan byte-for-byte.
    """
    if probes < 1:
        raise ValueError(f"probes must be >= 1, got {probes}")
    prod = c1 * gtd
    dt = jnp.asarray(alphabar).dtype
    n_rungs = max_iters + 1  # the sequential search evaluates at most these
    n_fans = -(-n_rungs // probes)
    offsets = jnp.arange(probes, dtype=dt)
    # per-fan ladder factors: fan i covers rungs i*P .. i*P+P-1
    fan_step = jnp.asarray(0.5**probes, dt)

    def fan_eval(base, j0):
        """One widened pass over `probes` consecutive rungs from `base`."""
        alphas = base * (0.5**offsets)
        if fan_phi is not None:
            losses, auxs = fan_phi(alphas)
        else:
            losses, auxs = jax.vmap(phi_aux)(alphas)
        rung = j0 + jnp.arange(probes, dtype=jnp.int32)
        valid = rung < n_rungs
        ok = valid & ~(losses > f_old + alphas * prod)
        any_ok = ok.any()
        first_ok = jnp.argmax(ok)
        last_valid = jnp.minimum(probes - 1, n_rungs - 1 - j0)
        pick = jnp.where(any_ok, first_ok, last_valid).astype(jnp.int32)
        sel = lambda a: jnp.take(a, pick, axis=0)
        n_valid = jnp.sum(valid.astype(jnp.int32))
        return (
            sel(alphas),
            sel(losses),
            jax.tree.map(sel, auxs),
            any_ok,
            n_valid,
            # exhausting the ladder terminates like the sequential budget
            any_ok | (j0 + last_valid >= max_iters),
        )

    # fan 0 runs unconditionally (the sequential search always evaluates
    # alphabar); the loop continues only while unaccepted rungs remain
    a0, l0, aux0, _, ev0, done0 = fan_eval(alphabar, jnp.int32(0))
    vz = vma_zero(f_old)
    iz = vz.astype(jnp.int32)

    def cond(carry):
        (fan, _, _, _, _, done), _ = carry
        return jnp.logical_and(fan < n_fans - 1, jnp.logical_not(done))

    def body(carry):
        (fan, base, alpha, loss, aux, done), evals = carry
        # the NEXT fan: rungs (fan+1)*P .. , starting P rungs below `base`
        a, l, x, _, ev_f, done_f = fan_eval(base * fan_step, (fan + 1) * probes)
        new = (fan + 1, base * fan_step, a, l, x, done_f)
        frozen = _freeze(done, new, (fan, base, alpha, loss, aux, done))
        # a frozen client's fan result is discarded, so its count must
        # not grow either (the fan still RAN under vmap, but the honest
        # per-client accounting charges only the evaluations that could
        # influence that client's accepted step)
        evals = jnp.where(done, evals, evals + ev_f)
        return frozen, evals

    init = (
        (
            jnp.int32(0) + iz,
            alphabar + vz,
            a0 + vz,
            l0 + vz,
            aux0,
            done0 | (vz != 0),
        ),
        ev0 + iz,
    )
    (_, _, alpha, _, aux, _), evals = lax.while_loop(cond, body, init)
    return alpha, evals, aux


class _CubicConsts(NamedTuple):
    sigma: float = 0.1
    rho: float = 0.01
    t1: float = 9.0
    t2: float = 0.1
    t3: float = 0.5


def _dphi(phi: PhiFn, a: Scalar, step: float) -> Scalar:
    """Central-difference directional derivative (reference src/lbfgsnew.py:209-217)."""
    return (phi(a + step) - phi(a - step)) / (2.0 * step)


def _cubic_interpolate(phi: PhiFn, a: Scalar, b: Scalar, step: float) -> Scalar:
    """Cubic minimizer on [a,b] (or [b,a]); reference src/lbfgsnew.py:306-392."""
    f0 = phi(a)
    f0d = _dphi(phi, a, step)
    f1 = phi(b)
    f1d = _dphi(phi, b, step)

    aa = 3.0 * (f0 - f1) / (b - a) + f1d - f0d
    disc = aa * aa - f0d * f1d

    def pos_branch(_):
        cc = jnp.sqrt(jnp.maximum(disc, 0.0))
        denom = f1d - f0d + 2.0 * cc
        z0 = jnp.where(
            denom == 0.0, (a + b) * 0.5, b - (f1d + cc - aa) * (b - a) / denom
        )
        hi = jnp.maximum(a, b)
        lo = jnp.minimum(a, b)
        in_range = jnp.logical_and(z0 <= hi, z0 >= lo)
        # out-of-range probes get f0+f1 so they lose the 3-way minimum
        fz0 = jnp.where(in_range, phi(jnp.clip(z0, lo, hi)), f0 + f1)
        best_ab = jnp.where(f1 < fz0, b, z0)
        return jnp.where(jnp.logical_and(f0 < f1, f0 < fz0), a, best_ab)

    def neg_branch(_):
        return jnp.where(f0 < f1, a, b)

    return lax.cond(disc > 0.0, pos_branch, neg_branch, operand=None)


def _zoom(
    phi: PhiFn,
    a: Scalar,
    b: Scalar,
    phi_0: Scalar,
    gphi_0: Scalar,
    consts: _CubicConsts,
    step: float,
    max_iters: int = 4,
) -> Scalar:
    """Zoom stage on bracket [a,b]; reference src/lbfgsnew.py:399-482.

    vmap-safe: once an element's `found` flag is set its carry is frozen
    (under vmap the body keeps running while any sibling still searches,
    and the bracket update would otherwise drift past the accepted step).
    """

    def cond(carry):
        ci, _, _, _, found = carry
        return jnp.logical_and(ci < max_iters, jnp.logical_not(found))

    def body(carry):
        ci, aj, bj, alphak, found = carry
        p01 = aj + consts.t2 * (bj - aj)
        p02 = bj - consts.t3 * (bj - aj)
        alphaj = _cubic_interpolate(phi, p01, p02, step)
        phi_j = phi(alphaj)
        phi_aj = phi(aj)

        armijo_fail = jnp.logical_or(
            phi_j > phi_0 + consts.rho * alphaj * gphi_0, phi_j >= phi_aj
        )

        gphi_j = _dphi(phi, alphaj, step)
        roundoff = (aj - alphaj) * gphi_j <= step
        curvature_ok = jnp.abs(gphi_j) <= -consts.sigma * gphi_0
        found_now = jnp.logical_and(
            jnp.logical_not(armijo_fail), jnp.logical_or(roundoff, curvature_ok)
        )

        # bracket updates when not found
        bj_new = jnp.where(
            armijo_fail,
            alphaj,
            jnp.where(gphi_j * (bj - aj) >= 0.0, aj, bj),
        )
        aj_new = jnp.where(armijo_fail, aj, alphaj)
        # a frozen element keeps its whole carry, including found=True
        return _freeze(
            found, (ci + 1, aj_new, bj_new, alphaj, found | found_now), carry
        )

    vz = vma_zero(phi_0)
    iz = vz.astype(jnp.int32)
    _, _, _, alphak, _ = lax.while_loop(
        cond, body, (jnp.int32(0) + iz, a + vz, b + vz, a + vz, vz != 0)
    )
    return alphak


def cubic_linesearch(
    phi: PhiFn,
    phi_0: Scalar,
    lr: float,
    step: float = 1e-6,
    max_iters: int = 3,
) -> Scalar:
    """Strong-Wolfe cubic line search; reference src/lbfgsnew.py:179-303.

    `phi(alpha) = loss(x + alpha * d)`, `phi_0 = phi(0)` (already evaluated).
    Returns the chosen step size. The outer bracketing loop runs at most 3
    extrapolations (reference `ci=1; while ci<4`, src/lbfgsnew.py:232-236);
    the zoom stage at most 4 (`ci=0; while ci<4`, :421-423).
    """
    consts = _CubicConsts()
    dt = jnp.asarray(phi_0).dtype
    tol = jnp.minimum(phi_0 * 0.01, 1e-6)
    gphi_0 = _dphi(phi, jnp.asarray(0.0, dt), step)
    mu = (tol - phi_0) / (consts.rho * gphi_0)

    # Outer bracketing loop. Exit codes: 0 = keep looping, 1 = accept alphai,
    # 2 = zoom(alphai1, alphai), 3 = zoom(alphai, alphai1).
    def cond(carry):
        ci, _, _, _, code = carry
        return jnp.logical_and(ci < max_iters, code == 0)

    def body(carry):
        ci, alphai, alphai1, phi_prev, code_in = carry
        phi_i = phi(alphai)

        accept0 = phi_i < tol
        bracket1 = jnp.logical_or(
            phi_i > phi_0 + alphai * gphi_0,
            jnp.logical_and(ci > 0, phi_i >= phi_prev),
        )
        gphi_i = _dphi(phi, alphai, step)
        accept2 = jnp.abs(gphi_i) <= -consts.sigma * gphi_0
        bracket3 = gphi_i >= 0.0

        code = jnp.where(
            accept0,
            1,
            jnp.where(bracket1, 2, jnp.where(accept2, 1, jnp.where(bracket3, 3, 0))),
        ).astype(jnp.int32)

        # extrapolation step (only meaningful when code==0)
        take_mu = mu <= 2.0 * alphai - alphai1
        p01 = 2.0 * alphai - alphai1
        p02 = jnp.minimum(mu, alphai + consts.t1 * (alphai - alphai1))
        alphai_interp = _cubic_interpolate(phi, p01, p02, step)
        alphai_next = jnp.where(take_mu, mu, alphai_interp)
        alphai1_next = jnp.where(take_mu, alphai, alphai1)

        # vmap-safety: an element that already exited (code_in != 0) must
        # keep its carry bit-identical — re-running the body with the
        # incremented ci can flip `bracket1`'s `ci > 0` clause and change
        # the exit code (see module docstring on batched while_loops).
        # `keep` is algorithmic (an element whose exit code was just set
        # keeps the alphai it exited with); the _freeze handles elements
        # that exited on a PREVIOUS iteration.
        keep = code == 0
        new = (
            ci + 1,
            jnp.where(keep, alphai_next, alphai),
            jnp.where(keep, alphai1_next, alphai1),
            jnp.where(keep, phi_i, phi_prev),
            code,
        )
        return _freeze(code_in != 0, new, carry)

    vz = vma_zero(phi_0)
    iz = vz.astype(jnp.int32)
    alpha1 = jnp.asarray(10.0 * lr, dt) + vz
    ci, alphai, alphai1, _, code = lax.while_loop(
        cond,
        body,
        (jnp.int32(0) + iz, alpha1, vz, phi_0, jnp.int32(0) + iz),
    )

    def do_zoom(bracket):
        a, b = bracket
        return _zoom(phi, a, b, phi_0, gphi_0, consts, step)

    alphak = lax.switch(
        jnp.clip(code, 0, 3),
        [
            # loop exhausted: fall back to lr (+vz matches the other
            # branches' varying-axis type)
            lambda _: jnp.asarray(lr, dt) + vz,
            lambda _: alphai,  # accepted directly
            lambda _: do_zoom((alphai1, alphai)),
            lambda _: do_zoom((alphai, alphai1)),
        ],
        operand=None,
    )

    # degenerate cases: flat direction or non-finite mu -> step 1.0
    degenerate = jnp.logical_or(jnp.abs(gphi_0) < 1e-12, jnp.isnan(mu))
    return jnp.where(degenerate, jnp.asarray(1.0, dt), alphak)
