"""The training engine: configs, sharded step builders, and the driver.

Collapses the reference's five near-clone driver scripts (SURVEY.md §1)
into one `Trainer` over pluggable consensus strategies, with the hot loops
compiled as sharded XLA programs (see `steps.py`).
"""

from federated_pytorch_test_tpu.engine.config import (
    KNOB_DOMAINS,
    PRESETS,
    ExperimentConfig,
    get_preset,
)
from federated_pytorch_test_tpu.engine.trainer import Trainer, run_experiment

__all__ = [
    "ExperimentConfig",
    "KNOB_DOMAINS",
    "PRESETS",
    "Trainer",
    "get_preset",
    "run_experiment",
]
