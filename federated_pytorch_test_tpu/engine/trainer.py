"""The experiment driver: partition rounds, consensus, eval, checkpointing.

One `Trainer` replaces all five reference driver scripts (SURVEY.md §1:
they are near-clones differing only in model, loop sizes, and which
coordination algorithm is inlined). The loop nest is the reference's
`Nloop { groups { Nadmm { epochs { batches } } } }`
(reference src/federated_trio.py:11-14,256-285). By default the whole
`Nadmm { epochs { batches } + consensus + eval }` body of one partition
round — the `check_results` eval sweeps included (`fold_eval`) — is ONE
jitted dispatch (`_run_round_fused`, engine/steps.py build_round_fn);
with `--no-fuse-rounds` (or where fusion cannot preserve semantics —
`_fused_enabled`) each `{batches}` body is one jitted sharded epoch call
and each consensus exchange one jitted collective, the same trajectory
bit for bit. Evals that run outside a fused program are ASYNC: the
sweep is enqueued at its cadence point and the blocking host fetch is
deferred to the round boundary (`evaluate_deferred`,
utils/metrics.py Deferred), so no eval stalls the device queue between
rounds.

With `--virtual-clients N --cohort C` (clients/, docs/SCALE.md) the
loop nest grows one outer stage: each `Nloop` iteration GATHERS a
seeded, replayable cohort of C virtual clients out of a host-side
chunked store into exactly these programs (the client axis is then the
cohort, sharded over the mesh as ever), runs the loop's partition
rounds unchanged — still one dispatch per round — and SCATTERS the
survivors' state back before the loop's stream marker and checkpoint.
By default the NEXT loop's gather is prefetched on a background thread
while this loop trains (clients/prefetch.py — bitwise-identical
adoption, `--no-prefetch` fallback), and the store's resident set can
be LRU-bounded (`--store-resident-chunks`) so host RSS stays flat in N
(docs/SCALE.md §Spilled store).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import time
import warnings
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.clients import (
    ClientStore,
    CohortPrefetcher,
    CohortSampler,
)
from federated_pytorch_test_tpu.consensus import quarantine_release_2f
from federated_pytorch_test_tpu.data import (
    client_stats,
    load_cifar,
    make_federated,
    virtual_shard_assignment,
)
from federated_pytorch_test_tpu.engine.config import ExperimentConfig
from federated_pytorch_test_tpu.exchange import GroupScheduler, make_codec
from federated_pytorch_test_tpu.engine.steps import (
    GroupContext,
    build_consensus_fn,
    build_epoch_fn,
    build_eval_fn,
    build_round_fn,
    build_round_init_fn,
    build_stream_epoch_fn,
)
from federated_pytorch_test_tpu.fault import (
    FaultInjector,
    FaultPlan,
    IntegrityError,
    step_budgets,
    storage_shim_for,
)
from federated_pytorch_test_tpu.models import MODELS
from federated_pytorch_test_tpu.obs import (
    CommLedger,
    DeadlineController,
    DispatchCounter,
    FlightRecorder,
    HealthEngine,
    JsonlSink,
    TraceRecorder,
    cached_stamp,
    incidents_dir,
    memory_record,
    roofline_record,
)
from federated_pytorch_test_tpu.obs.sinks import jsonable
from jax.sharding import NamedSharding, PartitionSpec

from federated_pytorch_test_tpu.parallel import (
    CLIENT_AXIS,
    client_sharding,
    largest_feasible_mesh,
    mesh_size,
    replicated_sharding,
    shard_map,
)
from federated_pytorch_test_tpu.partition import (
    Partition,
    Segment,
    flatten_params,
)
from federated_pytorch_test_tpu.utils import (
    Deferred,
    MetricsRecorder,
    checkpoint_path,
    load_checkpoint,
    save_checkpoint,
)
from federated_pytorch_test_tpu.utils.checkpoint import _list_steps

PyTree = Any

# On-device materialization for host arrays that will later be DONATED.
# jax's CPU device_put can be ZERO-COPY: the device buffer aliases the
# source numpy memory. Donating such a buffer (the epoch fn donates
# flat/lstate/stats) lets XLA reuse memory whose lifetime is tied to a
# host array that may already be freed — observed as flaky garbage in
# the first shard of a restored `flat` (tests/test_fault.py crash-resume
# replay). One jitted copy allocates an XLA-owned buffer; module-level so
# the executable is cached across Trainer instances.
_owned_copy = jax.jit(jnp.copy)


def _epoch_seed(base: int, *parts: int) -> np.random.Generator:
    return np.random.default_rng([base & 0x7FFFFFFF, *[p & 0x7FFFFFFF for p in parts]])


class Trainer:
    """Builds all device state and step functions for one experiment."""

    def __init__(
        self, cfg: ExperimentConfig, verbose: bool = True, source=None, mesh=None
    ):
        """`mesh` overrides the auto-built device mesh — pass
        `parallel.multihost_client_mesh(K)` on pods (its `clients` axis
        size must divide `cfg.n_clients`)."""
        self.cfg = cfg
        self.recorder = MetricsRecorder(verbose=verbose)
        # run-lifecycle flags (obs/flight.py crash dumps): `close()` only
        # writes a crash bundle for a run that ENTERED `run()` and never
        # completed — benchmarks driving `run_round` by hand and then
        # closing must not leave phantom incidents
        self._run_started = False
        self._run_completed = False

        if cfg.compile_cache:
            # persistent XLA executable cache (`--compile-cache DIR`):
            # process-global jax config, set before any program below is
            # built so the first compile already populates it
            cache = os.path.abspath(cfg.compile_cache)
            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)

        if source is None:
            source = load_cifar(
                cfg.dataset,
                cfg.data_root,
                synthetic_ok=cfg.synthetic_ok,
                synthetic_n_train=cfg.synthetic_n_train,
                synthetic_n_test=cfg.synthetic_n_test,
            )
        # cross-device cohort mode (clients/, docs/SCALE.md): the data is
        # split into `data_shards` disjoint shards (default one per
        # virtual client) and virtual client v holds shard v mod shards;
        # the compiled programs' client axis is the COHORT (config
        # normalization forces n_clients == cohort), so `self.fed` here
        # is the shard POOL — only the sampled cohort's shards are ever
        # device-resident (gathered per outer loop, _begin_loop_cohort)
        self._cohort_mode = cfg.virtual_clients is not None
        self._cohort_ids = None
        n_shards = (
            (cfg.data_shards or cfg.virtual_clients)
            if self._cohort_mode
            else cfg.n_clients
        )
        self.fed = make_federated(source, n_shards, biased=cfg.biased_input)
        if self.fed.steps_per_epoch(cfg.batch) == 0:
            raise ValueError(
                f"batch={cfg.batch} exceeds the per-client shard size "
                f"({self.fed.shard_size}): zero lockstep steps fit in an "
                "epoch — shrink the batch"
            )
        self.mesh = mesh if mesh is not None else largest_feasible_mesh(
            cfg.n_clients, cfg.max_devices
        )
        if cfg.n_clients % mesh_size(self.mesh) != 0:
            raise ValueError(
                f"n_clients={cfg.n_clients} not divisible by the mesh's "
                f"clients axis ({mesh_size(self.mesh)})"
            )

        model_cls = MODELS[cfg.model]
        fields = getattr(model_cls, "__dataclass_fields__", {})
        kw = {}
        if "num_classes" in fields:
            kw["num_classes"] = self.fed.num_classes
        if "dtype" in fields:
            kw["dtype"] = jnp.dtype(cfg.compute_dtype)
        # flax adds 'parent'/'name' to every Module's dataclass fields;
        # they are wiring, not model knobs
        settable = set(fields) - {"parent", "name"}
        bad = sorted(set(cfg.model_kwargs) - settable)
        if bad:
            raise ValueError(
                f"model_kwargs {bad} are not fields of {cfg.model!r} "
                f"({model_cls.__name__}); valid extras: "
                f"{sorted(settable - set(kw))}"
            )
        kw.update(cfg.model_kwargs)
        self.model = model_cls(**kw)

        variables = self._init_variables()
        params_t = jax.tree.map(lambda x: x[0], variables["params"])
        flat0, self.unravel = flatten_params(params_t)
        self.n_params = int(flat0.shape[0])
        flat = jax.vmap(lambda p: flatten_params(p)[0])(variables["params"])
        self.has_stats = "batch_stats" in variables
        stats = variables.get("batch_stats", {})

        # virtual-client store + cohort sampler (clients/). The store's
        # pristine rows broadcast the common-seed init (config requires
        # init_model in cohort mode), so N never costs N inits or N rows
        # of host memory — only touched chunks materialize. Fields:
        # "flat", one per batch-stats leaf, and per-group "rho/<gid>"
        # registered lazily at each group's first scatter. Stats leaves
        # are addressed by tree path in canonical flatten order, the same
        # order `jax.tree.leaves(self.stats)` yields at scatter time.
        # storage-integrity plumbing (fault/io.py, docs/FAULT.md
        # §Storage-integrity axis): the plan is parsed ONCE here and the
        # one shim instance (None without a storage axis) is handed to
        # every disk-facing byte path — client store, checkpoint writer,
        # metric stream — plus the injector, whose scoreboard counts the
        # faults the shim actually fired
        self._fault_plan = (
            FaultPlan.parse(cfg.fault_plan) if cfg.fault_plan else None
        )
        self._storage_shim = (
            storage_shim_for(self._fault_plan) if self._fault_plan else None
        )

        self.store = None
        self.sampler = None
        self._prefetch = None
        if self._cohort_mode:
            n_v = cfg.virtual_clients
            # THE shard assignment + honest per-client sample counts
            # (data/pipeline.py virtual_shard_assignment)
            shard_ids, sample_counts = virtual_shard_assignment(
                source.train_images.shape[0], n_v, n_shards
            )
            if (
                cfg.store_resident_chunks is not None
                and jax.process_count() > 1
            ):
                # every process holds the full host-side store and
                # would race the SAME deterministic chunk filenames in
                # the shared spill dir (save() is process-0-gated for
                # exactly this reason, but evictions fire at scatter
                # time on every process). The multi-host client axis is
                # ROADMAP 4d — per-host shard-local stores land there.
                raise NotImplementedError(
                    "store_resident_chunks on a multi-process mesh is "
                    "not supported: eviction spills would race on the "
                    "shared spill directory (single-writer rule)"
                )
            self.store = ClientStore(
                n_v, shard_ids, sample_counts,
                chunk_clients=cfg.store_chunk_clients,
                # spilled residency (docs/SCALE.md §Spilled store): the
                # LRU budget bounds host RSS flat in N; evicted dirty
                # chunks spill under the checkpoint dir, where the next
                # manifest commits them like any other chunk version
                resident_chunks=cfg.store_resident_chunks,
                spill_dir=(
                    cfg.checkpoint_dir
                    if cfg.store_resident_chunks is not None
                    else None
                ),
                # storage integrity (docs/FAULT.md §Storage-integrity
                # axis): checksum every spilled chunk + manifest, verify
                # before rows reach a gather, repair through the ladder
                checksums=cfg.store_checksums,
                storage_io=self._storage_shim,
            )
            self.store.register_field("flat", np.asarray(flat0))
            stats_leaves, self._stats_def = jax.tree_util.tree_flatten(stats)
            stats_paths = jax.tree_util.tree_flatten_with_path(stats)[0]
            self._stats_fields = []
            for (path, leaf) in stats_paths:
                name = "stats/" + jax.tree_util.keystr(path)
                self._stats_fields.append(name)
                self.store.register_field(name, np.asarray(leaf[0]))
            # per-virtual-client reliability state (telemetry-steered
            # cohorts, docs/SCALE.md): scalar counters accumulated in
            # the store at scatter time — they ride the dirty-chunk
            # checkpoint, so a restored run samples from exactly the
            # history its checkpoint committed
            if cfg.cohort_weighting == "telemetry":
                for name in self._TELEM_FIELDS:
                    self.store.register_field(
                        name, np.zeros((), np.float32)
                    )
            self.sampler = CohortSampler(
                n_v,
                cfg.cohort,
                seed=cfg.cohort_seed,
                weighting=cfg.cohort_weighting,
                sample_counts=self.store.sample_counts,
                telemetry_weights=(
                    self._telemetry_weights
                    if cfg.cohort_weighting == "telemetry"
                    else None
                ),
                # lazy: the injector is built further down — and churn-
                # free plans return None (an unrestricted pool)
                availability=self._pool_availability,
            )
            # normalization stats are a property of the VIRTUAL client
            # (they follow it into whatever cohort slot it lands in);
            # cycled exactly like the legacy per-client stats
            self._vmean, self._vstd = client_stats(n_v, cfg.biased_input)
            # pipelined cohort prefetch (clients/prefetch.py): loop
            # n+1's gather runs on a background thread while loop n
            # trains. Single-process only: a background jit/device_put
            # on global arrays would break the every-process-same-order
            # launch rule of multi-controller jax — multi-host runs
            # gather synchronously (the per-host shard-local gather is
            # ROADMAP 4d).
            if cfg.prefetch and jax.process_count() == 1:
                self._prefetch = CohortPrefetcher(self._prefetch_worker)

        # transformer-family checkpoints carry the fused-qkv column-order
        # version: the layout changed between rounds (head-major v2,
        # models/transformer.py QKV_LAYOUT_VERSION) and a stale checkpoint
        # would load shape-compatibly but compute scrambled attention
        self._qkv_layout = None
        if any(
            "qkv" in jax.tree_util.keystr(path)
            for path, _ in jax.tree_util.tree_flatten_with_path(params_t)[0]
        ):
            from federated_pytorch_test_tpu.models.transformer import (
                QKV_LAYOUT_VERSION,
            )

            self._qkv_layout = QKV_LAYOUT_VERSION

        # model partition (layer/block groups + metadata)
        self.model_partition = self.model.partition(params_t)
        # training partition: the trivial whole-vector group for independent
        # training (reference src/no_consensus_trio.py trains the full model)
        if cfg.strategy == "none":
            self.partition = Partition(
                groups=((Segment(0, self.n_params),),), total=self.n_params
            )
            self.group_order = [0]
        else:
            self.partition = self.model_partition
            order = list(
                self.model_partition.train_order
                or range(self.model_partition.num_groups)
            )
            if cfg.shuffle_group_order:
                # reference src/federated_trio_resnet.py:296-297: one fixed
                # np.seed(0) permutation, reused for every outer loop
                rng = np.random.RandomState(0)
                order = list(rng.permutation(self.model_partition.num_groups))
            if cfg.max_groups is not None:
                order = order[: cfg.max_groups]
            self.group_order = [int(g) for g in order]

        # device placement. Single-process, `_put` is jax.device_put; on a
        # multi-process (multi-host) mesh, device_put cannot address other
        # hosts' devices, so each process instead supplies its OWN shards
        # from the (identical, deterministically built) host array —
        # make_array_from_callback assembles the global array without any
        # cross-host data motion: the multi-host data feed is just "every
        # host indexes its slice of the same recipe"
        def _put(x, sh):
            if jax.process_count() == 1:
                return jax.device_put(x, sh)  # device-side reshard, no copy
            x = np.asarray(x)
            return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

        csh = client_sharding(self.mesh)
        rsh = replicated_sharding(self.mesh)
        self._put = _put
        self.flat = _put(flat, csh)
        self.stats = jax.tree.map(lambda x: _put(x, csh), stats)

        # training-data placement: resident (default) or host-streaming
        # when the dataset exceeds the HBM budget (see config;
        # VERDICT round-1 weak #5 — the native PrefetchBatcher existed but
        # the engine could only train device-resident data)
        data_bytes = (
            self.fed.train_images.nbytes + self.fed.train_labels.nbytes
        )
        self._stream = (
            cfg.hbm_data_budget_mb is not None
            and data_bytes > cfg.hbm_data_budget_mb * (1 << 20)
        )
        self._batchers = None
        if self._stream:
            if cfg.eval_every_batch:
                raise NotImplementedError(
                    "eval_every_batch needs the resident data path"
                )
            if cfg.save_model and jax.process_count() > 1:
                # fail FAST: save() would raise this after a full outer
                # loop of training otherwise (see save() for why)
                raise NotImplementedError(
                    "checkpointing a multi-process STREAMING run is not "
                    "supported (no process holds the full-K stream "
                    "positions); disable save_model or use the resident "
                    "data path"
                )
            from federated_pytorch_test_tpu.data.native import PrefetchBatcher

            self.shard_imgs = None
            self.shard_labels = None
            # HOST-SHARDED streaming (round-4 VERDICT item 8): each
            # process batches only the clients whose mesh devices it
            # owns — the natural extension of the per-client batchers.
            # `_put`'s make_array_from_callback path then assembles the
            # global chunk with each process supplying its own columns;
            # streams are pure functions of (seed, batch, client), so
            # any process layout produces the identical global data
            # order (asserted against the single-process twin in
            # tests/test_multiprocess.py).
            self._stream_clients = self._local_clients()
            self._batchers = {
                c: PrefetchBatcher(
                    np.ascontiguousarray(self.fed.train_images[c]),
                    np.ascontiguousarray(self.fed.train_labels[c]),
                    cfg.batch,
                    seed=cfg.seed + 1000 + c,
                )
                for c in self._stream_clients
            }
        elif self._cohort_mode:
            # only the sampled cohort's shards ever reach the device:
            # _begin_loop_cohort gathers [C]-leading slices per outer
            # loop (the data half of the gather → round → scatter cycle)
            self.shard_imgs = None
            self.shard_labels = None
        else:
            self.shard_imgs = _put(self.fed.train_images, csh)
            self.shard_labels = _put(self.fed.train_labels, csh)
        if self._cohort_mode:
            # placeholder until the first gather: run() replaces these
            # with the cohort's per-virtual-client stats each loop
            self.mean = _put(self._vmean[: cfg.n_clients], csh)
            self.std = _put(self._vstd[: cfg.n_clients], csh)
        else:
            self.mean = _put(self.fed.mean, csh)
            self.std = _put(self.fed.std, csh)
        # the padded test sweep is staged as device-resident COMMITTED
        # arrays exactly once, here: every eval — standalone program or
        # folded into the fused round — reuses these buffers with zero
        # per-eval host->device transfer (regression-tested under
        # jax.transfer_guard in tests/test_fold_eval.py). The true test
        # count is cached host-side too, so computing an accuracy from
        # correct counts costs no device fetch of the mask.
        t_imgs, t_labels, t_mask = self._stack_test()
        self._test_total = int(t_mask.sum())
        self.test_imgs = _put(t_imgs, rsh)
        self.test_labels = _put(t_labels, rsh)
        self.test_mask = _put(t_mask, rsh)

        # per-group jitted functions, built lazily and cached
        self._epoch_fns: Dict[int, Any] = {}
        self._consensus_fns: Dict[int, Any] = {}
        self._init_fns: Dict[int, Any] = {}
        self._round_fns: Dict[int, Any] = {}  # fused one-dispatch rounds
        self._eval_fn = None
        self._health_fn = None
        self._completed_nloops = 0
        self._step_num = 0
        self._loop_quar = None  # telemetry cohorts: the loop's [C]
        # per-slot quarantine counts (reset each gather, folded into the
        # store's reliability rows at scatter)
        self._round_poisoned = False  # set by the fault checks in
        # rollback mode; consumed at each partition-round boundary
        # per-(group, client) ADMM penalty, PERSISTENT across outer loops:
        # the reference allocates rho=[L,K]*rho0 once outside both loops
        # (reference src/consensus_admm_trio.py:263), so BB adaptations for
        # a layer carry over to its next visit; y/z/yhat are re-zeroed per
        # round (reference :281-302) and are not stored
        self._rho_store: Dict[int, Any] = {}
        # per-(group, client) error-feedback residual (`--error-feedback`,
        # exchange/, docs/PERF.md): what the lossy wire codec lost at the
        # client's LAST exchange of a group, added back before its next
        # encode. Same lifecycle as rho — persistent across outer loops,
        # checkpointed, rolled back with a poisoned round, and carried
        # per VIRTUAL client through the ClientStore in cohort mode
        # (`ef/<gid>` fields, registered at the group's first scatter).
        self._ef_store: Dict[int, Any] = {}
        # adaptive layer-group scheduling (exchange/schedule.py): which
        # partition group each round slot runs — decided at slot start
        # from the streamed per-round drift signal, memoized here (and
        # streamed as `group_schedule`). roundrobin leaves all of this
        # machinery off: the legacy fixed order, bit-identical streams.
        self._adaptive = cfg.group_schedule == "adaptive"
        self._scheduler = None
        self._schedule_decisions: Dict[tuple, dict] = {}

        # fault injection (fault/): replayable chaos — per-round dropout
        # masks, straggler stalls, planned crash points. The all-ones mask
        # is the no-chaos default and is BIT-identical to the pre-mask
        # consensus math (consensus/fedavg.py, consensus/admm.py).
        self.injector = None
        if cfg.fault_plan:
            self.injector = FaultInjector(
                self._fault_plan,
                # cohort mode keys every schedule by VIRTUAL client id:
                # the plan draws [N] rows and the trainer gathers the
                # cohort's columns (_vslice), so a client's fault
                # identity — dropped, slow, Byzantine — follows it across
                # cohorts instead of being a property of its slot
                cfg.virtual_clients if self._cohort_mode else cfg.n_clients,
                # crash sentinels live with the checkpoints they recover
                # from; without checkpointing the record is process-local
                state_dir=cfg.checkpoint_dir if cfg.save_model else None,
                # the storage shim built above: its injected-fault count
                # joins the end-of-run scoreboard
                storage=self._storage_shim,
            )
            if self.injector.has_churn:
                if not self._cohort_mode:
                    raise ValueError(
                        "the fault plan schedules churn, which removes "
                        "virtual clients from the sampler's available "
                        "pool — it requires --virtual-clients/--cohort "
                        "(a fixed cross-silo cohort has no pool to "
                        "leave; model per-round absence with dropout)"
                    )
                if cfg.cohort_weighting == "identity":
                    raise ValueError(
                        "churn contradicts cohort_weighting='identity': "
                        "identity is full participation every loop, but "
                        "a churned client is unavailable to sample"
                    )
        self._full_mask = _put(
            np.ones(cfg.n_clients, np.float32), csh
        )

        # observability (obs/, docs/OBSERVABILITY.md): dispatch/recompile
        # counting, the communication-volume ledger, and host-side trace
        # spans. The JSONL metric sink attaches AFTER the restore below —
        # its truncation point is the restored loop cursor.
        self._dispatch = DispatchCounter()
        self._diag_fn = None  # jitted group_distances, built on first use
        # the ledger counts WIRE bytes (exchange/ codec zoo — the codec's
        # exact bytes_on_wire: half per value under bf16, index+value
        # pairs under topk, scale header + packed levels under quant)
        # against the full-model PARAMETER-width baseline. THE codec
        # instance is shared with the consensus body's build
        # (steps.py _wire_codec uses the same make_codec mapping), so
        # the program and the ledger cannot disagree about the wire.
        wire_dtype = cfg.exchange_dtype if cfg.strategy != "none" else "float32"
        self._wire_codec = make_codec(
            wire_dtype,
            cfg.exchange_codec if cfg.strategy != "none" else None,
            cfg.topk_fraction,
            cfg.quant_bits,
        )
        self._comm = CommLedger(
            self.partition,
            cfg.n_clients,
            dtype_bytes=int(jnp.dtype(self.flat.dtype).itemsize),
            data_floor_bytes=int(data_bytes),
            exchange_dtype=wire_dtype,
            codec=self._wire_codec,
        )
        if cfg.trace_out and jax.process_index() == 0:
            self.recorder.tracer = TraceRecorder()

        if cfg.load_model or cfg.resume == "auto":
            try:
                self._restore()
            except FileNotFoundError:
                if cfg.load_model:
                    raise  # load_model REQUIRES a checkpoint; resume=auto
                    # starts fresh when none exists (first run of a chaos
                    # experiment, or every checkpoint was torn)
        # partition rounds already accounted for (diagnostics cadence):
        # derived from the restored cursor, not process history, so a
        # resumed run samples group_distance at the same global rounds an
        # uninterrupted one does
        self._rounds_done = self._completed_nloops * len(self.group_order)
        replay = []
        if cfg.metrics_stream and jax.process_index() == 0:
            # single-writer like the checkpoints: on a multi-process mesh
            # every process records identical series (metrics come off
            # allgathered values), so process 0's stream is THE stream
            sink = JsonlSink(
                cfg.metrics_stream,
                tag=self._stream_tag(),
                storage_io=self._storage_shim,
            )
            replay = sink.open(
                resume_nloops=self._completed_nloops
                if cfg.resume == "auto"
                else None
            )
            self.recorder.add_sink(sink, replay=replay)
            # replayed rounds will not re-run: seed the ledger's totals
            # so the end-of-run comm summary covers the whole run
            self._comm.absorb(self.recorder.series.get("comm_bytes", []))
        # flight recorder (obs/flight.py): a SINK beside the JSONL one,
        # so its ring mirrors exactly the resolved records the stream
        # persists (observers would see unharvested Deferred values and
        # rollback-discarded evals). Replay rebuilds the ring + the
        # anomaly rising-edge state; open() clears stale bundles — all
        # of them on a fresh stream, those at or past the restore loop
        # on resume (their rounds re-run and re-dump identically).
        self._flight = None
        if (
            cfg.flight_recorder
            and cfg.metrics_stream
            and jax.process_index() == 0
        ):
            self._flight = FlightRecorder(
                window=cfg.flight_window,
                dir=incidents_dir(cfg.metrics_stream),
                tag=self._stream_tag(),
            )
            self._flight.open(
                resume_nloops=self._completed_nloops
                if cfg.resume == "auto"
                else None
            )
            if replay:
                self._flight.replay(replay)
            self.recorder.sinks.append(self._flight)
        # anomaly-triggered device profiling (`--profile-on-anomaly`):
        # armed at an anomalous round boundary, captures the NEXT round
        # under a jax.profiler window, bounded per process
        self._profile_pending = False
        self._profile_captures = 0
        # storage_fault incident rising edge: detections + repairs the
        # store has surfaced that a previous round already reported
        self._storage_fault_seen = 0
        # live status sidecar for the `watch` console (obs/console.py):
        # memory and the current cursor are process facts that never
        # enter the stream, so they surface through this atomically
        # rewritten file instead
        self._status_path = (
            cfg.metrics_stream + ".status.json"
            if cfg.metrics_stream and jax.process_index() == 0
            else None
        )
        # in-run health engine (obs/health.py): a pure observer of the
        # streamed records — zero device dispatches. Replay BEFORE
        # attaching: the replayed records rebuild sketch/window state, so
        # a resumed run's post-restore `health` records equal an
        # uninterrupted twin's (the stream-identity contract).
        self._health_engine = None
        if cfg.health_monitor:
            self._health_engine = HealthEngine(window=cfg.health_window)
            if replay:
                self._health_engine.replay(replay)
            self.recorder.observers.append(self._health_engine)
        # closed-loop round deadlines (`--round-deadline auto[:pXX]`,
        # obs/health.py DeadlineController): a pure observer of the
        # streamed client_time records, replayed BEFORE attaching like
        # the health engine. Each round's decision is memoized in
        # `_deadline_decisions` (and streamed as the `deadline` series);
        # replayed decisions seed the memo, so a resumed run's budget
        # schedule — and its scoreboard — replay the crashed run's
        # exactly instead of re-estimating from a cold sketch.
        self._deadline_ctl = None
        self._deadline_decisions: Dict[tuple, float] = {}
        if self._ragged_enabled() and cfg.deadline_is_auto:
            step_t = (
                self.injector.plan.step_time_s
                if self.injector is not None
                else 1.0
            )
            self._deadline_ctl = DeadlineController(
                cfg.deadline_quantile,
                # warmup: the nominal full-work time — full budgets for
                # nominal-speed clients until the sketch has evidence
                warmup_s=float(self._round_total_steps() * step_t),
            )
            if self._completed_nloops and not replay:
                raise ValueError(
                    "resuming under --round-deadline auto requires the "
                    "run's --metrics-stream: past deadline decisions are "
                    "replayed from the stream, never re-estimated fresh "
                    "(a cold sketch would silently shift every "
                    "post-resume budget schedule)"
                )
            if replay:
                self._deadline_ctl.replay(replay)
                for rec in self.recorder.series.get("deadline", []):
                    self._deadline_decisions[
                        (int(rec["nloop"]), int(rec["group"]))
                    ] = float(rec["value"]["seconds"])
            self.recorder.observers.append(self._deadline_ctl)
        # adaptive layer-group scheduler (exchange/schedule.py): a pure
        # observer of the streamed per-round `group_distance` signal,
        # replayed BEFORE attaching exactly like the deadline controller;
        # per-slot decisions are memoized in `_schedule_decisions` (and
        # streamed as `group_schedule`), with replayed decisions seeding
        # the memo so a resumed run re-runs the crashed loop's slots
        # identically instead of re-deciding from a shifted signal.
        if self._adaptive:
            self._scheduler = GroupScheduler(
                self.group_order, skip_frac=cfg.group_skip_frac
            )
            if self._completed_nloops and not replay:
                raise ValueError(
                    "resuming under --group-schedule adaptive requires "
                    "the run's --metrics-stream: past slot decisions and "
                    "the drift signal they consumed are replayed from "
                    "the stream, never re-estimated fresh (a cold "
                    "scheduler would silently reorder every post-resume "
                    "round)"
                )
            if replay:
                self._scheduler.replay(replay)
                for rec in self.recorder.series.get("group_schedule", []):
                    v = rec["value"]
                    self._schedule_decisions[
                        (int(rec["nloop"]), int(v["slot"]))
                    ] = dict(v)
            self.recorder.observers.append(self._scheduler)
        # AOT round-program cost analysis (obs/roofline.py), stashed by
        # compile_round per group: feeds the end-of-run `roofline` record.
        # Replayed step_time records are the CRASHED process's walls —
        # the roofline median must start past them (same process-local
        # rationale as the record's stream=False).
        self._round_cost: Dict[int, dict] = {}
        self._replayed_step_times = len(
            self.recorder.series.get("step_time", [])
        )
        if (
            self._completed_nloops
            and cfg.strategy != "none"
            and not self.recorder.series.get("comm_bytes")
        ):
            # resumed WITHOUT a stream to absorb (no metrics_stream, or
            # the stream was abandoned): the skipped loops' traffic is
            # still exactly recomputable — masks are pure in (plan seed,
            # round cursor) — so the comm summary covers the whole run.
            # (Total bytes count TRANSMITTING clients, i.e. plan
            # survivors, so this holds under quarantine too; only the
            # skipped loops' wasted-bytes attribution needs the model's
            # update norms and is not reconstructed here — resume with a
            # stream to keep it.)
            for nloop in range(self._completed_nloops):
                for gid in self.group_order:
                    budgets = (
                        self._round_hetero(nloop, gid)[1]
                        if self._ragged_enabled()
                        else None
                    )
                    for a in range(cfg.nadmm):
                        m = (
                            # cohort mode: the historical loop's cohort
                            # is re-derived purely (sampler is a pure
                            # function of (seed, nloop)) and the [N]
                            # mask sliced to its transmitting members
                            self._vslice(
                                self.injector.mask(nloop, gid, a), nloop
                            )
                            if self.injector is not None
                            else np.ones(cfg.n_clients, np.float32)
                        )
                        if budgets is not None:
                            # zero-budget clients never transmitted
                            # (deadline rounds) — same pure-plan recompute
                            m = m * (budgets[a] > 0)
                        self._comm.account(gid, int(m.sum()))
        if cfg.average_model:
            # one-shot whole-model average before training
            # (reference src/no_consensus_trio.py:22,134-160)
            if jax.process_count() == 1:
                # device-side mean: no host round trip. NOTE: XLA's f32
                # reduction order is its own — not guaranteed bitwise
                # equal to the multi-process branch's host numpy mean
                # (both are exact to ~1 ulp; runs comparing across the
                # two branches should compare curves, not bits)
                self.flat = self._put(
                    jnp.broadcast_to(
                        jnp.mean(self.flat, axis=0), self.flat.shape
                    ),
                    csh,
                )
            else:
                host_flat = self._fetch(self.flat)
                self.flat = _owned_copy(self._put(
                    np.broadcast_to(
                        host_flat.mean(axis=0), host_flat.shape
                    ).copy(),
                    csh,
                ))

    # ---------------------------------------------------------------- setup

    def _stream_tag(self) -> str:
        """Identity stamp of the JSONL metric stream's header line.

        A resumed run must only splice onto a stream written by the SAME
        experiment, so the tag digests the WHOLE config (any knob —
        nepoch, batch, strategy, model_kwargs... — changes the series)
        except the pure output paths, plus the parsed fault plan's digest
        (fault/injector.py plan_tag — `fault_plan` may be a file path
        whose contents changed). A mismatch costs a fresh stream with a
        warning; a silent splice of two experiments would be worse.
        """
        d = dataclasses.asdict(self.cfg)
        # excluded: pure output paths, and `resume` — the recovery switch
        # is exactly the knob a restarted run flips, and the trajectory it
        # continues is guarded by the checkpoint-marker alignment, not by
        # config identity. `compile_cache` is an output-side path too, and
        # `fold_eval`/`async_eval` are dispatch-shape knobs whose record
        # streams are identical by contract (tests/test_fold_eval.py) —
        # a resumed run may flip any of them and still splice.
        # `linesearch_probes` and `exchange_dtype` are deliberately NOT
        # excluded: both change the trajectory (batched-reduction ulps /
        # wire rounding), so a resumed run that flips either must refuse
        # to splice (tests/test_exchange.py). The health knobs are
        # analysis-only (a pure observer of the records — never
        # trajectory-changing), so like the dispatch-shape knobs a
        # resumed run may flip them and still splice
        # (tests/test_health.py splice-accepted regression). The flight/
        # memory/profiler knobs are analysis-only in the same sense:
        # rings, bundles, RSS reads, and profiler windows never touch
        # the trajectory (tests/test_flight.py). `prefetch` is a
        # dispatch-shape knob like fold_eval (the adopted gather is
        # bit-identical to a cold one — tests/test_prefetch.py) and
        # `store_resident_chunks` a memory-shape one (residency never
        # changes a gathered byte): a resumed run may flip either and
        # still splice. `store_checksums` is a durability knob on the
        # same byte path — verified reads return the same bytes
        # unverified ones would (tests/test_integrity.py), so a resumed
        # run may flip it and still splice.
        for k in (
            "metrics_stream", "trace_out", "profile_dir", "resume",
            "compile_cache", "fold_eval", "async_eval",
            "health_monitor", "health_window",
            "flight_recorder", "flight_window", "memory_telemetry",
            "profile_on_anomaly", "profile_budget",
            "prefetch", "store_resident_chunks", "store_checksums",
        ):
            d.pop(k, None)
        cfg_tag = hashlib.md5(
            json.dumps(d, sort_keys=True, default=repr).encode()
        ).hexdigest()[:8]
        plan = self.injector.plan_tag if self.injector is not None else "noplan"
        return f"{self.cfg.name}:seed{self.cfg.seed}:cfg{cfg_tag}:{plan}"

    def _init_variables(self) -> PyTree:
        """Stacked client variables.

        `init_model=True`: all clients identical (common-seed Xavier init,
        reference src/federated_trio.py:229-236). Otherwise each client gets
        its own draw (the reference's three independently-constructed nets,
        reference src/no_consensus_trio.py:114-116).
        """
        cfg = self.cfg
        dummy = jnp.zeros((1,) + tuple(self.model.input_shape()), jnp.float32)
        if cfg.init_model:
            v = self.model.init(jax.random.PRNGKey(cfg.seed), dummy, train=False)
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (cfg.n_clients,) + x.shape),
                v,
            )
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), cfg.n_clients)
        vs = [self.model.init(k, dummy, train=False) for k in keys]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *vs)

    def _stack_test(self):
        """Pad + stack the test sweep as HOST [T,B,...] arrays.

        Stays numpy: the caller `_put`s the stack straight to its final
        replicated sharding, one transfer — a `jnp.asarray` here would
        first materialize an uncommitted copy on the default device and
        then reshard it.
        """
        b = self.cfg.eval_batch
        imgs, labels, masks = [], [], []
        for i, l, m in self.fed.test_batches(b):
            imgs.append(i)
            labels.append(l)
            masks.append(m)
        return (
            np.stack(imgs),
            np.stack(labels),
            np.stack(masks),
        )

    def _ctx(self, gid: int) -> GroupContext:
        cfg = self.cfg
        reg_on_active = (
            cfg.reg_mode == "active_linear"
            and gid in self.partition.linear_group_ids
        )
        reg_segments = ()
        if cfg.reg_mode == "first_linear" and self.model_partition.linear_group_ids:
            first = self.model_partition.linear_group_ids[0]
            reg_segments = self.model_partition.groups[first]
        return GroupContext(
            model=self.model,
            unravel=self.unravel,
            partition=self.partition,
            gid=gid,
            has_stats=self.has_stats,
            lbfgs=cfg.lbfgs_config(),
            strategy=cfg.strategy,
            admm=cfg.admm_config(),
            reg_on_active=reg_on_active,
            reg_segments=reg_segments,
            lambda1=cfg.lambda1,
            lambda2=cfg.lambda2,
            remat=cfg.remat,
            # the switch load-balance term is only sown when the model has
            # experts; a zero coef keeps non-MoE programs free of the
            # intermediates collection entirely
            moe_aux_coef=(
                cfg.moe_aux_coef
                if getattr(self.model, "moe_experts", 0) else 0.0
            ),
            # the diagnostic forward is the only place running BN stats
            # refresh: models with batch stats always run it
            diag_forward=cfg.diag_forward or self.has_stats,
            fold_diag=cfg.fold_diag_forward,
            robust_agg=cfg.robust_agg,
            robust_f=cfg.robust_f,
            # exchange-bound defenses only exist where an exchange does
            quarantine_z=(
                cfg.quarantine_z if self._quarantine_enabled() else None
            ),
            corrupt=self._corruption_enabled(),
            corrupt_gauss=(
                self._corruption_enabled()
                and self.injector.plan.corrupt_mode == "gauss"
            ),
            ragged=self._ragged_enabled(),
            # the wire codec only exists where an exchange does; keeping
            # strategy-'none' contexts on the identity codec means their
            # programs (and cache keys) ignore the knob entirely
            exchange_dtype=(
                cfg.exchange_dtype if cfg.strategy != "none" else "float32"
            ),
            exchange_codec=(
                cfg.exchange_codec if cfg.strategy != "none" else None
            ),
            topk_fraction=cfg.topk_fraction,
            quant_bits=cfg.quant_bits,
            error_feedback=self._ef_enabled(),
            group_drift=self._adaptive,
            client_fold=cfg.client_fold,
        )

    def _quarantine_enabled(self) -> bool:
        return (
            self.cfg.quarantine_z is not None
            and self.cfg.strategy != "none"
        )

    def _quarantine_release_2f(self) -> Optional[int]:
        """The quarantine-release threshold, or None when release is off
        — consensus/robust.py `quarantine_release_2f`, THE one
        definition shared with the compiled program's in-scan release
        (engine/steps.py build_round_fn), applied here to the host
        replay of both trainer paths and the ledger's wasted-uplink
        attribution."""
        if not self._quarantine_enabled():
            return None
        return quarantine_release_2f(self.cfg.robust_agg, self.cfg.robust_f)

    def _effective_exchange_mask(self, transmit_np, qmask_np, quarantine):
        """One exchange's effective mask + wasted-sender count, the
        quarantine-release rule applied — the host twin of the fused
        program's in-scan decision (both paths call this; fused ==
        unfused == ledger by construction). Returns
        `(eff [K] f32, quarantined_now int)`: a released exchange
        consumes its suspects' uplink (nothing wasted)."""
        if not quarantine:
            return transmit_np, 0
        gated = transmit_np * qmask_np
        release_2f = self._quarantine_release_2f()
        if release_2f is not None and gated.sum() <= release_2f:
            return transmit_np, 0
        return gated, int((transmit_np * (1.0 - qmask_np)).sum())

    def _ef_enabled(self) -> bool:
        """Whether the consensus programs carry the error-feedback
        residual (steps.py `_ef_enabled` applies the same rule to the
        built context — ONE signature-fixing predicate per mechanism,
        the `_corruption_enabled` discipline). Config validation already
        requires a lossy codec; the strategy gate mirrors the codec's
        (no exchange, no wire, no residual)."""
        return self.cfg.error_feedback and self.cfg.strategy != "none"

    def _ef_for(self, gid: int):
        """The round's entry error-feedback residual `[K, group_size]`
        for `gid` — the persisted carry, or fresh zeros at the group's
        first-ever exchange (cohort mode gathers the cohort's rows at
        `_begin_loop_cohort` instead)."""
        ef = self._ef_store.get(gid)
        if ef is None:
            ef = self._put(
                np.zeros(
                    (self.cfg.n_clients, self.partition.group_size(gid)),
                    np.float32,
                ),
                client_sharding(self.mesh),
            )
        return ef

    def _corruption_enabled(self) -> bool:
        """Whether the consensus programs carry the corruption inputs.

        ONE definition on purpose: this predicate fixes the compiled
        programs' argument signature (GroupContext.corrupt) AND gates
        whether every call site passes the corruption rows — a drifted
        copy would be an argument-count mismatch at dispatch time.
        """
        return (
            self.injector is not None
            and self.injector.has_corruption
            and self.cfg.strategy != "none"
        )

    def _ragged_enabled(self) -> bool:
        """Whether rounds are deadline-based with ragged local work.

        Like `_corruption_enabled`, ONE definition fixes both the
        compiled programs' argument signature (GroupContext.ragged) and
        whether every call site passes the budget rows. Deadlines are a
        cohort concept — a client misses the deadline OF an exchange —
        so strategy-'none' runs (no exchange) stay lockstep.
        """
        return (
            self.cfg.round_deadline is not None
            and self.cfg.strategy != "none"
        )

    def _hetero_enabled(self) -> bool:
        """Whether the tail-latency telemetry records (client_time /
        step_budget / deadline_miss): any run with a deadline OR a plan
        scheduling slow clients. Homogeneous deadline-free runs record
        nothing, keeping their metric streams byte-identical to
        pre-heterogeneity ones."""
        return self.cfg.strategy != "none" and (
            self.cfg.round_deadline is not None
            or (self.injector is not None and self.injector.has_heterogeneity)
        )

    def _round_total_steps(self) -> int:
        """Lockstep inner steps of ONE consensus iteration's local work
        (the quantity a step budget is clipped against)."""
        return self.cfg.nepoch * self.fed.steps_per_epoch(self.cfg.batch)

    def _deadline_for(self, nloop: int, gid: int) -> Optional[float]:
        """Round `(nloop, gid)`'s deadline in simulated seconds.

        Fixed mode returns the configured constant; auto mode returns
        the memoized per-round decision (`_decide_deadline` takes it at
        round start; resume seeds the memo from replayed `deadline`
        records). Pure given the recorded history, so the budget rows,
        the straggler caps, and the end-of-run scoreboard all consume
        the ONE value per round. Never logs — the `deadline` record is
        `_decide_deadline`'s, emitted exactly once at the round's start
        (this accessor also serves resume-time reconstruction of
        historical fixed-deadline rounds, which must not re-stream).
        """
        if self.cfg.round_deadline is None:
            return None
        if not self.cfg.deadline_is_auto:
            return float(self.cfg.round_deadline)
        key = (int(nloop), int(gid))
        dl = self._deadline_decisions.get(key)
        if dl is None:
            # only run_round-adjacent paths reach here before the
            # decision record: take it now, un-streamed (the caller is
            # _decide_deadline itself or an out-of-band probe)
            dl, _ = self._deadline_ctl.decide()
            self._deadline_decisions[key] = dl
        return dl

    def _decide_deadline(self, nloop: int, gid: int) -> None:
        """Take (and stream) round `(nloop, gid)`'s deadline decision —
        called at the START of every deadline round, before any of the
        round's own records land in the sketch, so fused and unfused
        runs decide from the identical observation prefix."""
        key = (int(nloop), int(gid))
        if key in self._deadline_decisions:
            return  # replayed from the stream, or already decided
        if self.cfg.deadline_is_auto:
            dl, info = self._deadline_ctl.decide()
        else:
            dl, info = float(self.cfg.round_deadline), {"source": "fixed"}
        self._deadline_decisions[key] = dl
        self.recorder.log(
            "deadline", {"seconds": dl, **info}, nloop=nloop, group=gid
        )

    def _round_hetero(self, nloop: int, gid: int):
        """One round's heterogeneity schedule, all host-side numpy.

        Returns `(speeds [nadmm, K], budgets [nadmm, K] i32 or None,
        times [nadmm, K])`: per-step time multipliers from the plan's
        speed axis (all-ones without one), the deadline step budgets
        (None without a deadline), and each client's SIMULATED seconds
        to complete its full local work — the tail-latency evidence
        (`client_time` percentiles). Pure in (plan seed, cursor, config),
        so resumed runs re-derive identical records.
        """
        cfg = self.cfg
        total = self._round_total_steps()
        if self.injector is not None:
            # [nadmm, N] in cohort mode (virtual-id-keyed speed axis),
            # sliced to the loop's cohort columns
            speeds = self._vslice(
                self.injector.speeds_for_round(nloop, gid, cfg.nadmm), nloop
            )
            step_t = self.injector.plan.step_time_s
        else:
            speeds = np.ones((cfg.nadmm, cfg.n_clients), np.float32)
            step_t = 1.0
        times = total * step_t * speeds
        budgets = None
        dl = self._deadline_for(nloop, gid)
        if dl is not None:
            # the ONE deadline->budget conversion (fault/injector.py
            # step_budgets) — shared with the scoreboard so the program's
            # budgets and the deadline_misses rows cannot drift apart
            budgets = step_budgets(speeds, step_t, total, dl)
        return speeds, budgets, times

    def _record_hetero(
        self, times_a: np.ndarray, budgets_a, *, nloop, gid, a, total
    ) -> None:
        """Record one exchange's tail-latency observability: simulated
        client-time percentiles (+ the round's simulated wall — capped
        at the deadline, since the coordinator closes the round there),
        the per-client step budgets, and a `deadline_miss` record when
        any client's budget fell short of the lockstep step count."""
        deadline = self._deadline_for(nloop, gid)
        round_time = float(times_a.max())
        if deadline is not None:
            round_time = min(round_time, float(deadline))
        pct = {
            "p50": float(np.percentile(times_a, 50)),
            "p95": float(np.percentile(times_a, 95)),
            "p99": float(np.percentile(times_a, 99)),
            "max": float(times_a.max()),
            "round": round_time,
        }
        self.recorder.client_times(pct, nloop=nloop, group=gid, nadmm=a)
        if budgets_a is not None:
            self.recorder.step_budgets(
                budgets_a, nloop=nloop, group=gid, nadmm=a
            )
            missed = np.where(budgets_a < total)[0]
            if missed.size:
                self.recorder.deadline_miss(
                    missed, nloop=nloop, group=gid, nadmm=a
                )

    # ------------------------------------------------- virtual clients
    # (clients/, docs/SCALE.md): the gather -> rounds -> scatter cycle of
    # one outer loop, plus the virtual-id -> cohort-slot projection every
    # fault schedule rides.

    def _vslice(self, arr: np.ndarray, nloop: int):
        """Project a virtual-client-keyed last axis onto loop `nloop`'s
        cohort slots (identity in legacy mode).

        Fault schedules are drawn over the FULL virtual population
        ([..., N] rows, keyed by virtual id) and the compiled round
        program consumes cohort-slot rows ([..., C]); the projection is
        pure — the sampler re-derives any loop's cohort from (seed,
        nloop) — so resumed, fused, and unfused runs all slice the
        identical columns.
        """
        if not self._cohort_mode:
            return arr
        return np.asarray(arr)[..., self.sampler.cohort(nloop)]

    # per-virtual-client reliability counters (telemetry-steered
    # cohorts): scalar store fields, one row per client, accumulated at
    # scatter time from the loop's PURE fault schedule (speeds, masks,
    # budgets) plus the quarantine detections the round bookkeeping
    # observed — the one execution-derived input, which the trajectory
    # replay re-derives identically on resume.
    _TELEM_FIELDS = (
        "telem/exchanges",    # exchanges the client was scheduled into
        "telem/speed_sum",    # Σ per-exchange speed multipliers
        "telem/misses",       # deadline misses (budget < lockstep steps)
        "telem/drops",        # plan dropouts while sampled
        "telem/quarantines",  # times the defense flagged the client
        "telem/repairs",      # rows the integrity ladder had to repair
    )

    def _telemetry_weights(self) -> np.ndarray:
        """`[N]` positive sampling weights from the store's reliability
        counters — the CohortSampler's 'telemetry' provider.

        An unseen client gets the neutral prior (speed 1, no penalties,
        weight 1); an observed client's weight is
        `1 / (mean_speed * (1 + penalty_rate))` with `penalty_rate` the
        per-exchange rate of misses + drops + quarantines — slow or
        flaky phones are sampled less, reliable fast ones more, and no
        weight ever reaches 0 (every client stays reachable — starving
        a client forever on early evidence would be a fairness bug, not
        a policy). Pure in the store state, which is pure in (seed,
        nloop, recorded history) — so crashed+resumed twins, whose
        stores restore to the same committed snapshot, re-derive
        identical weights.
        """
        ids = np.arange(self.store.n_virtual, dtype=np.int64)
        ex = self.store.gather("telem/exchanges", ids).astype(np.float64)
        sp = self.store.gather("telem/speed_sum", ids).astype(np.float64)
        miss = self.store.gather("telem/misses", ids).astype(np.float64)
        drops = self.store.gather("telem/drops", ids).astype(np.float64)
        quar = self.store.gather(
            "telem/quarantines", ids
        ).astype(np.float64)
        # integrity repairs (docs/FAULT.md §Storage-integrity axis): a
        # client whose rows the ladder re-initialized carries a wiped,
        # untrustworthy history — penalize it like a miss so the sampler
        # leans on clients whose state is verified-intact. Zero on every
        # healthy run (retry-healed reads never count), so the weights —
        # and the trajectory — are unchanged unless data was truly lost.
        rep = self.store.gather("telem/repairs", ids).astype(np.float64)
        n = np.maximum(ex, 1.0)
        speed = np.where(ex > 0, sp / n, 1.0)
        penalty = (miss + drops + quar + rep) / n
        return 1.0 / (speed * (1.0 + penalty))

    def _pool_availability(self, nloop: int):
        """The sampler's availability hook: the churn axis's `[N]` pool
        mask for loop `nloop`, or None when the plan schedules no churn
        (an unrestricted pool). Pure in (plan seed, nloop)."""
        if self.injector is None or not self.injector.has_churn:
            return None
        return self.injector.availability(nloop)

    def _update_telemetry(self, nloop: int, ids: np.ndarray) -> None:
        """Fold one completed loop into the cohort's reliability rows
        (called from `_end_loop_cohort`, before the store snapshot that
        makes the loop durable — a crashed loop contributes nothing,
        and its re-run contributes exactly once).

        Speeds, drops, and budgets are re-derived from the pure plan
        (and the loop's memoized deadline decisions); quarantines come
        from the per-loop accumulator `_record_quarantine` maintains.
        Under the adaptive group schedule only rounds that actually RAN
        count (`_loop_visited_gids` — a dropout scheduled into a
        skipped slot never happened, and penalizing the client for it
        would skew the sampler; same rule as `injected_summary`'s
        visits).
        """
        cfg = self.cfg
        c = ids.size
        exchanges = np.zeros(c, np.float32)
        speed_sum = np.zeros(c, np.float32)
        misses = np.zeros(c, np.float32)
        drops = np.zeros(c, np.float32)
        total = self._round_total_steps()
        for gid in self._loop_visited_gids(nloop):
            if cfg.strategy == "none":
                break  # no exchange: nothing to be reliable AT
            speeds, budgets, _ = self._round_hetero(nloop, gid)
            masks = (
                self._vslice(
                    self.injector.masks_for_round(nloop, gid, cfg.nadmm),
                    nloop,
                )
                if self.injector is not None
                else np.ones((cfg.nadmm, c), np.float32)
            )
            exchanges += cfg.nadmm
            speed_sum += speeds.sum(axis=0).astype(np.float32)
            drops += (masks <= 0).sum(axis=0).astype(np.float32)
            if budgets is not None:
                misses += (budgets < total).sum(axis=0).astype(np.float32)
        updates = {
            "telem/exchanges": exchanges,
            "telem/speed_sum": speed_sum,
            "telem/misses": misses,
            "telem/drops": drops,
            "telem/quarantines": self._loop_quar.astype(np.float32),
        }
        for name, delta in updates.items():
            cur = self.store.gather(name, ids)
            self.store.scatter(name, ids, cur + delta)
        # repairs drain OUTSIDE the cohort: the ladder can fire on any
        # chunk a gather touched (telemetry weights read all N clients),
        # so the drained per-client counts are scattered wherever they
        # landed, not just into this loop's cohort rows
        repaired = self.store.take_repaired()
        if repaired:
            rids = np.asarray(sorted(repaired), np.int64)
            delta = np.asarray(
                [repaired[int(v)] for v in rids], np.float32
            )
            cur = self.store.gather("telem/repairs", rids)
            self.store.scatter("telem/repairs", rids, cur + delta)

    def _state_field_names(self) -> list:
        """Every store field the cohort gather assembles into device
        state, in gather order: `flat`, the batch-stats leaves, and the
        lazily-registered per-group `rho/<gid>` / `ef/<gid>` rows. THE
        one field list shared by the synchronous gather, the prefetch
        worker, and prefetch adoption — a drifted copy would gather a
        cohort missing a field."""
        return ["flat", *self._stats_fields] + [
            n for n in self.store.fields if n.startswith(("rho/", "ef/"))
        ]

    def _launch_prefetch(self, next_loop: int, known_dirty) -> None:
        """Start the background gather of loop `next_loop`'s cohort
        (clients/prefetch.py). Called at the weighting mode's decision
        point: the sampler draw here IS the loop's draw (memoized; the
        pure modes would re-derive it identically, the telemetry mode's
        caller pins this after the scatter committed the reliability
        history the draw reads)."""
        if self._prefetch is None or next_loop >= self.cfg.nloop:
            return
        ids = self.sampler.cohort(next_loop)
        self._prefetch.launch(next_loop, ids, known_dirty)

    def _prefetch_worker(self, nloop: int, ids, known_dirty):
        """The background half of the prefetch: store gathers, the
        cohort's data-shard slices, and their device puts — everything
        `_begin_loop_cohort`'s cold path does, off the round wall. Runs
        on the prefetch thread; the store's lock serializes its chunk
        reads against the main thread's scatter/save/evictions. Rows in
        `known_dirty` may go stale under the overlapping scatter, so
        state stays host-side for adoption-time patching unless the
        overlap is provably empty (data shards and normalization stats
        are static — never stale, always put here)."""
        csh = client_sharding(self.mesh)
        on_device = not np.intersect1d(ids, known_dirty).size
        with self.recorder.phase(
            "cohort_prefetch", record=False, nloop=nloop
        ):
            state = {
                name: self.store.gather(name, ids)
                for name in self._state_field_names()
            }
            if on_device:
                state = {
                    name: _owned_copy(self._put(arr, csh))
                    for name, arr in state.items()
                }
            shards = self.store.shard_ids[ids]
            data = (
                self._put(self.fed.train_images[shards], csh),
                self._put(self.fed.train_labels[shards], csh),
                self._put(self._vmean[ids], csh),
                self._put(self._vstd[ids], csh),
            )
        return {
            "fields": tuple(state),
            "state": state,
            "on_device": on_device,
            "known_dirty": np.asarray(known_dirty, np.int64),
            "data": data,
        }

    def _adopt_prefetch(self, pre: dict, ids, csh) -> dict:
        """Turn a prefetched payload into this loop's device state,
        bit-identical to a cold gather: patch the overlap rows the
        previous loop's scatter rewrote (they were unknowable at launch
        — re-gathered here, post-scatter), put any still-host-side
        fields, and gather fields registered after the launch (a
        group's first-ever rho/ef scatter happened mid-prefetch)."""
        state = dict(pre["state"])
        if not pre["on_device"]:
            overlap = np.nonzero(np.isin(ids, pre["known_dirty"]))[0]
            for name in pre["fields"]:
                arr = state[name]
                if overlap.size:
                    arr[overlap] = self.store.gather(name, ids[overlap])
                state[name] = _owned_copy(self._put(arr, csh))
        for name in self._state_field_names():
            if name not in state:
                state[name] = _owned_copy(
                    self._put(self.store.gather(name, ids), csh)
                )
        return state

    def _begin_loop_cohort(self, nloop: int) -> None:
        """Gather loop `nloop`'s cohort out of the virtual-client store.

        Everything slot-indexed that the round programs consume is
        assembled here, per outer loop: params (`flat`), batch stats,
        each group's persistent ADMM rho (pristine clients get the init
        row — exactly what `build_round_init_fn` would produce), the
        cohort members' data shards, and their per-virtual-client
        normalization stats. `_owned_copy` for the donated carries, as
        everywhere host arrays feed donating programs (module header).
        """
        if self.injector is not None and self.injector.has_churn:
            # the loop's pool state (pure in the plan seed): how many
            # virtual clients the churn axis removed from the sampler's
            # reach — streamed, so twins replay it identically
            avail = self.injector.availability(nloop)
            self.recorder.log(
                "availability",
                {
                    "available": int(avail.sum()),
                    "absent": int(avail.size - avail.sum()),
                },
                nloop=nloop,
            )
        ids = self.sampler.cohort(nloop)
        self._cohort_ids = ids
        if self.cfg.cohort_weighting == "telemetry":
            # the sampled cohort's normalized draw weights — the
            # steering evidence, aligned to cohort slots; pure in the
            # committed store history, so twins stream identical rows
            # (the sampler memoized the vector its draw used — no
            # second full-population telemetry gather)
            wn = self.sampler.draw_weights(nloop)
            self.recorder.log(
                "cohort_weight",
                {"weights": [round(float(wn[v]), 9) for v in ids]},
                nloop=nloop,
            )
            self._loop_quar = np.zeros(ids.size, np.float64)
        csh = client_sharding(self.mesh)
        with self.recorder.phase("cohort_gather", record=False, nloop=nloop):
            # take() INSIDE the span: if the background gather has not
            # finished, the blocking join lands on this wall — so the
            # span honestly shows any un-overlapped residue, and the
            # bench's prefetch_overlap_saved_s (off-span minus on-span)
            # cannot report overlap that never happened
            pre = (
                self._prefetch.take(nloop, ids)
                if self._prefetch is not None
                else None
            )
            if pre is None:
                state = {
                    name: _owned_copy(
                        self._put(self.store.gather(name, ids), csh)
                    )
                    for name in self._state_field_names()
                }
                shards = self.store.shard_ids[ids]
                self.shard_imgs = self._put(
                    self.fed.train_images[shards], csh
                )
                self.shard_labels = self._put(
                    self.fed.train_labels[shards], csh
                )
                self.mean = self._put(self._vmean[ids], csh)
                self.std = self._put(self._vstd[ids], csh)
            else:
                # adopt the background gather (clients/prefetch.py):
                # overlap rows are patched post-scatter, so the adopted
                # bytes are bit-identical to a cold gather's
                state = self._adopt_prefetch(pre, ids, csh)
                (self.shard_imgs, self.shard_labels,
                 self.mean, self.std) = pre["data"]
            self.flat = state.pop("flat")
            leaves = [state.pop(name) for name in self._stats_fields]
            self.stats = jax.tree_util.tree_unflatten(self._stats_def, leaves)
            # error-feedback residuals follow the VIRTUAL client like
            # rho: a client's uncompensated compression error rejoins it
            # in whatever cohort slot it lands in (pristine rows gather
            # the zero fill — a first-ever exchange has lost nothing)
            self._rho_store = {
                int(n.split("/", 1)[1]): a
                for n, a in state.items()
                if n.startswith("rho/")
            }
            self._ef_store = {
                int(n.split("/", 1)[1]): a
                for n, a in state.items()
                if n.startswith("ef/")
            }
        # the membership record: slot s of this loop's series holds
        # virtual client ids[s] — the slot->virtual-id key every other
        # per-client series of the loop is read against
        self.recorder.cohort(ids, nloop=nloop)
        if self.cfg.cohort_weighting != "telemetry":
            # pure-weighting decision point (docs/SCALE.md §Prefetch
            # lifecycle): loop nloop+1's cohort is already a pure
            # function of (seed, nloop+1), so its gather can overlap
            # this whole loop's rounds. This loop's own cohort is the
            # known-dirty set — the only rows the coming scatter writes.
            self._launch_prefetch(nloop + 1, known_dirty=ids)

    def _end_loop_cohort(self, nloop: int) -> None:
        """Scatter the cohort's updated state back into the store.

        The device->host copies are ENQUEUED asynchronously first (the
        rounds' dispatches are still draining when this runs, and
        `copy_to_host_async` overlaps the transfer with both the tail of
        that compute and the host-side bookkeeping here) and finalized
        by the blocking `_fetch`es below — which must complete before
        `commit_loop`'s stream marker and the checkpoint, so a crash
        never leaves the store behind the stream. Scatter must also
        complete before the NEXT loop's gather reads any row it wrote:
        consecutive cohorts may overlap, and a gather overtaking the
        scatter would hand the shared member stale rows. With prefetch
        on, the next gather may START earlier — the overlap rows are
        re-gathered post-scatter at adoption, which preserves exactly
        this ordering per row (clients/prefetch.py staleness rule).
        """
        ids = self._cohort_ids
        stats_leaves = jax.tree.leaves(self.stats)
        for arr in (
            self.flat, *stats_leaves,
            *self._rho_store.values(), *self._ef_store.values(),
        ):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass  # non-jax array (tests may inject numpy state)
        with self.recorder.phase(
            "cohort_scatter", record=False, nloop=nloop
        ), self.store.batched_writes():
            # batched_writes: ONE residency-eviction sweep for the whole
            # multi-field scatter (per-field enforcement would spill and
            # reload the same over-budget chunks once per field)
            self.store.scatter("flat", ids, self._fetch(self.flat))
            for name, leaf in zip(self._stats_fields, stats_leaves):
                self.store.scatter(name, ids, self._fetch(leaf))
            for gid, rho in sorted(self._rho_store.items()):
                rho_np = self._fetch(rho)
                name = f"rho/{gid}"
                if not self.store.has_field(name):
                    # pristine clients of later cohorts must gather the
                    # INIT rho — exactly admm_init's full(rho0) row
                    # (consensus/admm.py), so a client's first-ever round
                    # in any cohort starts from the same rho a legacy run
                    # would give it
                    self.store.register_field(
                        name,
                        np.full(
                            rho_np.shape[1:],
                            self.cfg.admm_rho0,
                            rho_np.dtype,
                        ),
                    )
                self.store.scatter(name, ids, rho_np)
            for gid, ef in sorted(self._ef_store.items()):
                ef_np = self._fetch(ef)
                name = f"ef/{gid}"
                if not self.store.has_field(name):
                    # pristine clients of later cohorts gather a ZERO
                    # residual — their first exchange has lost nothing
                    self.store.register_field(
                        name, np.zeros(ef_np.shape[1:], ef_np.dtype)
                    )
                self.store.scatter(name, ids, ef_np)
            if self.cfg.cohort_weighting == "telemetry":
                # reliability counters ride the same scatter-side commit
                # discipline as the state rows: a loop that crashes
                # before here contributes nothing, its re-run exactly
                # once (docs/SCALE.md §Telemetry-steered cohorts)
                self._update_telemetry(nloop, ids)
                self._loop_quar = None
        if self.cfg.cohort_weighting == "telemetry":
            # telemetry decision point (docs/SCALE.md §Prefetch
            # lifecycle): the draw reads reliability state this scatter
            # just committed, so it pins HERE — scatter-finalize — and
            # the launched gather overlaps the loop's commit tail
            # (stream marker + checkpoint), still ahead of loop
            # nloop+1's first dispatch. Nothing writes store ROWS
            # between here and adoption (the checkpoint writes files),
            # so the known-dirty set is empty.
            self._launch_prefetch(
                nloop + 1, known_dirty=np.empty(0, np.int64)
            )

    def _fns(self, gid: int):
        if gid not in self._epoch_fns:
            ctx = self._ctx(gid)
            builder = build_stream_epoch_fn if self._stream else build_epoch_fn
            c = self._dispatch
            self._epoch_fns[gid] = builder(ctx, self.mesh, counter=c)
            self._consensus_fns[gid] = build_consensus_fn(ctx, self.mesh, counter=c)
            self._init_fns[gid] = build_round_init_fn(ctx, self.mesh, counter=c)
        return self._epoch_fns[gid], self._consensus_fns[gid], self._init_fns[gid]

    def _init_fn(self, gid: int):
        if gid not in self._init_fns:
            self._init_fns[gid] = build_round_init_fn(
                self._ctx(gid), self.mesh, counter=self._dispatch
            )
        return self._init_fns[gid]

    def _fused_enabled(self) -> bool:
        """Whether `run_round` takes the fused one-dispatch path.

        Fusion must preserve the unfused semantics exactly, so it stands
        down when it cannot:
        * host-streaming data — minibatches are assembled per chunk on
          the host, which is inherently multi-dispatch;
        * `eval_every_batch` — the jitted eval sweep must interleave with
          single minibatches;
        * strategy 'none' with `check_results` — independent training
          evaluates per EPOCH, and the fused program only snapshots state
          at consensus boundaries;
        * rounds whose total scanned steps `nadmm*nepoch*S` exceed
          `max_scan_steps` — one fused dispatch would be exactly the
          long-scan program shape that cap exists to keep off fragile
          TPU runtimes (benchmarks/scan_bisect_tpu.py).
        """
        cfg = self.cfg
        if not cfg.fuse_rounds or self._stream:
            return False
        if cfg.check_results and cfg.eval_every_batch:
            return False
        if cfg.strategy == "none" and cfg.check_results:
            return False
        if cfg.max_scan_steps is not None:
            s = self.fed.steps_per_epoch(cfg.batch)
            if cfg.nadmm * cfg.nepoch * s > cfg.max_scan_steps:
                return False
        return True

    def _fold_eval_enabled(self) -> bool:
        """Whether the `check_results` eval cadence runs INSIDE the fused
        round program (the default). Folding requires the fused round
        itself (`_fused_enabled` is the whole fallback matrix — where
        fusion stands down, eval was never inside a program to fold) plus
        an eval cadence to fold (`check_results`) and the `fold_eval`
        knob (`--no-fold-eval` is the escape hatch, which keeps the fused
        round but evaluates its per-consensus snapshots outside)."""
        return (
            self._fused_enabled()
            and self.cfg.check_results
            and self.cfg.fold_eval
        )

    def _round_fn(self, gid: int):
        if gid not in self._round_fns:
            fold = self._fold_eval_enabled()
            self._round_fns[gid] = build_round_fn(
                self._ctx(gid),
                self.mesh,
                nadmm=self.cfg.nadmm,
                nepoch=self.cfg.nepoch,
                # mid-round state only needs materializing when an
                # OUTSIDE eval will read it; the folded eval consumes the
                # post-consensus state inside the program instead
                snapshot=self.cfg.check_results and not fold,
                fold_eval=fold,
                counter=self._dispatch,
            )
        return self._round_fns[gid]

    @property
    def eval_fn(self):
        if self._eval_fn is None:
            self._eval_fn = build_eval_fn(
                self.model, self.unravel, self.has_stats, self.mesh,
                counter=self._dispatch,
            )
        return self._eval_fn

    # ------------------------------------------------------------- training

    def _epoch_indices_host(self, *loop_ids: int) -> np.ndarray:
        """Per-client shuffled lockstep batch indices `[S, K, B]` (host).

        The `SubsetRandomSampler` equivalent (reference
        src/no_consensus_trio.py:59-61): each client reshuffles its own
        shard each epoch, deterministically in (seed, loop ids).
        """
        k, n = self.cfg.n_clients, self.fed.shard_size
        b = self.cfg.batch
        s = n // b
        rng = _epoch_seed(self.cfg.seed + 69, *loop_ids)
        perms = np.stack([rng.permutation(n) for _ in range(k)])  # [K, n]
        idx = perms[:, : s * b].reshape(k, s, b).transpose(1, 0, 2)  # [S,K,B]
        return idx.astype(np.int32)

    def _epoch_indices(self, *loop_ids: int) -> jnp.ndarray:
        """One epoch's indices, placed for the epoch fn's in_spec."""
        # _put keeps this correct on multi-host meshes (each host supplies
        # its own client columns of the deterministic permutation)
        sh = NamedSharding(self.mesh, PartitionSpec(None, CLIENT_AXIS))
        return self._put(self._epoch_indices_host(*loop_ids), sh)

    def _round_indices(self, nloop: int, gid: int) -> jnp.ndarray:
        """The whole round's shuffle schedule `[nadmm, nepoch, S, K, B]`.

        Row (a, e) is EXACTLY the unfused path's `_epoch_indices(nloop,
        gid, a, e)` draw, so the fused scan consumes the identical
        minibatch sequence (the bit-identity contract of
        tests/test_fused_round.py).
        """
        cfg = self.cfg
        idx = np.stack([
            np.stack([
                self._epoch_indices_host(nloop, gid, a, e)
                for e in range(cfg.nepoch)
            ])
            for a in range(cfg.nadmm)
        ])
        sh = NamedSharding(
            self.mesh, PartitionSpec(None, None, None, CLIENT_AXIS)
        )
        return self._put(idx, sh)

    def _fetch(self, x) -> np.ndarray:
        """Device -> host, multi-host-safe.

        np.asarray on an array spanning non-addressable devices raises;
        with >1 process the shards are all-gathered so every host sees
        the global value (outputs here are small: losses, counts, flat)."""
        if jax.process_count() == 1:
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    def evaluate(self, flat=None, stats=None) -> np.ndarray:
        """Per-client top-1 accuracy over the full test set, blocking.

        The synchronous convenience wrapper (external callers, parity
        harnesses): enqueue + immediate harvest. The training loop itself
        uses `evaluate_deferred` so the host sync moves off the hot path.
        """
        return self.evaluate_deferred(flat, stats).resolve()

    def evaluate_deferred(self, flat=None, stats=None) -> Deferred:
        """Enqueue the jitted eval sweep NOW, defer the host harvest.

        The dispatch is asynchronous: the device queue receives the eval
        program (reading `flat`/`stats` AS OF THIS CALL — a later
        rollback or donation cannot change what it computes) and the host
        returns immediately with a `Deferred` whose resolution performs
        the device->host fetch. The recorder harvests these at round
        boundaries, always before a commit marker/checkpoint
        (utils/metrics.py). With `async_eval=False` the fetch happens
        here instead — the pre-async timing, identical records.

        `flat`/`stats` default to the trainer's live state; the fused
        `--no-fold-eval` path passes its per-consensus-round snapshots.
        """
        with self.recorder.phase("eval_enqueue", record=False):
            correct = self.eval_fn(
                self.flat if flat is None else flat,
                self.stats if stats is None else stats,
                self.test_imgs,
                self.test_labels,
                self.test_mask,
                self.mean,
                self.std,
            )

        def harvest():
            with self.recorder.phase("eval_harvest", record=False):
                return self._fetch(correct) / self._test_total

        d = Deferred(harvest)
        if not self.cfg.async_eval:
            d.resolve()
        return d

    def _check_losses(self, losses: np.ndarray, **ctx) -> None:
        """Per-epoch failure detection: a client whose losses went
        non-finite is poisoned (the optimizer's NaN guards freeze its
        params, reference src/lbfgsnew.py:542, but the fault must surface)."""
        bad = np.where(~np.isfinite(losses).all(axis=0))[0]
        if bad.size:
            self.recorder.fault("nonfinite_loss", bad, **ctx)
            if self.cfg.fault_mode == "raise":
                raise FloatingPointError(
                    f"non-finite training loss on clients {bad.tolist()} ({ctx})"
                )
            self._round_poisoned = True

    def _check_params(self, **ctx) -> None:
        """Per-round failure detection: per-client parameter finiteness."""
        if self._health_fn is None:
            self._health_fn = self._dispatch.wrap(
                jax.jit(
                    lambda f: jnp.isfinite(f).all(axis=tuple(range(1, f.ndim)))
                ),
                "health",
            )
        self._check_param_flags(self._fetch(self._health_fn(self.flat)), **ctx)

    def _check_param_flags(self, ok_row: np.ndarray, **ctx) -> None:
        """`_check_params` from precomputed per-client finiteness flags.

        The fused round computes the post-consensus parameter check ON
        DEVICE for every consensus iteration (its mid-round parameters
        never reach the host) and returns the `[nadmm, K]` flag matrix;
        this applies the same warn/raise/rollback policy to one row.
        """
        bad = np.where(~np.asarray(ok_row, bool))[0]
        if bad.size:
            self.recorder.fault("nonfinite_params", bad, **ctx)
            if self.cfg.fault_mode == "raise":
                raise FloatingPointError(
                    f"non-finite parameters on clients {bad.tolist()} ({ctx})"
                )
            self._round_poisoned = True

    def _record_quarantine(
        self, qstats, qmask_np: np.ndarray, *, nloop, group, nadmm
    ) -> np.ndarray:
        """Record one exchange's auto-quarantine statistics and fold the
        new suspects into the round's quarantine mask (both trainer
        paths; consensus/robust.py `update_suspects` computed them on
        device). `qstats` is a pair of HOST `[K]` arrays — callers
        `_fetch` first (the fused path fetches its whole `[nadmm, K]`
        matrices once and slices). Returns the updated `[K]` qmask
        (1 = trusted)."""
        unorm, suspect = qstats
        u = np.asarray(unorm)
        s = np.asarray(suspect, np.float32)
        self.recorder.update_norms(u, nloop=nloop, group=group, nadmm=nadmm)
        flagged = np.where(s > 0)[0]
        if flagged.size:
            self.recorder.quarantine(
                flagged, nloop=nloop, group=group, nadmm=nadmm
            )
            if self._loop_quar is not None:
                # telemetry cohorts: quarantine history follows the
                # VIRTUAL client (slot -> id at scatter time)
                self._loop_quar[flagged] += 1
        return qmask_np * (1.0 - s)

    def _local_clients(self) -> list:
        """Global client ids whose mesh devices belong to THIS process.

        The 1-D `clients` mesh assigns each device a contiguous K/D
        block of local clients (parallel/mesh.py folding); a client is
        this process' iff its device is. Single-process: all of them.

        The computed ranges are ASSERTED against the sharding's own
        `devices_indices_map` and `addressable_devices`: streaming runs
        feed per-client host data through these ranges, so a future
        mesh/layout change that reorders device-to-shard assignment must
        fail loudly here rather than silently pair client c's stream
        with client c''s device column.
        """
        k = self.cfg.n_clients
        devs = list(self.mesh.devices.flat)
        per = k // len(devs)
        sh = client_sharding(self.mesh)
        dmap = sh.devices_indices_map((k,))
        for i, d in enumerate(devs):
            lo, hi, _ = dmap[d][0].indices(k)
            if (lo, hi) != (i * per, (i + 1) * per):
                raise AssertionError(
                    f"client sharding layout drifted: mesh device #{i} "
                    f"({d}) holds clients [{lo}, {hi}) but the contiguous "
                    f"K/D folding expects [{i * per}, {(i + 1) * per}) — "
                    "the host-side client ranges (streaming feed, "
                    "checkpoint positions) no longer match the device "
                    "layout"
                )
        if jax.process_count() == 1:
            return list(range(k))
        me = jax.process_index()
        local = [
            c
            for i, d in enumerate(devs)
            if d.process_index == me
            for c in range(i * per, (i + 1) * per)
        ]
        addressable = sorted(
            c
            for d in sh.addressable_devices
            for c in range(*dmap[d][0].indices(k)[:2])
        )
        if sorted(local) != addressable:
            raise AssertionError(
                f"_local_clients computed {sorted(local)} but the "
                f"sharding's addressable devices own {addressable}: the "
                "process-to-device mapping changed under the contiguous "
                "folding assumption"
            )
        return local

    def _ragged_args(self, budgets_np, offset: int, n_steps: int, last_loss):
        """Per-dispatch ragged arguments `(budgets [K], last_loss [K])`.

        The compiled epoch program masks steps against a budget LOCAL to
        its dispatch, so the round budget is offset by the lockstep
        steps already served (`offset`) and clipped to this dispatch's
        step count — the monotone prefix property (a client's active
        steps are the first `budget` of the round) makes the offset
        slicing exact.
        """
        csh = client_sharding(self.mesh)
        b = np.clip(budgets_np - offset, 0, n_steps).astype(np.int32)
        return self._put(b, csh), last_loss

    def _run_stream_epoch(
        self, epoch_fn, lstate, y, z, rho, budgets_np=None, last_loss=None
    ):
        """One epoch through the host-streaming path, double-buffered.

        Chunks of `stream_chunk_steps` lockstep minibatches are assembled
        host-side from the per-client PrefetchBatchers, `device_put`
        while the PREVIOUS chunk's jitted scan is still executing
        (dispatch is asynchronous), and consumed in order — H2D transfer
        overlaps compute, and only ~2 chunks of data are ever resident.
        `budgets_np` (ragged rounds) carries this EPOCH's per-client step
        budgets; each chunk gets the offset slice. Returns
        `(lstate, losses [S_total, K], last_loss)`.
        """
        cfg = self.cfg
        k = cfg.n_clients
        s_total = self.fed.steps_per_epoch(cfg.batch)  # > 0: checked at init
        chunk = max(1, min(cfg.stream_chunk_steps, s_total))
        sh = NamedSharding(self.mesh, PartitionSpec(None, CLIENT_AXIS))
        sample_shape = tuple(self.fed.train_images.shape[2:])

        def assemble(n_steps):
            # columns for clients owned by OTHER processes stay
            # uninitialized: `_put`'s per-device callback only ever reads
            # this process' own client columns (multi-host: each process
            # supplies its shards; single-process: all clients are local
            # and device_put reads everything)
            imgs = np.empty(
                (n_steps, k, cfg.batch) + sample_shape,
                self.fed.train_images.dtype,
            )
            labs = np.zeros((n_steps, k, cfg.batch), np.int32)
            for s in range(n_steps):
                for c in self._stream_clients:
                    im, lb = next(self._batchers[c])
                    imgs[s, c], labs[s, c] = im, lb
            return self._put(imgs, sh), self._put(labs, sh)

        remaining = s_total
        done = 0
        nxt = assemble(min(chunk, remaining))
        flat, stats = self.flat, self.stats
        losses = []
        while remaining > 0:
            n = min(chunk, remaining)
            remaining -= n
            cur_imgs, cur_labs = nxt
            if budgets_np is not None:
                b, ll = self._ragged_args(budgets_np, done, n, last_loss)
                flat, lstate, stats, l, last_loss = epoch_fn(
                    flat, lstate, stats, cur_imgs, cur_labs,
                    self.mean, self.std, y, z, rho, b, ll,
                )
            else:
                flat, lstate, stats, l = epoch_fn(
                    flat, lstate, stats, cur_imgs, cur_labs,
                    self.mean, self.std, y, z, rho,
                )  # asynchronous dispatch: host continues immediately
            done += n
            if remaining > 0:
                # assemble + stage the NEXT chunk while the device runs
                nxt = assemble(min(chunk, remaining))
            losses.append(l)
        self.flat, self.stats = flat, stats
        return lstate, np.concatenate(
            [self._fetch(l) for l in losses], axis=0
        ), last_loss

    def _run_resident_epoch(
        self, epoch_fn, lstate, y, z, rho, idx, budgets_np=None,
        last_loss=None,
    ):
        """One resident epoch, auto-chunked to `cfg.max_scan_steps`.

        A single jitted program scanning many hundred training steps can
        exceed what a TPU runtime will execute in one dispatch (the
        round-2 tunneled worker died on the 520-step ResNet epoch —
        benchmarks/scan_bisect_tpu.py pins the boundary), so epochs
        longer than the cap run as sequential calls over `idx` slices.
        The trajectory is bit-identical: the scan is sequential either
        way, and `flat/lstate/stats` carry across calls exactly as they
        carry across scan iterations. `budgets_np` (ragged rounds) is
        this epoch's per-client step budgets; chunked calls get offset
        slices. Returns `(lstate, losses [S, K], last_loss)`.
        """
        cap = self.cfg.max_scan_steps
        s_total = idx.shape[0]
        if cap is None or s_total <= cap:
            if budgets_np is not None:
                b, ll = self._ragged_args(budgets_np, 0, s_total, last_loss)
                (self.flat, lstate, self.stats, losses,
                 last_loss) = epoch_fn(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, idx, self.mean, self.std, y, z, rho,
                    b, ll,
                )
            else:
                self.flat, lstate, self.stats, losses = epoch_fn(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, idx, self.mean, self.std, y, z, rho,
                )
            return lstate, self._fetch(losses), last_loss
        losses = []
        for lo in range(0, s_total, cap):
            sl = idx[lo : lo + cap]
            if budgets_np is not None:
                b, ll = self._ragged_args(
                    budgets_np, lo, int(sl.shape[0]), last_loss
                )
                self.flat, lstate, self.stats, l, last_loss = epoch_fn(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, sl, self.mean, self.std, y, z, rho,
                    b, ll,
                )
            else:
                self.flat, lstate, self.stats, l = epoch_fn(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, sl, self.mean,
                    self.std, y, z, rho,
                )  # asynchronous dispatch: slices queue back-to-back
            losses.append(l)
        return lstate, np.concatenate(
            [self._fetch(l) for l in losses], axis=0
        ), last_loss

    def compile_round(self, gid: int) -> float:
        """AOT-compile one group's jitted programs WITHOUT executing the
        epoch.

        Lowers the epoch and consensus programs against the real round
        arguments (`jax.jit(...).lower(...).compile()` — no execution, no
        donation) so they land in the persistent XLA compile cache
        (utils/hostcpu.py). A later run of the same config pays only
        execution — the seeding half of the dryrun's two-phase scale64
        budget gate (`__graft_entry__.py`). Returns seconds spent.

        The cheap `init_fn` does execute: its outputs are the lowering
        arguments for the epoch program, and its own compile is seconds.
        """
        t0 = time.perf_counter()
        if self._stream:
            raise NotImplementedError(
                "compile_round seeds the resident epoch program; streaming "
                "epochs compile per-chunk shapes at first use instead"
            )
        if self._cohort_mode and self.shard_imgs is None:
            raise NotImplementedError(
                "compile_round in cohort mode needs a gathered cohort "
                "(the data arguments are per-loop slices); run() gathers "
                "one before its first round"
            )
        with self.recorder.phase("compile", record=False, group=gid):
            ctx_corrupt = self._corruption_enabled()
            if self._fused_enabled():
                # the hot program of a fused run IS the round program:
                # lower it against the real round arguments and stop —
                # the epoch / consensus programs would never be dispatched
                round_fn = self._round_fn(gid)
                lstate, y, z, rho, extra = self._init_fn(gid)(self.flat)
                idx = self._round_indices(0, gid)
                sh = NamedSharding(self.mesh, PartitionSpec(None, CLIENT_AXIS))
                masks = self._put(
                    np.ones((self.cfg.nadmm, self.cfg.n_clients), np.float32),
                    sh,
                )
                ef_args = (self._ef_for(gid),) if self._ef_enabled() else ()
                budget_args = ()
                if self._ragged_enabled():
                    budget_args = (
                        self._put(
                            np.full(
                                (self.cfg.nadmm, self.cfg.n_clients),
                                self._round_total_steps(),
                                np.int32,
                            ),
                            sh,
                        ),
                    )
                corr_args = ()
                if ctx_corrupt:
                    shape = (self.cfg.nadmm, self.cfg.n_clients)
                    corr_args = (
                        self._put(np.zeros(shape, np.int32), sh),
                        self._put(np.ones(shape, np.float32), sh),
                        self._put(np.zeros(shape, np.int32), sh),
                    )
                eval_args = (
                    (self.test_imgs, self.test_labels, self.test_mask)
                    if self._fold_eval_enabled()
                    else ()
                )
                compiled = round_fn.lower(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, idx, self.mean, self.std,
                    y, z, rho, extra, masks, *ef_args, *budget_args,
                    *corr_args, *eval_args,
                ).compile()
                self._stash_round_cost(gid, compiled)
                return time.perf_counter() - t0
            epoch_fn, consensus_fn, init_fn = self._fns(gid)
            lstate, y, z, rho, extra = init_fn(self.flat)
            idx = self._epoch_indices(0, gid, 0, 0)
            cap = self.cfg.max_scan_steps
            slices = [idx]
            if cap is not None and idx.shape[0] > cap:
                # chunked epochs execute [cap, K, B] slices plus one
                # remainder slice — both shapes must be seeded or the warm
                # run still pays a cold compile on the tail
                slices = [idx[:cap]]
                if idx.shape[0] % cap:
                    slices.append(idx[: idx.shape[0] % cap])
            for sl in slices:
                ragged_args = ()
                if self._ragged_enabled():
                    csh = client_sharding(self.mesh)
                    k = self.cfg.n_clients
                    ragged_args = (
                        self._put(
                            np.full(k, int(sl.shape[0]), np.int32), csh
                        ),
                        self._put(np.zeros(k, np.float32), csh),
                    )
                epoch_fn.lower(
                    self.flat, lstate, self.stats, self.shard_imgs,
                    self.shard_labels, sl, self.mean, self.std, y, z, rho,
                    *ragged_args,
                ).compile()
            if consensus_fn is not None:
                ef_args = (self._ef_for(gid),) if self._ef_enabled() else ()
                corr_args = ()
                if ctx_corrupt:
                    csh = client_sharding(self.mesh)
                    k = self.cfg.n_clients
                    corr_args = (
                        self._put(np.zeros(k, np.int32), csh),
                        self._put(np.ones(k, np.float32), csh),
                        self._put(np.zeros(k, np.int32), csh),
                    )
                consensus_fn.lower(
                    self.flat, y, z, rho, extra, jnp.int32(0),
                    self._full_mask, *ef_args, *corr_args,
                ).compile()
            return time.perf_counter() - t0

    def _stash_round_cost(self, gid: int, compiled) -> None:
        """Record the AOT-compiled round program's exact XLA FLOP/byte
        counts (the same counts the compiler schedules against —
        line-search probes, L-BFGS linear algebra, folded evals all
        included) for the end-of-run `roofline` record. Absent cost
        models degrade to no record, never a crash."""
        try:
            ca = compiled.cost_analysis()
            ca = ca if isinstance(ca, dict) else ca[0]
            flops = float(ca.get("flops", 0.0)) or None
            hbm = float(ca.get("bytes accessed", 0.0)) or None
            if flops or hbm:
                self._round_cost[gid] = {
                    "flops": flops,
                    "hbm_bytes": hbm,
                    "source": "xla_cost_analysis",
                }
        except Exception:
            pass

    def _entry_snapshot(self, gid: int):
        """Rollback-mode entry state: XLA-owned device copies.

        The epoch/round fns donate flat/stats, so holding the same arrays
        across the round would read donated buffers — but a fresh
        XLA-owned copy (never handed to the donating fn) survives
        donation, with no device->host round-trip (and no cross-host
        allgather on multi-process meshes).
        """
        return (
            _owned_copy(self.flat),
            jax.tree.map(_owned_copy, self.stats),
            _owned_copy(self._rho_store[gid])
            if gid in self._rho_store
            else None,
            # the error-feedback residual is round state like rho: a
            # rolled-back round's compression errors never happened
            _owned_copy(self._ef_store[gid])
            if gid in self._ef_store
            else None,
        )

    def _maybe_rollback(self, snap, nloop: int, gid: int) -> None:
        """Transactional rollback: discard the poisoned round wholesale
        and continue from its entry state. Everything else a round
        produces (lstate, y, z) is re-initialized per round anyway. The
        snapshots are XLA-owned device copies — safe to adopt directly
        (and to be donated by the next round's epoch fn).

        The round's evals go with it: their records are still pending
        (deferred, harvested only at the round boundary — after this),
        so a discarded round contributes NO test_accuracy records, in
        any eval mode (docs/FAULT.md §Rollback mode). The eval
        computations themselves already ran against the poisoned state;
        only their records are dropped."""
        if not self._round_poisoned:
            return
        self.recorder.discard_pending("test_accuracy")
        snap_flat, snap_stats, snap_rho, snap_ef = snap
        self.flat = snap_flat
        self.stats = snap_stats
        if snap_rho is not None:
            self._rho_store[gid] = snap_rho
        else:
            self._rho_store.pop(gid, None)
        if snap_ef is not None:
            self._ef_store[gid] = snap_ef
        else:
            self._ef_store.pop(gid, None)
        self.recorder.fault("round_rollback", [], nloop=nloop, group=gid)
        self._round_poisoned = False

    def run_round(self, nloop: int, gid: int) -> None:
        """One partition group's full round: init, Nadmm x (epochs + consensus).

        With `fault_mode='rollback'` the round is transactional: a host
        snapshot of (params, stats, rho) is taken on entry and restored if
        any epoch loss or post-consensus parameter goes NaN/Inf — the
        poisoned round is discarded wholesale and the run continues from
        its entry state (docs/FAULT.md).

        Default path: the whole round — every epoch and every consensus
        exchange — executes as ONE jitted program (`_run_round_fused`,
        engine/steps.py build_round_fn). The per-dispatch paths of
        `_run_round_unfused` remain for `--no-fuse-rounds` and the cases
        fusion cannot cover (`_fused_enabled`); both produce bit-identical
        trajectories.

        This wrapper is the round's observability boundary (obs/): one
        trace span covering the round, per-round `dispatch_count` /
        `recompile_count` deltas, the `--diagnostics-every` cadence, the
        health digest + `memory` record, the flight recorder's incident
        dump, the anomaly-armed profiler window, the `watch` status
        sidecar, and the per-round sink flush. The `health` record is
        logged BEFORE `dispatch_count`, which is therefore the round's
        FINAL streamed record in both trainer paths — the flight ring's
        segmentation boundary (obs/flight.py). An injected crash skips
        the per-round counters (their round never completed; the resumed
        run re-records it) but still flushes, so the crashed stream
        holds everything the round logged.
        """
        before = self._dispatch.snapshot()
        compiled_before = self._dispatch.compiled_programs()
        if self._ragged_enabled():
            # the round's deadline decision (and its `deadline` record)
            # is taken HERE, before any of the round's own client_time
            # observations can land in the auto policy's sketch — the
            # same position in both trainer paths, so fused and unfused
            # runs decide from the identical prefix
            self._decide_deadline(nloop, gid)
        # anomaly-armed profiler window (`--profile-on-anomaly DIR`): the
        # PREVIOUS round's health alert armed it; capture this round
        # under a jax.profiler trace, bounded by the per-process budget —
        # profiling that costs nothing until something is wrong
        prof_cm = contextlib.nullcontext()
        prof_dir = None
        if self._profile_pending:
            self._profile_pending = False
            if self._profile_captures < self.cfg.profile_budget:
                prof_dir = os.path.join(
                    self.cfg.profile_on_anomaly, f"round-{nloop}-{gid}"
                )
                os.makedirs(prof_dir, exist_ok=True)
                prof_cm = jax.profiler.trace(prof_dir)
                self._profile_captures += 1
        try:
            with prof_cm:
                with self.recorder.phase(
                    "round", record=False, nloop=nloop, group=gid
                ):
                    if self._fused_enabled():
                        self._run_round_fused(nloop, gid)
                    else:
                        self._run_round_unfused(nloop, gid)
        finally:
            self.recorder.flush()
        if prof_dir is not None:
            # a capture path is a fact about THIS process (a resumed run
            # re-arms from its own alerts): stream=False, like roofline
            self.recorder.log(
                "profile_capture", {"dir": prof_dir}, stream=False,
                nloop=nloop, group=gid,
            )
        self._rounds_done += 1
        # the diagnostics sample runs BEFORE the delta is taken, so its
        # dispatch (and first-use compile) land in THIS round's
        # dispatch_count/recompile_count instead of falling between
        # every delta window. The adaptive scheduler SUPERSEDES the
        # cadence: it already records `group_distance` every round from
        # the in-scan signal (exchange/schedule.py), so sampling again
        # here would duplicate records and (fused) waste a dispatch.
        every = self.cfg.diagnostics_every
        if (
            every is not None
            and not self._adaptive
            and self._rounds_done % every == 0
        ):
            self._record_group_distances(nloop, gid)
        # the round's health digest (obs/health.py): sketches + windowed
        # rates over the records logged above, no device work. A crashed
        # round never reaches this (like the counters) — the resumed run
        # re-records it, and the stream replay rebuilt the engine's state
        # so the re-recorded value matches an uninterrupted twin's.
        # Logged BEFORE dispatch_count: the counter record must stay the
        # round's final streamed line (the flight ring's boundary).
        anomalies: list = []
        if self._health_engine is not None:
            hval, anomalies = self._health_engine.round_record()
            self.recorder.log("health", hval, nloop=nloop, group=gid)
            if self.recorder.tracer is not None:
                for kind in anomalies:
                    self.recorder.tracer.instant(
                        f"health:{kind}", nloop=nloop, group=gid
                    )
        if self.cfg.memory_telemetry:
            # host RSS + device allocator stats (obs/memory.py): host
            # reads only, zero dispatches; a process fact, so
            # stream=False keeps twin streams byte-identical. Cohort
            # runs fold the store's live residency digest in — the
            # spilled-store gate reads RSS and residency off the same
            # record (and `watch` off the status sidecar it feeds).
            mem = memory_record()
            if self.store is not None:
                mem["store"] = self.store.residency()
            self.recorder.log(
                "memory", mem, stream=False,
                nloop=nloop, group=gid,
            )
        self.recorder.log(
            "dispatch_count",
            self._dispatch.delta_since(before),
            nloop=nloop,
            group=gid,
        )
        # recompiles are PROCESS-local (a resumed run recompiles programs
        # the crashed one had warm): kept out of the stream (stream=False)
        self.recorder.log(
            "recompile_count",
            self._dispatch.compiled_programs() - compiled_before,
            stream=False,
            nloop=nloop,
            group=gid,
        )
        if self.recorder.tracer is not None:
            # fold-mode-tagged counter track: Perfetto traces from a
            # 'gemm' and a 'vmap' run are distinguishable at a glance
            # (ISSUE-17 satellite; the dispatch_count METRIC categories
            # above stay untagged — every {round: 1} budget gate keys
            # on them)
            self.recorder.tracer.counter(
                f"dispatches:{self.cfg.client_fold}", self._dispatch.counts
            )
        self.recorder.flush()
        if self.store is not None:
            # storage_fault incident (docs/FAULT.md §Storage-integrity
            # axis): a round in which the store DETECTED corruption or
            # ran the repair ladder joins the anomaly path — the flight
            # recorder dumps a forensics bundle (rising-edge deduped
            # like any health anomaly). Retry-healed reads count as
            # detections here: the operator wants the bundle while the
            # flaky disk is still flaky.
            dig = self.store.integrity_digest()
            seen = (
                int(dig["failures"])
                + int(dig["repairs_prior"])
                + int(dig["repairs_reinit"])
            )
            if seen > self._storage_fault_seen:
                anomalies = list(anomalies) + ["storage_fault"]
            self._storage_fault_seen = seen
        if anomalies:
            if self.cfg.profile_on_anomaly:
                # capture the NEXT round (this one already ran)
                self._profile_pending = True
            if self._flight is not None:
                # the ring just closed this round's bucket
                # (dispatch_count above) — dump the incident bundle, the
                # triggering round last in it. The `incident` record is
                # a process fact (the bundle is a file beside the
                # stream): stream=False, twin streams untouched.
                path = self._flight.incident(
                    anomalies,
                    nloop=nloop,
                    group=gid,
                    round_ix=self._rounds_done - 1,
                    # bound method, not a call: the extras (plan slice,
                    # decision memos) are only built when the bundle
                    # actually dumps — a chronic anomaly dedupes first
                    extra=self._incident_extra,
                )
                if path is not None:
                    self.recorder.log(
                        "incident",
                        {
                            "kinds": list(anomalies),
                            "bundle": os.path.basename(path),
                            "round": self._rounds_done - 1,
                        },
                        stream=False,
                        nloop=nloop,
                        group=gid,
                    )
                    if self.recorder.tracer is not None:
                        self.recorder.tracer.instant(
                            "incident", kinds=list(anomalies),
                            nloop=nloop, group=gid,
                        )
                    if self.recorder.verbose:
                        print(
                            f"INCIDENT kinds={list(anomalies)} "
                            f"bundle={path}"
                        )
        if self._status_path is not None:
            self._write_status(nloop, gid)

    def _incident_extra(self) -> dict:
        """The non-ring half of an incident bundle (obs/flight.py): the
        deadline/schedule decision memos, the fault plan's slice over
        the in-ring rounds, and the latest `memory` record — everything
        a postmortem reaches for beyond the raw series, self-contained
        in the one file."""
        extra: dict = {
            "decisions": {
                "deadline": {
                    f"{n}:{g}": s
                    for (n, g), s in sorted(self._deadline_decisions.items())
                },
                "schedule": {
                    f"{n}:{s}": dict(v)
                    for (n, s), v in sorted(self._schedule_decisions.items())
                },
            },
            "memory": self.recorder.latest("memory"),
            "fault_plan": None,
        }
        if self.injector is not None:
            sl: dict = {}
            for bucket in self._flight.rounds() if self._flight else ():
                n, g = bucket.get("nloop"), bucket.get("group")
                if n is None or g is None:
                    continue
                per_round: dict = {}
                modes = None
                if self.injector.has_corruption:
                    modes = self.injector.corruption_for_round(
                        int(n), int(g), self.cfg.nadmm
                    )[0]
                for a in range(self.cfg.nadmm):
                    row: dict = {}
                    mask = self._vslice(
                        self.injector.mask(int(n), int(g), a), int(n)
                    )
                    dropped = np.where(mask == 0.0)[0]
                    if dropped.size:
                        row["dropped"] = [int(i) for i in dropped]
                    if modes is not None:
                        corrupted = np.where(
                            self._vslice(modes[a], int(n)) != 0
                        )[0]
                        if corrupted.size:
                            row["corrupted"] = [int(i) for i in corrupted]
                    if row:
                        per_round[str(a)] = row
                if per_round:
                    sl[f"{int(n)}:{int(g)}"] = per_round
            extra["fault_plan"] = {
                "spec": self.cfg.fault_plan,
                "tag": self.injector.plan_tag,
                "slice": sl,
            }
        return extra

    def _write_status(self, nloop: int, gid: int) -> None:
        """Atomically rewrite the `watch` console's live sidecar
        (`<stream>.status.json`): the current cursor plus the process
        facts — memory, profiler captures, incident count — that never
        enter the stream (obs/console.py reads it; a torn or missing
        file degrades to no panel, never an error)."""
        doc = {
            "nloop": int(nloop),
            "group": int(gid),
            "rounds_done": int(self._rounds_done),
            "nloops_total": int(self.cfg.nloop),
            "memory": self.recorder.latest("memory"),
            "deadline": self._deadline_for(nloop, gid),
            "incidents": len(self.recorder.series.get("incident", [])),
            "profile_captures": int(self._profile_captures),
            # who is producing these numbers (obs/provenance.py):
            # backend/chip/commit, cached so the per-round rewrite
            # never forks git — `watch` renders it as the prov row
            "provenance": cached_stamp(),
        }
        if self.store is not None:
            # live store residency for `watch` (and the spill smoke's
            # RSS-ceiling read rides the sidecar's memory block)
            doc["store"] = self.store.residency()
            doc["store"]["traffic"] = self.store.traffic()
            # live integrity digest (verified reads / failures / repair
            # ladder counts) — process facts like residency, surfaced
            # here and via `report --integrity`, never in the stream
            doc["integrity"] = self.store.integrity_digest()
        if self._storage_shim is not None:
            doc["storage_faults"] = int(self._storage_shim.injected)
        tmp = self._status_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, default=jsonable)
            os.replace(tmp, self._status_path)
        except OSError:
            pass  # a read-only run dir must not kill the round

    def _record_group_distances(self, nloop: int, gid: int) -> None:
        """Sample `parallel/diagnostics.py group_distances` into the
        `group_distance` series: per-group mean distance of each client's
        parameters from the cross-client mean, at the current `flat`."""
        if self._diag_fn is None:
            from federated_pytorch_test_tpu.parallel.diagnostics import (
                group_distances,
            )

            part = self.partition
            self._diag_fn = self._dispatch.wrap(
                jax.jit(
                    shard_map(
                        lambda xl: group_distances(xl, part),
                        mesh=self.mesh,
                        in_specs=PartitionSpec(CLIENT_AXIS),
                        out_specs=PartitionSpec(),
                    )
                ),
                "diagnostics",
            )
        dists = self._fetch(self._diag_fn(self.flat))
        self.recorder.group_distance(dists, nloop=nloop, group=gid)

    def _run_round_unfused(self, nloop: int, gid: int) -> None:
        """`run_round`'s per-dispatch path (see its docstring)."""
        cfg = self.cfg
        check = cfg.fault_mode != "off"
        rollback = cfg.fault_mode == "rollback"
        if rollback:
            snap = self._entry_snapshot(gid)
        self._round_poisoned = False
        epoch_fn, consensus_fn, init_fn = self._fns(gid)
        lstate, y, z, rho, extra = init_fn(self.flat)
        if cfg.strategy == "admm" and gid in self._rho_store:
            rho = self._rho_store[gid]  # carry BB-adapted rho across loops
        gsize = self.partition.group_size(gid)
        corrupt = self._corruption_enabled()
        quarantine = self._quarantine_enabled()
        ragged = self._ragged_enabled()
        hetero = self._hetero_enabled()
        ef_on = self._ef_enabled()
        # the error-feedback residual carried across this round's
        # exchanges (the fused path threads the same carry in-scan)
        ef = self._ef_for(gid) if ef_on else None
        total_steps = self._round_total_steps()
        s_epoch = self.fed.steps_per_epoch(cfg.batch)
        budgets_m = times_m = None
        if hetero:
            _, budgets_m, times_m = self._round_hetero(nloop, gid)
        # the ragged last-loss carry, threaded ACROSS the round's epoch
        # dispatches (the fused path carries it in-scan): a masked step's
        # loss row repeats the client's last recorded loss of the round
        last_loss = (
            self._put(
                np.zeros(cfg.n_clients, np.float32),
                client_sharding(self.mesh),
            )
            if ragged
            else None
        )
        # the round-scoped quarantine mask (1 = trusted): suspects flagged
        # at one exchange are excluded from the round's later exchanges —
        # the host-side twin of the fused round's in-carry qmask
        qmask_np = np.ones(cfg.n_clients, np.float32)

        for nadmm in range(cfg.nadmm):
            budgets_a = budgets_m[nadmm] if budgets_m is not None else None
            for epoch in range(cfg.nepoch):
                # this epoch's slice of the consensus iteration's budget
                # (steps already served by earlier epochs offset it)
                budget_e = (
                    budgets_a - epoch * s_epoch if ragged else None
                )
                # streaming shuffles inside the PrefetchBatcher instead
                idx = (
                    None
                    if self._stream
                    else self._epoch_indices(nloop, gid, nadmm, epoch)
                )
                self._step_num += 1
                per_batch_eval = cfg.check_results and cfg.eval_every_batch
                with self.recorder.phase(
                    "epoch", nloop=nloop, group=gid, nadmm=nadmm, epoch=epoch,
                    client_fold=cfg.client_fold,
                ), jax.profiler.StepTraceAnnotation(
                    "epoch", step_num=self._step_num
                ):
                    if self._stream:
                        lstate, losses, last_loss = self._run_stream_epoch(
                            epoch_fn, lstate, y, z, rho, budget_e, last_loss
                        )
                    elif per_batch_eval:
                        # reference check_results=True telemetry: evaluate
                        # after EVERY optimizer step (reference
                        # src/no_consensus_trio.py:266-267) — the epoch
                        # runs one jitted minibatch at a time so the
                        # jitted eval sweep interleaves
                        rows = []
                        for s in range(idx.shape[0]):
                            ragged_args = ()
                            if ragged:
                                ragged_args = self._ragged_args(
                                    budget_e, s, 1, last_loss
                                )
                            outs = epoch_fn(
                                self.flat,
                                lstate,
                                self.stats,
                                self.shard_imgs,
                                self.shard_labels,
                                idx[s : s + 1],
                                self.mean,
                                self.std,
                                y,
                                z,
                                rho,
                                *ragged_args,
                            )
                            if ragged:
                                (self.flat, lstate, self.stats, l_s,
                                 last_loss) = outs
                            else:
                                self.flat, lstate, self.stats, l_s = outs
                            rows.append(self._fetch(l_s)[0])
                            self.recorder.accuracies(
                                self.evaluate_deferred(),
                                nloop=nloop,
                                group=gid,
                                nadmm=nadmm,
                                epoch=epoch,
                                minibatch=s,
                            )
                        losses = np.stack(rows)  # [S, K]
                    else:
                        lstate, losses, last_loss = self._run_resident_epoch(
                            epoch_fn, lstate, y, z, rho, idx, budget_e,
                            last_loss,
                        )  # [S, K]
                for s in range(losses.shape[0]):
                    self.recorder.batch_losses(
                        losses[s],
                        nloop=nloop,
                        group=gid,
                        nadmm=nadmm,
                        epoch=epoch,
                        minibatch=s,
                    )
                if check:
                    self._check_losses(
                        losses, nloop=nloop, group=gid, nadmm=nadmm, epoch=epoch
                    )
                if (
                    cfg.strategy == "none"
                    and cfg.check_results
                    and not per_batch_eval  # already recorded per batch
                ):
                    # independent training has no consensus round; eval per
                    # epoch (the reference evals per batch,
                    # src/no_consensus_trio.py:266-267 — `eval_every_batch`
                    # reproduces that cadence exactly; per-epoch is the
                    # default because it keeps the epoch one computation)
                    self.recorder.accuracies(
                        self.evaluate_deferred(),
                        nloop=nloop, group=gid, nadmm=nadmm, epoch=epoch,
                    )
            if consensus_fn is not None:
                m_np = np.ones(cfg.n_clients, np.float32)
                if self.injector is not None:
                    m_np = self._vslice(
                        self.injector.mask(nloop, gid, nadmm), nloop
                    )
                    delay = self.injector.straggler_delay(nloop, gid, nadmm)
                    if delay > 0:
                        dl_cap = self._deadline_for(nloop, gid)
                        if dl_cap is not None:
                            # deadline rounds cap the coordinator's wait:
                            # past the deadline the round closes without
                            # the straggler instead of stalling for it
                            delay = min(delay, dl_cap)
                        # the coordinator waiting out a slow client before
                        # declaring the round: a host-side stall, recorded
                        # so chaos runs show up in the timing series
                        self.recorder.step_time(
                            "straggler_wait",
                            delay,
                            nloop=nloop,
                            group=gid,
                            nadmm=nadmm,
                        )
                        time.sleep(delay)
                if hetero:
                    self._record_hetero(
                        times_m[nadmm], budgets_a,
                        nloop=nloop, gid=gid, a=nadmm, total=total_steps,
                    )
                # a zero-budget client produced no report by the deadline:
                # it transmits nothing and drops out of the exchange like
                # a plan-dropped client
                transmit_np = (
                    m_np * (budgets_a > 0) if ragged else m_np
                ).astype(np.float32)
                # quarantined clients still transmit (they don't know);
                # the exchange discards their contribution — unless the
                # release rule fires (_effective_exchange_mask), in
                # which case it consumes it
                eff_np, quarantined_now = self._effective_exchange_mask(
                    transmit_np, qmask_np, quarantine
                )
                mask = (
                    self._full_mask
                    if eff_np.sum() >= self.cfg.n_clients
                    else self._put(
                        eff_np.astype(np.float32), client_sharding(self.mesh)
                    )
                )
                corr_args = ()
                if corrupt:
                    cm, cs, csd = (
                        self._vslice(row, nloop)
                        for row in self.injector.plan.corruption(
                            self.injector.n_clients, nloop, gid, nadmm
                        )
                    )
                    csh = client_sharding(self.mesh)
                    corr_args = (
                        self._put(cm, csh),
                        self._put(cs, csh),
                        self._put(csd, csh),
                    )
                ef_args = (ef,) if ef_on else ()
                with self.recorder.phase(
                    "consensus", nloop=nloop, group=gid, nadmm=nadmm
                ), jax.profiler.TraceAnnotation("consensus"):
                    (self.flat, y, z, rho, extra, met, qstats,
                     ef_out) = consensus_fn(
                        self.flat, y, z, rho, extra, jnp.int32(nadmm), mask,
                        *ef_args, *corr_args,
                    )
                    if ef_on:
                        ef = ef_out
                    dual, primal, mean_rho, survivors = (
                        self._fetch(m) for m in met
                    )
                is_admm = cfg.strategy == "admm"
                self.recorder.residuals(
                    primal if is_admm else None,
                    dual,
                    mean_rho if is_admm else None,
                    nloop=nloop,
                    group=gid,
                    nadmm=nadmm,
                    group_size=gsize,
                )
                if self.injector is not None:
                    self.recorder.participation(
                        int(survivors),
                        cfg.n_clients,
                        nloop=nloop,
                        group=gid,
                        nadmm=nadmm,
                    )
                # exact communicated bytes of this exchange (obs/ledger.py):
                # the active group's coordinates, every TRANSMITTING
                # client — plan survivors; a quarantined client's bytes
                # still cross the wire and are attributed as wasted
                self._comm.record(
                    self.recorder, gid, int(transmit_np.sum()),
                    nloop=nloop, nadmm=nadmm, quarantined=quarantined_now,
                )
                if quarantine:
                    qmask_np = self._record_quarantine(
                        (self._fetch(qstats[0]), self._fetch(qstats[1])),
                        qmask_np, nloop=nloop, group=gid, nadmm=nadmm,
                    )
            if check:
                self._check_params(nloop=nloop, group=gid, nadmm=nadmm)
            if self.injector is not None:
                # planned crash AFTER the round's consensus exchange —
                # exactly the state an outer-loop checkpoint mid-flight
                # would recover through resume='auto' (fault/injector.py)
                self.injector.maybe_crash(nloop, gid, nadmm)
            if cfg.check_results and not (
                cfg.eval_every_batch and cfg.strategy == "none"
                # params unchanged since the last per-batch eval (no
                # consensus step ran): the round-end record would be a
                # duplicate of it
            ):
                self.recorder.accuracies(
                    self.evaluate_deferred(), nloop=nloop, group=gid, nadmm=nadmm
                )
        if cfg.strategy == "admm":
            self._rho_store[gid] = rho
        if ef_on:
            self._ef_store[gid] = ef
        if self._adaptive and not (rollback and self._round_poisoned):
            # the adaptive scheduler's signal: the standalone jitted
            # group_distances program on the post-round state — the SAME
            # body the fused path computes in-program. A round the
            # rollback is about to DISCARD records no drift: its state
            # never survives, and a finite-but-poisoned distance (a
            # large-scale corruption the combiner let through) would
            # permanently inflate the scheduler's skip anchor — the
            # scheduler keeps its previous estimate, matching the
            # restored parameters (warn mode keeps the state, so its
            # drift records stay).
            self._record_group_distances(nloop, gid)
        if rollback:
            self._maybe_rollback(snap, nloop, gid)

    def _run_round_fused(self, nloop: int, gid: int) -> None:
        """One partition group's full round as ONE jitted dispatch.

        Semantically `run_round`'s loop nest with the dispatch tail
        harvested: the `nadmm x (nepoch + 1)` program launches collapse
        into a single donated-carry program (steps.build_round_fn), and
        everything the host used to do between launches moves to one
        side or the other of it —

        * epoch shuffle schedules and participation masks are precomputed
          (`_round_indices`, injector.masks_for_round) and fed as scan
          inputs;
        * straggler stalls are served as one up-front stall (the
          coordinator waiting out every slow client of the round),
          recorded per consensus iteration as before;
        * the loss/parameter fault checks inspect the round's outputs
          ONCE after the dispatch — losses come back as the `[nadmm,
          nepoch, S, K]` telemetry series anyway, and the mid-round
          parameter finiteness arrives as on-device `[nadmm, K]` flags.
          Rollback semantics are unchanged: the round was already
          transactional, and a poisoned round restores the entry
          snapshot wholesale;
        * the `check_results` eval cadence is FOLDED INTO the program by
          default (`_fold_eval_enabled`): each consensus iteration's
          full-test-sweep correct counts come back as a `[nadmm, K]`
          round output — zero standalone eval dispatches, zero extra
          host syncs, and the `[nadmm, K, N]` state snapshots are never
          materialized. With `--no-fold-eval` the program snapshots its
          per-consensus `(flat, stats)` instead and the standalone eval
          program runs on them outside, deferred (`evaluate_deferred`);
        * planned crashes fire at their recorded round cursor, after the
          dispatch — the process exits and recovery replays from the
          checkpoint exactly as before (the device state a crashing
          unfused run would have discarded was never observable).
        """
        cfg = self.cfg
        check = cfg.fault_mode != "off"
        rollback = cfg.fault_mode == "rollback"
        if rollback:
            snap = self._entry_snapshot(gid)
        self._round_poisoned = False
        round_fn = self._round_fn(gid)
        lstate, y, z, rho, extra = self._init_fn(gid)(self.flat)
        if cfg.strategy == "admm" and gid in self._rho_store:
            rho = self._rho_store[gid]  # carry BB-adapted rho across loops
        gsize = self.partition.group_size(gid)

        idx = self._round_indices(nloop, gid)
        masks_np = np.ones((cfg.nadmm, cfg.n_clients), np.float32)
        total_delay = 0.0
        # masks and straggler stalls belong to the CONSENSUS exchange —
        # the unfused path draws them under `if consensus_fn is not None`,
        # so independent (strategy 'none') chaos runs must not stall or
        # record them here either
        if self.injector is not None and cfg.strategy != "none":
            masks_np = self._vslice(
                self.injector.masks_for_round(nloop, gid, cfg.nadmm), nloop
            )
            for a, d in enumerate(
                self.injector.straggler_delays_for_round(nloop, gid, cfg.nadmm)
            ):
                if d > 0:
                    dl_cap = self._deadline_for(nloop, gid)
                    if dl_cap is not None:
                        # deadline rounds cap the coordinator's wait: past
                        # the deadline the round closes without the
                        # straggler instead of stalling for it
                        d = min(d, dl_cap)
                    self.recorder.step_time(
                        "straggler_wait", d, nloop=nloop, group=gid, nadmm=a
                    )
                    total_delay += d
                if self.injector.will_crash(nloop, gid, a):
                    # the unfused replay crashes at the END of iteration
                    # `a`: its own stall is served, later iterations'
                    # never happen — truncate so fused wall time and the
                    # straggler_wait series match (and the resumed run,
                    # sentinel fired, serves the full schedule like the
                    # unfused one)
                    break
        if total_delay > 0 and rollback:
            # rollback keeps the pre-dispatch stall: the transactional
            # round's observable ordering (coordinator waits out the
            # stragglers, THEN the round's work runs and is judged) must
            # not change — a rolled-back round's wall must still include
            # the stall it provoked, not hide it under discarded compute
            time.sleep(total_delay)
        hetero = self._hetero_enabled()
        ragged = self._ragged_enabled()
        total_steps = self._round_total_steps()
        budgets_np = times_np = None
        budget_args = ()
        if hetero:
            _, budgets_np, times_np = self._round_hetero(nloop, gid)
        if ragged:
            budget_args = (
                self._put(
                    budgets_np,
                    NamedSharding(
                        self.mesh, PartitionSpec(None, CLIENT_AXIS)
                    ),
                ),
            )
        masks = self._put(
            masks_np,
            NamedSharding(self.mesh, PartitionSpec(None, CLIENT_AXIS)),
        )
        corrupt = self._corruption_enabled()
        corr_args = ()
        if corrupt:
            sh = NamedSharding(self.mesh, PartitionSpec(None, CLIENT_AXIS))
            corr_args = tuple(
                self._put(self._vslice(arr, nloop), sh)
                for arr in self.injector.corruption_for_round(
                    nloop, gid, cfg.nadmm
                )
            )
        quarantine = self._quarantine_enabled()
        ef_on = self._ef_enabled()
        ef_args = (self._ef_for(gid),) if ef_on else ()

        fold = self._fold_eval_enabled()
        eval_args = (
            (self.test_imgs, self.test_labels, self.test_mask)
            if fold
            else ()
        )
        self._step_num += cfg.nadmm * cfg.nepoch
        with self.recorder.phase(
            "fused_round", nloop=nloop, group=gid,
            client_fold=cfg.client_fold,
        ), jax.profiler.StepTraceAnnotation(
            "fused_round", step_num=self._step_num
        ):
            (self.flat, lstate, self.stats, y, z, rho, extra,
             losses_d, met, param_ok_d, qstats_d, snaps, correct_d,
             ef_d, drift_d) = round_fn(
                self.flat, lstate, self.stats, self.shard_imgs,
                self.shard_labels, idx, self.mean, self.std,
                y, z, rho, extra, masks, *ef_args, *budget_args,
                *corr_args, *eval_args,
            )
            if total_delay > 0 and not rollback:
                # the round is already ENQUEUED (dispatch is
                # asynchronous): serving the coordinator's straggler wait
                # here overlaps the device computing the round instead of
                # delaying its start — the stall costs wall time only
                # where it exceeds the round's own compute
                time.sleep(total_delay)
            # device->host fetch of an output is the completion barrier
            # (the telemetry series is needed host-side regardless)
            losses = self._fetch(losses_d)  # [nadmm, nepoch, S, K]
        param_ok = self._fetch(param_ok_d)  # [nadmm, K]
        dual, primal, mean_rho, survivors = (self._fetch(m) for m in met)
        # the folded evals' correct counts ride the same completion
        # barrier: one [nadmm, K] fetch covers every eval of the round
        correct = self._fetch(correct_d) if fold else None
        is_admm = cfg.strategy == "admm"
        # quarantine replay state: the in-carry decision already happened
        # on device; qmask_np re-derives each exchange's trusted set so
        # the host bookkeeping (wasted-uplink attribution) matches it.
        # The [nadmm, K] statistic matrices are fetched ONCE here — the
        # per-round read steps.py's docstring promises — and the replay
        # loop below slices host arrays only.
        qmask_np = np.ones(cfg.n_clients, np.float32)
        if quarantine:
            qnorm_m = self._fetch(qstats_d[0])  # [nadmm, K]
            qsusp_m = self._fetch(qstats_d[1])

        # host bookkeeping replay, in the unfused path's per-round order
        for a in range(cfg.nadmm):
            for e in range(cfg.nepoch):
                for s in range(losses.shape[2]):
                    self.recorder.batch_losses(
                        losses[a, e, s],
                        nloop=nloop, group=gid, nadmm=a, epoch=e, minibatch=s,
                    )
                if check:
                    self._check_losses(
                        losses[a, e], nloop=nloop, group=gid, nadmm=a, epoch=e
                    )
            if cfg.strategy != "none":
                if hetero:
                    self._record_hetero(
                        times_np[a],
                        budgets_np[a] if budgets_np is not None else None,
                        nloop=nloop, gid=gid, a=a, total=total_steps,
                    )
                self.recorder.residuals(
                    float(primal[a]) if is_admm else None,
                    float(dual[a]),
                    float(mean_rho[a]) if is_admm else None,
                    nloop=nloop, group=gid, nadmm=a, group_size=gsize,
                )
                if self.injector is not None:
                    self.recorder.participation(
                        int(survivors[a]), cfg.n_clients,
                        nloop=nloop, group=gid, nadmm=a,
                    )
                # same comm accounting as the unfused path, one record per
                # consensus iteration of the fused scan (obs/ledger.py):
                # every transmitting (plan-alive, deadline-making)
                # client's bytes, with a quarantined sender's attributed
                # as wasted
                transmit = masks_np[a]
                if ragged:
                    transmit = transmit * (budgets_np[a] > 0)
                _, quarantined_now = self._effective_exchange_mask(
                    transmit, qmask_np, quarantine
                )
                self._comm.record(
                    self.recorder, gid, int(transmit.sum()),
                    nloop=nloop, nadmm=a, quarantined=quarantined_now,
                )
                if quarantine:
                    qmask_np = self._record_quarantine(
                        (qnorm_m[a], qsusp_m[a]), qmask_np,
                        nloop=nloop, group=gid, nadmm=a,
                    )
            if check:
                self._check_param_flags(
                    param_ok[a], nloop=nloop, group=gid, nadmm=a
                )
            if self.injector is not None:
                self.injector.maybe_crash(nloop, gid, a)
            if cfg.check_results:
                if fold:
                    # already computed inside the round program and
                    # fetched above; Deferred keeps the record on the
                    # same harvest/discard path as the outside evals
                    acc = Deferred(
                        lambda a=a: correct[a] / self._test_total
                    )
                else:
                    flat_snaps, stats_snaps = snaps
                    acc = self.evaluate_deferred(
                        flat=flat_snaps[a],
                        stats=jax.tree.map(lambda x: x[a], stats_snaps),
                    )
                self.recorder.accuracies(acc, nloop=nloop, group=gid, nadmm=a)
        if is_admm:
            self._rho_store[gid] = rho
        if ef_on:
            self._ef_store[gid] = ef_d
        if self._adaptive and not (rollback and self._round_poisoned):
            # the in-program drift signal (one fetch, replicated) — the
            # scheduler observes the record at log time; position in the
            # stream matches the unfused path's post-round record, and a
            # round the rollback is about to discard records no drift
            # (see _run_round_unfused — a poisoned distance must not
            # steer the scheduler or inflate its skip anchor)
            self.recorder.group_distance(
                self._fetch(drift_d), nloop=nloop, group=gid
            )
        if rollback:
            self._maybe_rollback(snap, nloop, gid)

    def _decide_group(self, nloop: int, slot: int) -> Optional[int]:
        """Which partition group round slot `(nloop, slot)` runs.

        Round-robin returns `group_order[slot]` with zero bookkeeping —
        the legacy schedule, bit-identical streams. Adaptive asks the
        scheduler (exchange/schedule.py) ONCE per slot — decided at slot
        start from the drift signal of COMPLETED rounds, memoized, and
        streamed as a `group_schedule` record (replayed decisions seed
        the memo on resume, so crashed+resumed twins run identical
        slots). Returns None for a SKIPPED slot: the scheduler judged
        every remaining group drift-quiet, the slot sends nothing, and
        the record carries the uplink bytes the skipped round's
        exchanges would have cost (`saved_bytes` — what `report` sums
        into bytes_saved_by_skipping), priced over the PURE plan's
        transmitting survivors (`_forgone_round_bytes`) so the saving
        is never inflated under chaos plans.
        """
        if self._scheduler is None:
            return self.group_order[slot]
        key = (int(nloop), int(slot))
        dec = self._schedule_decisions.get(key)
        if dec is None:
            visited = {
                self._schedule_decisions[(int(nloop), s)]["group"]
                for s in range(slot)
            }
            gid, info = self._scheduler.decide(visited)
            dec = {"slot": int(slot), "group": int(gid), **info}
            if dec.get("skipped"):
                dec["saved_bytes"] = self._forgone_round_bytes(nloop, gid)
            self._schedule_decisions[key] = dec
            self.recorder.log("group_schedule", dec, nloop=nloop)
        return None if dec.get("skipped") else int(dec["group"])

    def _loop_visited_gids(self, nloop: int) -> list:
        """The groups loop `nloop`'s rounds actually RAN, in slot order
        — `group_order` verbatim for round-robin; the non-skipped slot
        decisions under the adaptive schedule (pure given the recorded
        `group_schedule` history, which resume replays). THE one
        definition for every consumer that must not count skipped
        rounds: the telemetry reliability counters and the
        `injected_summary` visits mapping."""
        if self._scheduler is None:
            return list(self.group_order)
        return [
            d["group"]
            for (l, s), d in sorted(self._schedule_decisions.items())
            if l == nloop and not d.get("skipped")
        ]

    def _forgone_round_bytes(self, nloop: int, gid: int) -> int:
        """Uplink bytes round `(nloop, gid)` WOULD have shipped — the
        skipped-slot `saved_bytes` pricing. Pure in (plan seed, cursor,
        deadline decisions): the same masks-and-budgets arithmetic the
        resume path uses to reconstruct unstreamed rounds, so the
        report's `bytes_saved_by_skipping` counts exactly the
        transmitting clients `comm_bytes` would have (plan dropouts and
        zero deadline budgets excluded; quarantine only affects the
        wasted attribution, never the transmit count).

        Deadline budgets come from ALREADY-memoized decisions only —
        never through `_deadline_for`, whose auto path would TAKE a
        decision for a round that never runs: a phantom, un-streamed
        memo entry a resumed twin (which replays `saved_bytes` from the
        record instead of re-pricing) would not hold, breaking the
        every-memoized-decision-is-streamed invariant. A skipped slot
        never decided a deadline, so under the auto policy its pricing
        simply applies no budget exclusion — identical live and
        resumed."""
        cfg = self.cfg
        if self.injector is not None:
            masks = self._vslice(
                self.injector.masks_for_round(nloop, gid, cfg.nadmm), nloop
            )
        else:
            masks = np.ones((cfg.nadmm, cfg.n_clients), np.float32)
        if self._ragged_enabled():
            dl = (
                self._deadline_decisions.get((int(nloop), int(gid)))
                if cfg.deadline_is_auto
                else float(cfg.round_deadline)
            )
            if dl is not None:
                if self.injector is not None:
                    speeds = self._vslice(
                        self.injector.speeds_for_round(
                            nloop, gid, cfg.nadmm
                        ),
                        nloop,
                    )
                    step_t = self.injector.plan.step_time_s
                else:
                    speeds = np.ones(
                        (cfg.nadmm, cfg.n_clients), np.float32
                    )
                    step_t = 1.0
                budgets = step_budgets(
                    speeds, step_t, self._round_total_steps(), dl
                )
                masks = masks * (budgets > 0)
        return int(
            sum(self._comm.round_bytes(gid, int(m.sum())) for m in masks)
        )

    def run_loop(self, nloop: int) -> None:
        """ONE outer loop: cohort gather (cohort mode) → every round
        slot's partition round → cohort scatter.

        The public per-loop entry point — `run()`'s loop body minus the
        commit/checkpoint boundary, and the unit the cohort benchmarks
        time (bench.py `_cohort_probe`,
        benchmarks/client_scaling_tpu.py `_cohort_sweep`): one warm call
        is exactly one gather→rounds→scatter cycle. A loop holds
        `len(group_order)` round SLOTS; round-robin maps slot s to
        `group_order[s]` (the legacy schedule, verbatim) while the
        adaptive scheduler picks each slot's group by drift — or skips
        the slot outright (`_decide_group`). The scatter runs BEFORE the
        caller's stream marker and checkpoint: everything a committed
        loop claims durable includes the store rows it wrote (an
        injected crash inside `run_round` skips the scatter, leaving
        the store at the previous loop — exactly what that loop's
        checkpoint describes).
        """
        if self._cohort_mode:
            self._begin_loop_cohort(nloop)
        for slot in range(len(self.group_order)):
            gid = self._decide_group(nloop, slot)
            if gid is None:
                continue  # skipped slot: nothing trains, nothing ships
            self.run_round(nloop, gid)
        if self._cohort_mode:
            self._end_loop_cohort(nloop)

    def run(self) -> MetricsRecorder:
        """The full experiment (all Nloop outer loops).

        With `cfg.profile_dir` set, the whole run is captured as a
        jax.profiler trace (device + host timelines, viewable in
        TensorBoard/Perfetto) — the tracing subsystem the reference lacks
        (SURVEY.md §5: a dead `start_time=time.time()` is all it has).
        `cfg.trace_out` is the complementary HOST-side trace: the loop
        nest's round/epoch/consensus/eval/compile spans as Chrome
        trace-event JSON (obs/trace.py), written even when the run dies on
        an injected crash so the chaos timeline survives for post-mortem.
        """
        self._run_started = True
        try:
            if self.cfg.profile_dir:
                with jax.profiler.trace(self.cfg.profile_dir):
                    out = self._run_impl()
            else:
                out = self._run_impl()
            self._run_completed = True
            return out
        finally:
            self.close()

    def close(self) -> None:
        """Flush and close the observability outputs (idempotent): dump
        the flight recorder's crash bundle when a started run never
        completed, write the Chrome trace atomically, flush and close
        the metric sinks."""
        if self._prefetch is not None:
            # drop any in-flight prefetch: the daemon thread finishes
            # into the void and its device buffers release
            self._prefetch.cancel()
        if (
            self._flight is not None
            and self._run_started
            and not self._run_completed
        ):
            try:
                path = self._flight.crash_dump(
                    nloop=self._completed_nloops,
                    round_ix=self._rounds_done,
                    extra=self._incident_extra,
                )
                if path is not None and self.recorder.verbose:
                    print(f"INCIDENT kinds=['crash'] bundle={path}")
            except Exception as e:  # same rule as the trace write below:
                # the dying run's own outcome must not be masked
                import warnings

                warnings.warn(f"could not write crash incident: {e}")
        if self._status_path is not None and self._run_started:
            # stamp the sidecar's terminal state (the `watch` console's
            # live/finished/crashed discriminator — a stale sidecar must
            # not read as a live run forever)
            try:
                with open(self._status_path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = {}
            doc["completed" if self._run_completed else "crashed"] = True
            # the end-of-run roofline (fold mode + effective GEMM M
            # included) is stream=False like every process fact — the
            # `watch` console renders it from here
            roof = self.recorder.latest("roofline")
            if roof is not None:
                doc["roofline"] = roof
            if self.store is not None:
                # the final residency digest: the per-round sidecar was
                # last written BEFORE the closing scatter/save, and a
                # finished run's `watch` panel should show where the
                # store actually ended up
                doc["store"] = self.store.residency()
                doc["store"]["traffic"] = self.store.traffic()
                doc["integrity"] = self.store.integrity_digest()
            if self._storage_shim is not None:
                doc["storage_faults"] = int(self._storage_shim.injected)
            tmp = self._status_path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=jsonable)
                os.replace(tmp, self._status_path)
            except OSError:
                pass
        if self.recorder.tracer is not None and self.cfg.trace_out:
            try:
                self.recorder.tracer.save(self.cfg.trace_out)
            except Exception as e:  # close() runs in run()'s finally: a
                # failed trace write (read-only dir, unserializable span
                # arg) must not mask the run's own outcome (incl. an
                # InjectedCrash) nor skip the sink close below
                import warnings

                warnings.warn(f"could not write trace {self.cfg.trace_out}: {e}")
        self.recorder.close()

    def _run_impl(self) -> MetricsRecorder:
        cfg = self.cfg
        for nloop in range(self._completed_nloops, cfg.nloop):
            self.run_loop(nloop)
            self._completed_nloops = nloop + 1
            # stream durability barrier, BEFORE the checkpoint write: a
            # crash between the two leaves the stream AHEAD of the
            # checkpoint, which resume handles gracefully (truncate to
            # the restored cursor's marker, re-run one loop). The reverse
            # order could leave a checkpoint ahead of the stream — a
            # state the sink can only treat as unresumable, abandoning
            # the whole stream (obs/sinks.py _scan).
            self.recorder.commit_loop(nloop)
            if cfg.save_model:
                self.save(step=self._completed_nloops)
        if cfg.save_model:
            self.save(step=cfg.nloop)
        # end-of-run injected-fault totals (CLI `# faults injected:`
        # line): drawn from the PURE plan over the full round schedule —
        # resume-proof, unlike execution counters — plus the quarantines
        # the defense actually fired (a detection, so recorder-sourced:
        # resume-proof only when a metrics stream replays the pre-crash
        # records; without one the count covers the re-run loops only)
        if self.injector is not None or "quarantine" in self.recorder.series:
            # adaptive schedule: faults only fire on rounds that RAN —
            # the per-loop visited-group lists are pure given the
            # recorded decision history (every slot decided by now, live
            # or stream-replayed), so the totals stay resume-proof
            visits = None
            if self._scheduler is not None:
                visits = {
                    l: self._loop_visited_gids(l) for l in range(cfg.nloop)
                }
            counts = (
                self.injector.injected_summary(
                    cfg.nloop,
                    self.group_order,
                    cfg.nadmm,
                    visits=visits,
                    exchanges=cfg.strategy != "none",
                    total_steps=self._round_total_steps(),
                    # deadline rows only where deadline rounds are active
                    # (_ragged_enabled — strategy 'none' has no exchange
                    # to miss the deadline of); auto mode hands the
                    # scoreboard its per-round decision history (every
                    # round decided by now — live or stream-replayed),
                    # so the totals stay resume-proof
                    deadline_s=(
                        (
                            dict(self._deadline_decisions)
                            if cfg.deadline_is_auto
                            else float(cfg.round_deadline)
                        )
                        if self._ragged_enabled()
                        else None
                    ),
                    # cohort mode: only faults scheduled onto SAMPLED
                    # clients were injected (an unsampled client's
                    # dropout never happened); the sampler's purity
                    # keeps the totals resume-proof
                    cohort=(
                        self.sampler.cohort if self._cohort_mode else None
                    ),
                )
                if self.injector is not None
                else {"drops": 0, "stragglers": 0, "crashes": 0,
                      "corruptions": 0}
            )
            counts["quarantines"] = sum(
                len(r["value"]["clients"])
                for r in self.recorder.series.get("quarantine", [])
            )
            # stream=False: derivable from the plan at any time, and the
            # crash count is exactly the field a crashed-and-resumed
            # twin's plan legitimately differs in — streaming it would
            # break the stream-identity contract for no information
            self.recorder.log("injected_faults", counts, stream=False)
        # end-of-run communication summary: partial-parameter exchange vs
        # the hypothetical full-model exchange vs the ship-the-data floor
        self.recorder.log("comm_summary", self._comm.summary())
        # end-of-run roofline records (obs/roofline.py): the AOT round
        # program's exact XLA cost counts (stashed by compile_round)
        # over the measured per-round walls — ROADMAP item 2's honest
        # roofline note as a recorded artifact. stream=False: walls are
        # facts about THIS PROCESS (a resumed run's differ), exactly
        # like recompile_count — and for the same reason only walls THIS
        # process measured count (a resumed stream replays the crashed
        # process's step_time records into the series). Median wall
        # absorbs the compile-heavy first round. Plans that schedule
        # straggler stalls skip the record entirely: the stall's host
        # sleep lands inside the fused_round span (deliberately — it
        # overlaps device compute), so those walls measure the injected
        # stall, not the program, and the "honest roofline" would lie
        # about exactly the chaos runs it described.
        stalls = (
            self.injector is not None
            and self.injector.plan.straggler_p > 0.0
            and self.injector.plan.straggler_delay_s > 0.0
        )
        for gid, cost in sorted(self._round_cost.items()):
            if stalls:
                break
            walls = [
                r["value"]["seconds"]
                for r in self.recorder.series.get("step_time", [])[
                    self._replayed_step_times:
                ]
                if r["value"].get("phase") == "fused_round"
                and r.get("group") == gid
            ]
            if not walls:
                continue
            rec = roofline_record(
                wall_s=float(np.median(walls)),
                flops=cost.get("flops"),
                hbm_bytes=cost.get("hbm_bytes"),
                device_kind=jax.devices()[0].device_kind,
                source=cost.get("source", "measured"),
                # the stamp that keeps this record from ever serving as
                # a cross-backend baseline downstream (obs/benchdb.py)
                provenance=cached_stamp(),
            )
            # the intensity claim as a recorded number, not prose
            # (ISSUE-17): what M the MXU sees through the probe fan.
            # 'gemm' folds the fan into the example axis — M = K·P·B
            # rows feed one widened contraction per frozen layer —
            # while 'vmap' (and any probe-less config, where no fan
            # exists to fold) lowers to K·P skinny dots of M = B each.
            rec["client_fold"] = cfg.client_fold
            rec["effective_gemm_m"] = int(
                cfg.n_clients * cfg.batch * cfg.linesearch_probes
                if cfg.client_fold == "gemm" and cfg.linesearch_probes > 1
                else cfg.batch
            )
            self.recorder.log("roofline", rec, stream=False, group=gid)
        if self._cohort_mode:
            # per-virtual-client participation digest — pure in
            # (cohort_seed, nloop), so a crashed-and-resumed run records
            # the same totals as its uninterrupted twin
            counts = self.sampler.participation_counts(cfg.nloop)
            self.recorder.log(
                "cohort_participation",
                {
                    "n_virtual": int(cfg.virtual_clients),
                    "cohort": int(cfg.cohort),
                    "loops": int(cfg.nloop),
                    "sampled_ever": int((counts > 0).sum()),
                    "min": int(counts.min()),
                    "max": int(counts.max()),
                    "mean": round(float(counts.mean()), 6),
                },
            )
            # store occupancy is a fact about THIS process' host memory
            # (a resumed run re-materializes only what its manifests
            # name), so it stays out of the stream
            self.recorder.log(
                "store_summary", self.store.summary(), stream=False
            )
            # storage-integrity digest (clients/store.py): verified
            # reads / failures / heals / repairs are process facts for
            # the same reason — a resumed run's counts cover its own
            # reads only — so stream=False; `report --integrity` and
            # the status sidecar are their surfaces
            self.recorder.log(
                "integrity", self.store.integrity_digest(), stream=False
            )
        return self.recorder

    # ----------------------------------------------------------- checkpoint

    def save(self, step: int) -> str:
        state = {
            "flat": self._fetch(self.flat),
            "batch_stats": jax.tree.map(self._fetch, self.stats),
            "completed_nloops": np.int64(self._completed_nloops),
            # rho is the ONE piece of consensus state that outlives a
            # round (see _rho_store); keyed by group id as strings for
            # the checkpoint tree
            "rho_store": {
                str(g): self._fetch(r) for g, r in self._rho_store.items()
            },
        }
        if self._ef_store:
            # error-feedback residuals persist like rho (exchange/,
            # docs/PERF.md); absent for EF-free runs so their
            # checkpoints stay byte-compatible with pre-EF builds
            state["ef_store"] = {
                str(g): self._fetch(e) for g, e in self._ef_store.items()
            }
        if self._qkv_layout is not None:
            state["qkv_layout"] = np.int64(self._qkv_layout)
        if self._cohort_mode and self._completed_nloops:
            # the completed loops' cohort draws, [completed, C] — tiny.
            # Uniform/samples draws are re-derivable from (seed, nloop)
            # alone, but telemetry-weighted draws depend on the evolving
            # reliability state: a resumed run must REPLAY history, not
            # re-draw it from whatever state it restored mid-stream.
            state["cohort_history"] = np.stack(
                [
                    np.asarray(self.sampler.cohort(l), np.int64)
                    for l in range(self._completed_nloops)
                ]
            )
        if self._stream:
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "checkpointing a multi-process STREAMING run is not "
                    "supported: each process holds only its own clients' "
                    "stream positions, so no single process can write the "
                    "full-K position vector (restore of a single-process "
                    "streaming checkpoint onto a multi-process mesh IS "
                    "supported — positions index by global client id)"
                )
            # the streams are pure functions of (seed, batch, drop_last,
            # drawn-count) — the count IS the data-pipeline state
            state["stream_positions"] = np.asarray(
                [self._batchers[c].drawn for c in sorted(self._batchers)],
                np.int64,
            )
            # 1 = native batcher, 0 = numpy fallback (different streams),
            # saved PER BATCHER: a failed batcher_create falls back to
            # numpy even with the lib loaded, and a mixed run must not
            # collapse into either label
            state["stream_impl_native"] = np.asarray(
                [self._batchers[c].is_native for c in sorted(self._batchers)],
                np.int64,
            )
        path = checkpoint_path(self.cfg.checkpoint_dir, step)
        if self._cohort_mode and jax.process_index() == 0:
            # dirty-chunk store snapshot BEFORE the orbax commit (same
            # single-writer discipline): a crash between the two leaves a
            # dangling manifest no checkpoint names — resume falls back
            # to the previous (checkpoint, manifest) pair, both intact
            # because chunk files are versioned, never overwritten
            self.store.save(self.cfg.checkpoint_dir, step)
        if jax.process_count() > 1:
            # single-writer: `state` is byte-identical on every process
            # (_fetch allgathers), and save_checkpoint's host-side staging
            # (rmtree + os.replace) must not race on a shared directory —
            # process 0 writes, everyone else waits at the barrier so no
            # process runs ahead of a checkpoint it may need to resume from
            from jax.experimental import multihost_utils

            if jax.process_index() == 0:
                save_checkpoint(
                    self.cfg.checkpoint_dir, state, step=step,
                    storage_io=self._storage_shim,
                )
            multihost_utils.sync_global_devices(f"checkpoint_step_{step}")
            return path
        return save_checkpoint(
            self.cfg.checkpoint_dir, state, step=step,
            storage_io=self._storage_shim,
        )

    def _restore(self) -> None:
        """Restore from the newest checkpoint whose FULL state — orbax
        tree AND (cohort mode) client-store snapshot — actually loads
        and verifies. A corrupt store manifest, or a chunk that fails
        checksum verification past the repair ladder (IntegrityError),
        disqualifies that step exactly like a torn orbax tree does:
        fall back to the next-newest instead of wedging the resume."""
        root = os.path.abspath(self.cfg.checkpoint_dir)
        steps = _list_steps(root)
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {root}")
        for s in reversed(steps):
            try:
                state = load_checkpoint(self.cfg.checkpoint_dir, step=s)
            except Exception as e:
                warnings.warn(
                    f"skipping unreadable checkpoint step {s}: "
                    f"{type(e).__name__}: {e}; falling back to the "
                    "next-newest"
                )
                continue
            try:
                self._apply_restore(state)
                return
            except (FileNotFoundError, IntegrityError) as e:
                warnings.warn(
                    f"checkpoint step {s} loads but its client-store "
                    f"snapshot is unusable ({e}); falling back to the "
                    "next-newest"
                )
                continue
        raise FileNotFoundError(
            f"no restorable checkpoint under {root} (tried steps {steps})"
        )

    def _apply_restore(self, state) -> None:
        csh = client_sharding(self.mesh)
        # _owned_copy: flat/stats flow into the epoch fn's donated slots;
        # they must not remain zero-copy views of the (soon-freed)
        # checkpoint host arrays (see module header)
        self.flat = _owned_copy(self._put(state["flat"], csh))
        self.stats = jax.tree.map(
            lambda x: _owned_copy(self._put(x, csh)), state["batch_stats"]
        )
        self._completed_nloops = int(state["completed_nloops"])
        if self._qkv_layout is not None:
            saved = int(state.get("qkv_layout", 1))  # pre-stamp ckpts are v1
            if saved != self._qkv_layout:
                raise ValueError(
                    f"checkpoint's fused-qkv column order is v{saved} but "
                    f"this build uses v{self._qkv_layout} "
                    "(models/transformer.py QKV_LAYOUT_VERSION): the same "
                    "kernel shapes would be read as different heads' q/k/v "
                    "and attention would be silently scrambled — re-train "
                    "or convert the checkpoint"
                )
        # cleared before refill: a failed newer-step attempt must not
        # leak per-group entries an older checkpoint does not carry
        self._rho_store.clear()
        self._ef_store.clear()
        for g, r in state.get("rho_store", {}).items():
            self._rho_store[int(g)] = _owned_copy(self._put(r, csh))
        for g, e in state.get("ef_store", {}).items():
            self._ef_store[int(g)] = _owned_copy(self._put(e, csh))
        if self._cohort_mode:
            # the store snapshot committed WITH this checkpoint (its
            # manifest step is the restored loop cursor — Trainer.save
            # writes both under the same step). Loaded and VERIFIED
            # first — a manifest or chunk that fails its checksum raises
            # IntegrityError here, before any sampler history is seeded,
            # so _restore can fall back to the previous step cleanly.
            self.store.load(
                self.cfg.checkpoint_dir, step=self._completed_nloops
            )
            if self.cfg.store_checksums:
                # resume-time gate: every manifest-referenced chunk's
                # bytes verify BEFORE the run adopts this snapshot
                self.store.verify_all()
            hist = state.get("cohort_history")
            if hist is not None:
                # seed the sampler's draw history with the completed
                # loops' cohorts: telemetry-weighted draws are history-
                # dependent (the weights evolved with the store), so the
                # resumed run REPLAYS them instead of re-drawing from
                # restored state; for the pure weightings this is a
                # transparent cache (re-derivation would match bitwise)
                hist = np.asarray(hist)
                for l in range(min(int(hist.shape[0]),
                                   self._completed_nloops)):
                    self.sampler.seed_history(l, hist[l])
            # Lazily-registered rho fields the crashed run had scattered
            # are re-registered from the manifest's recorded shapes with
            # the init-rho fill, so restored chunks stay addressable
            # before the group's first round of the resumed run.
            for name, meta in self.store.saved_fields.items():
                if name.startswith("rho/") and not self.store.has_field(name):
                    self.store.register_field(
                        name,
                        np.full(
                            [int(s) for s in meta["shape"]],
                            self.cfg.admm_rho0,
                            np.dtype(meta["dtype"]),
                        ),
                    )
                if name.startswith("ef/") and not self.store.has_field(name):
                    # lazily-registered error-feedback fields restore
                    # with the zero fill pristine clients gather
                    self.store.register_field(
                        name,
                        np.zeros(
                            [int(s) for s in meta["shape"]],
                            np.dtype(meta["dtype"]),
                        ),
                    )
        if not self._stream and "stream_positions" in state:
            # the mirror-image mismatch: a streaming checkpoint resumed
            # resident would silently continue under the reseeded
            # _epoch_indices stream instead of the saved batcher positions
            raise ValueError(
                "checkpoint was written by a STREAMING run; resuming it "
                "on the resident data path would silently change the "
                "minibatch order (set hbm_data_budget_mb to match the "
                "original run)"
            )
        if self._stream:
            if "stream_positions" not in state:
                raise ValueError(
                    "checkpoint was written by a resident-data run; it "
                    "cannot seed the streaming batchers' positions "
                    "(rerun without hbm_data_budget_mb, or restart)"
                )
            saved = np.asarray(state["stream_impl_native"]).reshape(-1)
            positions = np.asarray(state["stream_positions"]).reshape(-1)
            # index by GLOBAL client id: this process may own a subset of
            # the clients (host-sharded streaming) while the checkpoint
            # carries the full-K vectors
            for c in sorted(self._batchers):
                b = self._batchers[c]
                if int(saved[c]) != int(b.is_native):
                    raise ValueError(
                        f"checkpoint stream positions for client {c} were "
                        f"written under batcher impl {int(saved[c])} "
                        f"(1=native, 0=numpy fallback) but this process "
                        f"built {int(b.is_native)} — the two permutation "
                        "streams differ, so resuming would silently change "
                        "the data order (set/unset FEDTPU_NO_NATIVE to "
                        "match)"
                    )
                b.skip(int(positions[c]))


def run_experiment(cfg: ExperimentConfig, verbose: bool = True) -> MetricsRecorder:
    """Build a `Trainer` for `cfg`, run it to completion, return metrics."""
    return Trainer(cfg, verbose=verbose).run()
