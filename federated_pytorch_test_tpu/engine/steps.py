"""Sharded, jitted step builders: the hot loops of every experiment.

The reference's hot loop is Python: for each minibatch it builds three
closures and steps three optimizers sequentially in one process
(reference src/federated_trio.py:285-338). Here ONE jitted function per
(model, partition-group) runs a whole epoch for ALL clients:

* `shard_map` over the `clients` mesh axis — each device holds a local
  block of K/D clients (their params, optimizer state, data shard);

The builders are SHAPE-polymorphic in the client axis: nothing here
knows whether the `[K]`-leading arrays are the legacy static population
(every configured client, resident on device for the whole run) or a
GATHERED `[C]` cohort of virtual clients (clients/, docs/SCALE.md — the
trainer gathers C of N ≫ C host-stored clients per outer loop, runs the
identical programs with the cohort as the client axis, and scatters the
survivors back). Either way the axis shards across the mesh devices, so
per-device work is (cohort or K)/D — constant in the virtual-population
size N. Participation masks, corruption rows, and step budgets arrive as
slot-indexed inputs; in cohort mode the trainer projects them from
virtual-client-keyed schedules before the dispatch (fault identity
follows the virtual id, not the slot).
* `vmap` over the local block — every client's L-BFGS step (line-search
  probes included) is batched into single XLA ops; with
  `--linesearch-probes P` the Armijo search's probe fan stacks a P-wide
  alpha axis onto this client vmap, so one widened `[P*K]` forward
  serves what the sequential search ran as P dependent per-client
  passes (optim/linesearch.py, docs/PERF.md);
* `lax.scan` over the epoch's minibatches — the per-step index gather
  happens on device from the resident uint8 shard, so a full epoch is one
  device computation with zero host round-trips.

The consensus exchange stays OUTSIDE the epoch function (it runs once per
averaging round, reference src/federated_trio.py:353-363) and is its own
tiny jitted collective; only the active group's coordinates cross the
interconnect (reference README.md:2's bandwidth contract).

On top of these per-dispatch builders, `build_round_fn` fuses a whole
partition round — `nadmm x (nepoch epochs + consensus)` — into ONE jitted
donated-carry program by scanning the same epoch body and consensus
collective over the round's precomputed shuffle schedule and fault masks.
One dispatch per round instead of `nadmm*(nepoch+1)` harvests the flat
~0.1 s dispatch floor that dominates the dispatch-latency-bound schedules
(benchmarks/epoch_attribution.json); the per-dispatch builders remain the
`--no-fuse-rounds` escape hatch and serve the cases fusion cannot
(streaming, per-batch eval, per-epoch eval cadence, over-cap scans).
With `fold_eval=True` (the default when `check_results` is on) the
per-consensus-round eval sweep rides INSIDE the same program — one
dispatch carries the round's training, consensus, and evals, and the
standalone eval program never launches (`--no-fold-eval` restores the
snapshot + outside-eval path).

BatchNorm models thread a `batch_stats` collection through the scan.
Deliberate deviation (SURVEY.md §7 hard part 5): the reference mutates
running stats at EVERY closure evaluation inside the line search; here
stats update once per optimizer step, from the diagnostic forward pass at
the accepted parameters (the same forward the reference runs for its
per-batch loss print, reference src/federated_trio.py:341-352). Stats stay
client-local and are never averaged.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.parallel.shardmap import shard_map

from federated_pytorch_test_tpu.consensus import (
    ADMMConfig,
    ADMMState,
    FedAvgState,
    admm_init,
    admm_penalty,
    admm_round,
    apply_corruption,
    elastic_net,
    fedavg_init,
    fedavg_round,
    quarantine_release_2f,
    update_suspects,
)
from federated_pytorch_test_tpu.data import normalize
from federated_pytorch_test_tpu.exchange import make_codec
from federated_pytorch_test_tpu.models.base import active_leaf_mask, fold_params
from federated_pytorch_test_tpu.parallel.diagnostics import group_distances
from federated_pytorch_test_tpu.optim import (
    LBFGSConfig,
    lbfgs_init,
    lbfgs_step,
    vma_zero,
)
from federated_pytorch_test_tpu.parallel import (
    CLIENT_AXIS,
    mark_varying,
    path_component_name,
)
from federated_pytorch_test_tpu.parallel.collectives import client_sum
from federated_pytorch_test_tpu.partition import Partition

PyTree = Any


def _check_vma(ctx: Optional["GroupContext"] = None) -> bool:
    """Whether shard_map's varying-axis checking can stay ON.

    Off only when a Pallas kernel would run in INTERPRET mode inside the
    mapped function (the interpreter cannot propagate varying-mesh-axis
    metadata through its internal slicing); compiled TPU kernels carry the
    vma via their out_shape annotation, so the real-chip path keeps JAX's
    sharding checks enabled.

    The engine's ONLY Pallas path today is the L-BFGS 'pallas' direction
    backend, so that is all this detects. If model-level Pallas ever
    becomes reachable through the engine registry (e.g. an `attn_impl`
    config knob routing flash attention into the epoch/eval fns), extend
    this check — and build_eval_fn's hard-coded True — to cover it.
    """
    uses_pallas = ctx is not None and ctx.lbfgs.direction == "pallas"
    return not (uses_pallas and jax.default_backend() != "tpu")


class GroupContext(NamedTuple):
    """Everything static a group's step functions close over."""

    model: Any  # flax module
    unravel: Callable[[jnp.ndarray], PyTree]  # flat [N] -> params tree
    partition: Partition  # the TRAINING partition (may be the trivial one)
    gid: int
    has_stats: bool  # model carries a batch_stats collection
    lbfgs: LBFGSConfig
    strategy: str  # none | fedavg | admm
    admm: ADMMConfig
    # elastic-net on the active group's coordinates (reg_mode active_linear,
    # reference src/federated_trio.py:309-310)
    reg_on_active: bool
    # elastic-net on fixed segments of the FULL flat vector (reg_mode
    # first_linear, the no_consensus fc1 quirk, reference
    # src/no_consensus_trio.py:195-196 + src/simple_models.py:34)
    reg_segments: Tuple = ()
    lambda1: float = 1e-4
    lambda2: float = 1e-4
    # rematerialize the forward in the backward pass (jax.checkpoint)
    remat: bool = False
    # >0: collect the model's sown `moe_aux` load-balance terms
    # (models/moe.py:145) and add coef * sum to the training loss — without
    # it a MoE model trained through the engine can collapse its routing
    moe_aux_coef: float = 0.0
    # run the per-batch diagnostic forward at accepted params (reference
    # src/federated_trio.py:341-352). Must stay True for models with
    # batch stats — it is where running BN statistics refresh.
    diag_forward: bool = True
    # fold the diagnostic forward into the accepted line-search
    # evaluation (no extra model pass; parameter trajectory identical,
    # BN stats/telemetry equal to ulps) — False forces the explicit
    # diagnostic forward, for comparison tests and telemetry that must
    # match pre-round-5 runs bitwise (config.fold_diag_forward)
    fold_diag: bool = True
    # Byzantine-robust aggregation (consensus/robust.py): which combiner
    # the consensus exchange uses ('mean' keeps the reference math,
    # untouched) and the trimmed-mean per-side trim count
    robust_agg: str = "mean"
    robust_f: int = 0
    # auto-quarantine z-score threshold; None disables the update-norm
    # statistics entirely (the consensus program is then unchanged)
    quarantine_z: Optional[float] = None
    # the fault plan schedules update corruption: the consensus body
    # takes the per-round [K] mode/strength/seed rows and corrupts the
    # chosen updates in transit. Static so corruption-free runs compile
    # the exact pre-corruption programs.
    corrupt: bool = False
    # whether the plan's single corrupt_mode is 'gauss' — static, so
    # non-gauss plans compile the per-client PRNG draw out of the hot
    # program (a vmapped switch evaluates every branch)
    corrupt_gauss: bool = True
    # ragged local work (deadline rounds, docs/FAULT.md §Heterogeneity):
    # the epoch/round programs take per-client inner-step budgets and a
    # masked step is an identity carry update — flat/lstate/stats keep
    # their pre-step bits and the loss series repeats the client's last
    # recorded loss. Static, so deadline-free runs compile the exact
    # lockstep programs; a ragged program fed all-full budgets is
    # bit-identical to them (every select picks the stepped operand).
    ragged: bool = False
    # exchange wire format (exchange/, docs/PERF.md): the codec applied
    # to the UPLINKED partition-group slice — the aggregation (mean,
    # robust combiners, quarantine statistics) consumes the DECODED f32
    # view while clients, master weights, and z stay f32. Static:
    # 'float32' (identity codec) compiles the exact pre-codec program.
    exchange_dtype: str = "float32"
    # codec-zoo member beyond the dense dtype members (exchange/codec.py
    # make_codec): 'topk' (fraction below) / 'quant' (bits below) /
    # None (defer to exchange_dtype). Static like exchange_dtype.
    exchange_codec: Optional[str] = None
    topk_fraction: float = 0.1
    quant_bits: int = 8
    # per-(client, group) error-feedback residual (docs/PERF.md): the
    # sender encodes x + e and carries e' = (x+e) - decode(encode(x+e))
    # to its NEXT exchange of this group. Static — the consensus body
    # (and the fused round's carry) grow an ef slot only when set, so
    # EF-free runs compile the exact pre-EF programs. Only meaningful
    # with a lossy codec (the engine's config validation enforces it;
    # a hand-built context with an identity codec compiles EF away).
    error_feedback: bool = False
    # adaptive layer-group scheduling's in-scan signal (exchange/
    # schedule.py): the fused round program ends with the shared
    # `group_distances` body on the final post-round flat and returns
    # the [num_groups] drift vector as a round output — the one-dispatch
    # property holds with the signal in-program. Static: roundrobin
    # runs compile the exact pre-drift programs.
    group_drift: bool = False
    # widened client GEMM (docs/PERF.md §Widened GEMM): how the probe
    # fan's alpha axis meets the model's dots. 'vmap' batches the WHOLE
    # params tree along the fan — XLA lowers every layer to P skinny
    # batched dots with M=B — and compiles today's exact programs
    # byte-for-byte. 'gemm' re-batches at the tree level: only the
    # ACTIVE group's leaves ride the fan (models/base.py fold_params);
    # every frozen layer's dot then folds the P axis into its M
    # dimension (M = P·B per client, M = K·P·B across the client vmap)
    # and the probe-invariant prefix below the first active layer is
    # computed ONCE for all probes. Same values — vmap's dot_general
    # batching rule only restructures the contraction — but the wide
    # reduction may reorder, so 'gemm' is parity-gated to documented
    # ulps (tests/test_widened.py) and joins the stream tag. Static:
    # the default keeps hand-built contexts on the unchanged programs;
    # the ENGINE default is 'gemm' (engine/config.py client_fold).
    client_fold: str = "vmap"


def _data_loss(ctx: GroupContext, flat: jnp.ndarray, stats: PyTree, images, labels):
    """One client's CE loss (+ updated batch stats) at full flat params."""
    return _tree_data_loss(ctx, ctx.unravel(flat), stats, images, labels)


def _tree_data_loss(ctx: GroupContext, params: PyTree, stats: PyTree,
                    images, labels):
    """`_data_loss` at an already-unraveled params TREE.

    The tree-level entry exists for the widened-GEMM fan
    (`client_fold='gemm'`): there the params tree is assembled by
    `fold_params` — active-group leaves probe-batched, frozen leaves
    unbatched — rather than by one `unravel` call, and THIS body is
    what both assemblies share, so the two fold modes run the identical
    loss ops on identical values.
    """
    collections = []
    if ctx.has_stats:
        collections.append("batch_stats")
    if ctx.moe_aux_coef:
        collections.append("intermediates")
    if collections:
        variables = {"params": params}
        if ctx.has_stats:
            variables["batch_stats"] = stats
        logits, updated = ctx.model.apply(
            variables, images, train=True, mutable=collections
        )
        new_stats = updated["batch_stats"] if ctx.has_stats else stats
    else:
        logits = ctx.model.apply({"params": params}, images, train=True)
        updated = {}
        new_stats = stats
    # loss always in f32: under compute_dtype=bfloat16 the logits arrive
    # bf16, and the softmax/CE must not round (L-BFGS line-search decisions
    # compare loss values at 1e-9 tolerances)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()
    if ctx.moe_aux_coef:
        # every MoE layer sows its switch load-balance term under moe_aux
        aux = [
            jnp.asarray(leaf, jnp.float32)
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                updated.get("intermediates", {})
            )[0]
            if any(
                path_component_name(k) == "moe_aux" for k in path
            )
        ]
        if aux:
            loss = loss + ctx.moe_aux_coef * sum(jnp.sum(a) for a in aux)
    return loss, new_stats


def _regularizer(ctx: GroupContext, x: jnp.ndarray, flat: jnp.ndarray):
    """Elastic-net term for one client (reference src/federated_trio.py:303-333)."""
    reg = jnp.asarray(0.0, x.dtype)
    if ctx.reg_on_active:
        reg = reg + elastic_net(x, ctx.lambda1, ctx.lambda2)
    if ctx.reg_segments:
        parts = [
            lax.slice(flat, (s.start,), (s.start + s.size,))
            for s in ctx.reg_segments
        ]
        v = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        reg = reg + elastic_net(v, ctx.lambda1, ctx.lambda2)
    return reg


def _client_train_step(ctx: GroupContext):
    """One client's optimizer step on the active group's coordinates.

    Equivalent of one `opt_k.step(closure_k)` + the diagnostic forward
    (reference src/federated_trio.py:304-352), as a pure function.
    """

    # compute dtype of the model's matmuls/convs; when it is narrower
    # than f32 the FULL parameter vector is cast ONCE per minibatch here
    # instead of once per closure evaluation inside the line search —
    # measured on a v5e, the per-eval casts (62 leaves x ~9 evals/step)
    # were most of bfloat16 mode's overhead, not the MXU work
    model_dt = getattr(ctx.model, "dtype", jnp.float32)
    hoist_cast = model_dt != jnp.float32

    # FOLDED diagnostic forward (round-4 VERDICT item 5): every line-
    # search evaluation already runs the full model forward — including
    # the BN batch-statistics update that _data_loss computes and the
    # closure then discards — and the Armijo path's ACCEPTED evaluation
    # is exactly at the step's final parameters. Threading that
    # evaluation's (data loss, new stats) out through lbfgs_step's
    # has_aux channel reproduces the reference's per-batch diagnostic
    # print + stats refresh (src/federated_trio.py:341-352) WITHOUT the
    # extra model pass. The parameter trajectory is bit-identical either
    # way (BN running stats never enter a train-mode loss); the running
    # stats and printed loss may differ from the unfolded path by XLA
    # fusion ulps only. `fold_diag` exists so tests can compare the two
    # paths; the rare NaN-step fallback keeps the PREVIOUS stats (aux_ok
    # gating below) instead of refreshing at the unevaluated point.
    fold = (
        ctx.fold_diag
        and ctx.lbfgs.line_search
        and ctx.lbfgs.batch_mode
        and (ctx.diag_forward or ctx.has_stats)
    )

    # WIDENED client GEMM (`client_fold='gemm'`, docs/PERF.md §Widened
    # GEMM): the default probe fan vmaps the WHOLE `phi_aux` — because
    # `objective` inserts the probed x into the full flat and unravels,
    # EVERY leaf (frozen layers included) arrives probe-batched, and XLA
    # lowers each layer to P skinny batched dots with M=B. The fan built
    # here re-batches at the TREE level instead: the probed unravel
    # contributes only the ACTIVE group's leaves (they genuinely vary
    # along the fan), every other leaf comes from `unravel(base)` closed
    # over OUTSIDE the alpha vmap. vmap's dot_general batching rule then
    # folds the fan axis into the frozen layers' M dimension, and the
    # probe-invariant prefix below the first active layer is computed
    # once for all P probes. Values are the inserted full vector's
    # either way (the frozen coordinates of `insert(base, gid, xc)` ARE
    # `base`'s bits), so the fan computes the same objective — only the
    # reduction structure of the widened dots may reorder (documented
    # ulps, tests/test_widened.py). Static per (group, fold mode): off
    # when probes==1, where the sequential search never builds a fan.
    fan_gemm = (
        ctx.client_fold == "gemm"
        and ctx.lbfgs.line_search
        and ctx.lbfgs.batch_mode
        and ctx.lbfgs.ls_probes > 1
    )
    leaf_mask = (
        active_leaf_mask(ctx.unravel, ctx.partition, ctx.gid)
        if fan_gemm
        else None
    )

    def step(flat, lstate, stats, images_u8, labels, mean, std, y, z, rho):
        images = normalize(images_u8, mean, std)
        base = flat.astype(model_dt) if hoist_cast else flat

        def objective_with(params_of, x):
            # substituting the active group into the PRE-CAST remainder is
            # numerically identical to casting inside: the frozen
            # coordinates round f32->bf16 the same either way, and x's
            # own cast keeps the gradient path to f32 x
            xc = x.astype(model_dt) if hoist_cast else x
            full = ctx.partition.insert(base, ctx.gid, xc)
            data_loss, new_stats = _tree_data_loss(
                ctx, params_of(full), stats, images, labels
            )
            loss = data_loss
            if ctx.reg_segments and hoist_cast:
                # fixed-segment elastic net reads FROZEN coordinates of
                # the full vector: keep that in f32 (the segments don't
                # change within the step, so this inserts into f32 flat)
                full_reg = ctx.partition.insert(flat, ctx.gid, x)
            else:
                full_reg = full
            loss = loss + _regularizer(ctx, x, full_reg)
            if ctx.strategy == "admm":
                loss = loss + admm_penalty(x, y, z, rho)
            return loss, (data_loss, new_stats)

        def objective(x):
            return objective_with(ctx.unravel, x)

        if fold:
            loss_fn = objective
        else:
            def loss_fn(x):
                return objective(x)[0]

        if ctx.remat:
            # grad recomputes the forward instead of keeping activations —
            # every line-search probe is forward-only and unaffected
            loss_fn = jax.checkpoint(loss_fn)

        if fan_gemm:
            # frozen leaves evaluated OUTSIDE the alpha vmap — closing
            # over them unbatched is what lets vmap widen M; XLA
            # dead-code-eliminates the probed unravel's unused slices
            frozen = ctx.unravel(base)

            def params_of(full):
                return fold_params(ctx.unravel(full), frozen, leaf_mask)

            def fan_fn(x_cur, d, alphas):
                def phi(alpha):
                    loss, aux = objective_with(params_of, x_cur + alpha * d)
                    # mirror lbfgs_step's loss_fn_aux contract: the fan's
                    # aux structure must match the sequential path's
                    return (loss, aux) if fold else (loss, ())

                return jax.vmap(phi)(alphas)
        else:
            fan_fn = None

        x0 = ctx.partition.extract(flat, ctx.gid)
        x1, lstate, aux = lbfgs_step(
            loss_fn, x0, lstate, ctx.lbfgs, has_aux=fold, fan_fn=fan_fn
        )
        flat = ctx.partition.insert(flat, ctx.gid, x1)
        if fold:
            data_loss_f, stats_f = aux.aux
            entry_data_loss, _ = aux.entry_aux
            # NaN-step fallback (aux_ok False): the final point was never
            # evaluated — report the ENTRY DATA loss and keep the stats.
            # Reporting aux.loss here (the entry OBJECTIVE, penalties
            # included) would silently change what the train_loss series
            # means on exactly the poisoned steps fault detection cares
            # about; the entry data loss keeps the series one meaning
            # (penalty-free data loss, like the explicit-diag path).
            diag_loss = jnp.where(aux.aux_ok, data_loss_f, entry_data_loss)
            stats = jax.tree.map(
                lambda new, old: jnp.where(aux.aux_ok, new, old),
                stats_f, stats,
            )
        elif ctx.diag_forward or ctx.has_stats:
            # the invariant lives with the mechanism, not only in
            # Trainer._ctx: the diagnostic forward is the ONLY place
            # running BN statistics refresh outside the fold, so models
            # with batch stats always run it even if a hand-built
            # GroupContext says otherwise. Explicit-diag path kept for
            # non-Armijo solver configs and for fold-equivalence tests.
            diag_loss, stats = _data_loss(ctx, flat, stats, images, labels)
        else:
            # throughput mode (BN-less models only): one fewer model pass
            # per batch, identical parameter trajectory. Reported loss is
            # the optimizer's entry OBJECTIVE — data loss PLUS any
            # elastic-net/ADMM penalty terms, one step earlier — so the
            # telemetry is not comparable to diag_forward=True series
            # (and NaN detection trails by one batch).
            diag_loss = aux.loss
        return flat, lstate, stats, diag_loss

    return step


def _ragged_select(keep):
    """Per-client select for one `[K_loc, ...]` carry leaf.

    Where `keep[k]` holds the stepped value is adopted; elsewhere the
    pre-step bits survive VERBATIM — the identity carry update of a
    masked ragged step (GroupContext.ragged). With an all-true mask the
    select returns the stepped operand bit for bit, which is what makes
    a full-budget ragged program reproduce the lockstep trajectory
    exactly (tests/test_hetero.py).
    """

    def sel(new, old):
        return jnp.where(
            keep.reshape(keep.shape + (1,) * (new.ndim - 1)), new, old
        )

    return sel


def _ragged_scan(step_all, budgets, flat, lstate, stats, last_loss,
                 data_xs, n_steps: int):
    """Scan `n_steps` RAGGED training steps over one client block.

    The one definition of the masked-step semantics, shared by
    `build_epoch_fn`, `build_stream_epoch_fn`, and `build_round_fn` —
    the ragged-fused==unfused bitwise contract (tests/test_hetero.py)
    only holds while all three paths run the identical per-step selects.
    Step t is an identity carry update for client k when
    `t >= budgets[k]` (flat/lstate/stats keep their pre-step bits), and
    the emitted loss row repeats the client's carried last loss.
    `step_all(flat, lstate, stats, data_t)` runs one lockstep step on
    the per-step slice of `data_xs`. Returns
    `(flat, lstate, stats, losses [n_steps, K_loc], last_loss)`.
    """

    def body(carry, xs_t):
        flat, lstate, stats, last_loss = carry
        data_t, t = xs_t
        flat2, lstate2, stats2, losses = step_all(flat, lstate, stats, data_t)
        sel = _ragged_select(t < budgets)
        flat = sel(flat2, flat)
        lstate = jax.tree.map(sel, lstate2, lstate)
        stats = jax.tree.map(sel, stats2, stats)
        last_loss = sel(losses, last_loss)
        return (flat, lstate, stats, last_loss), last_loss

    (flat, lstate, stats, last_loss), losses = lax.scan(
        body,
        (flat, lstate, stats, last_loss),
        (data_xs, jnp.arange(n_steps, dtype=jnp.int32)),
    )
    return flat, lstate, stats, losses, last_loss


def _counted(fn, counter, category: str):
    """Wrap a built program in the dispatch-counting proxy (obs/trace.py).

    The builders are the one place that knows what KIND of program was
    built, so the `dispatch_count` series' categories are tagged here;
    `counter=None` (benchmarks, tests poking builders directly) returns
    the bare jitted fn.
    """
    return fn if counter is None or fn is None else counter.wrap(fn, category)


def build_epoch_fn(ctx: GroupContext, mesh, counter=None):
    """Jitted epoch: scan over minibatches, vmap over local clients.

    Signature:
      (flat [K,N], lstate, stats, shard_imgs [K,n,H,W,C] u8,
       shard_labels [K,n], idx [S,K,B], mean [K], std [K],
       y [K,G], z [G], rho [K,1]
       [, budgets [K] i32, last_loss [K] — static `ctx.ragged` only])
      -> (flat, lstate, stats, losses [S,K][, last_loss [K]])

    For non-ADMM strategies `y/z/rho` are zero-size placeholders (static
    python `None` is avoided so one signature serves all strategies).

    With `ctx.ragged` the signature grows the per-client step `budgets`
    of THIS dispatch (the trainer offsets the round budget by the steps
    already served — epoch index, scan chunk, streamed chunk) and the
    `last_loss` carry threaded across the round's dispatches: step t is
    an identity carry update for client k when `t >= budgets[k]`, and
    its loss row repeats `last_loss[k]` (docs/FAULT.md §Heterogeneity).
    """
    client_step = _client_train_step(ctx)

    def local(flat, lstate, stats, shard_imgs, shard_labels, idx, mean, std,
              y, z, rho, *rest):
        # the replicated consensus vector is closed over by the L-BFGS
        # while_loop inside client_step; promote it to varying up front —
        # JAX's vma fixpoint re-applies recorded pvary insertions when
        # loop carries get promoted, which errors on an unvarying
        # closed-over constant (see parallel.mark_varying)
        z = mark_varying(z, CLIENT_AXIS)

        def step_all(flat, lstate, stats, idx_t):
            images = jnp.take_along_axis(
                shard_imgs, idx_t[:, :, None, None, None], axis=1
            )
            labels = jnp.take_along_axis(shard_labels, idx_t, axis=1)
            return jax.vmap(
                client_step,
                in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, 0),
            )(flat, lstate, stats, images, labels, mean, std, y, z, rho)

        if ctx.ragged:
            budgets, last_loss = rest
            return _ragged_scan(
                step_all, budgets, flat, lstate, stats, last_loss,
                idx, idx.shape[0],
            )

        def body(carry, idx_t):
            flat, lstate, stats = carry
            flat, lstate, stats, losses = step_all(flat, lstate, stats, idx_t)
            return (flat, lstate, stats), losses

        (flat, lstate, stats), losses = lax.scan(
            body, (flat, lstate, stats), idx
        )
        return flat, lstate, stats, losses

    c = P(CLIENT_AXIS)
    r = P()
    in_specs = (c, c, c, c, c, P(None, CLIENT_AXIS), c, c, c, r, c)
    out_specs = (c, c, c, P(None, CLIENT_AXIS))
    if ctx.ragged:
        in_specs = in_specs + (c, c)  # budgets, last_loss
        out_specs = out_specs + (c,)  # last_loss carry out
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=_check_vma(ctx),
    )
    # params/opt-state/batch-stats are consumed and re-emitted every epoch:
    # donate them so XLA updates in place instead of double-buffering
    return _counted(jax.jit(sharded, donate_argnums=(0, 1, 2)), counter, "epoch")


def build_stream_epoch_fn(ctx: GroupContext, mesh, counter=None):
    """Jitted epoch CHUNK for the host-streaming data path.

    Like `build_epoch_fn` but the minibatches arrive pre-assembled as
    raw-u8 `images [S, K, B, H, W, C]` / `labels [S, K, B]` (normalized
    on device, exactly like the resident path) instead of being gathered
    on device from a resident shard. The trainer feeds
    chunks of S steps from the native `PrefetchBatcher`
    (data/native.py) and double-buffers the next chunk's `device_put`
    against this chunk's compute, so datasets larger than HBM stream
    through without ever fully residing on device (VERDICT round-1 weak
    #5: the batcher existed but nothing could train from it).

    Signature:
      (flat [K,N], lstate, stats, images [S,K,B,H,W,C] u8,
       labels [S,K,B], mean [K], std [K], y [K,G], z [G], rho [K,1]
       [, budgets [K] i32, last_loss [K] — static `ctx.ragged` only])
      -> (flat, lstate, stats, losses [S,K][, last_loss [K]])

    Ragged budgets are per CHUNK, like `build_epoch_fn`'s per-dispatch
    contract: the trainer offsets the round budget by the lockstep steps
    already streamed.
    """
    client_step = _client_train_step(ctx)

    def local(flat, lstate, stats, images_u8, labels, mean, std, y, z, rho,
              *rest):
        z = mark_varying(z, CLIENT_AXIS)  # see build_epoch_fn

        def step_all(flat, lstate, stats, batch):
            imgs_t, labels_t = batch  # [K,B,H,W,C], [K,B]
            return jax.vmap(
                client_step,
                in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, 0),
            )(flat, lstate, stats, imgs_t, labels_t, mean, std, y, z, rho)

        if ctx.ragged:
            budgets, last_loss = rest
            return _ragged_scan(
                step_all, budgets, flat, lstate, stats, last_loss,
                (images_u8, labels), labels.shape[0],
            )

        def body(carry, batch):
            flat, lstate, stats = carry
            flat, lstate, stats, losses = step_all(flat, lstate, stats, batch)
            return (flat, lstate, stats), losses

        (flat, lstate, stats), losses = lax.scan(
            body, (flat, lstate, stats), (images_u8, labels)
        )
        return flat, lstate, stats, losses

    c = P(CLIENT_AXIS)
    r = P()
    sc = P(None, CLIENT_AXIS)  # [S, K, ...] chunks: K is the mesh axis
    in_specs = (c, c, c, sc, sc, c, c, c, r, c)
    out_specs = (c, c, c, sc)
    if ctx.ragged:
        in_specs = in_specs + (c, c)
        out_specs = out_specs + (c,)
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=_check_vma(ctx),
    )
    # donate params/opt-state/stats as in build_epoch_fn; the image chunk
    # is NOT donated (the host reuses its staging buffer)
    return _counted(jax.jit(sharded, donate_argnums=(0, 1, 2)), counter, "epoch")


def build_round_init_fn(ctx: GroupContext, mesh, counter=None):
    """Fresh per-group optimizer + consensus state from current params.

    The reference creates a fresh `LBFGSNew` per partition round
    (reference src/federated_trio.py:273-275) and zeroed y/z per group
    (reference src/consensus_admm_trio.py:281-288).
    """

    def local(flat):
        x = jax.vmap(lambda f: ctx.partition.extract(f, ctx.gid))(flat)
        lstate = jax.vmap(lambda xg: lbfgs_init(xg, ctx.lbfgs))(x)
        if ctx.strategy == "admm":
            cstate = admm_init(x, ctx.admm)
            y, z, rho = cstate.y, cstate.z, cstate.rho
            extra = (cstate.yhat0, cstate.x0)
        else:
            g = ctx.partition.group_size(ctx.gid)
            z = fedavg_init(g, x.dtype).z
            y = jnp.zeros((x.shape[0], 0), x.dtype)  # placeholders
            rho = jnp.zeros((x.shape[0], 0), x.dtype)
            extra = (y, y)
        return lstate, y, z, rho, extra

    c = P(CLIENT_AXIS)
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(c,),
        out_specs=(c, c, P(), c, (c, c)),
        check_vma=True,
    )
    return _counted(jax.jit(sharded), counter, "round_init")


def _wire_codec(ctx: GroupContext):
    """The context's exchange codec (exchange/codec.py make_codec — the
    ONE config-to-codec mapping, shared with the trainer's ledger)."""
    return make_codec(
        ctx.exchange_dtype, ctx.exchange_codec,
        ctx.topk_fraction, ctx.quant_bits,
    )


def _ef_enabled(ctx: GroupContext) -> bool:
    """Whether the consensus programs carry the error-feedback residual.

    ONE definition (the `_corruption_enabled` rule): this predicate
    fixes the compiled programs' argument/carry/output signature AND
    gates every call site's ef argument — a drifted copy would be an
    argument-count mismatch at dispatch. EF only exists where a LOSSY
    exchange does: identity-codec or strategy-'none' contexts compile
    the exact pre-EF programs whatever the flag says.
    """
    return (
        ctx.error_feedback
        and ctx.strategy != "none"
        and not _wire_codec(ctx).is_identity
    )


def _consensus_local(ctx: GroupContext):
    """The per-device consensus body, shared by the standalone consensus
    program (`build_consensus_fn`) and the fused round (`build_round_fn`).

    `(flat, y, z, rho, extra, nadmm, mask[, ef][, cmode, cstr, cseed]) ->
    (flat, y, z, rho, extra, (dual, primal, mean_rho, survivors),
    qstats, ef')`. The `ef` slot exists only when `_ef_enabled(ctx)`
    (the per-(client, group) error-feedback residual `[K_loc, G]`; `ef'`
    is `()` otherwise); the corruption args only when `ctx.corrupt`
    (the plan schedules update corruption — static, so corruption-free
    runs compile the pre-corruption program); `qstats` is
    `(unorm, suspect)` — the auto-quarantine update-norm statistics —
    when `ctx.quarantine_z` is set, else `()`. `mask` is the EFFECTIVE
    participation vector (plan dropout AND any quarantine accumulated by
    the caller). Returns None for strategy 'none' (independent training
    has no consensus exchange).
    """
    if ctx.strategy == "none":
        return None
    quarantine = ctx.quarantine_z is not None
    codec = _wire_codec(ctx)
    # static: the identity codec compiles the exact pre-codec program
    wire = not codec.is_identity
    ef_on = _ef_enabled(ctx)

    def send_view(x, ef, mask, corr):
        """The aggregation's view of the updates (what the exchange
        RECEIVED) plus the sender's next error-feedback residual.

        The sender adds its carried residual (error feedback — the
        compensation that keeps a lossy codec's bias from accumulating),
        encodes through the wire codec (exchange/ — decode back to f32
        models the receiver's view; identity is a no-op compiled away),
        and keeps what the wire lost. An in-transit corruption fault
        garbles the wire AFTER the encoder (and after the sender's EF
        bookkeeping — the sender doesn't know its link is hostile; mode
        0 selects the bits verbatim). The residual only updates for
        clients IN the exchange (`mask`): a dropped / zero-budget /
        still-quarantined client never transmitted, so it carries its
        residual unchanged — and a non-finite residual (poisoned
        sender) resets to zero rather than wedging every later wire.
        Every consumer downstream — mean, robust combiners, quarantine
        statistics — sees decoded f32."""
        ef_new = ()
        if wire:
            x_comp = x + ef if ef_on else x
            sent = codec.roundtrip(x_comp)
            if ef_on:
                resid = x_comp - sent
                resid = jnp.where(jnp.isfinite(resid), resid, 0.0)
                ef_new = jnp.where(mask[:, None] > 0, resid, ef)
        else:
            sent = x
        if ctx.corrupt:
            sent = apply_corruption(sent, *corr, gauss=ctx.corrupt_gauss)
        return sent, ef_new

    def qstats_of(x_send, z_prev, mask):
        if not quarantine:
            return ()
        return update_suspects(x_send, z_prev, mask, ctx.quarantine_z)

    def parse_rest(rest):
        """THE one `*rest` layout of the consensus body — [ef] when
        error feedback is carried, then the corruption rows. Positional
        and order-sensitive, so both strategy branches (and any future
        optional slot) must unpack through this single definition."""
        rest = list(rest)
        ef = rest.pop(0) if ef_on else ()
        return ef, tuple(rest)

    if ctx.strategy == "fedavg":

        def local(flat, y, z, rho, extra, nadmm, mask, *rest):
            ef, corr = parse_rest(rest)
            x = jax.vmap(lambda f: ctx.partition.extract(f, ctx.gid))(flat)
            x_send, ef_new = send_view(x, ef, mask, corr)
            state, met = fedavg_round(
                x_send,
                FedAvgState(z=z),
                ctx.admm.z_soft_threshold,
                mask=mask,
                combine=ctx.robust_agg,
                robust_f=ctx.robust_f,
            )
            flat = jax.vmap(
                lambda f, mk: ctx.partition.insert(
                    f,
                    ctx.gid,
                    jnp.where(mk > 0, state.z, ctx.partition.extract(f, ctx.gid)),
                )
            )(flat, mask)
            zeros = jnp.zeros((), x.dtype)
            return flat, y, state.z, rho, extra, (
                met["dual_residual"],
                zeros,
                zeros,
                met["survivors"],
            ), qstats_of(x_send, z, mask), ef_new

    else:  # admm

        def local(flat, y, z, rho, extra, nadmm, mask, *rest):
            ef, corr = parse_rest(rest)
            x = jax.vmap(lambda f: ctx.partition.extract(f, ctx.gid))(flat)
            x_send, ef_new = send_view(x, ef, mask, corr)
            yhat0, x0 = extra
            state = ADMMState(y=y, z=z, rho=rho, yhat0=yhat0, x0=x0)
            state, met = admm_round(
                x,
                state,
                nadmm,
                ctx.admm,
                mask=mask,
                # the z-update consumes the exchange's RECEIVED view
                # whenever it differs from the client's true x — codec
                # wire format and/or in-transit corruption; None keeps
                # the clean program's identical graph
                x_agg=x_send if (ctx.corrupt or wire) else None,
                combine=ctx.robust_agg,
                robust_f=ctx.robust_f,
            )
            return flat, state.y, state.z, state.rho, (state.yhat0, state.x0), (
                met.dual_residual,
                met.primal_residual,
                met.mean_rho,
                met.survivors,
            ), qstats_of(x_send, z, mask), ef_new

    return local


def build_consensus_fn(ctx: GroupContext, mesh, counter=None):
    """Jitted averaging/ADMM round over the active group's coordinates.

    FedAvg: z = mean_k x_k, broadcast back into every client's params
    (reference src/federated_trio.py:353-363). ADMM: BB-rho (if due),
    weighted z-update, y-update; clients keep their own x (reference
    src/consensus_admm_trio.py:395-513).

    `mask` is the `[K]` EFFECTIVE participation vector of the round
    (fault/plan.py dropout AND any quarantine the trainer accumulated;
    all-ones when no fault plan is active — bit-identical to the unmasked
    math). FedAvg's broadcast-back honors it too: a dropped client missed
    the round, so it keeps its own x instead of receiving znew and rejoins
    from stale parameters — the partial-participation regime of TAMUNA
    (arXiv:2302.09832). Metrics gain the psum'd survivor count.

    With `_ef_enabled(ctx)` the signature grows the `[K, G]`
    error-feedback residual after `mask` and the outputs gain the
    updated residual (the trainer carries it across exchanges and outer
    loops — `engine/trainer.py _ef_store`). With `ctx.corrupt` the
    signature grows the round's `[K]` corruption mode/strength/seed rows
    (fault/injector.py) and the exchange consumes the
    in-transit-corrupted updates; with `ctx.quarantine_z` the returned
    `qstats` tuple carries the `[K]` update norms and suspect flags the
    trainer folds into the NEXT exchange's mask (consensus/robust.py;
    all empty/absent otherwise — the clean program is unchanged).
    """
    local = _consensus_local(ctx)
    if local is None:
        return None
    ef_on = _ef_enabled(ctx)

    c = P(CLIENT_AXIS)
    r = P()
    in_specs = (c, c, r, c, (c, c), r, c)
    if ef_on:
        in_specs = in_specs + (c,)
    if ctx.corrupt:
        in_specs = in_specs + (c, c, c)
    qspec = (c, c) if ctx.quarantine_z is not None else ()
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(
            c, c, r, c, (c, c), (r, r, r, r), qspec,
            c if ef_on else (),
        ),
        check_vma=True,
    )
    # no donation here: the round-init placeholders alias buffers (e.g.
    # the fedavg extra=(y, y)) and these arrays are one group wide anyway
    return _counted(jax.jit(sharded), counter, "consensus")


def _client_eval_fn(model, unravel, has_stats: bool):
    """One client's full-test-sweep correct-count body.

    Shared by the standalone eval program (`build_eval_fn`) and the
    folded per-consensus-round eval inside the fused round
    (`build_round_fn(fold_eval=True)`): the SAME ops in the same order,
    so a folded round's correct counts equal the standalone program's.
    `(flat [N], stats, test_imgs [T,B,...], test_labels [T,B],
    test_mask [T,B], mean, std) -> correct (i32 scalar)`.
    """

    def client_eval(flat, stats, test_imgs, test_labels, test_mask, mean, std):
        params = unravel(flat)
        variables = {"params": params}
        if has_stats:
            variables["batch_stats"] = stats

        def body(correct, batch):
            img, lab, msk = batch
            logits = model.apply(variables, normalize(img, mean, std), train=False)
            pred = jnp.argmax(logits, axis=-1)
            return correct + jnp.sum((pred == lab) & msk), None

        # seed the scan carry with the client axis's varying type —
        # required by vma checking, numerically an exact zero
        correct, _ = lax.scan(
            body,
            jnp.int32(0) + vma_zero(mean).astype(jnp.int32),
            (test_imgs, test_labels, test_mask),
        )
        return correct

    return client_eval


def build_round_fn(
    ctx: GroupContext,
    mesh,
    *,
    nadmm: int,
    nepoch: int,
    snapshot: bool = False,
    fold_eval: bool = False,
    counter=None,
):
    """One partition group's FULL averaging round as ONE jitted program.

    The unfused round is `nadmm * (nepoch + 1)` separately dispatched XLA
    programs (epochs + consensus), and on dispatch-latency-bound runtimes
    each dispatch pays a flat ~0.1 s floor (benchmarks/
    epoch_attribution.json) — the wall for the batch-32 flagship. Here the
    whole round is one `lax.scan` over the `nadmm` consensus iterations,
    each scan step running the epoch minibatch scan (`nepoch * S` steps of
    the SAME body `build_epoch_fn` scans) followed by the consensus body
    (`_consensus_local` — the identical collective). One dispatch per
    round; the trajectory is bit-identical to the unfused path because
    scan iterations execute the identical per-step computation in the
    identical order (the same property `max_scan_steps` chunking relies
    on, tests/test_engine.py::test_resident_auto_chunking_is_bit_identical).

    Signature:
      (flat [K,N], lstate, stats, shard_imgs [K,n,H,W,C] u8,
       shard_labels [K,n], idx [nadmm, nepoch, S, K, B],
       mean [K], std [K], y [K,G], z [G], rho [K,1], extra,
       masks [nadmm, K]
       [, ef0 [K, G] — static `_ef_enabled(ctx)` only]
       [, budgets [nadmm, K] i32 — static `ctx.ragged` only]
       [, cmodes [nadmm, K] i32, cstrengths [nadmm, K], cseeds
          [nadmm, K] i32 — static `ctx.corrupt` only]
       [, test_imgs [T,B,...], test_labels [T,B], test_mask [T,B]
          — static `fold_eval=True` only])
      -> (flat, lstate, stats, y, z, rho, extra,
          losses [nadmm, nepoch, S, K],
          met (dual, primal, mean_rho, survivors) each [nadmm],
          param_ok [nadmm, K] bool,
          qstats, snaps, correct, ef [K, G], drift [num_groups])

    * `idx` is the whole round's shuffle schedule, precomputed host-side
      (the trainer stacks its deterministic per-(nadmm, epoch)
      `_epoch_indices` draws), fed as scan xs.
    * `masks [nadmm, K]` are the per-consensus-round participation masks
      (fault/injector.py `masks_for_round`), scan xs; all-ones without a
      fault plan — bit-identical to the maskless math.
    * `budgets [nadmm, K]` (static `ctx.ragged` only) are the per-client
      inner-step budgets of each consensus iteration
      (fault/injector.py `step_budgets_for_round`), scan xs: step t of
      an iteration is an identity carry update for client k when
      `t >= budgets[k]` — flat/lstate/stats keep their pre-step bits and
      the loss row repeats the client's last recorded loss of the round
      (zero until its first active step). A ZERO-budget client produced
      no report by the deadline, so it is ANDed out of that iteration's
      effective participation mask exactly like a dropped client — the
      all-zero-budget exchange keeps z, and all-FULL budgets are
      bit-identical to the lockstep program (tests/test_hetero.py).
    * `cmodes`/`cstrengths`/`cseeds` (static `ctx.corrupt` only) are the
      round's corruption schedule (fault/injector.py
      `corruption_for_round`), scan xs: each consensus iteration's
      exchange sees the in-transit-corrupted updates
      (consensus/robust.py `apply_corruption`) while the clients keep
      their true parameters.
    * `qstats` (static `ctx.quarantine_z` only, else `()`): the
      auto-quarantine statistics `(update_norm [nadmm, K], suspect
      [nadmm, K])`. The suspect mask accumulates IN-CARRY and ANDs into
      the following exchanges' participation masks — the quarantine
      decision happens inside the one dispatch, no host round-trip; the
      host reads the matrices once per round for telemetry and the comm
      ledger's wasted-uplink attribution.
    * `param_ok` is the `fault_mode` parameter check as on-device flags:
      per-client post-consensus finiteness, accumulated across the scan
      and inspected ONCE per round by the host (the rollback round is
      transactional, so the per-nadmm inspection the unfused path does
      adds nothing but dispatches). Loss finiteness is inspected from the
      returned `losses` — already a round output for telemetry.
    * `snaps` (static `snapshot=True` only, else `()`): the
      `(flat, stats)` state after EVERY consensus exchange,
      `[nadmm, K, ...]` — what `check_results`' per-round eval cadence
      reads when eval runs OUTSIDE the program (`--no-fold-eval`).
    * `correct` (static `fold_eval=True` only, else `()`): the
      `check_results` eval cadence FOLDED INTO the round — after every
      consensus exchange the scan body runs the full padded test sweep
      (`_client_eval_fn`, the exact body `build_eval_fn` dispatches
      standalone) against the post-consensus `(flat, stats)` and emits
      the `[nadmm, K]` i32 correct counts. One dispatch then carries the
      round's training, consensus, AND evals — no standalone eval
      launches, no mid-round `[nadmm, K, N]` state snapshots
      materialized. `snapshot` and `fold_eval` are mutually exclusive
      (folding replaces the snapshot consumer).
    * `ef` (static `_ef_enabled(ctx)` only, else `()`): the round's
      final per-(client, group) error-feedback residual — `ef0` carried
      through every consensus exchange of the scan (a residual the
      codec lost at exchange a compensates at exchange a+1 WITHIN the
      one dispatch); the trainer persists it to the next outer loop.
    * `drift` (static `ctx.group_drift` only, else `()`): the
      `[num_groups]` post-round per-group drift signal — the SHARED
      `parallel/diagnostics.py group_distances` body on the final flat,
      inside the same dispatch (the standalone program the unfused path
      dispatches runs the identical ops, the `_client_eval_fn` sharing
      pattern) — what the adaptive layer-group scheduler consumes
      (exchange/schedule.py).

    `nadmm`/`nepoch` are static (they shape the scan); donation matches
    `build_epoch_fn` (flat/lstate/stats update in place; the test sweep
    is NOT donated — it is staged once and reused every round).
    """
    if snapshot and fold_eval:
        raise ValueError(
            "snapshot and fold_eval are mutually exclusive: folding runs "
            "the eval inside the program, so the snapshots it would feed "
            "are never materialized"
        )
    client_step = _client_train_step(ctx)
    consensus_local = _consensus_local(ctx)
    client_eval = (
        _client_eval_fn(ctx.model, ctx.unravel, ctx.has_stats)
        if fold_eval
        else None
    )

    corrupt = ctx.corrupt and consensus_local is not None
    quarantine = (
        ctx.quarantine_z is not None and consensus_local is not None
    )
    # quarantine RELEASE threshold (consensus/robust.py
    # quarantine_release_2f — THE one definition, shared with the
    # trainer's host replay): an exchange whose quarantine-trusted
    # cohort would be <= 2f releases the mask (suspects transmit and
    # are combined; the trim itself is the defense) while detection —
    # the suspect flags, their records, the qmask carry — continues
    # unchanged. Static: None compiles the exact pre-release program.
    release_2f = (
        quarantine_release_2f(ctx.robust_agg, ctx.robust_f)
        if quarantine
        else None
    )
    ragged = ctx.ragged
    ef_on = _ef_enabled(ctx)
    drift_on = ctx.group_drift

    def local(flat, lstate, stats, shard_imgs, shard_labels, idx, mean, std,
              y, z, rho, extra, masks, *rest):
        # *rest, by static flags: [ef0] when error feedback is carried,
        # then [budgets] when the round is ragged, then [cmodes,
        # cstrengths, cseeds] when the plan schedules corruption, then
        # [test_imgs, test_labels, test_mask] when the eval is folded
        rest = list(rest)
        ef0 = rest.pop(0) if ef_on else ()
        budget_rows = rest.pop(0) if ragged else ()
        corr_rows = tuple(rest[:3]) if corrupt else ()
        if corrupt:
            rest = rest[3:]
        test_imgs, test_labels, test_mask = (
            rest if fold_eval else (None, None, None)
        )

        def round_body(carry, xs):
            flat, lstate, stats, y, z, rho, extra, qmask, lloss, ef = carry
            # [nepoch, S, K_loc, B], [K_loc], i32, per-iteration [K_loc]
            # budget and corruption rows
            idx_a, mask_a, na, budget_a, corr_a = xs
            # replicated consensus vector -> varying for the closed-over
            # L-BFGS while_loop (see build_epoch_fn); the CARRY keeps the
            # unvarying z so its type is stable across scan iterations
            # (the consensus psum emits an unvarying znew)
            zv = mark_varying(z, CLIENT_AXIS)

            def step_all(flat, lstate, stats, idx_t):
                images = jnp.take_along_axis(
                    shard_imgs, idx_t[:, :, None, None, None], axis=1
                )
                labels = jnp.take_along_axis(shard_labels, idx_t, axis=1)
                return jax.vmap(
                    client_step,
                    in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, 0),
                )(flat, lstate, stats, images, labels, mean, std, y, zv, rho)

            # the epoch boundary is invisible to the minibatch body (a
            # fresh shuffle is just the next idx rows), so nepoch epochs
            # flatten into one [nepoch*S] scan — iteration-for-iteration
            # the sequence the unfused path runs as nepoch programs
            s = idx_a.shape[1]
            idx_flat = idx_a.reshape((nepoch * s,) + idx_a.shape[2:])
            if ragged:
                # per-client step masks (_ragged_scan — the shared
                # masked-step semantics): the lloss carry crosses
                # consensus iterations, so a zero-budget iteration shows
                # the client's last loss from an EARLIER iteration
                flat, lstate, stats, losses, lloss = _ragged_scan(
                    step_all, budget_a, flat, lstate, stats, lloss,
                    idx_flat, nepoch * s,
                )
            else:

                def batch_body(c, idx_t):
                    flat, lstate, stats = c
                    flat, lstate, stats, losses = step_all(
                        flat, lstate, stats, idx_t
                    )
                    return (flat, lstate, stats), losses

                (flat, lstate, stats), losses = lax.scan(
                    batch_body, (flat, lstate, stats), idx_flat
                )
            losses = losses.reshape((nepoch, s) + losses.shape[1:])

            if consensus_local is not None:
                # quarantine ANDs into the plan mask: a client flagged at
                # an earlier exchange of THIS round is excluded here. A
                # zero-budget client never produced a report by the
                # deadline, so it drops out of the exchange the same way.
                eff_mask = mask_a
                if ragged:
                    eff_mask = eff_mask * (budget_a > 0).astype(
                        eff_mask.dtype
                    )
                if quarantine:
                    gated = eff_mask * qmask
                    if release_2f is not None:
                        # release the quarantine where it would leave
                        # the trimmed combiner <= 2f trusted clients
                        # (see build-time comment); the host replays
                        # this decision from the fetched suspect
                        # matrices for the ledger's wasted-uplink
                        # attribution (engine/trainer.py)
                        trusted = client_sum(gated, local_axis=0)
                        eff_mask = jnp.where(
                            trusted > release_2f, gated, eff_mask
                        )
                    else:
                        eff_mask = gated
                ef_args = (ef,) if ef_on else ()
                flat, y, z, rho, extra, met, qstats, ef_new = consensus_local(
                    flat, y, z, rho, extra, na, eff_mask, *ef_args, *corr_a
                )
                if ef_on:
                    ef = ef_new
            else:
                zeros = jnp.zeros((), flat.dtype)
                met = (zeros, zeros, zeros, zeros)
                qstats = ()
            param_ok = jnp.isfinite(flat).all(axis=tuple(range(1, flat.ndim)))

            ys = (losses, met, param_ok)
            if quarantine:
                unorm, suspect = qstats
                qmask = qmask * (1.0 - suspect)
                ys = ys + ((unorm, suspect),)
            if snapshot:
                ys = ys + ((flat, stats),)
            if fold_eval:
                # the folded check_results cadence: the full test sweep at
                # the post-consensus state, inside the same dispatch — the
                # per-client body is build_eval_fn's, bit for bit
                correct = jax.vmap(
                    client_eval, in_axes=(0, 0, None, None, None, 0, 0)
                )(flat, stats, test_imgs, test_labels, test_mask, mean, std)
                ys = ys + (correct,)
            return (
                flat, lstate, stats, y, z, rho, extra, qmask, lloss, ef
            ), ys

        # the quarantine carry starts all-clear; derived from the varying
        # masks input so its vma type matches the suspect-driven updates
        qmask0 = jnp.ones_like(masks[0]) if quarantine else ()
        # the ragged last-loss carry starts at zero (a client reports 0.0
        # until its first active step of the round); vma_zero keeps the
        # varying type the per-client selects produce
        lloss0 = vma_zero(mean) if ragged else ()
        carry = (
            flat, lstate, stats, y, z, rho, extra, qmask0, lloss0, ef0
        )
        na_seq = jnp.arange(nadmm, dtype=jnp.int32)
        # corr_rows (and budget_rows) are () when their static flag is
        # off — a leafless xs entry whose per-step slice stays (), so one
        # scan call serves every build
        carry, ys = lax.scan(
            round_body, carry, (idx, masks, na_seq, budget_rows, corr_rows)
        )
        flat, lstate, stats, y, z, rho, extra, _, _, ef_out = carry
        losses, met, param_ok = ys[:3]
        i = 3
        qstats = (ys[i][0], ys[i][1]) if quarantine else ()
        i += 1 if quarantine else 0
        snaps = ys[i] if snapshot else ()
        correct = ys[-1] if fold_eval else ()
        # the adaptive scheduler's in-scan signal: the SHARED
        # group_distances body on the round's final parameters — one
        # psum, replicated [num_groups] output, same dispatch
        drift = group_distances(flat, ctx.partition) if drift_on else ()
        return (flat, lstate, stats, y, z, rho, extra,
                losses, met, param_ok, qstats, snaps, correct,
                ef_out, drift)

    c = P(CLIENT_AXIS)
    r = P()
    sc1 = P(None, CLIENT_AXIS)  # [nadmm, K, ...]
    in_specs = (
        c, c, c, c, c,
        P(None, None, None, CLIENT_AXIS),  # idx [nadmm, nepoch, S, K, B]
        c, c, c, r, c, (c, c),
        sc1,  # masks [nadmm, K]
    )
    if ef_on:
        in_specs = in_specs + (c,)  # error-feedback residual [K, G]
    if ragged:
        in_specs = in_specs + (sc1,)  # step budgets [nadmm, K]
    if corrupt:
        in_specs = in_specs + (sc1, sc1, sc1)  # corruption mode/str/seed
    if fold_eval:
        in_specs = in_specs + (r, r, r)  # replicated [T,B,...] test sweep
    out_specs = (
        c, c, c, c, r, c, (c, c),
        P(None, None, None, CLIENT_AXIS),  # losses [nadmm, nepoch, S, K]
        (r, r, r, r),  # per-nadmm metric series
        sc1,  # param_ok [nadmm, K]
        (sc1, sc1) if quarantine else (),  # update norms + suspect flags
        (sc1, sc1) if snapshot else (),  # post-consensus state snapshots
        sc1 if fold_eval else (),  # folded-eval correct counts [nadmm, K]
        c if ef_on else (),  # final error-feedback residual [K, G]
        r if drift_on else (),  # post-round drift signal [num_groups]
    )
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=_check_vma(ctx),
    )
    # donated carry: params/opt-state/batch-stats are consumed and
    # re-emitted every round, exactly as in build_epoch_fn. y/z/rho/extra
    # are NOT donated — the round-init placeholders alias buffers (e.g.
    # the fedavg extra=(y, y)), same reason build_consensus_fn never
    # donates.
    return _counted(
        jax.jit(sharded, donate_argnums=(0, 1, 2)), counter, "round"
    )


def build_eval_fn(model, unravel, has_stats: bool, mesh, counter=None):
    """Jitted full-test-set evaluation for every client.

    The reference's `verification_error_check` iterates each client's
    testloader in Python (reference src/federated_trio.py:199-223); here
    one call scans the whole padded `[T,B,...]` test set on device for all
    clients and returns `[K]` correct counts (top-1). The per-client body
    is `_client_eval_fn` — shared with the fused round's folded eval, so
    the standalone and folded cadences compute identical counts.
    """
    client_eval = _client_eval_fn(model, unravel, has_stats)

    def local(flat, stats, test_imgs, test_labels, test_mask, mean, std):
        # the client-sharded out-spec assembles local [K_loc] blocks into
        # the global [K] — no gather collective needed
        return jax.vmap(
            client_eval, in_axes=(0, 0, None, None, None, 0, 0)
        )(flat, stats, test_imgs, test_labels, test_mask, mean, std)

    c = P(CLIENT_AXIS)
    r = P()
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(c, c, r, r, r, c, c),
        out_specs=c,
        check_vma=True,
    )
    return _counted(jax.jit(sharded), counter, "eval")
