"""Experiment configuration: one dataclass, five reference presets.

The reference's "config system" is a block of module-level constants at the
top of each driver script (reference src/federated_trio.py:17-34,
src/consensus_admm_trio.py:16-44, src/no_consensus_trio.py:10-25) edited by
hand; each of the five scripts is one experiment. Here those exact knobs
are fields of `ExperimentConfig`, and the five scripts become the five
entries of `PRESETS`. A real CLI lives in
`federated_pytorch_test_tpu.__main__`.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Tuple

from federated_pytorch_test_tpu.consensus import ADMMConfig, ROBUST_METHODS
from federated_pytorch_test_tpu.exchange import (
    EXCHANGE_CODECS,
    EXCHANGE_DTYPES,
    GROUP_SCHEDULES,
    make_codec,
    validate_group_skip_frac,
)
from federated_pytorch_test_tpu.optim import LBFGSConfig


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """All knobs of the five reference drivers, in one place.

    Defaults follow the FedAvg simple-CNN driver
    (reference src/federated_trio.py:17-34).
    """

    name: str = "custom"
    model: str = "net"  # net | net1 | net2 | resnet18 | vit (models.MODELS)
    # extra constructor kwargs for the model class (validated against its
    # dataclass fields by the Trainer) — e.g. {"moe_experts": 8} turns the
    # ViT into a switch-MoE ViT (models/moe.py)
    model_kwargs: dict = dataclasses.field(default_factory=dict)
    # weight of the switch load-balance aux loss when the model sows
    # `moe_aux` (models/moe.py:145); ignored for non-MoE models. Without
    # this term routing can collapse onto few experts.
    moe_aux_coef: float = 0.01
    # 'bfloat16' runs convs/matmuls AND norm elementwise math in bf16
    # (params, the loss, and ALL L-BFGS math stay f32 — mixed precision,
    # not low precision). 'float32' matches the reference bit-for-bit in
    # spirit — and note that XLA's default matmul precision already runs
    # f32 convs as single bf16 MXU passes, so on CIFAR-sized workloads
    # f32 keeps bf16's compute speed without its cast seams. Round-2
    # profiling (BASELINE.md roofline note) recovered bf16 from 2.1x to
    # ~1.3x slower on the batch-32 flagship (hoisted closure cast,
    # fusable bf16 BN reductions); f32 stays the default — the knob pays
    # off where activation memory is the binding constraint (long-context
    # transformers, large batches with remat), not small CNNs.
    compute_dtype: str = "float32"
    # rematerialize the forward during backprop (jax.checkpoint): trades
    # ~1/3 more FLOPs for activation memory — the lever for batch sizes /
    # models that do not fit HBM otherwise
    remat: bool = False
    dataset: str = "cifar10"  # cifar10 | cifar100
    data_root: str | None = None  # None => $CIFAR_DATA_DIR or ./torchdata
    synthetic_ok: bool = True  # fall back to synthetic data if no archive
    # shrink the SYNTHETIC fallback only (smoke runs / CI); a real archive
    # is never truncated
    synthetic_n_train: int | None = None
    synthetic_n_test: int | None = None

    n_clients: int = 3
    batch: int = 512  # reference `default_batch`
    strategy: str = "fedavg"  # none | fedavg | admm

    # --- cross-device scale: virtual clients + cohort sampling ---
    # (clients/, docs/SCALE.md). With `virtual_clients=N` the experiment
    # models a population of N virtual clients whose state lives in a
    # host-side chunked store (clients/store.py); each outer loop a
    # seeded, replayable cohort of `cohort` clients (clients/cohort.py —
    # pure in (cohort_seed, nloop), like a FaultPlan) is GATHERED into
    # the unchanged one-dispatch round program, trains every partition
    # round of that loop, and is SCATTERED back. The compiled programs'
    # client axis is then the cohort: `n_clients` is DERIVED (forced to
    # `cohort`) in this mode, and the cohort axis shards across the mesh
    # exactly as the static-K axis did (parallel/shardmap.py — per-device
    # work is cohort/D, constant in N). Fault schedules stay keyed by
    # VIRTUAL client id, so a client's chaos identity follows it across
    # cohorts (docs/FAULT.md). N=K with cohort=K and
    # cohort_weighting='identity' reproduces the legacy trajectory
    # bitwise (tests/test_clients.py). None = legacy cross-silo mode.
    virtual_clients: int | None = None
    # cohort size C: virtual clients gathered per outer loop (required
    # with virtual_clients; becomes the compiled client-axis width)
    cohort: int | None = None
    # cohort sampler seed — folded through the shared SEED_FOLDS
    # registry (fault/plan.py), so even cohort_seed == fault-plan seed
    # draws independent schedules
    cohort_seed: int = 0
    # 'uniform' | 'samples' (probability ∝ per-client sample count) |
    # 'identity' (full participation; requires cohort == virtual_clients)
    # | 'telemetry' (probability from observed per-virtual-client
    # reliability: mean speed, deadline misses, dropouts, quarantine
    # history — accumulated in the ClientStore at scatter time and pure
    # in (seed, nloop, recorded history), so crashed+resumed twins
    # sample identical cohorts; clients/cohort.py, docs/SCALE.md)
    cohort_weighting: str = "uniform"
    # how many disjoint data shards the virtual population maps onto
    # (client v holds shard v % data_shards; the store records the
    # assignment). None = one shard per virtual client — fine while
    # n_train/N >= batch, set explicitly for N near or beyond the sample
    # count (real cross-device fleets share far fewer distinct data
    # distributions than devices).
    data_shards: int | None = None
    # virtual clients per store chunk (clients/store.py): the unit of
    # lazy materialization and of the dirty-chunk checkpoint delta. One
    # touched client materializes (and one dirtied chunk rewrites) a
    # whole chunk — chunk_clients * n_params * 4 bytes — so the default
    # stays small enough that a net-sized model's chunk is ~16 MB;
    # raise it for tiny models where per-file overhead dominates.
    store_chunk_clients: int = 64
    # LRU bound on store chunks held in RAM (clients/store.py,
    # docs/SCALE.md §Spilled store): beyond it, clean chunks evict (a
    # later gather memory-maps their `.npz` back in) and dirty chunks
    # spill to `checkpoint_dir/client_store` first — host RSS becomes
    # O(resident + cohort), flat in the virtual population, which is
    # what lets one host run N=1M virtual clients. None = resident
    # forever (the legacy keep-everything behavior). Approximate bytes
    # budget: resident_chunks * store_chunk_clients * row bytes
    # (n_params * 4 for `flat`).
    store_resident_chunks: int | None = None
    # pipelined cohort prefetch (clients/prefetch.py, docs/SCALE.md
    # §Prefetch lifecycle): gather loop n+1's cohort — store chunk
    # reads, data shards, device puts — on a background thread while
    # loop n trains, so the gather leaves the round wall. The adopted
    # buffers are bit-identical to a cold gather's (`--no-prefetch` is
    # the bitwise fallback); a dispatch-shape-only knob like fold_eval,
    # excluded from the metric-stream tag.
    prefetch: bool = True
    # crc32 checksums on every spilled/checkpointed store chunk file and
    # manifest (clients/store.py, fault/io.py): stamped at write, verified
    # on every spill read BEFORE a row can reach a gather, with the
    # three-step repair ladder behind detection (docs/FAULT.md §Storage-
    # integrity axis). Off = legacy byte path (chunks written without
    # digests are still readable by checksumming runs — the v1-accepted
    # format contract). A durability knob, not a trajectory knob:
    # excluded from the metric-stream tag like prefetch.
    store_checksums: bool = True

    # loop nest sizes (reference src/federated_trio.py:20-22)
    nloop: int = 12  # outer loops over the partition groups
    nepoch: int = 1  # epochs per averaging round
    nadmm: int = 3  # averaging / ADMM rounds per partition group

    # regularization (reference src/federated_trio.py:25-26)
    lambda1: float = 1e-4
    lambda2: float = 1e-4
    # 'active_linear': elastic net on the active group's coordinates when
    #   that group is a linear layer (reference src/federated_trio.py:309-310);
    # 'first_linear': elastic net on the FIRST linear group's coordinates of
    #   the full vector — the no_consensus driver's behavior, where the
    #   `or`-quirk makes `linear_layer_parameters()` return only fc1
    #   (reference src/simple_models.py:34,74, src/no_consensus_trio.py:195-196);
    # 'none': no regularization (the resnet drivers' closures).
    reg_mode: str = "active_linear"

    biased_input: bool = True  # per-client normalization (reference :31-34)

    # per-batch diagnostic forward at the ACCEPTED params (the reference
    # prints this loss every minibatch, src/federated_trio.py:341-352).
    # Measured (benchmarks/epoch_attribution.json): one extra model
    # forward of the epoch step's ~9 model passes. False skips it — the
    # parameter trajectory is bit-identical (tested), but the recorded
    # per-batch loss becomes the optimizer's entry OBJECTIVE (data loss
    # PLUS any elastic-net/ADMM penalty, one step earlier), so the
    # series is NOT comparable to diag_forward=True telemetry, and NaN
    # fault detection trails by one batch. A pure-throughput knob for
    # BN-less models; models WITH batch stats always run the forward (it
    # is the only place running BN statistics refresh — enforced in the
    # step itself).
    diag_forward: bool = True
    # fold the diagnostic forward into the accepted line-search
    # evaluation (round 5): the Armijo-accepted evaluation IS at the
    # step's final parameters and already computes the BN batch
    # statistics the closure used to discard, so the diagnostic print +
    # stats refresh come out of lbfgs_step's aux channel with one fewer
    # model pass per minibatch. The PARAMETER trajectory is bit-identical
    # either way (train-mode BN never reads running stats); running
    # stats and the printed loss can differ from the unfolded path by
    # XLA fusion ulps. False forces the explicit diagnostic forward
    # (pre-round-5 bitwise telemetry; equivalence tested in
    # tests/test_engine.py).
    fold_diag_forward: bool = True

    # inner optimizer (reference src/federated_trio.py:273-275)
    lbfgs_history: int = 10
    lbfgs_max_iter: int = 4
    lbfgs_lr: float = 1.0
    # 'compact' (Byrd–Nocedal, MXU matmuls), 'pallas' (compact with the
    # history traffic fused into two Pallas kernels, ops/compact_pallas.py)
    # or 'two_loop' (sequential recursion — the escape hatch)
    lbfgs_direction: str = "compact"
    # batched multi-alpha Armijo fan width (optim/linesearch.py
    # backtracking_armijo_probes_aux, docs/PERF.md): P candidate step
    # sizes — consecutive rungs of the halving ladder from alphabar —
    # evaluated in ONE widened vmapped pass per line-search iteration,
    # with the first Armijo-satisfying rung selected on device. 1 (the
    # default) dispatches to the UNCHANGED sequential search and is
    # bitwise-identical to pre-probe builds; > 1 selects the same ladder
    # rung (up to ulp-boundary Armijo ties) while the loss/aux values
    # carry batched-reduction ulps, so this is a TRAJECTORY-CHANGING
    # knob (it lives in the
    # metrics-stream tag, unlike the dispatch-shape-only fold/async
    # knobs). The roofline lever: the sequential search's mean ~4 probes
    # per step each re-stream the full parameter vector from HBM; a fan
    # streams once per P probes (bench.py probe_batch_speedup).
    linesearch_probes: int = 1
    # widened client GEMM (engine/steps.py GroupContext.client_fold,
    # docs/PERF.md §Widened GEMM): 'gemm' (the default) re-batches the
    # probe fan at the params-tree level so frozen layers fold the P
    # alpha axis into their GEMM M dimension (the MXU sees M = K·P·B
    # across the client vmap instead of K·P skinny M=B dots) and the
    # probe-invariant prefix runs once per fan; 'vmap' is the escape
    # hatch that compiles today's exact probe-fan programs
    # byte-for-byte. Same objective values, but the wide reduction may
    # reorder, so like linesearch_probes this is a TRAJECTORY-CHANGING
    # knob and lives in the metrics-stream tag. Inert at
    # linesearch_probes=1 (no fan is ever built — both modes compile
    # the identical sequential-search program).
    client_fold: str = "gemm"

    # ADMM (reference src/consensus_admm_trio.py:23,37-44)
    admm_rho0: float = 1e-3
    bb_update: bool = False
    bb_period: int = 2
    bb_alphacorrmin: float = 0.2
    bb_epsilon: float = 1e-3
    bb_rhomax: float = 0.1

    # elastic-net consensus: soft-threshold the z-update with this value
    # (> 0 enables; the reference ships it commented out but keeps the
    # helper, src/consensus_admm_trio_resnet.py:416-419)
    z_soft_threshold: float = 0.0

    # exchange wire format (exchange/, docs/PERF.md): the codec applied
    # to the UPLINKED partition-group slice of every consensus exchange.
    # 'float32' is the identity codec — bit-transparent, the exact
    # pre-codec program. 'bfloat16' halves the uplink bytes (the comm
    # ledger records the wire bytes exactly); master weights, z, and all
    # L-BFGS math stay f32, and the aggregation — mean, robust
    # combiners, z-score quarantine — operates on the decoded f32 views.
    # TRAJECTORY-CHANGING (one round-to-nearest-even per exchanged
    # value), so it lives in the metrics-stream tag.
    exchange_dtype: str = "float32"

    # --- codec zoo + layer-group scheduling (exchange/, docs/PERF.md) ---
    # lossy compression BEYOND the dense dtype members: 'topk' ships each
    # client's ceil(topk_fraction * group_size) largest-magnitude
    # coordinates as (index, value) pairs; 'quant' ships one f32 scale
    # plus quant_bits bits per value (stochastic rounding with a
    # deterministic per-value dither). None defers to exchange_dtype
    # (identity / bf16). Mutually exclusive with
    # exchange_dtype='bfloat16' — one wire compression at a time. The
    # combiners and quarantine still consume the DECODED f32 views, and
    # the comm ledger records each codec's exact bytes_on_wire.
    # TRAJECTORY-CHANGING (like exchange_dtype): stream-tag member.
    exchange_codec: str | None = None
    # 'topk' keep fraction in (0, 1] (1.0 keeps everything: dense values
    # but still index+value wire pricing)
    topk_fraction: float = 0.1
    # 'quant' wire width: 8 (q8) or 4 (q4) bits per value
    quant_bits: int = 8
    # per-(client, group) error-feedback residual: the sender adds its
    # carried residual before encoding and keeps (x+e) - decode(encode(
    # x+e)) for its next exchange of that group — the standard EF
    # compensation that turns a biased compressor into an unbiased-in-
    # the-limit one. Carried in the fused round's scan carry, persisted
    # across outer loops beside the ADMM rho (checkpointed; rides the
    # ClientStore per virtual client in cohort mode). Requires a LOSSY
    # codec (exchange_codec set, or exchange_dtype='bfloat16').
    error_feedback: bool = False
    # WHICH partition group each round slot exchanges (exchange/
    # schedule.py): 'roundrobin' is the reference's fixed visit order —
    # bit-identical to pre-scheduler builds; 'adaptive' picks the
    # highest-drift unvisited group per slot from the in-scan post-round
    # per-group distance signal (streamed as `group_distance` every
    # round, decisions streamed as `group_schedule` and replayed on
    # resume — resuming an adaptive run REQUIRES a metrics stream, like
    # auto deadlines). Requires a consensus strategy.
    group_schedule: str = "roundrobin"
    # adaptive-only: a TAIL slot whose best remaining group has drifted
    # to <= this fraction of the run's peak observed drift SENDS
    # NOTHING (no round runs — zero bytes, recorded as a skipped
    # group_schedule decision and summed by `report` as
    # bytes_saved_by_skipping). A loop's FIRST slot never skips — every
    # loop trains at least one group, so the drift signal refreshes and
    # an all-quiet state cannot become absorbing (exchange/schedule.py).
    # 0 disables skipping (adaptive ordering only).
    group_skip_frac: float = 0.0

    # HBM budget for the TRAINING data (MiB). None = the whole dataset is
    # put on device up front (fastest; the default — CIFAR is 150 MB).
    # When set and the dataset exceeds it, the trainer STREAMS: data stays
    # host-side, the native PrefetchBatcher (data/native.py) assembles
    # lockstep minibatch chunks per client, and each chunk's device_put
    # double-buffers against the previous chunk's jitted compute — the
    # path for datasets that do not fit HBM.
    hbm_data_budget_mb: int | None = None
    # lockstep minibatches per streamed chunk (one jitted scan per chunk;
    # larger chunks amortize dispatch, smaller ones bound staging memory)
    stream_chunk_steps: int = 8
    # fuse each partition group's FULL averaging round — all nepoch
    # epochs plus the consensus/ADMM exchange, scanned over nadmm — into
    # ONE jitted donated-carry program (engine/steps.py build_round_fn):
    # one dispatch per round instead of nadmm*(nepoch+1), which on a
    # dispatch-latency-bound runtime (~0.1 s floor per program,
    # benchmarks/epoch_attribution.json) is most of the wall time of the
    # full reference schedules. The fused trajectory is BIT-identical to
    # the unfused path (tests/test_fused_round.py). `--no-fuse-rounds`
    # is the escape hatch. The trainer falls back to the unfused path
    # when fusion cannot preserve semantics or dispatch bounds:
    # host-streaming data, eval_every_batch, per-epoch eval cadence
    # (strategy 'none' with check_results), or a round whose total
    # scanned steps nadmm*nepoch*S exceed max_scan_steps (the one-
    # dispatch program would be exactly the long-scan shape that cap
    # exists to avoid).
    fuse_rounds: bool = True
    # fold the `check_results` eval cadence INTO the fused round program:
    # each consensus iteration's full-test-set sweep runs inside the same
    # jitted dispatch, against the same post-consensus state the outside
    # path would snapshot — a fused+folded round is exactly ONE program
    # launch with ZERO standalone eval dispatches and no blocking host
    # sync before the next round enqueues (the eval tail PR 2 left
    # behind: the full fedavg/admm schedules issued 180/300 standalone
    # eval launches against 60 round launches, each ending in a host
    # sync). Correct counts are bit-identical to the standalone eval
    # program's (the per-client body is shared — engine/steps.py
    # _client_eval_fn; tested in tests/test_fold_eval.py).
    # `--no-fold-eval` is the escape hatch; folding stands down wherever
    # round fusion itself does (`Trainer._fused_enabled`), falling back
    # to the async outside-the-program eval path below.
    fold_eval: bool = True
    # defer the device->host harvest of evals that run OUTSIDE the fused
    # program (the unfused/fallback paths and `--no-fold-eval`): the
    # jitted eval sweep is ENQUEUED at its cadence point (dispatch is
    # asynchronous) but the blocking fetch moves to the round boundary,
    # where all of a round's deferred records are harvested in batch —
    # always before the metric stream's `nloop_complete` marker and the
    # checkpoint are written, so crash-safety and the resumed-stream
    # identity contract are unchanged (utils/metrics.py Deferred,
    # obs/sinks.py). False makes every eval's fetch BLOCK at its call
    # site (the pre-async stall pattern, for timing comparisons); the
    # record itself still rides the round-boundary harvest — stream
    # content and order are identical either way, and verbose accuracy
    # prints appear at the harvest in both modes (that shared path is
    # what lets rollback discard a poisoned round's evals in every
    # eval mode).
    async_eval: bool = True
    # cap on lockstep minibatches per RESIDENT jitted epoch call: epochs
    # longer than this run as ceil(S/cap) sequential calls over index
    # slices (bit-identical trajectory — the scan is sequential either
    # way; the remainder slice costs one extra compile). Exists because a
    # single program scanning many hundred ResNet steps can exceed what a
    # TPU runtime will execute in one dispatch (the round-2 tunneled-v5e
    # worker died on the 520-step fedavg_resnet epoch; see
    # benchmarks/scan_bisect_tpu.py for the probe that pins the boundary).
    # None = never chunk.
    max_scan_steps: int | None = 256

    # write a jax.profiler trace of each epoch here (TPU/host timelines)
    profile_dir: str | None = None

    # JAX persistent compilation cache directory (`--compile-cache DIR`):
    # XLA executables are cached on disk, so a warm rerun of the same
    # config pays tracing but not backend compilation — minutes off the
    # full reference schedules' first round. None leaves whatever cache
    # the process already configured (the test conftest sets one
    # globally; utils/hostcpu.py compile_cache_dir is the repo-level
    # location). The cache is keyed by program + compile options, so
    # sharing one directory across configs is safe.
    compile_cache: str | None = None

    # --- observability (obs/, docs/OBSERVABILITY.md) ---
    # crash-safe append-only JSONL metric stream: every record is written
    # as it is logged and committed at checkpoint boundaries; with
    # resume='auto' a crashed run's stream is truncated to the restore
    # point and continued, so the series is identical to an uninterrupted
    # run's (obs/sinks.py JsonlSink). None = in-memory metrics only.
    metrics_stream: str | None = None
    # write a Chrome trace-event JSON of the host-side loop nest here
    # (round/epoch/consensus/eval/compile spans — open in
    # https://ui.perfetto.dev); complements profile_dir's device
    # timelines (obs/trace.py TraceRecorder)
    trace_out: str | None = None
    # record the `group_distance` diagnostic series every N partition
    # rounds (parallel/diagnostics.py group_distances — the reference's
    # never-called distance_of_layers, given a cadence). None = off; the
    # diagnostic is one extra tiny jitted dispatch per sampled round.
    diagnostics_every: int | None = None
    # in-run health engine (obs/health.py HealthEngine): streaming
    # P²-style percentile sketches over train loss / update norms /
    # client-time tails plus a windowed anomaly monitor, emitting one
    # `health` record per partition round and `health:*` trace instants.
    # Pure host bookkeeping over values the trainer already fetched —
    # ZERO extra device dispatches (the folded round stays
    # {round: 1, round_init: 1}) — and replay-identical across
    # crash+resume. ANALYSIS-ONLY knobs: never trajectory-changing, so
    # both are excluded from the metrics-stream header tag (a resumed
    # run may flip them and still splice — Trainer._stream_tag).
    health_monitor: bool = True
    # completed partition rounds in the monitor's anomaly window (rates,
    # loss explosion/plateau + quarantine-burst/deadline-miss-spike
    # detection)
    health_window: int = 8
    # flight recorder (obs/flight.py): a bounded ring over exactly the
    # records the JSONL sink persists, dumped as a self-contained
    # `incident-<nloop>-<round>.json` bundle (beside the stream, in
    # `<stream>.incidents/`) whenever the health engine fires an anomaly
    # or the process dies mid-run. Rides `--metrics-stream` (the ring
    # mirrors the sink feed — no stream, nothing to mirror); incidents
    # are process facts (the `incident` series is stream=False), so
    # crash+resume twin streams stay byte-identical. ANALYSIS-ONLY knobs
    # like the health pair: excluded from the stream tag.
    flight_recorder: bool = True
    # completed partition rounds the ring retains (= the rounds an
    # incident bundle holds)
    flight_window: int = 8
    # per-round memory telemetry (obs/memory.py): host RSS + per-device
    # allocator stats as the `memory` series — process facts, recorded
    # stream=False (a resumed run's RSS has nothing to do with the
    # crashed one's), surfaced live through the `<stream>.status.json`
    # sidecar the `watch` console reads. Zero device dispatches.
    memory_telemetry: bool = True
    # anomaly-triggered device profiling: the round AFTER a health alert
    # runs under a jax.profiler trace window written beneath this
    # directory (`round-<nloop>-<group>/`) — profiling that costs
    # nothing until something is wrong. Bounded by `profile_budget`
    # captures per process. Mutually exclusive with `profile_dir` (the
    # whole-run trace — jax.profiler windows cannot nest). None = off.
    profile_on_anomaly: str | None = None
    # per-process cap on anomaly-triggered profiler captures
    profile_budget: int = 3

    # failure detection (SURVEY.md §5 — absent in the reference): check
    # per-client losses each epoch and per-client parameter finiteness
    # each consensus round. 'warn' records a `fault` metric and continues
    # (the optimizer's NaN guards already freeze a poisoned client);
    # 'raise' aborts the run; 'rollback' restores the pre-round snapshot
    # of a partition round whose losses/params went NaN/Inf and moves on
    # (docs/FAULT.md — the round is sacrificed, the run survives);
    # 'off' skips the checks.
    fault_mode: str = "warn"

    # failure INJECTION (fault/plan.py): a path to a FaultPlan JSON file
    # or an inline spec like "seed=1,dropout=0.3,crash=0:1:2,
    # corrupt=1:scale:10". Dropped clients are excluded from consensus
    # via the participation mask, stragglers stall the round host-side,
    # crash points raise InjectedCrash at the named round boundary
    # (recover with resume='auto'), and corruption faults garble chosen
    # clients' updates in transit before the exchange. None = no chaos;
    # every fault is a pure function of (plan seed, round cursor), so
    # chaos runs replay exactly.
    fault_plan: str | None = None

    # Byzantine-robust aggregation (consensus/robust.py, docs/FAULT.md):
    # how the consensus exchange combines the surviving clients' updates.
    # 'mean' is the reference's participation-masked average (untouched
    # code path — bit-identical to pre-robust runs); 'median'/'trimmed'/
    # 'clip' are order-statistic combiners that tolerate up to
    # `robust_f` corrupted updates per round instead of averaging them
    # into the consensus variable (or tripping the rollback machinery).
    robust_agg: str = "mean"
    # clients trimmed per SIDE by the 'trimmed' combiner (tolerates f
    # Byzantine clients per round; needs n_clients > 2f). Ignored by the
    # other combiners.
    robust_f: int = 1
    # auto-quarantine threshold: flag a client whose update norm's
    # cross-client z-score exceeds this (or whose update is non-finite)
    # and exclude it from the REST OF THE ROUND's exchanges — the suspect
    # mask ANDs into the participation mask, round-scoped. None = off.
    # Small-cohort note: with K alive clients a single outlier's
    # population-std z-score cannot exceed sqrt(K-1) (~1.41 at K=3), so
    # thresholds near 1.0 are the operating range for trio-sized runs;
    # 0 is the hair trigger.
    quarantine_z: float | None = None

    # deadline-based rounds (docs/FAULT.md §Heterogeneity): the SIMULATED
    # seconds each consensus round's local work may take. With a fault
    # plan's compute-speed axis (`slow=<k-or-p>[:factor]`,
    # `step_time=<s>`), every client gets the inner-step budget it can
    # afford before the deadline — ragged local work via per-client step
    # masks inside the round program (a masked step is an identity carry
    # update) — and clients that miss the deadline contribute their
    # PARTIAL update through the participation machinery instead of
    # stalling the cohort (a zero-budget client has no report and is
    # excluded like a dropped one). Host-side straggler stalls are
    # capped at the deadline. None = lockstep rounds (the slowest client
    # sets the round's simulated wall clock). Requires a consensus
    # strategy; uniform budgets (a deadline no client misses) reproduce
    # the lockstep trajectory bitwise (tests/test_hetero.py).
    # CLOSED LOOP: 'auto' (= 'auto:p50') or 'auto:pXX' makes each
    # round's deadline track the online client_time percentile sketch
    # (obs/health.py DeadlineController): the pXX of the observed
    # per-exchange cross-client p95 simulated times, falling back to
    # the nominal full-work time until the sketch has
    # DEADLINE_WARMUP_OBS observations. Decisions are pure in the
    # recorded history (streamed as the `deadline` series) and
    # replay-identical across crash+resume — resuming an auto run
    # REQUIRES a metrics stream to replay them from (docs/FAULT.md
    # §Heterogeneity).
    round_deadline: float | str | None = None

    # 'auto': restore the latest READABLE checkpoint under checkpoint_dir
    # if one exists, else start fresh — the crash-recovery switch a chaos
    # run restarts with (load_model instead *requires* a checkpoint).
    # 'off': only load_model controls restoring.
    resume: str = "off"

    # flags (reference src/federated_trio.py:28-31)
    init_model: bool = True  # common-seed init across clients
    load_model: bool = False
    save_model: bool = False
    check_results: bool = True  # eval after each averaging round
    # with `check_results`, ALSO evaluate after every minibatch — the
    # reference's exact telemetry cadence for check_results=True
    # (reference src/no_consensus_trio.py:266-267, every `opt.step`).
    # The epoch then runs one jitted minibatch at a time so the jitted
    # eval sweep can interleave; per-epoch cadence stays the default
    # because it keeps the whole epoch one device computation.
    eval_every_batch: bool = False
    average_model: bool = False  # one-shot whole-model mean at start
    #   (reference src/no_consensus_trio.py:22,134-160)

    # resnet drivers shuffle the block visit order once with np.seed(0)
    # (reference src/federated_trio_resnet.py:296-297)
    shuffle_group_order: bool = False

    seed: int = 0
    eval_batch: int = 500
    checkpoint_dir: str = "./checkpoints"
    max_devices: int | None = None
    # train only the FIRST N groups of the (possibly shuffled) partition
    # order — the reduced-schedule knob every smoke run, benchmark, and
    # parity config wants (each outer loop still visits those N groups
    # with the full consensus/eval machinery). None = all groups.
    max_groups: int | None = None

    def __post_init__(self):
        # cohort-mode normalization FIRST: later checks (trimmed-mean
        # sizing, mesh divisibility at Trainer init) must see the
        # DERIVED n_clients — in cohort mode the compiled programs'
        # client axis is the cohort, so n_clients is forced to it here
        # (the one place the rule lives).
        if self.virtual_clients is not None:
            if self.virtual_clients < 1:
                raise ValueError(
                    f"virtual_clients must be >= 1, got {self.virtual_clients}"
                )
            if self.cohort is None:
                raise ValueError(
                    "virtual_clients requires a cohort size (--cohort C: "
                    "how many virtual clients train per outer loop)"
                )
            if not 1 <= self.cohort <= self.virtual_clients:
                raise ValueError(
                    f"cohort must be in [1, virtual_clients="
                    f"{self.virtual_clients}], got {self.cohort}"
                )
            if self.cohort_weighting not in (
                "uniform", "samples", "identity", "telemetry"
            ):
                raise ValueError(
                    "cohort_weighting must be 'uniform', 'samples', "
                    f"'identity' or 'telemetry', got "
                    f"{self.cohort_weighting!r}"
                )
            if (
                self.cohort_weighting == "identity"
                and self.cohort != self.virtual_clients
            ):
                raise ValueError(
                    "cohort_weighting='identity' is full participation: "
                    f"cohort ({self.cohort}) must equal virtual_clients "
                    f"({self.virtual_clients})"
                )
            if self.data_shards is not None and not (
                1 <= self.data_shards <= self.virtual_clients
            ):
                raise ValueError(
                    f"data_shards must be in [1, virtual_clients="
                    f"{self.virtual_clients}], got {self.data_shards}"
                )
            if not self.init_model:
                raise ValueError(
                    "virtual clients require init_model=True: the store's "
                    "pristine rows broadcast ONE common-seed init "
                    "(clients/store.py), and per-client draws for N "
                    "virtual clients would cost N model inits up front"
                )
            if self.hbm_data_budget_mb is not None:
                raise ValueError(
                    "cohort mode and host-streaming data are mutually "
                    "exclusive: the streaming batchers hold per-client "
                    "positions for a FIXED client set, but a cohort's "
                    "membership changes every loop (the cohort data "
                    "gather already keeps only C shards device-resident)"
                )
            if self.store_resident_chunks is not None:
                if not isinstance(
                    self.store_resident_chunks, int
                ) or isinstance(self.store_resident_chunks, bool):
                    raise ValueError(
                        f"store_resident_chunks must be an int >= 1, got "
                        f"{self.store_resident_chunks!r}"
                    )
                if self.store_resident_chunks < 1:
                    raise ValueError(
                        f"store_resident_chunks must be >= 1, got "
                        f"{self.store_resident_chunks}"
                    )
            object.__setattr__(self, "n_clients", int(self.cohort))
        else:
            # every cohort knob set away from its default without
            # virtual_clients is a config mistake, not a no-op: a user
            # who asked for weighted sampling must not silently get the
            # legacy full-participation engine
            if self.cohort is not None or self.data_shards is not None:
                bad = "cohort" if self.cohort is not None else "data_shards"
                raise ValueError(
                    f"{bad} requires virtual_clients (cohort sampling "
                    "only exists over a virtual-client population)"
                )
            chunk_default = type(self).__dataclass_fields__[
                "store_chunk_clients"
            ].default
            if (
                self.cohort_weighting != "uniform"
                or self.cohort_seed != 0
                or self.store_chunk_clients != chunk_default
                or self.store_resident_chunks is not None
                or not self.prefetch
            ):
                raise ValueError(
                    "cohort_weighting/cohort_seed/store_chunk_clients/"
                    "store_resident_chunks/prefetch require "
                    "virtual_clients (cohort sampling only exists over a "
                    "virtual-client population)"
                )
        if self.store_chunk_clients < 1:
            raise ValueError(
                f"store_chunk_clients must be >= 1, "
                f"got {self.store_chunk_clients}"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16', "
                f"got {self.compute_dtype!r}"
            )
        if not isinstance(self.linesearch_probes, int) or isinstance(
            self.linesearch_probes, bool
        ):
            raise ValueError(
                f"linesearch_probes must be an int >= 1, "
                f"got {self.linesearch_probes!r}"
            )
        if self.linesearch_probes < 1:
            raise ValueError(
                f"linesearch_probes must be >= 1, got {self.linesearch_probes}"
            )
        if self.client_fold not in ("gemm", "vmap"):
            raise ValueError(
                f"client_fold must be 'gemm' or 'vmap', "
                f"got {self.client_fold!r}"
            )
        if self.exchange_dtype not in EXCHANGE_DTYPES:
            raise ValueError(
                f"exchange_dtype must be one of {list(EXCHANGE_DTYPES)}, "
                f"got {self.exchange_dtype!r}"
            )
        if self.exchange_codec is not None:
            if self.exchange_codec not in EXCHANGE_CODECS:
                raise ValueError(
                    f"exchange_codec must be one of {list(EXCHANGE_CODECS)} "
                    f"(or unset for the --exchange-dtype member), got "
                    f"{self.exchange_codec!r}"
                )
            if self.exchange_dtype != "float32":
                raise ValueError(
                    "exchange_codec and exchange_dtype='bfloat16' are "
                    "mutually exclusive: one wire compression at a time "
                    f"(got exchange_codec={self.exchange_codec!r} with "
                    f"exchange_dtype={self.exchange_dtype!r})"
                )
            # the zoo members OWN their parameter validation
            # (exchange/codec.py __post_init__ raises naming the field);
            # constructing the configured member here surfaces it at
            # config time instead of at the first program build — one
            # range definition, not a drifting copy
            make_codec(
                "float32", self.exchange_codec,
                self.topk_fraction, self.quant_bits,
            )
        # a zoo knob set away from its default without its member active
        # is a config mistake, not a no-op (the cohort-knob rule above):
        # the user asked for a compression parameter the wire ignores
        if self.topk_fraction != 0.1 and self.exchange_codec != "topk":
            raise ValueError(
                "topk_fraction requires exchange_codec='topk' "
                f"(got topk_fraction={self.topk_fraction!r} with "
                f"exchange_codec={self.exchange_codec!r})"
            )
        if self.quant_bits != 8 and self.exchange_codec != "quant":
            raise ValueError(
                "quant_bits requires exchange_codec='quant' "
                f"(got quant_bits={self.quant_bits!r} with "
                f"exchange_codec={self.exchange_codec!r})"
            )
        if self.error_feedback and self.exchange_codec is None and (
            self.exchange_dtype == "float32"
        ):
            raise ValueError(
                "error_feedback requires a LOSSY codec (exchange_codec "
                "'topk'/'quant', or exchange_dtype 'bfloat16'): the "
                "identity wire has no compression error to feed back"
            )
        if self.group_schedule not in GROUP_SCHEDULES:
            raise ValueError(
                f"group_schedule must be one of {list(GROUP_SCHEDULES)}, "
                f"got {self.group_schedule!r}"
            )
        if self.group_schedule == "adaptive" and self.strategy == "none":
            raise ValueError(
                "group_schedule='adaptive' requires a consensus strategy: "
                "independent training has no exchange to schedule"
            )
        # the scheduler owns its range definition (the make_codec
        # delegation pattern above — exchange/schedule.py)
        validate_group_skip_frac(self.group_skip_frac)
        if self.group_skip_frac > 0 and self.group_schedule != "adaptive":
            raise ValueError(
                "group_skip_frac requires group_schedule='adaptive' "
                "(roundrobin never skips a slot)"
            )
        if self.fault_mode not in ("warn", "raise", "rollback", "off"):
            raise ValueError(
                f"fault_mode must be 'warn', 'raise', 'rollback' or 'off', "
                f"got {self.fault_mode!r}"
            )
        if self.resume not in ("off", "auto"):
            raise ValueError(
                f"resume must be 'off' or 'auto', got {self.resume!r}"
            )
        if self.strategy not in ("none", "fedavg", "admm"):
            raise ValueError(
                f"strategy must be 'none', 'fedavg' or 'admm', "
                f"got {self.strategy!r}"
            )
        if self.reg_mode not in ("active_linear", "first_linear", "none"):
            raise ValueError(
                f"reg_mode must be 'active_linear', 'first_linear' or "
                f"'none', got {self.reg_mode!r}"
            )
        if self.max_groups is not None and self.max_groups < 1:
            raise ValueError(f"max_groups must be >= 1, got {self.max_groups}")
        if self.max_scan_steps is not None and self.max_scan_steps < 1:
            raise ValueError(
                f"max_scan_steps must be >= 1, got {self.max_scan_steps}"
            )
        if self.diagnostics_every is not None and self.diagnostics_every < 1:
            raise ValueError(
                f"diagnostics_every must be >= 1, got {self.diagnostics_every}"
            )
        if self.health_window < 1:
            raise ValueError(
                f"health_window must be >= 1, got {self.health_window}"
            )
        # strict int checks in the linesearch_probes style: a bool quacks
        # as an int and must be rejected naming the field
        if not isinstance(self.flight_window, int) or isinstance(
            self.flight_window, bool
        ):
            raise ValueError(
                f"flight_window must be an int >= 1, "
                f"got {self.flight_window!r}"
            )
        if self.flight_window < 1:
            raise ValueError(
                f"flight_window must be >= 1, got {self.flight_window}"
            )
        if not isinstance(self.profile_budget, int) or isinstance(
            self.profile_budget, bool
        ):
            raise ValueError(
                f"profile_budget must be an int >= 1, "
                f"got {self.profile_budget!r}"
            )
        if self.profile_budget < 1:
            raise ValueError(
                f"profile_budget must be >= 1, got {self.profile_budget}"
            )
        if self.profile_on_anomaly is not None and self.profile_dir is not None:
            raise ValueError(
                "profile_on_anomaly and profile_dir are mutually "
                "exclusive: the whole-run jax.profiler trace cannot nest "
                "an anomaly-triggered capture window inside itself"
            )
        if self.profile_on_anomaly is not None and not self.health_monitor:
            raise ValueError(
                "profile_on_anomaly requires the health monitor: captures "
                "are armed by health anomalies, so with "
                "health_monitor=False the knob could never fire (a config "
                "mistake, not a no-op)"
            )
        # a budget without the trigger directory is a config mistake,
        # not a no-op (the cohort-knob rule above)
        budget_default = type(self).__dataclass_fields__[
            "profile_budget"
        ].default
        if (
            self.profile_budget != budget_default
            and self.profile_on_anomaly is None
        ):
            raise ValueError(
                "profile_budget requires profile_on_anomaly (the budget "
                "bounds anomaly-triggered profiler captures), got "
                f"profile_budget={self.profile_budget!r} with "
                "profile_on_anomaly=None"
            )
        if self.robust_agg not in ROBUST_METHODS:
            raise ValueError(
                f"robust_agg must be one of {list(ROBUST_METHODS)}, "
                f"got {self.robust_agg!r}"
            )
        if self.robust_f < 0:
            raise ValueError(f"robust_f must be >= 0, got {self.robust_f}")
        if (
            self.robust_agg == "trimmed"
            and self.n_clients <= 2 * self.robust_f
        ):
            raise ValueError(
                f"trimmed-mean with robust_f={self.robust_f} trims "
                f"{2 * self.robust_f} of n_clients={self.n_clients} "
                "updates per round — nothing would remain to average "
                "(need n_clients > 2*robust_f)"
            )
        if self.quarantine_z is not None and self.quarantine_z < 0:
            raise ValueError(
                f"quarantine_z must be >= 0, got {self.quarantine_z}"
            )
        if self.round_deadline is not None:
            rd = self.round_deadline
            if isinstance(rd, str):
                # the CLI hands every value through as a string; numeric
                # ones normalize to the float they always were, 'auto'
                # canonicalizes to 'auto:p50' so equal policies hash —
                # and stream-tag — equally
                s = rd.strip()
                try:
                    rd = float(s)
                except ValueError:
                    m = re.fullmatch(r"auto(?::p([1-9][0-9]?))?", s)
                    if m is None:
                        raise ValueError(
                            "round_deadline must be a positive number of "
                            "simulated seconds, 'auto', or 'auto:pXX' "
                            f"(XX an integer percentile in [1, 99]), "
                            f"got {self.round_deadline!r}"
                        )
                    rd = f"auto:p{m.group(1) or 50}"
            if not isinstance(rd, str):
                # anything that is not the auto policy must BE a
                # positive finite number — coerced, so numpy scalars
                # validate (and normalize) like the floats they quack as
                # instead of bypassing the check on an isinstance test
                if isinstance(rd, bool):
                    raise ValueError(
                        f"round_deadline must be > 0, got {rd!r}"
                    )
                try:
                    rd = float(rd)
                except (TypeError, ValueError):
                    raise ValueError(
                        "round_deadline must be a positive number of "
                        "simulated seconds, 'auto', or 'auto:pXX', "
                        f"got {self.round_deadline!r}"
                    )
                if not (math.isfinite(rd) and rd > 0):
                    raise ValueError(
                        f"round_deadline must be > 0, got {rd}"
                    )
            object.__setattr__(self, "round_deadline", rd)

    @property
    def deadline_is_auto(self) -> bool:
        """Whether `round_deadline` is the closed-loop 'auto:pXX' policy
        (already canonicalized by `__post_init__`)."""
        return isinstance(self.round_deadline, str)

    @property
    def deadline_quantile(self) -> float:
        """The auto policy's sketch quantile in (0, 1) — e.g. 0.5 for
        'auto:p50'. Only meaningful when `deadline_is_auto`."""
        assert self.deadline_is_auto, self.round_deadline
        return int(self.round_deadline.split(":p")[1]) / 100.0

    def lbfgs_config(self) -> LBFGSConfig:
        return LBFGSConfig(
            lr=self.lbfgs_lr,
            max_iter=self.lbfgs_max_iter,
            history_size=self.lbfgs_history,
            line_search=True,
            batch_mode=True,
            direction=self.lbfgs_direction,
            ls_probes=self.linesearch_probes,
        )

    def admm_config(self) -> ADMMConfig:
        return ADMMConfig(
            rho0=self.admm_rho0,
            bb_update=self.bb_update,
            bb_period=self.bb_period,
            bb_alphacorrmin=self.bb_alphacorrmin,
            bb_epsilon=self.bb_epsilon,
            bb_rhomax=self.bb_rhomax,
            z_soft_threshold=self.z_soft_threshold,
        )

    def replace(self, **kw) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)

    def __hash__(self):
        # frozen dataclasses generate __hash__ from raw field values, and
        # the dict-valued model_kwargs would make that raise TypeError the
        # first time a config is used as a dict key / set member / jit
        # static argument. Canonicalize containers recursively (sorted by
        # repr so mixed-type dict keys stay orderable) so configs remain
        # hashable whatever model_kwargs holds; an explicit __hash__
        # suppresses the generated one (dataclass hash_action table:
        # has_explicit_hash).
        def canon(v):
            if isinstance(v, dict):
                return tuple(
                    sorted(
                        ((canon(k), canon(x)) for k, x in v.items()),
                        key=repr,
                    )
                )
            if isinstance(v, (list, tuple, set, frozenset)):
                items = tuple(canon(x) for x in v)
                return tuple(sorted(items, key=repr)) if isinstance(
                    v, (set, frozenset)
                ) else items
            return v

        return hash(
            tuple(canon(getattr(self, f.name)) for f in dataclasses.fields(self))
        )


# The five reference driver scripts as presets. Loop sizes, batch sizes,
# rho, and flags are each script's module constants (citations per field
# above; per-preset deltas cited inline).
PRESETS = {
    # reference src/no_consensus_trio.py: Net1, batch 32, 12 epochs of
    # independent training, fc-only elastic net, eval per round.
    "no_consensus": ExperimentConfig(
        name="no_consensus",
        model="net1",
        batch=32,
        strategy="none",
        nloop=1,
        nepoch=12,
        nadmm=1,
        reg_mode="first_linear",
        init_model=False,  # reference src/no_consensus_trio.py:19
    ),
    # reference src/federated_trio.py: Net, batch 512, Nloop=12, Nadmm=3.
    "fedavg": ExperimentConfig(name="fedavg", model="net", strategy="fedavg"),
    # reference src/federated_trio_resnet.py: ResNet18, batch 32, Nadmm=3,
    # no regularization, shuffled block order, and a SINGLE unbiased
    # normalization for all clients (one transform, :27-29 — the resnet
    # drivers have no biased_input machinery).
    "fedavg_resnet": ExperimentConfig(
        name="fedavg_resnet",
        model="resnet18",
        batch=32,
        strategy="fedavg",
        reg_mode="none",
        biased_input=False,
        shuffle_group_order=True,
    ),
    # reference src/consensus_admm_trio.py: Net, batch 512, Nadmm=5,
    # rho0=1e-3 with BB adaptation on.
    "admm": ExperimentConfig(
        name="admm",
        model="net",
        strategy="admm",
        nadmm=5,
        bb_update=True,
    ),
    # reference src/consensus_admm_trio_resnet.py: ResNet18, batch 32,
    # Nadmm=3, fixed scalar rho=0.001 (:333), no BB, shuffled block order.
    "admm_resnet": ExperimentConfig(
        name="admm_resnet",
        model="resnet18",
        batch=32,
        strategy="admm",
        nadmm=3,
        reg_mode="none",
        biased_input=False,
        bb_update=False,
        shuffle_group_order=True,
    ),
    # BASELINE.json config #5 (scale-out, no reference script): K=64
    # ResNet18 clients on CIFAR100, one client per core on a v4-64 —
    # the mesh maps clients to devices 1:1 when 64 devices are present,
    # or folds K into local blocks on smaller meshes (parallel/mesh.py).
    "fedavg_scale64": ExperimentConfig(
        name="fedavg_scale64",
        model="resnet18",
        dataset="cifar100",
        n_clients=64,
        batch=32,
        strategy="fedavg",
        reg_mode="none",
        biased_input=False,
        shuffle_group_order=True,
        check_results=False,
    ),
    "admm_scale64": ExperimentConfig(
        name="admm_scale64",
        model="resnet18",
        dataset="cifar100",
        n_clients=64,
        batch=32,
        strategy="admm",
        nadmm=3,
        reg_mode="none",
        biased_input=False,
        bb_update=False,
        shuffle_group_order=True,
        check_results=False,
    ),
}


def get_preset(name: str, **overrides) -> ExperimentConfig:
    """Fetch a preset by name, optionally overriding fields."""
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return cfg.replace(**overrides) if overrides else cfg


# --------------------------------------------------------- knob domains
#
# THE machine-readable knob-domain table (ISSUE 20): one entry per
# trajectory-relevant ExperimentConfig knob, declaring its valid domain
# AND one representative out-of-domain value. Two consumers:
#
# * the chaos generator (fault/chaos.py ChaosPlanGenerator) draws lattice
#   values from `choices`/`lo`/`hi`, so a knob's searched range cannot
#   drift from what `__post_init__` accepts — generator/validator
#   agreement is a table lookup, not two hand-maintained copies;
# * the meta-test (tests/test_chaos.py) walks the table injecting each
#   entry's `bad` value into a valid carrier config (`requires` supplies
#   the context that makes the knob live, so the injected value's OWN
#   validation is what fires) and asserts the raised ValueError names
#   the field — the repo's every-error-names-its-field house rule,
#   machine-enforced instead of enforced by convention.
#
# Entry keys: `kind` ('choice' | 'int' | 'float' | 'flag' | 'str'),
# `choices` (for 'choice'), `lo`/`hi` (inclusive numeric bounds the
# generator draws within; None = unbounded on that side), `requires`
# (field overrides forming the valid carrier context), `bad` (a value
# whose injection into that context must raise naming the field).
KNOB_DOMAINS: dict = {
    "strategy": {
        "kind": "choice", "choices": ["none", "fedavg", "admm"],
        "requires": {}, "bad": "gossip",
    },
    "compute_dtype": {
        "kind": "choice", "choices": ["float32", "bfloat16"],
        "requires": {}, "bad": "float16",
    },
    "reg_mode": {
        "kind": "choice",
        "choices": ["active_linear", "first_linear", "none"],
        "requires": {}, "bad": "l1",
    },
    "robust_agg": {
        "kind": "choice", "choices": list(ROBUST_METHODS),
        "requires": {}, "bad": "krum",
    },
    "robust_f": {
        # trimmed additionally needs n_clients > 2*robust_f — the
        # generator sizes f against its drawn client axis
        "kind": "int", "lo": 0, "hi": None,
        "requires": {}, "bad": -1,
    },
    "quarantine_z": {
        "kind": "float", "lo": 0.0, "hi": None,
        "requires": {}, "bad": -0.5,
    },
    "exchange_dtype": {
        "kind": "choice", "choices": list(EXCHANGE_DTYPES),
        "requires": {}, "bad": "float16",
    },
    "exchange_codec": {
        "kind": "choice", "choices": [None] + list(EXCHANGE_CODECS),
        "requires": {}, "bad": "gzip",
    },
    "topk_fraction": {
        "kind": "float", "lo": 0.05, "hi": 1.0,
        "requires": {"exchange_codec": "topk"}, "bad": 1.5,
    },
    "quant_bits": {
        "kind": "choice", "choices": [4, 8],
        "requires": {"exchange_codec": "quant"}, "bad": 5,
    },
    "error_feedback": {
        # valid only beside a LOSSY codec; `bad` injects it on the
        # identity wire, whose error must name the knob
        "kind": "flag", "requires": {}, "bad": True,
    },
    "group_schedule": {
        "kind": "choice", "choices": list(GROUP_SCHEDULES),
        "requires": {}, "bad": "random",
    },
    "group_skip_frac": {
        "kind": "float", "lo": 0.0, "hi": 0.99,
        "requires": {"group_schedule": "adaptive"}, "bad": 1.5,
    },
    "round_deadline": {
        # float seconds or the 'auto[:pXX]' policy; the generator draws
        # from `choices` when set (a continuous deadline is derived from
        # the plan's step_time axis, not from this table)
        "kind": "choice", "choices": [None, "auto", "auto:p75"],
        "requires": {}, "bad": "auto:p0",
    },
    "virtual_clients": {
        "kind": "int", "lo": 1, "hi": None,
        "requires": {"cohort": None}, "bad": 0,
    },
    "cohort": {
        "kind": "int", "lo": 1, "hi": None,
        "requires": {"virtual_clients": 6}, "bad": 9,
    },
    "cohort_seed": {
        # any int is in-domain; the invalid use is setting it WITHOUT a
        # virtual population, and that error must still name the knob
        "kind": "int", "lo": 0, "hi": None,
        "requires": {}, "bad": 1,
    },
    "cohort_weighting": {
        "kind": "choice",
        "choices": ["uniform", "samples", "identity", "telemetry"],
        "requires": {"virtual_clients": 6, "cohort": 3}, "bad": "speed",
    },
    "data_shards": {
        "kind": "int", "lo": 1, "hi": None,
        "requires": {"virtual_clients": 6, "cohort": 3}, "bad": 9,
    },
    "store_chunk_clients": {
        "kind": "int", "lo": 1, "hi": None,
        "requires": {"virtual_clients": 6, "cohort": 3}, "bad": 0,
    },
    "store_resident_chunks": {
        "kind": "int", "lo": 1, "hi": None,
        "requires": {"virtual_clients": 6, "cohort": 3}, "bad": 0,
    },
    "prefetch": {
        # in-domain over a virtual population; `bad` disables it in
        # legacy mode, whose error must name the knob
        "kind": "flag", "requires": {}, "bad": False,
    },
    "client_fold": {
        "kind": "choice", "choices": ["gemm", "vmap"],
        "requires": {}, "bad": "loop",
    },
    "linesearch_probes": {
        "kind": "int", "lo": 1, "hi": 4,
        "requires": {}, "bad": 0,
    },
    "fault_mode": {
        "kind": "choice", "choices": ["warn", "raise", "rollback", "off"],
        "requires": {}, "bad": "panic",
    },
    "resume": {
        "kind": "choice", "choices": ["off", "auto"],
        "requires": {}, "bad": "always",
    },
    "health_window": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
    "flight_window": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
    "profile_budget": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
    "max_groups": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
    "max_scan_steps": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
    "diagnostics_every": {
        "kind": "int", "lo": 1, "hi": None, "requires": {}, "bad": 0,
    },
}
