"""Data pipelines: CIFAR sources, K-way disjoint shards, biased normalization.

Capability parity with the reference's per-driver data setup (reference
src/no_consensus_trio.py:27-82, duplicated in every driver): CIFAR10 split
into K disjoint contiguous shards, optional per-client "biased"
normalization, shuffled per-epoch batches consumed in lockstep across
clients.

TPU-first design: the host pipeline hands out stacked `[K, batch, ...]`
uint8 arrays laid out for the client mesh axis; the `/255` + per-client
mean/std normalization is a jittable function applied on device (uint8
crosses PCIe, float32 never does). The reference instead bakes
normalization into torchvision transforms on the host
(reference src/no_consensus_trio.py:34-50).
"""

from federated_pytorch_test_tpu.data.cifar import (
    DataSource,
    load_cifar,
    load_cifar10,
    load_cifar100,
    synthetic_cifar,
)
from federated_pytorch_test_tpu.data.native import (
    PrefetchBatcher,
    chw_to_hwc,
    decode_records,
)
from federated_pytorch_test_tpu.data.pipeline import (
    BIASED_STATS,
    FederatedDataset,
    client_splits,
    client_stats,
    make_federated,
    normalize,
    virtual_shard_assignment,
)

__all__ = [
    "BIASED_STATS",
    "DataSource",
    "FederatedDataset",
    "PrefetchBatcher",
    "chw_to_hwc",
    "client_splits",
    "client_stats",
    "decode_records",
    "load_cifar",
    "load_cifar10",
    "load_cifar100",
    "make_federated",
    "normalize",
    "synthetic_cifar",
    "virtual_shard_assignment",
]
