"""K-client sharding, biased normalization constants, lockstep batching.

Reference behavior being matched (src/no_consensus_trio.py:27-82):

* the train set is split into K disjoint *contiguous* index ranges
  (`subset1=range(0,16666)`, ... :28-30) — `client_splits` reproduces the
  same floor-split boundaries for any (n, K);
* with `biased_input`, clients normalize with different (mean, std):
  (.5,.5), (.3,.4), (.6,.5) (:34-45) — extended to K>3 by cycling;
* each client draws shuffled batches from its own shard
  (`SubsetRandomSampler`, :59-61) and the drivers consume one batch per
  client per global step via `zip(trainloader1, ...)`
  (reference src/federated_trio.py:285) — here a single iterator yields the
  already-stacked `[K, B, ...]` arrays that land sharded on the client mesh
  axis;
* every client evaluates on the full test set under its own normalization
  (:65-75).

Deliberate deviation (documented per SURVEY.md §2.2 guidance): batches have
static shapes for XLA, so each epoch yields `min_k(n_k) // B` full batches
and drops the ragged tail; torch's DataLoader default would emit one final
partial batch. At CIFAR scale this drops <0.4% of samples per epoch.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Tuple

import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.data.cifar import DataSource

# Per-client (mean, std), cycled for K>3. Reference
# src/no_consensus_trio.py:34-45 (channels share one value).
BIASED_STATS = ((0.5, 0.5), (0.3, 0.4), (0.6, 0.5))
UNBIASED_STAT = (0.5, 0.5)


def client_splits(n: int, k: int) -> Tuple[Tuple[int, int], ...]:
    """K disjoint contiguous [start, end) ranges covering [0, n).

    Matches the reference's hand-written thirds for (50000, 3):
    (0,16666), (16666,33333), (33333,50000).
    """
    bounds = [n * i // k for i in range(k + 1)]
    return tuple((bounds[i], bounds[i + 1]) for i in range(k))


def virtual_shard_assignment(
    n_train: int, n_virtual: int, n_shards: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Virtual-client → data-shard mapping for cohort mode (docs/SCALE.md).

    `(shard_ids [N] int64, sample_counts [N] int64)`: virtual client v
    holds shard `v mod n_shards`, and its sample count is the TRUE
    `client_splits` range length of that shard (before
    `make_federated`'s rectangular truncation) — the honest
    weighted-cohort-sampling weight. THE one definition of the
    assignment: the client store records it and the trainer gathers
    cohort data through it; a drifted copy would pair a client's
    sampler weight with another client's data.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    shard_ids = np.arange(n_virtual, dtype=np.int64) % n_shards
    split_sizes = np.asarray(
        [e - s for s, e in client_splits(n_train, n_shards)], np.int64
    )
    return shard_ids, split_sizes[shard_ids]


def client_stats(k: int, biased: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Per-client normalization constants, shaped [K] (scalar per client)."""
    if biased:
        stats = [BIASED_STATS[i % len(BIASED_STATS)] for i in range(k)]
    else:
        stats = [UNBIASED_STAT] * k
    means = np.asarray([m for m, _ in stats], np.float32)
    stds = np.asarray([s for _, s in stats], np.float32)
    return means, stds


def normalize(images_u8: jnp.ndarray, mean: jnp.ndarray, std: jnp.ndarray) -> jnp.ndarray:
    """Jittable on-device `(x/255 - mean)/std`.

    `images_u8` is `[..., H, W, C]` uint8. `mean`/`std` may be scalars or
    arrays whose axes align with the LEADING axes of `images_u8` — e.g. the
    `[K]` per-client stats against a `[K, B, H, W, C]` stacked batch; they
    are reshaped to `[K, 1, 1, 1, 1]` here so they can never silently
    broadcast against the trailing channel axis (K == C == 3 in the
    flagship trio configuration). Equivalent of torchvision
    `ToTensor()+Normalize(...)` (reference src/no_consensus_trio.py:34-45)
    moved into the XLA program.
    """
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    if mean.ndim:
        mean = mean.reshape(mean.shape + (1,) * (images_u8.ndim - mean.ndim))
    if std.ndim:
        std = std.reshape(std.shape + (1,) * (images_u8.ndim - std.ndim))
    x = images_u8.astype(jnp.float32) / 255.0
    return (x - mean) / std


@dataclasses.dataclass
class FederatedDataset:
    """Host-side federated view of a `DataSource` for K clients.

    train_images: [K, n, 32, 32, 3] uint8 (disjoint shards, truncated to the
      smallest shard so the stack is rectangular)
    test_images:  [M, 32, 32, 3] uint8 (shared; every client normalizes it
      with its own stats on device)
    mean/std: [K] float32 per-client normalization scalars
    """

    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    mean: np.ndarray
    std: np.ndarray
    num_classes: int

    @property
    def n_clients(self) -> int:
        return self.train_images.shape[0]

    @property
    def shard_size(self) -> int:
        return self.train_images.shape[1]

    def steps_per_epoch(self, batch: int) -> int:
        return self.shard_size // batch

    def epoch(
        self, batch: int, seed: int
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield lockstep stacked batches `([K,B,32,32,3] u8, [K,B] i32)`.

        Each client's shard is independently reshuffled every epoch —
        the `SubsetRandomSampler` equivalent (reference
        src/no_consensus_trio.py:59-61) — with a deterministic seed.
        """
        k, n = self.train_images.shape[:2]
        rng = np.random.default_rng(seed)
        perms = np.stack([rng.permutation(n) for _ in range(k)])  # [K, n]
        for step in range(self.steps_per_epoch(batch)):
            idx = perms[:, step * batch : (step + 1) * batch]  # [K, B]
            images = np.take_along_axis(
                self.train_images, idx[:, :, None, None, None], axis=1
            )
            labels = np.take_along_axis(self.train_labels, idx, axis=1)
            yield images, labels

    def test_batches(
        self, batch: int
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Full-test-set sweep in `[B]` batches (shared across clients; pad
        the tail by repeating the last sample, with a validity mask)."""
        m = self.test_images.shape[0]
        for start in range(0, m, batch):
            idx = np.arange(start, min(start + batch, m))
            pad = batch - idx.size
            mask = np.concatenate([np.ones(idx.size, bool), np.zeros(pad, bool)])
            if pad:
                idx = np.concatenate([idx, np.full(pad, m - 1)])
            yield self.test_images[idx], self.test_labels[idx], mask


def make_federated(
    source: DataSource, n_clients: int, biased: bool = True
) -> FederatedDataset:
    """Shard a `DataSource` across K clients with per-client normalization."""
    splits = client_splits(source.train_images.shape[0], n_clients)
    n_min = min(e - s for s, e in splits)
    tr_i = np.stack([source.train_images[s : s + n_min] for s, _ in splits])
    tr_l = np.stack(
        [source.train_labels[s : s + n_min].astype(np.int32) for s, _ in splits]
    )
    mean, std = client_stats(n_clients, biased)
    return FederatedDataset(
        train_images=tr_i,
        train_labels=tr_l,
        test_images=source.test_images,
        test_labels=source.test_labels.astype(np.int32),
        mean=mean,
        std=std,
        num_classes=source.num_classes,
    )
