"""ctypes bindings for the native data-loader runtime (native/cifar_loader.cpp).

The shared library is compiled on demand with g++ into
``native/build/libcifar_loader.so`` (no pybind11 in this environment; the
C ABI + ctypes keeps the binding dependency-free). Every entry point has a
numpy fallback, selected automatically when the toolchain or library is
unavailable or ``FEDTPU_NO_NATIVE=1`` is set — behavior is bit-identical
either way (tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "cifar_loader.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libcifar_loader.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile to a process-unique temp path, then rename: os.rename is
    # atomic, so concurrent first-use builds from several processes can
    # never dlopen a partially written .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        warnings.warn(f"native loader build failed ({e}); using numpy fallback")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if
    unavailable (fallbacks engage)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("FEDTPU_NO_NATIVE") == "1":
            _lib_failed = True
            return None
        # a prebuilt .so without the source alongside (stripped install) is
        # used as-is; rebuild only when the source is present and newer
        have_so = os.path.exists(_SO)
        have_src = os.path.exists(_SRC)
        stale = (
            have_so and have_src and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if not have_so or stale:
            if not have_src or not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            warnings.warn(f"native loader dlopen failed ({e}); using numpy fallback")
            _lib_failed = True
            return None
        lib.cifar_chw_to_hwc.argtypes = [_u8p, ctypes.c_int64, _u8p, ctypes.c_int]
        lib.cifar_chw_to_hwc.restype = None
        lib.cifar_decode_records.argtypes = [
            _u8p, ctypes.c_int64, ctypes.c_int, _u8p, _i32p, ctypes.c_int,
        ]
        lib.cifar_decode_records.restype = None
        lib.batcher_create.argtypes = [
            _u8p, _i32p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
        ]
        lib.batcher_create.restype = ctypes.c_void_p
        lib.batcher_next.argtypes = [ctypes.c_void_p, _u8p, _i32p]
        lib.batcher_next.restype = ctypes.c_int64
        lib.batcher_destroy.argtypes = [ctypes.c_void_p]
        lib.batcher_destroy.restype = None
        _lib = lib
        return _lib


def _threads() -> int:
    return max(1, os.cpu_count() or 1)


def chw_to_hwc(flat: np.ndarray) -> np.ndarray:
    """[n, 3072] CHW-plane uint8 -> [n, 32, 32, 3] HWC uint8 (a flat/1-D
    multiple of 3072 is reshaped, matching the numpy reshape(-1, ...))."""
    flat = np.ascontiguousarray(flat, np.uint8)
    if flat.size % 3072 != 0:
        raise ValueError(f"image buffer of {flat.size} bytes is not a "
                         "multiple of 3072")
    flat = flat.reshape(-1, 3072)
    n = flat.shape[0]
    lib = get_lib()
    if lib is None:
        return flat.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).copy()
    out = np.empty((n, 32, 32, 3), np.uint8)
    lib.cifar_chw_to_hwc(
        flat.ctypes.data_as(_u8p), n, out.ctypes.data_as(_u8p), _threads()
    )
    return out


def decode_records(raw: np.ndarray, label_bytes: int) -> Tuple[np.ndarray, np.ndarray]:
    """[n, label_bytes + 3072] raw .bin records -> (HWC images, int32 fine
    labels). Fine label = last label byte (cifar-100 records are
    [coarse, fine])."""
    raw = np.ascontiguousarray(raw, np.uint8)
    if raw.ndim != 2 or raw.shape[1] != label_bytes + 3072:
        raise ValueError(
            f"records of shape {raw.shape} do not match label_bytes="
            f"{label_bytes} (expected [n, {label_bytes + 3072}])"
        )
    n = raw.shape[0]
    lib = get_lib()
    if lib is None:
        labels = raw[:, label_bytes - 1].astype(np.int32)
        images = chw_to_hwc(raw[:, label_bytes:])
        return images, labels
    images = np.empty((n, 32, 32, 3), np.uint8)
    labels = np.empty((n,), np.int32)
    lib.cifar_decode_records(
        raw.ctypes.data_as(_u8p), n, label_bytes,
        images.ctypes.data_as(_u8p), labels.ctypes.data_as(_i32p), _threads(),
    )
    return images, labels


class PrefetchBatcher:
    """Background-thread minibatch prefetcher over a host dataset.

    Reshuffles every epoch (deterministic in `seed`) and stages up to
    `prefetch_depth` batches ahead in native buffers — the host-streaming
    companion to the on-device index-gather pipeline (data/pipeline.py),
    for datasets that do not fit on device. Iterating yields
    `(images [b,32,32,3] uint8, labels [b] int32)` forever; call `close()`
    (or use as a context manager) to stop the producer thread.

    Falls back to a numpy implementation with the same epoch semantics
    (different permutation stream) when the native library is unavailable.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch: int,
        seed: int = 0,
        drop_last: bool = True,
        prefetch_depth: int = 4,
    ):
        assert images.ndim == 4 and images.dtype == np.uint8
        assert len(images) == len(labels)
        if not 0 < batch <= len(images):
            raise ValueError(
                f"batch {batch} must be in (0, {len(images)}] — a batch "
                "larger than the dataset can never be filled"
            )
        # keep references so the native side's borrowed pointers stay alive
        self._images = np.ascontiguousarray(images)
        self._labels = np.ascontiguousarray(labels, np.int32)
        self.batch = int(batch)
        self.drop_last = drop_last
        self._seed = seed
        self._lib = get_lib()
        self._handle = None
        self._closed = False
        if self._lib is not None:
            self._handle = self._lib.batcher_create(
                self._images.ctypes.data_as(_u8p),
                self._labels.ctypes.data_as(_i32p),
                len(self._images), self.batch, seed, int(drop_last),
                prefetch_depth,
            )
        if self._handle is None:
            self._rng = np.random.default_rng(seed)
            self._order: list[int] = []
            self._off = 0
        # batches drawn so far: with a fixed (seed, batch, drop_last) the
        # stream is a pure function of this count, so `drawn` + `skip()`
        # are the checkpoint/resume contract for streaming training runs
        self.drawn = 0

    @property
    def is_native(self) -> bool:
        """True iff THIS batcher draws from the native producer.

        Not the same as "the library loaded": a failed `batcher_create`
        silently falls back to the numpy stream, whose permutations
        differ — checkpoints must record what actually ran."""
        return self._handle is not None

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._closed:
            raise StopIteration
        if self._handle is not None:
            img = np.empty((self.batch, 32, 32, 3), np.uint8)
            lbl = np.empty((self.batch,), np.int32)
            n = self._lib.batcher_next(
                self._handle, img.ctypes.data_as(_u8p), lbl.ctypes.data_as(_i32p)
            )
            if n < 0:
                raise StopIteration
            self.drawn += 1
            return img[:n], lbl[:n]
        # numpy fallback
        n_total = len(self._images)
        if self._off + self.batch > n_total and (
            self.drop_last or self._off >= n_total
        ):
            self._order = []
        if not self._order:
            self._order = list(self._rng.permutation(n_total))
            self._off = 0
        idx = self._order[self._off : self._off + self.batch]
        self._off += self.batch
        self.drawn += 1
        return self._images[idx], self._labels[idx]

    def skip(self, n: int) -> None:
        """Fast-forward the stream by `n` batches (draw and discard).

        Used on checkpoint resume: a fresh batcher with the same
        construction arguments, skipped to the saved `drawn` count,
        replays the remaining stream bit-identically. Cost is the
        producer pipeline's memcpys — ~100 ns/KB, so even a 100k-batch
        skip is seconds, not minutes. NOTE: the native and numpy-fallback
        permutation streams differ; a checkpoint must be resumed under
        the same implementation that wrote it (FEDTPU_NO_NATIVE guards
        it explicitly in the trainer's restore path).
        """
        for _ in range(n):
            next(self)

    def close(self):
        self._closed = True
        if self._handle is not None:
            self._lib.batcher_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
