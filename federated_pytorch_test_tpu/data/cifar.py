"""CIFAR dataset sources: on-disk loaders + deterministic synthetic fallback.

The reference downloads CIFAR10 through torchvision (reference
src/no_consensus_trio.py:52-57). This environment has no network egress and
no torchvision, so the equivalent capability is provided two ways:

* `load_cifar10` / `load_cifar100` read the standard published archive
  layouts (python-pickle batches or the binary ``*.bin`` format) from a
  local directory, producing identical uint8 HWC arrays to torchvision's
  in-memory representation.
* `synthetic_cifar` generates a deterministic, *learnable*
  class-conditional dataset with the same shapes/dtypes, used by tests and
  benchmarks when no real archive is present.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import tarfile
import warnings
from typing import Tuple

import numpy as np


class ArchiveNotFound(FileNotFoundError):
    """No dataset archive present at the given root (distinct from a
    present-but-corrupt archive, which must not silently fall back)."""


@dataclasses.dataclass(frozen=True)
class DataSource:
    """An image-classification dataset in canonical uint8 NHWC layout."""

    train_images: np.ndarray  # [N, 32, 32, 3] uint8
    train_labels: np.ndarray  # [N] int32
    test_images: np.ndarray  # [M, 32, 32, 3] uint8
    test_labels: np.ndarray  # [M] int32
    num_classes: int
    name: str = "cifar10"

    def __post_init__(self):
        assert self.train_images.dtype == np.uint8
        assert self.train_images.shape[1:] == (32, 32, 3)


def _planes_to_hwc(flat: np.ndarray) -> np.ndarray:
    """CIFAR stores 3072 bytes as R/G/B planes; convert to HWC uint8.

    Routed through the native multithreaded transpose
    (native/cifar_loader.cpp) when available; numpy otherwise — identical
    bytes either way."""
    from federated_pytorch_test_tpu.data.native import chw_to_hwc

    return chw_to_hwc(np.asarray(flat, np.uint8))


def _load_pickle_batches(root: str, files, label_key: bytes):
    images, labels = [], []
    for fn in files:
        with open(os.path.join(root, fn), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        images.append(_planes_to_hwc(np.asarray(d[b"data"], np.uint8)))
        labels.append(np.asarray(d[label_key], np.int32))
    return np.concatenate(images), np.concatenate(labels)


def _load_bin_records(root: str, files, label_bytes: int):
    """The binary archive layout: each record is `label_bytes` label bytes
    followed by 3072 image bytes (fine label is the last label byte).
    Decoded by the native loader (native/cifar_loader.cpp) when available."""
    from federated_pytorch_test_tpu.data.native import decode_records

    images, labels = [], []
    rec = label_bytes + 3072
    for fn in files:
        raw = np.fromfile(os.path.join(root, fn), np.uint8).reshape(-1, rec)
        img, lbl = decode_records(raw, label_bytes)
        images.append(img)
        labels.append(lbl)
    return np.concatenate(images), np.concatenate(labels)


def load_cifar10(root: str) -> DataSource:
    """Load CIFAR-10 from `root`: either the python-pickle layout
    (``cifar-10-batches-py``, tarball ``cifar-10-python.tar.gz``) or the
    binary layout (``cifar-10-batches-bin``); `root` may be the directory
    containing the archive dir or the archive dir itself."""
    try:
        d = _resolve(root, "cifar-10-batches-py", "cifar-10-python.tar.gz")
        tr_i, tr_l = _load_pickle_batches(
            d, [f"data_batch_{i}" for i in range(1, 6)], b"labels"
        )
        te_i, te_l = _load_pickle_batches(d, ["test_batch"], b"labels")
    except ArchiveNotFound:
        d = _resolve(root, "cifar-10-batches-bin", "cifar-10-binary.tar.gz")
        tr_i, tr_l = _load_bin_records(
            d, [f"data_batch_{i}.bin" for i in range(1, 6)], 1
        )
        te_i, te_l = _load_bin_records(d, ["test_batch.bin"], 1)
    return DataSource(tr_i, tr_l, te_i, te_l, 10, "cifar10")


def load_cifar100(root: str) -> DataSource:
    try:
        d = _resolve(root, "cifar-100-python", "cifar-100-python.tar.gz")
        tr_i, tr_l = _load_pickle_batches(d, ["train"], b"fine_labels")
        te_i, te_l = _load_pickle_batches(d, ["test"], b"fine_labels")
    except ArchiveNotFound:
        d = _resolve(root, "cifar-100-binary", "cifar-100-binary.tar.gz")
        tr_i, tr_l = _load_bin_records(d, ["train.bin"], 2)  # coarse+fine
        te_i, te_l = _load_bin_records(d, ["test.bin"], 2)
    return DataSource(tr_i, tr_l, te_i, te_l, 100, "cifar100")


def _resolve(root: str, subdir: str, tarball: str) -> str:
    if os.path.basename(os.path.normpath(root)) == subdir:
        if not os.path.isdir(root):
            raise ArchiveNotFound(f"{root} does not exist")
        return root
    cand = os.path.join(root, subdir)
    if os.path.isdir(cand):
        return cand
    tb = os.path.join(root, tarball)
    if os.path.isfile(tb):
        with tarfile.open(tb) as t:
            t.extractall(root, filter="data")
        return cand
    raise ArchiveNotFound(f"no {subdir} under {root}")


def synthetic_cifar(
    n_train: int = 50_000,
    n_test: int = 10_000,
    num_classes: int = 10,
    seed: int = 0,
    noise: float = 35.0,
    overlap: float = 0.0,
    label_noise: float = 0.0,
) -> DataSource:
    """Deterministic learnable stand-in with CIFAR shapes.

    Each class c gets a fixed low-frequency prototype image; samples are
    `clip(prototype + noise)`. A small CNN separates the classes well above
    chance within one epoch, so convergence smoke tests (SURVEY.md §4d)
    remain meaningful without the real archive.

    The default set is nearly separable — every healthy configuration
    reaches ~1.0, which cannot DISCRIMINATE a correct implementation from
    a subtly wrong one. For a discriminating convergence oracle
    (benchmarks/convergence_parity.py) use:

    * `overlap` in [0, 1): blends each class prototype with its
      neighbour's, shrinking class margins;
    * `label_noise` in [0, 1): flips that fraction of labels (train AND
      test) to a uniformly random other class, capping achievable test
      accuracy at ~(1 - p) + p/C — e.g. 0.25 caps it at ~0.78, so the
      accuracy curve plateaus below ceiling and has discriminating shape.

    Both are deterministic in `seed`.
    """
    rng = np.random.default_rng(seed)
    # low-frequency prototypes: upsampled 4x4 color patterns
    proto_small = rng.uniform(60, 195, size=(num_classes, 4, 4, 3))
    proto = proto_small.repeat(8, axis=1).repeat(8, axis=2)  # [C,32,32,3]
    if overlap:
        proto = (1.0 - overlap) * proto + overlap * np.roll(proto, 1, axis=0)

    def draw(n: int, r: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
        labels = r.integers(0, num_classes, size=n).astype(np.int32)
        eps = r.normal(0.0, noise, size=(n, 32, 32, 3))
        images = np.clip(proto[labels] + eps, 0, 255).astype(np.uint8)
        if label_noise:
            flip = r.random(n) < label_noise
            shift = r.integers(1, num_classes, size=n).astype(np.int32)
            labels = np.where(
                flip, (labels + shift) % num_classes, labels
            ).astype(np.int32)
        return images, labels

    tr_i, tr_l = draw(n_train, rng)
    te_i, te_l = draw(n_test, rng)
    return DataSource(tr_i, tr_l, te_i, te_l, num_classes, "synthetic")


def load_cifar(
    name: str = "cifar10",
    root: str | None = None,
    synthetic_ok: bool = True,
    synthetic_n_train: int | None = None,
    synthetic_n_test: int | None = None,
) -> DataSource:
    """Load `name` from `root` (or $CIFAR_DATA_DIR), falling back to the
    synthetic source only when NO archive is present at all. A present but
    corrupt/partial archive raises — it must not silently train on
    synthetic data. The `synthetic_*` sizes apply only to the fallback
    (smoke tests / CI shrink it; a real archive is never truncated)."""
    root = root or os.environ.get("CIFAR_DATA_DIR", "./torchdata")
    loader = {"cifar10": load_cifar10, "cifar100": load_cifar100}[name]
    try:
        return loader(root)
    except ArchiveNotFound:
        if not synthetic_ok:
            raise
        warnings.warn(
            f"no {name} archive under {root}; using the deterministic "
            "synthetic stand-in dataset",
            stacklevel=2,
        )
        sizes = {
            k: v
            for k, v in (
                ("n_train", synthetic_n_train),
                ("n_test", synthetic_n_test),
            )
            if v is not None  # else synthetic_cifar's own defaults apply
        }
        return synthetic_cifar(
            num_classes=10 if name == "cifar10" else 100, **sizes
        )
