"""Fused Pallas kernels for the compact-representation L-BFGS direction.

`optim/compact.py` computes -H·g (Byrd–Nocedal compact form) as a chain of
XLA ops whose heavy terms each re-read the `[m, N]` history buffers from
HBM: `S Yᵀ`, `Sᵀg`, `Yᵀg`, `u @ Y`, `w @ S` — several history-sized HBM
passes per direction, with N up to ~11M (ResNet18) and m = 10. The
arithmetic is trivial next to the bandwidth, so fusing passes is the whole
game (the reference's two-loop recursion, src/lbfgsnew.py:615-637, is even
worse: 2m sequentially-dependent BLAS1 passes).

Two kernels bound the history traffic at the minimum of two passes:

* `fused_gram_projections` — ONE pass over (S, Y, g) tiles producing all
  four contractions `S Yᵀ` [m,m], `Y Yᵀ` [m,m], `Sᵀg` [m], `Yᵀg` [m]:
  each grid step loads a `[m, T]` tile of S and Y once and feeds both the
  MXU (tile Grams) and the VPU reductions, accumulating into VMEM-resident
  outputs. Computing `Y Yᵀ` in the same pass makes the `(YᵀY)u` term of
  the compact form an m×m matvec instead of its own pair of [N] passes.
* `fused_direction_assembly` — ONE pass producing
  `hg = γ·g + wᵀS − γ·(uᵀY)` tile by tile from the same S/Y tiles.

History-slot validity (`i < count`) is masked INSIDE the kernels (a
sublane-iota row mask next to the lane tail mask), so the raw history
buffers feed the kernels directly — no masked [m, N] copies are
materialized in HBM beforehand. The m×m triangular solves between the
passes are `optim.compact.compact_solves`, shared with the pure-JAX
backend so the two cannot drift.

Off-TPU the kernels run in Pallas interpret mode, so the CPU test mesh and
the multi-chip dry run exercise the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from federated_pytorch_test_tpu.optim.compact import compact_solves

# Tile width along N. Swept on a real chip at ResNet18 scale
# (N ≈ 11.2M, m = 10): 1024 is badly grid-overhead-bound (~10x slower),
# >=16384 matches XLA's schedule. Under `vmap` (the engine maps the
# direction over each device's local client block) the batch axis lands in
# the BLOCK, not the grid, so VMEM holds K_local tiles at once: at 16384,
# 2 arrays x [K, 10, T] f32 double-buffered is ~5.2 MB x K/2 — safe for
# the realistic on-chip K_local (1 on pods, 3 for the single-chip bench).
# The tail tile is masked inside the kernels, so any N works.
_TILE_N = 16384


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _masks(i, n: int, m: int, count):
    """(row [m,1], col [1,T]) validity masks for one grid step.

    Rows `>= count` are invalid history slots; lanes past `n` are the tail
    tile's padding (OOB block reads are unspecified, incl. NaNs).
    """
    col = jax.lax.broadcasted_iota(jnp.int32, (1, _TILE_N), 1) + i * _TILE_N
    row = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
    return row < count, col < n


def _gram_kernel(
    cnt_ref, s_ref, y_ref, g_ref, sy_ref, yy_ref, p_ref, q_ref, *, n: int
):
    """One grid step: accumulate tile contributions of S Yᵀ, Y Yᵀ, Sᵀg, Yᵀg."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sy_ref[:] = jnp.zeros_like(sy_ref)
        yy_ref[:] = jnp.zeros_like(yy_ref)
        p_ref[:] = jnp.zeros_like(p_ref)
        q_ref[:] = jnp.zeros_like(q_ref)

    row, col = _masks(i, n, s_ref.shape[0], cnt_ref[0, 0])
    mask = row & col
    s = jnp.where(mask, s_ref[:], 0.0)
    y = jnp.where(mask, y_ref[:], 0.0)
    g = jnp.where(col, g_ref[:], 0.0)

    contract = (((1,), (1,)), ((), ()))
    sy_ref[:] += jax.lax.dot_general(
        s, y, contract, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )
    yy_ref[:] += jax.lax.dot_general(
        y, y, contract, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )
    p_ref[:] += jnp.sum(s * g, axis=1, keepdims=True)
    q_ref[:] += jnp.sum(y * g, axis=1, keepdims=True)


def fused_gram_projections(s, y, g, count=None):
    """(S Yᵀ, Y Yᵀ, Sᵀg, Yᵀg) in one HBM pass over the [m, N] history.

    s, y: [m, N]; g: [N]; count: valid-slot count (rows `>= count` are
    ignored; defaults to all m). Returns (sy [m,m], yy [m,m], p [m],
    q [m]), f32.
    """
    m, n = s.shape
    if count is None:
        count = m
    grid = (pl.cdiv(n, _TILE_N),)
    mm = pl.BlockSpec((m, m), lambda i: (0, 0))
    m1 = pl.BlockSpec((m, 1), lambda i: (0, 0))
    sy, yy, p, q = pl.pallas_call(
        functools.partial(_gram_kernel, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((m, _TILE_N), lambda i: (0, i)),
            pl.BlockSpec((m, _TILE_N), lambda i: (0, i)),
            pl.BlockSpec((1, _TILE_N), lambda i: (0, i)),
        ],
        out_specs=[mm, mm, m1, m1],
        out_shape=[
            jax.ShapeDtypeStruct((m, m), jnp.float32),
            jax.ShapeDtypeStruct((m, m), jnp.float32),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(jnp.asarray(count, jnp.int32).reshape(1, 1), s, y, g[None, :])
    return sy, yy, p[:, 0], q[:, 0]


def _assembly_kernel(
    cnt_ref, hd_ref, s_ref, y_ref, g_ref, w_ref, u_ref, out_ref, *, n: int
):
    """One grid step: hg_tile = γ·g + wᵀS − γ·(uᵀY) for one N tile.

    w, u are zero at invalid slots already, but invalid S/Y rows may hold
    anything (public-API buffers) — 0·NaN would poison the dot, so rows
    are masked here too.
    """
    i = pl.program_id(0)
    row, col = _masks(i, n, s_ref.shape[0], cnt_ref[0, 0])
    mask = row & col
    s = jnp.where(mask, s_ref[:], 0.0)
    y = jnp.where(mask, y_ref[:], 0.0)
    g = jnp.where(col, g_ref[:], 0.0)
    hd = hd_ref[0, 0]
    contract = (((1,), (0,)), ((), ()))  # [1, m] @ [m, T]
    ws = jax.lax.dot_general(
        w_ref[:].T, s, contract, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )
    uy = jax.lax.dot_general(
        u_ref[:].T, y, contract, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )
    out_ref[:] = hd * g + ws - hd * uy


def fused_direction_assembly(s, y, g, w, u, h_diag, count=None):
    """hg = h_diag * g + w @ S - h_diag * (u @ Y) in one HBM pass."""
    m, n = s.shape
    if count is None:
        count = m
    grid = (pl.cdiv(n, _TILE_N),)
    smem11 = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    hg = pl.pallas_call(
        functools.partial(_assembly_kernel, n=n),
        grid=grid,
        in_specs=[
            smem11,
            smem11,
            pl.BlockSpec((m, _TILE_N), lambda i: (0, i)),
            pl.BlockSpec((m, _TILE_N), lambda i: (0, i)),
            pl.BlockSpec((1, _TILE_N), lambda i: (0, i)),
            pl.BlockSpec((m, 1), lambda i: (0, 0)),
            pl.BlockSpec((m, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TILE_N), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.float32),
        interpret=_interpret(),
    )(
        jnp.asarray(count, jnp.int32).reshape(1, 1),
        jnp.asarray(h_diag, jnp.float32).reshape(1, 1),
        s,
        y,
        g[None, :],
        w[:, None],
        u[:, None],
    )
    return hg[0]


def compact_direction_pallas(g, s_hist, y_hist, count, h_diag):
    """-H·g via the compact representation, history traffic fused to 2 passes.

    Drop-in replacement for `optim.compact.compact_direction` (same
    signature, same result up to reduction order); see that module's
    docstring for the algebra and the masking of invalid/degenerate slots.
    """
    m = s_hist.shape[0]
    dt = g.dtype
    f32 = jnp.float32
    # f32 casts are free for the engine's f32 trees; row masking happens
    # inside the kernels, so no masked [m, N] copies hit HBM
    g32 = g.astype(f32)
    s32 = s_hist.astype(f32)
    y32 = y_hist.astype(f32)

    sy, yy, p, q = fused_gram_projections(s32, y32, g32, count)

    valid = jnp.arange(m) < count
    u, w, _, _ = compact_solves(
        sy, p, q, valid, h_diag.astype(f32), lambda u: (yy @ u, None)
    )

    hg = fused_direction_assembly(s32, y32, g32, w, u, h_diag, count)
    return (-hg).astype(dt)
