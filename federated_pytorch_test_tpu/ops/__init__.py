"""Pallas TPU kernels for the framework's hot ops.

The compute path is JAX/XLA; these kernels exist where fusion beyond what
XLA does automatically pays off on TPU — primarily the L-BFGS compact
direction, whose history-sized matmul chain XLA schedules as ~5 HBM passes
over the `[m, N]` buffers but a fused pair of kernels does in 2
(see `ops/compact_pallas.py`).

All kernels run in interpret mode off-TPU (CPU tests / the virtual
8-device mesh) and compiled on real TPU chips.
"""

from federated_pytorch_test_tpu.ops.compact_pallas import (
    compact_direction_pallas,
    fused_gram_projections,
)
from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.ops.grouped_gemm import (
    grouped_matmul,
    grouped_matmul_pallas,
)

__all__ = [
    "compact_direction_pallas",
    "flash_attention",
    "fused_gram_projections",
    "grouped_matmul",
    "grouped_matmul_pallas",
]
