"""Grouped block GEMM: `[G, M, K] x [G, K, N] -> [G, M, N]`.

The widened client fold (`--client-fold gemm`, engine/steps.py) turns the
probe fan's frozen layers into genuinely wide contractions, but the
ACTIVE group's per-client/per-probe weights stay a G-way family of dots
sharing one logical shape — exactly the contraction the layer-group
partition guarantees is legal to batch (all clients share identical
group shapes). XLA lowers it as a batched `dot_general`, which on TPU
refuses to widen M across the group axis for small per-group M: each
group member becomes its own skinny MXU launch. The kernel here sweeps
the M tiles of ALL groups through one `pallas_call` so the MXU pipeline
sees G·M rows back to back — the grouped-GEMM arrangement the ISSUE's
`[K, B·P, in] x [K, in, out]` contraction names.

`grouped_matmul` is the public entry: the default backend is the einsum
(`'gmk,gkn->gmn'` — what `jax.vmap` of a dense layer lowers to anyway,
byte-for-byte engine-safe on every platform and under every transform);
`backend='pallas'` opts into the TPU kernel (interpret mode off-TPU, so
CPU tests exercise the same code path). The kernel keeps K untiled — the
engine's per-group inner dims are at most a few thousand, so a
`[TM, K] + [K, TN]` working set fits VMEM comfortably — and pads M/N
tails through Pallas block padding (K is never masked, so no padding
value can contaminate a valid output row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# MXU-friendly tiles; f32 minimum tile is (8, 128) so both are multiples.
# M tiles sized for the fold's realistic per-group rows (B·P = 128..1024);
# the tail tile is block-padded, any M/N works.
_TILE_M = 256
_TILE_N = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _grouped_kernel(lhs_ref, rhs_ref, out_ref):
    """One grid step: out[g, i·TM:(i+1)·TM, j·TN:(j+1)·TN] = lhs @ rhs.

    K arrives whole, so the contraction never crosses a block boundary
    and M/N tail padding stays confined to discarded output rows/cols —
    no masks needed (a padded lhs row can only produce a padded out row).
    """
    out_ref[:] = jax.lax.dot_general(
        lhs_ref[:],
        rhs_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(out_ref.dtype)


def grouped_matmul_pallas(lhs: jnp.ndarray, rhs: jnp.ndarray) -> jnp.ndarray:
    """The TPU grouped GEMM: grid sweeps (group, M tile, N tile).

    lhs: [G, M, K]; rhs: [G, K, N] -> [G, M, N] in lhs's dtype, f32
    accumulation. Interpret mode off-TPU.
    """
    g, m, k = lhs.shape
    g2, k2, n = rhs.shape
    if g != g2 or k != k2:
        raise ValueError(
            f"grouped_matmul shapes disagree: lhs {lhs.shape}, rhs {rhs.shape}"
        )
    tm = min(_TILE_M, m)
    tn = min(_TILE_N, n)
    grid = (g, pl.cdiv(m, tm), pl.cdiv(n, tn))
    return pl.pallas_call(
        _grouped_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, tm, k), lambda gi, i, j: (gi, i, 0)),
            pl.BlockSpec((None, k, tn), lambda gi, i, j: (gi, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, tm, tn), lambda gi, i, j: (gi, i, j)),
        out_shape=jax.ShapeDtypeStruct((g, m, n), lhs.dtype),
        interpret=_interpret(),
    )(lhs, rhs)


def grouped_matmul(
    lhs: jnp.ndarray, rhs: jnp.ndarray, backend: str = "einsum"
) -> jnp.ndarray:
    """`[G, M, K] x [G, K, N] -> [G, M, N]`, backend-selectable.

    'einsum' (default) is the engine-safe path — identical lowering to
    the `jax.vmap`-of-dense formulation it replaces, on every platform;
    'pallas' is the explicit TPU opt-in (interpret mode off-TPU). The
    engine itself never routes through 'pallas' implicitly: model-level
    Pallas would change `engine/steps.py _check_vma`'s contract.
    """
    if backend == "einsum":
        return jnp.einsum("gmk,gkn->gmn", lhs, rhs)
    if backend == "pallas":
        return grouped_matmul_pallas(lhs, rhs)
    raise ValueError(
        f"grouped_matmul backend must be 'einsum' or 'pallas', got {backend!r}"
    )
