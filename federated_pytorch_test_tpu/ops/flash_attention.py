"""Flash attention as Pallas TPU kernels (forward + flash-2 backward).

`parallel.dense_attention` materializes the `[B, H, S, S]` score matrix —
fine at ViT's 64 tokens, hostile at long context: HBM traffic and memory
grow with S². These kernels compute exact attention blockwise in VMEM
(online softmax, never more than a `[BQ, BK]` tile of scores live), with
the standard flash-2 backward from the saved per-row logsumexp:

    fwd:  for each Q block, stream KV blocks; carry (m, l, o); save
          L = m + log(l) per row.
    bwd:  D = rowsum(dO * O); then
          dV_j = sum_i P_ij^T dO_i,   dP_ij = dO_i V_j^T,
          dS_ij = P_ij (dP_ij - D_i),
          dQ_i = sum_j dS_ij K_j * scale,  dK_j = sum_i dS_ij^T Q_i * scale
          with P recomputed blockwise from (Q, K, L).

Memory: NOTHING is whole-sequence-resident in VMEM. Every kernel runs a
3-D grid `(batch*head, outer block, streamed block)` — the streamed
operand (KV for fwd/dq, Q/dO for dk/dv) enters one `[128, D]` tile per
grid step through its BlockSpec while accumulators live in VMEM scratch,
initialized on the first streamed step and flushed to the revisited
output block on the last. Sequence length is therefore HBM-bound, not
VMEM-bound. Causal skipping is `@pl.when` predication on the streamed
index (the tile DMA still happens; the compute does not).

Layout: kernels take `[S, D]` per (batch, head) — Q/K/V arrive as
`[BH, S, D]`. The public entry `flash_attention(q, k, v)` keeps the
framework's `[B, S, H, D]` convention of `parallel/ring.py` and is a
drop-in for `dense_attention` (same signature, exact same math —
tests/test_flash.py). Composable with sequence parallelism: inside a
`seq`-axis shard_map each device can run this kernel on its resident
block while `ring_attention` handles the cross-device streaming. MXU
dots are pinned to HIGHEST precision — the f32 reference comparison
exposes the default fast-precision passes at long S.

Off-TPU the kernels run in Pallas interpret mode, so CPU tests exercise
the exact code path the TPU compiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30

# Tile heights. 128 matches the MXU systolic edge; S must be a multiple
# (the LM/ViT sequence lengths are powers of two — assert, don't silently
# pad, so callers see the constraint).
_BQ = 128
_BK = 128
# the causal skip predicates (j <= qi / i >= ki) assume equal tile
# heights; retuning one constant requires reinstating block-ratio bounds
assert _BQ == _BK

_HI = jax.lax.Precision.HIGHEST


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# dot_general contracting specs: last-with-last ([M,D]x[N,D] -> [M,N]),
# last-with-first ([M,N]x[N,D] -> [M,D]), first-with-first (transpose-left)
_LL = ((1,), (1,))
_LF = ((1,), (0,))
_FF = ((0,), (0,))


def _dot(a, b, dims):
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32,
        precision=_HI,
    )


def _p_block(q, k, lse, qblk, kblk, causal, scale):
    """Recompute the probability tile P = exp(S*scale - lse) for one
    (Q block, KV block) pair — shared by both backward kernels."""
    sc = _dot(q * scale, k, _LL)  # [BQ, BK]
    if causal:
        sc = _causal_mask(sc, qblk, kblk)
    return jnp.exp(sc - lse[:, None])


def _run_unless_skipped(causal, keep_pred, compute):
    """Predicate the streamed-step compute on the causal skip (compute
    runs unconditionally when not causal)."""
    if causal:
        pl.when(keep_pred)(compute)
    else:
        compute()


def _causal_mask(sc, qblk, kblk):
    qpos = qblk * _BQ + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 0)
    kpos = kblk * _BK + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 1)
    return jnp.where(kpos <= qpos, sc, _NEG_BIG)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, o_acc, m_acc, l_acc,
                *, nkv: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    j = pl.program_id(2)  # streamed KV block

    @pl.when(j == 0)
    def _():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG_BIG)
        l_acc[:] = jnp.zeros_like(l_acc)

    def compute():
        q = q_ref[0] * scale  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]
        sc = _dot(q, k, _LL)  # [BQ, BK]
        if causal:
            sc = _causal_mask(sc, qi, j)
        m = m_acc[:, 0]
        l = l_acc[:, 0]
        m_new = jnp.maximum(m, jnp.max(sc, axis=1))
        p = jnp.exp(sc - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        o_acc[:] = o_acc[:] * corr[:, None] + _dot(p, v, _LF)
        m_acc[:] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new[:, None], l_acc.shape)

    # causal: KV blocks past this Q block are fully masked
    _run_unless_skipped(causal, j <= qi, compute)

    @pl.when(j == nkv - 1)
    def _():
        l = l_acc[:, 0]
        m = m_acc[:, 0]
        o_ref[0] = o_acc[:] / l[:, None]
        lse_ref[0] = (m + jnp.log(l))[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, nkv: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        do = do_ref[0]
        delta = delta_ref[0][:, 0]
        k = k_ref[0]
        p = _p_block(q_ref[0], k, lse_ref[0][:, 0], qi, j, causal, scale)
        dp = _dot(do, v_ref[0], _LL)
        ds = p * (dp - delta[:, None])
        dq_acc[:] = dq_acc[:] + _dot(ds, k, _LF)

    _run_unless_skipped(causal, j <= qi, compute)

    @pl.when(j == nkv - 1)
    def _():
        dq_ref[0] = dq_acc[:] * scale


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, nq: int, causal: bool, scale: float):
    ki = pl.program_id(1)
    i = pl.program_id(2)  # streamed Q block

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        do = do_ref[0]
        delta = delta_ref[0][:, 0]
        p = _p_block(q, k_ref[0], lse_ref[0][:, 0], i, ki, causal, scale)
        dv_acc[:] = dv_acc[:] + _dot(p, do, _FF)
        dp = _dot(do, v_ref[0], _LL)
        ds = p * (dp - delta[:, None])
        dk_acc[:] = dk_acc[:] + _dot(ds, q, _FF)

    # causal: Q blocks before this KV block see none of it
    _run_unless_skipped(causal, i >= ki, compute)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:] * scale
        dv_ref[0] = dv_acc[:]


def _check_shapes(s: int, d: int):
    if s % _BQ != 0 or s % _BK != 0:
        raise ValueError(
            f"flash attention needs S divisible by {max(_BQ, _BK)}; got {s} "
            "(use parallel.dense_attention for short/ragged sequences)"
        )
    if d > 256:
        raise ValueError(f"head dim {d} too large for a single VMEM tile")


def _fwd(q3, k3, v3, causal: bool, scale: float):
    bh, s, d = q3.shape
    nq, nkv = s // _BQ, s // _BK
    qspec = pl.BlockSpec((1, _BQ, d), lambda b, i, j: (b, i, 0))
    # causal: fully-masked steps (j > i) revisit the resident tile — the
    # repeated block index makes the DMA a no-op, so skipped blocks cost
    # neither bandwidth nor compute
    kvdx = (lambda b, i, j: (b, jnp.minimum(j, i), 0)) if causal else (
        lambda b, i, j: (b, j, 0)
    )
    kvspec = pl.BlockSpec((1, _BK, d), kvdx)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nkv=nkv, causal=causal, scale=scale),
        grid=(bh, nq, nkv),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[qspec, pl.BlockSpec((1, _BQ, 1), lambda b, i, j: (b, i, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_BQ, d), jnp.float32),    # o accumulator
            pltpu.VMEM((_BQ, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((_BQ, 128), jnp.float32),  # running sum-exp (col 0)
        ],
        interpret=_interpret(),
    )(q3, k3, v3)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash3(q3, k3, v3, causal: bool, scale: float):
    return _fwd(q3, k3, v3, causal, scale)[0]


def _flash3_fwd(q3, k3, v3, causal, scale):
    o, lse = _fwd(q3, k3, v3, causal, scale)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(causal, scale, res, do):
    q3, k3, v3, o, lse = res
    bh, s, d = q3.shape
    nq, nkv = s // _BQ, s // _BK
    do = do.astype(jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [BH, S, 1]

    # dq: outer = Q blocks, streamed = KV blocks (causal: clamp skipped
    # steps onto the resident tile — no-op DMA, see _fwd)
    qspec = pl.BlockSpec((1, _BQ, d), lambda b, i, j: (b, i, 0))
    q1spec = pl.BlockSpec((1, _BQ, 1), lambda b, i, j: (b, i, 0))
    kvdx = (lambda b, i, j: (b, jnp.minimum(j, i), 0)) if causal else (
        lambda b, i, j: (b, j, 0)
    )
    kvspec = pl.BlockSpec((1, _BK, d), kvdx)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nkv=nkv, causal=causal, scale=scale),
        grid=(bh, nq, nkv),
        in_specs=[qspec, kvspec, kvspec, qspec, q1spec, q1spec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_BQ, d), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse, delta)

    # dk/dv: outer = KV blocks, streamed = Q blocks (causal: Q blocks
    # before the KV block are skipped — clamp them onto the resident tile)
    kspec = pl.BlockSpec((1, _BK, d), lambda b, j, i: (b, j, 0))
    qdx = (lambda b, j, i: (b, jnp.maximum(i, j), 0)) if causal else (
        lambda b, j, i: (b, i, 0)
    )
    q1dx = (lambda b, j, i: (b, jnp.maximum(i, j), 0)) if causal else (
        lambda b, j, i: (b, i, 0)
    )
    qstream = pl.BlockSpec((1, _BQ, d), qdx)
    q1stream = pl.BlockSpec((1, _BQ, 1), q1dx)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, nq=nq, causal=causal, scale=scale),
        grid=(bh, nkv, nq),
        in_specs=[qstream, kspec, kspec, qstream, q1stream, q1stream],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_BK, d), jnp.float32),
            pltpu.VMEM((_BK, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse, delta)

    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention, blockwise in VMEM. q,k,v: [B, S, H, D] -> same.

    Drop-in for `parallel.dense_attention` at long S (S must be a
    multiple of 128): no [S, S] score matrix ever exists in HBM, nothing
    whole-sequence-resident ever sits in VMEM, forward or backward.
    """
    b, s, h, d = q.shape
    _check_shapes(s, d)
    if isinstance(sm_scale, jax.core.Tracer):
        raise TypeError(
            "sm_scale must be static (it is baked into the kernel); close "
            "over it rather than passing a traced value"
        )
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (float(d) ** 0.5)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, -1).astype(jnp.float32)

    o = _flash3(to3(q), to3(k), to3(v), causal, float(scale))
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(q.dtype)
