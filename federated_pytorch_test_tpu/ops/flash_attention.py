"""Flash attention as Pallas TPU kernels (forward + flash-2 backward).

`parallel.dense_attention` materializes the `[B, H, S, S]` score matrix —
fine at ViT's 64 tokens, hostile at long context: HBM traffic and memory
grow with S². These kernels compute exact attention blockwise in VMEM
(online softmax, never more than a `[BQ, BK]` tile of scores live), with
the standard flash-2 backward from the saved per-row logsumexp:

    fwd:  for each Q block, stream KV blocks; carry (m, l, o); save
          L = m + log(l) per row.
    bwd:  D = rowsum(dO * O); then
          dV_j = sum_i P_ij^T dO_i,   dP_ij = dO_i V_j^T,
          dS_ij = P_ij (dP_ij - D_i),
          dQ_i = sum_j dS_ij K_j * scale,  dK_j = sum_i dS_ij^T Q_i * scale
          with P recomputed blockwise from (Q, K, L).

Layout: kernels take `[S, D]` per (batch, head) and the grid's leading
axis sweeps B*H — Q/K/V arrive as `[BH, S, D]`. The public entry
`flash_attention(q, k, v)` keeps the framework's `[B, S, H, D]`
convention of `parallel/ring.py` and is a drop-in for `dense_attention`
(same signature semantics, exact same math — tests/test_flash.py).
Composable with sequence parallelism: inside a `seq`-axis shard_map each
device can run this kernel on its resident block while `ring_attention`
handles the cross-device streaming.

Off-TPU the kernels run in Pallas interpret mode, so CPU tests exercise
the exact code path the TPU compiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30

# Q/KV tile heights. 128 matches the MXU systolic edge; S must be a
# multiple (the LM/ViT sequence lengths are powers of two — assert, don't
# silently pad, so callers see the constraint).
_BQ = 128
_BK = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, s: int, causal: bool,
                scale: float):
    qi = pl.program_id(1)
    q = q_ref[0] * scale  # [BQ, D]
    d = q.shape[-1]
    nkv = s // _BK

    def body(j, carry):
        o, m, l = carry
        k = k_ref[0, pl.ds(j * _BK, _BK), :]  # [BK, D]
        v = v_ref[0, pl.ds(j * _BK, _BK), :]
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [BQ, BK]
        if causal:
            qpos = qi * _BQ + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 0)
            kpos = j * _BK + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 1)
            sc = jnp.where(kpos <= qpos, sc, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(sc, axis=1))
        p = jnp.exp(sc - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1)
        o = o * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return o, m_new, l

    o0 = jnp.zeros((_BQ, d), jnp.float32)
    m0 = jnp.full((_BQ,), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((_BQ,), jnp.float32)
    # causal: KV blocks past this Q block are fully masked — skip them
    upper = (qi + 1) * _BQ // _BK if causal else nkv
    o, m, l = jax.lax.fori_loop(0, upper, body, (o0, m0, l0))

    o_ref[0] = o / l[:, None]
    lse_ref[0] = (m + jnp.log(l))[:, None]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, s: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0]  # [BQ, D] (unscaled)
    do = do_ref[0]
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    d = q.shape[-1]
    nkv = s // _BK

    def body(j, dq):
        k = k_ref[0, pl.ds(j * _BK, _BK), :]
        v = v_ref[0, pl.ds(j * _BK, _BK), :]
        sc = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if causal:
            qpos = qi * _BQ + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 0)
            kpos = j * _BK + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 1)
            sc = jnp.where(kpos <= qpos, sc, _NEG_BIG)
        p = jnp.exp(sc - lse[:, None])  # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    upper = (qi + 1) * _BQ // _BK if causal else nkv
    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((_BQ, d), jnp.float32))
    dq_ref[0] = dq * scale


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, s: int, causal: bool, scale: float):
    ki = pl.program_id(1)
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    d = k.shape[-1]
    nq = s // _BQ

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * _BQ, _BQ), :]
        do = do_ref[0, pl.ds(i * _BQ, _BQ), :]
        lse = lse_ref[0, pl.ds(i * _BQ, _BQ), :][:, 0]
        delta = delta_ref[0, pl.ds(i * _BQ, _BQ), :][:, 0]
        sc = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [BQ, BK]
        if causal:
            qpos = i * _BQ + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 0)
            kpos = ki * _BK + jax.lax.broadcasted_iota(jnp.int32, (_BQ, _BK), 1)
            sc = jnp.where(kpos <= qpos, sc, _NEG_BIG)
        p = jnp.exp(sc - lse[:, None])
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return dk, dv

    # causal: Q blocks before this KV block see none of it — skip them
    lower = ki * _BK // _BQ if causal else 0
    dk, dv = jax.lax.fori_loop(
        lower, nq, body,
        (jnp.zeros((_BK, d), jnp.float32), jnp.zeros((_BK, d), jnp.float32)),
    )
    dk_ref[0] = dk * scale
    dv_ref[0] = dv


# The kernels keep each (batch, head)'s full K/V (forward, dq) or Q/dO
# (dk/dv) resident in VMEM and stream tiles out of them with pl.ds — so
# S·D per operand is VMEM-bounded. ~8 MB for the two resident operands
# leaves room for tiles/accumulators in a ~16 MB VMEM: S ≤ 16384 at
# D=64. Past that, the KV/Q stream must move to a grid dimension with
# scratch-carried accumulators (future work); the guard makes the
# ceiling loud instead of letting Mosaic fail obscurely.
_VMEM_OPERAND_BUDGET = 8 * 1024 * 1024


def _check_shapes(s: int, d: int):
    if s % _BQ != 0 or s % _BK != 0:
        raise ValueError(
            f"flash attention needs S divisible by {max(_BQ, _BK)}; got {s} "
            "(use parallel.dense_attention for short/ragged sequences)"
        )
    if d > 256:
        raise ValueError(f"head dim {d} too large for a single VMEM tile")
    if 2 * s * d * 4 > _VMEM_OPERAND_BUDGET:
        raise ValueError(
            f"S={s}, D={d} exceeds the kernel's VMEM-resident ceiling "
            f"(2*S*D*4 > {_VMEM_OPERAND_BUDGET} bytes); shard the sequence "
            "over a mesh with parallel.ring_attention instead"
        )


def _fwd(q3, k3, v3, causal: bool, scale: float):
    bh, s, d = q3.shape
    grid = (bh, s // _BQ)
    qspec = pl.BlockSpec((1, _BQ, d), lambda b, i: (b, i, 0))
    kvspec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, s=s, causal=causal,
                          scale=scale),
        grid=grid,
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[qspec, pl.BlockSpec((1, _BQ, 1), lambda b, i: (b, i, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash3(q3, k3, v3, causal: bool, scale: float):
    return _fwd(q3, k3, v3, causal, scale)[0]


def _flash3_fwd(q3, k3, v3, causal, scale):
    o, lse = _fwd(q3, k3, v3, causal, scale)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(causal, scale, res, do):
    q3, k3, v3, o, lse = res
    bh, s, d = q3.shape
    do = do.astype(jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [BH, S, 1]

    qspec = pl.BlockSpec((1, _BQ, d), lambda b, i: (b, i, 0))
    q1spec = pl.BlockSpec((1, _BQ, 1), lambda b, i: (b, i, 0))
    full = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    full1 = pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0))
    kspec = pl.BlockSpec((1, _BK, d), lambda b, j: (b, j, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, s=s, causal=causal,
                          scale=scale),
        grid=(bh, s // _BQ),
        in_specs=[qspec, full, full, qspec, q1spec, q1spec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        interpret=_interpret(),
    )(q3, k3, v3, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, s=s, causal=causal,
                          scale=scale),
        grid=(bh, s // _BK),
        in_specs=[full, kspec, kspec, full, full1, full1],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do, lse, delta)

    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention, blockwise in VMEM. q,k,v: [B, S, H, D] -> same.

    Drop-in for `parallel.dense_attention` at long S (S must be a
    multiple of 128): no [S, S] score matrix ever exists in HBM, forward
    or backward.
    """
    b, s, h, d = q.shape
    _check_shapes(s, d)
    scale = sm_scale if sm_scale is not None else 1.0 / (float(d) ** 0.5)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, -1).astype(jnp.float32)

    o = _flash3(to3(q), to3(k), to3(v), causal, float(scale))
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(q.dtype)
