"""Flash attention as Pallas TPU kernels (forward + flash-2 backward).

`parallel.dense_attention` materializes the `[B, H, S, S]` score matrix —
fine at ViT's 64 tokens, hostile at long context: HBM traffic and memory
grow with S². These kernels compute exact attention blockwise in VMEM
(online softmax, never more than a `[BQ, BK]` tile of scores live), with
the standard flash-2 backward from the saved per-row logsumexp:

    fwd:  for each Q block, stream KV blocks; carry (m, l, o); save
          L = m + log(l) per row.
    bwd:  D = rowsum(dO * O); then
          dV_j = sum_i P_ij^T dO_i,   dP_ij = dO_i V_j^T,
          dS_ij = P_ij (dP_ij - D_i),
          dQ_i = sum_j dS_ij K_j * scale,  dK_j = sum_i dS_ij^T Q_i * scale
          with P recomputed blockwise from (Q, K, L).

Memory: NOTHING is whole-sequence-resident in VMEM. Every kernel runs a
3-D grid `(batch*head, outer block, streamed block)` — the streamed
operand (KV for fwd/dq, Q/dO for dk/dv) enters one `[128, D]` tile per
grid step through its BlockSpec while accumulators live in VMEM scratch,
initialized on the first streamed step and flushed to the revisited
output block on the last. Sequence length is therefore HBM-bound, not
VMEM-bound.

Causal iteration comes in two shapes:

* ALIGNED (the single-device `flash_attention` path, offsets == 0,
  s_q == s_kv): the grid itself is TRIANGULAR — a `(batch*head, npairs)`
  grid over exactly the lower-triangular (Q block, KV block) pairs,
  driven by scalar-prefetched (i, j) lookup tables that the BlockSpec
  index maps read. Skipped tiles do not exist: no grid step, no DMA, no
  compute is spent above the diagonal, so causal runs the ~S²/2 work a
  causal kernel should, not predicated-S².
* OFFSET (`flash_block` under ring attention, device-varying traced
  offsets): the rectangular grid stays (the useful-pair count is not
  static), with `@pl.when` predication plus index-map CLAMPING onto the
  last useful block — a repeated block index makes the tile DMA a no-op,
  so skipped steps still cost neither bandwidth nor MXU compute, only
  grid-step overhead.

Global-position offsets: every kernel takes an int32 `[q_off, k_off]`
scalar-prefetch operand placing this call's Q and K/V blocks on the
GLOBAL sequence axis, so the causal mask compares `k_off + kcol <=
q_off + qrow`. The single-device entry `flash_attention` passes (0, 0);
`flash_block` takes device-varying offsets and additionally returns the
per-row logsumexp — that pair is exactly the partial result
`ring_attention(use_flash=True)` (parallel/ring.py) folds across ring
steps, composing sequence parallelism with the VMEM-blockwise kernel:
the ring streams K/V blocks across devices over ICI while this kernel
streams tiles within the device. A KV block entirely in a causal Q row's
future contributes `lse = -1e30` and a zero output row, which the ring's
online-softmax merge discards exactly. The backward treats the lse
cotangent analytically: d lse/d scores is the softmax itself, so `dlse`
just shifts the flash-2 `delta` term (`delta = rowsum(dO*O) - dlse`) and
the kernels are unchanged.

Layout: kernels take `[S, D]` per (batch, head) — Q/K/V arrive as
`[BH, S, D]`. The public entries keep the framework's `[B, S, H, D]`
convention of `parallel/ring.py`; `flash_attention(q, k, v)` is a
drop-in for `dense_attention` (same signature, exact same math —
tests/test_flash.py). MXU dots are pinned to HIGHEST precision — the
f32 reference comparison exposes the default fast-precision passes at
long S.

Off-TPU the kernels run in Pallas interpret mode, so CPU tests exercise
the exact code path the TPU compiles.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30

_LOG2E = 1.4426950408889634  # 1/ln 2: exp(x) == exp2(x * _LOG2E)
_LN2 = 0.6931471805599453

# Default tile heights; S must be a multiple of the resolved tile (the
# LM/ViT sequence lengths are powers of two — raise, don't silently pad,
# so callers see the constraint). 128 is the MXU systolic edge and the
# floor; at D=64 a 128-row tile leaves every grid step overhead-dominated
# (~1 us/step vs ~20 ns of MXU work), so the defaults are larger — see
# benchmarks/flash_bf16_tiles.json for the measured sweep on a v5e.
# `flash_attention` upgrades the default to 1024 for bf16 inputs at
# D <= 64 (measured best; bf16 halves tile VMEM so 1024 compiles).
# Both public entries take block_q/block_k overrides.
_BQ = 512
_BK = 512

_HI = jax.lax.Precision.HIGHEST


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# dot_general contracting specs: last-with-last ([M,D]x[N,D] -> [M,N]),
# last-with-first ([M,N]x[N,D] -> [M,D]), first-with-first (transpose-left)
_LL = ((1,), (1,))
_LF = ((1,), (0,))
_FF = ((0,), (0,))


def _dot(a, b, dims, prec=_HI):
    if a.dtype != b.dtype:
        # mixed tiles (bf16 residuals dotted against f32 cotangents):
        # promote both sides — dot_general requires matching dtypes
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    )


def _causal_mask(sc, qpos0, kpos0):
    """Mask scores where global k position exceeds global q position.

    `qpos0`/`kpos0` are the global positions of the tile's first row/col
    (offset + block index * tile height); they may be traced scalars.
    """
    qpos = qpos0 + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
    kpos = kpos0 + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
    return jnp.where(kpos <= qpos, sc, _NEG_BIG)


def _p_block(q, k, lse, qpos0, kpos0, causal, scale, prec):
    """Recompute the probability tile P = exp(S*scale - lse) for one
    (Q block, KV block) pair — shared by both backward kernels."""
    sc = _dot(q * scale, k, _LL, prec)  # [BQ, BK]
    if causal:
        sc = _causal_mask(sc, qpos0, kpos0)
        # a fully-masked row has lse == sc == _NEG_BIG and exp(0) would
        # be 1; such rows (possible for non-tile-aligned k_off - q_off,
        # where a KEPT tile still contains maskless rows) have P == 0
        return jnp.where(
            (lse > _NEG_BIG * 0.5)[:, None], jnp.exp(sc - lse[:, None]), 0.0
        )
    return jnp.exp(sc - lse[:, None])


def _run_unless_skipped(causal, keep_pred, compute):
    """Predicate the streamed-step compute on the causal skip (compute
    runs unconditionally when not causal)."""
    if causal:
        pl.when(keep_pred)(compute)
    else:
        compute()


def _online_softmax_update(sc, m, l, o, v, prec, guard_masked_rows: bool):
    """Fold one score tile into the (m, l, o) online-softmax accumulators.

    Used by the rectangular (offset/ring) forward kernel; the triangular
    kernel carries its own exp2-domain copy of this recurrence with the
    round-5 layout changes (fused denominator, slice-written statistics —
    `_fwd_kernel_tri`). A numerical fix here likely applies there too.
    `guard_masked_rows` zeroes
    rows whose running max is still _NEG_BIG — they have seen only masked
    scores (sc - m_new == 0 there, NOT -inf), possible for non-tile-
    aligned offsets in the OFFSET path; the ALIGNED triangular path never
    produces such rows (every causal row's diagonal tile holds its own
    key), so it skips the guard. The threshold assumes real scores
    satisfy |score| << 5e29 — true for any f32 q,k.
    """
    m_new = jnp.maximum(m, jnp.max(sc, axis=1))
    p = jnp.exp(sc - m_new[:, None])
    if guard_masked_rows:
        p = jnp.where((m_new > _NEG_BIG * 0.5)[:, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=1)
    o_new = o * corr[:, None] + _dot(p, v, _LF, prec)
    return m_new, l_new, o_new


def _p_ds_tile(q, k, v, do, lse, delta, qpos0, kpos0, causal, scale, prec):
    """Recompute P and dS = P * (dP - delta) for one tile — the shared
    backward-pass core (flash-2: dP = dO V^T)."""
    p = _p_block(q, k, lse, qpos0, kpos0, causal, scale, prec)
    dp = _dot(do, v, _LL, prec)
    return p, p * (dp - delta[:, None])


# ---------------------------------------------------------------------------
# causal block-skip predicates and DMA-elision index maps, in terms of the
# global offsets. A streamed block is USEFUL iff its tile overlaps the
# lower-triangular region of the (global q, global k) plane:
#   kv block j vs q block i:  k_off + j*BK  <=  q_off + (i+1)*BQ - 1
# Skipped steps clamp their streamed-operand index onto the last/first
# useful block — the repeated block index makes the DMA a no-op, so
# skipped blocks cost neither bandwidth nor compute.
# ---------------------------------------------------------------------------


def _kv_keep(off, i, j, bq, bk):
    return off[1] + j * bk <= off[0] + (i + 1) * bq - 1


def _kv_clamp(off, i, j, nkv, bq, bk):
    # last useful kv block for q block i (may be <0: whole row masked)
    jmax = (off[0] + (i + 1) * bq - 1 - off[1]) // bk
    return jnp.clip(jnp.minimum(j, jmax), 0, nkv - 1)


def _q_keep(off, j, i, bq, bk):
    return off[0] + (i + 1) * bq - 1 >= off[1] + j * bk


def _q_clamp(off, j, i, nq, bq, bk):
    # first useful q block for kv block j (may be >= nq: block unseen)
    imin = (off[1] + j * bk - off[0]) // bq
    return jnp.clip(jnp.maximum(i, imin), 0, nq - 1)


# ---------------------------------------------------------------------------
# Triangular-grid causal kernels (aligned path). The iteration space is the
# npairs = nq(nq+1)/2 lower-triangular tile pairs; two int32 tables map the
# flat pair index p -> (i, j) and are scalar-prefetched so the BlockSpec
# index maps can read them. i is the outer (Q, accumulate) block and runs
# majored, so each output block's visits are consecutive (Pallas's revisit
# rule) and the accumulators init at j == 0 and flush at the diagonal
# j == i. For dk/dv the roles swap: j outer, i streamed from the diagonal
# down, flush at i == nq - 1.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _tri_tables_qmajor(nq: int):
    """(i_of_p, j_of_p): i-major lower-triangular pairs, j = 0..i."""
    import numpy as np

    i = np.repeat(np.arange(nq), np.arange(1, nq + 1))
    j = np.concatenate([np.arange(r + 1) for r in range(nq)])
    return i.astype(np.int32), j.astype(np.int32)


@functools.lru_cache(maxsize=None)
def _tri_tables_kmajor(nq: int):
    """(j_of_p, i_of_p): j-major lower-triangular pairs, i = j..nq-1."""
    import numpy as np

    j = np.repeat(np.arange(nq), np.arange(nq, 0, -1))
    i = np.concatenate([np.arange(r, nq) for r in range(nq)])
    return j.astype(np.int32), i.astype(np.int32)


def _fwd_kernel_tri(itab, jtab, q_ref, k_ref, v_ref, o_ref, lse_ref,
                    acc, m_acc, l_acc, *, bq: int, d: int, cast16: bool,
                    fuse_l: bool, prec):
    """VPU-lean aligned-causal forward (the measured redesign, round 5).

    The round-3 kernel spent ~80% of its step in VPU softmax work, not
    in the D=64 half-filled MXU dots the round-4 ceiling analysis blamed
    (attribution in `benchmarks/flash_attrib_probe.json`). Measured
    changes, largest first:

    * `fuse_l` (bf16 inputs, D not a lane multiple): `v_ref` is V with a
      ones column appended at `d` (then zero-padded to the 128-lane
      multiple): `p @ v` accumulates the softmax denominator l into
      `acc[:, d]` inside the SAME MXU dot that accumulates o — the
      separate [BQ, BK] rowsum pass and the l scratch disappear. Free
      exactly when the single-pass bf16 PV dot pads its output to the
      next 128 lanes anyway; at f32 precisions the wider dot costs real
      passes (measured: +29% on a 'highest' forward), so those take the
      plain path with an l scratch (`l_acc`, ignored otherwise).
    * the running max / denominator write back as [BQ, 1] lane slices
      instead of broadcast [BQ, 128] stores (~20% of the old step time).
    * scores live in base 2 — Q arrives pre-scaled by scale*log2(e), so
      `exp2` replaces `exp` and the flush converts lse back to natural
      log (lse_nat = lse2 * ln2); the public contract is unchanged.

    With `cast16` the probability tile feeds the MXU in bf16 (inputs
    were bf16 and the caller asked for 'default' precision — the same
    rounding class XLA's dense softmax@V takes on that path). A
    diagonal-only causal mask via `lax.cond` was tried and reverted:
    Mosaic's cond costs more than the masked-tile arithmetic it saves
    (measured: +50% on the backward, where it ran per recompute tile).
    """
    p_id = pl.program_id(1)
    i = itab[p_id]
    j = jtab[p_id]

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG_BIG)
        if not fuse_l:
            l_acc[:] = jnp.zeros_like(l_acc)

    sc = _dot(q_ref[0], k_ref[0], _LL, prec)  # [BQ, BK], base-2 domain
    sc = _causal_mask(sc, i * bq, j * bq)
    m = m_acc[:, 0]
    m_new = jnp.maximum(m, jnp.max(sc, axis=1))
    p = jnp.exp2(sc - m_new[:, None])
    if cast16:
        p = p.astype(jnp.bfloat16)
    corr = jnp.exp2(m - m_new)
    acc[:] = acc[:] * corr[:, None] + _dot(p, v_ref[0], _LF, prec)
    if not fuse_l:
        l_acc[:, 0:1] = (
            l_acc[:, 0] * corr + jnp.sum(p.astype(jnp.float32), axis=1)
        )[:, None]
    m_acc[:, 0:1] = m_new[:, None]

    @pl.when(j == i)
    def _():
        a = acc[:]
        l = jnp.maximum(a[:, d] if fuse_l else l_acc[:, 0], 1e-30)
        o_ref[0] = a[:, :d] / l[:, None]
        lse_ref[0] = ((m_acc[:, 0] + jnp.log2(l)) * _LN2)[:, None]


def _p_ds_tile_tri(q, k, v, do, lse, delta, i, j, bq, prec, cast16):
    """P and dS for one triangular-grid tile, in the base-2 domain.

    `q` arrives pre-scaled by scale*log2(e) (as in the forward), so the
    raw dot IS the base-2 score and `exp2` recovers the exact softmax
    P = exp2(s2 - lse*log2e) = exp(s_nat - lse); `lse` stays natural-log
    (the public contract) and converts per row. P and dP are domain-free,
    so the returned dS = P*(dP - delta) is the ordinary NATURAL-domain
    flash-2 cotangent dL/ds_nat — only the callers' final constant
    multiplies account for the q pre-scaling (see the flush comments).
    With `cast16`, P and dS feed the MXU in bf16.
    """
    sc = _causal_mask(_dot(q, k, _LL, prec), i * bq, j * bq)
    p = jnp.exp2(sc - (lse * _LOG2E)[:, None])
    dp = _dot(do, v, _LL, prec)
    ds = p * (dp - delta[:, None])
    if cast16:
        p = p.astype(jnp.bfloat16)
        ds = ds.astype(jnp.bfloat16)
    return p, ds


def _bwd_dq_kernel_tri(itab, jtab, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, dq_ref, dq_acc, *, bq: int, scale: float,
                       cast16: bool, prec):
    p_id = pl.program_id(1)
    i = itab[p_id]
    j = jtab[p_id]

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    k = k_ref[0]
    _, ds = _p_ds_tile_tri(q_ref[0], k, v_ref[0], do_ref[0],
                           lse_ref[0][:, 0], delta_ref[0][:, 0], i, j, bq,
                           prec, cast16)
    dq_acc[:] = dq_acc[:] + _dot(ds, k, _LF, prec)

    @pl.when(j == i)
    def _():
        # ds is natural-domain and k is unscaled: dL/dq = scale*(ds @ k),
        # exactly as in the offset-path kernel
        dq_ref[0] = dq_acc[:] * scale


def _bwd_dkv_kernel_tri(jtab, itab, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                        *, nq: int, bq: int, cast16: bool, prec):
    p_id = pl.program_id(1)
    j = jtab[p_id]
    i = itab[p_id]

    @pl.when(i == j)  # first streamed Q block for this KV block
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    do = do_ref[0]
    p, ds = _p_ds_tile_tri(q, k_ref[0], v_ref[0], do, lse_ref[0][:, 0],
                           delta_ref[0][:, 0], i, j, bq, prec, cast16)
    # under cast16, dO was cast to bf16 at HBM level in _bwd_tri, so
    # this dot is already bf16 x bf16
    dv_acc[:] = dv_acc[:] + _dot(p, do, _FF, prec)
    dk_acc[:] = dk_acc[:] + _dot(ds, q, _FF, prec)

    @pl.when(i == nq - 1)
    def _():
        # the q tile is PRE-SCALED by scale2 = scale*log2e, so the
        # accumulated ds^T @ q_scaled = scale2*(ds^T @ q); the true
        # dL/dk = scale*(ds^T @ q) = (scale/scale2)*acc = ln2 * acc
        dk_ref[0] = dk_acc[:] * _LN2
        dv_ref[0] = dv_acc[:]


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                o_acc, m_acc, l_acc, *, nkv: int, causal: bool, scale: float,
                prec, bq: int, bk: int):
    qi = pl.program_id(1)
    j = pl.program_id(2)  # streamed KV block

    @pl.when(j == 0)
    def _():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG_BIG)
        l_acc[:] = jnp.zeros_like(l_acc)

    def compute():
        q = q_ref[0] * scale  # [BQ, D]
        sc = _dot(q, k_ref[0], _LL, prec)  # [BQ, BK]
        if causal:
            sc = _causal_mask(sc, off_ref[0] + qi * bq, off_ref[1] + j * bk)
        # masked-row guard on: non-aligned ring offsets can produce tiles
        # whose kept rows still see no key (see _online_softmax_update)
        m_new, l_new, o_new = _online_softmax_update(
            sc, m_acc[:, 0], l_acc[:, 0], o_acc[:], v_ref[0], prec,
            guard_masked_rows=causal,
        )
        o_acc[:] = o_new
        m_acc[:] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new[:, None], l_acc.shape)

    _run_unless_skipped(causal, _kv_keep(off_ref, qi, j, bq, bk), compute)

    @pl.when(j == nkv - 1)
    def _():
        l = l_acc[:, 0]
        m = m_acc[:, 0]
        # rows with no visible key (possible when k_off > q positions in
        # the ring's off-diagonal blocks): emit 0 output and -BIG lse so
        # the caller's online-softmax merge gives them zero weight
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = o_acc[:] / l_safe[:, None]
        lse_ref[0] = jnp.where(l > 0, m + jnp.log(l_safe), _NEG_BIG)[:, None]


def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, nkv: int, causal: bool, scale: float,
                   prec, bq: int, bk: int):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        k = k_ref[0]
        _, ds = _p_ds_tile(q_ref[0], k, v_ref[0], do_ref[0],
                           lse_ref[0][:, 0], delta_ref[0][:, 0],
                           off_ref[0] + qi * bq, off_ref[1] + j * bk,
                           causal, scale, prec)
        dq_acc[:] = dq_acc[:] + _dot(ds, k, _LF, prec)

    _run_unless_skipped(causal, _kv_keep(off_ref, qi, j, bq, bk), compute)

    @pl.when(j == nkv - 1)
    def _():
        dq_ref[0] = dq_acc[:] * scale


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, nq: int, causal: bool, scale: float, prec,
                    bq: int, bk: int):
    ki = pl.program_id(1)
    i = pl.program_id(2)  # streamed Q block

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _p_ds_tile(q, k_ref[0], v_ref[0], do, lse_ref[0][:, 0],
                           delta_ref[0][:, 0],
                           off_ref[0] + i * bq, off_ref[1] + ki * bk,
                           causal, scale, prec)
        dv_acc[:] = dv_acc[:] + _dot(p, do, _FF, prec)
        dk_acc[:] = dk_acc[:] + _dot(ds, q, _FF, prec)

    _run_unless_skipped(causal, _q_keep(off_ref, ki, i, bq, bk), compute)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:] * scale
        dv_ref[0] = dv_acc[:]


def _resolve_blocks(s_q: int, s_kv: int, d: int, block_q, block_k):
    """Pick (bq, bk) tile heights: explicit overrides, else the largest
    default that divides the sequence (floor 128, the MXU edge)."""
    if s_q % 128 != 0 or s_kv % 128 != 0:
        raise ValueError(
            f"flash attention needs S divisible by 128; got ({s_q}, {s_kv}) "
            "(use parallel.dense_attention for short/ragged sequences)"
        )
    bq = block_q or min(_BQ, s_q)
    bk = block_k or min(_BK, s_kv)
    if block_q is None:  # only DEFAULTS shrink to fit; overrides must fit
        while s_q % bq != 0 and bq > 128:
            bq //= 2
    if block_k is None:
        while s_kv % bk != 0 and bk > 128:
            bk //= 2
    if s_q % bq != 0 or s_kv % bk != 0 or bq % 128 != 0 or bk % 128 != 0:
        raise ValueError(
            f"tile heights must be multiples of 128 dividing S; got "
            f"({bq}, {bk}) for S=({s_q}, {s_kv})"
        )
    if d > 256:
        raise ValueError(f"head dim {d} too large for a single VMEM tile")
    return bq, bk


def _grid_spec(grid, in_specs, out_specs, scratch_shapes):
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )


def _augmented_v(v3, d: int, da: int):
    """V with a ones column at `d`, zero-padded to `da` lanes (the fused
    softmax-denominator operand — see `_fwd_kernel_tri`)."""
    bh, s, _ = v3.shape
    parts = [v3, jnp.ones((bh, s, 1), v3.dtype)]
    if da > d + 1:
        parts.append(jnp.zeros((bh, s, da - d - 1), v3.dtype))
    return jnp.concatenate(parts, axis=-1)


def _prescale_q(q3, scale: float):
    """Q pre-scaled into the base-2 score domain (one f32 multiply in
    HBM, so bf16 inputs round once rather than per tile)."""
    return (q3.astype(jnp.float32) * (scale * _LOG2E)).astype(q3.dtype)


def _fwd_tri(q3, k3, v3, scale: float, vma, prec, bq: int, cast16: bool):
    """Aligned-causal forward on the triangular pair grid."""
    bh, s_q, d = q3.shape
    nq = s_q // bq
    # fused softmax denominator: only where the wider PV dot is free —
    # the single-pass bf16 probability dot (cast16) with D below the next
    # 128-lane boundary (see the kernel docstring). bf16 inputs at
    # 'highest' precision keep f32 probabilities, so they take the plain
    # l-scratch path like f32 — the fused dot would pay the multi-pass
    # wider-N cost there.
    fuse_l = cast16 and d % 128 != 0
    da = ((d + 1) + 127) // 128 * 128 if fuse_l else d
    itab, jtab = _tri_tables_qmajor(nq)
    qspec = pl.BlockSpec((1, bq, d), lambda b, p, it, jt: (b, it[p], 0))
    kspec = pl.BlockSpec((1, bq, d), lambda b, p, it, jt: (b, jt[p], 0))
    vspec = pl.BlockSpec((1, bq, da), lambda b, p, it, jt: (b, jt[p], 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_tri, bq=bq, d=d, cast16=cast16,
                          fuse_l=fuse_l, prec=prec),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, itab.shape[0]),
            in_specs=[qspec, kspec, vspec],
            out_specs=[
                qspec,
                pl.BlockSpec((1, bq, 1), lambda b, p, it, jt: (b, it[p], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, da), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
    )(jnp.asarray(itab), jnp.asarray(jtab), _prescale_q(q3, scale), k3,
      _augmented_v(v3, d, da) if fuse_l else v3)
    return o, lse


def _fwd(q3, k3, v3, off, causal: bool, scale: float, vma=None, prec=_HI,
         aligned: bool = False, bq: int = _BQ, bk: int = _BK,
         cast16: bool = False):
    bh, s_q, d = q3.shape
    s_kv = k3.shape[1]
    if causal and aligned and s_q == s_kv and bq == bk:
        return _fwd_tri(q3, k3, v3, scale, vma, prec, bq, cast16)
    nq, nkv = s_q // bq, s_kv // bk
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j, off: (b, i, 0))
    kvdx = (
        (lambda b, i, j, off: (b, _kv_clamp(off, i, j, nkv, bq, bk), 0))
        if causal
        else (lambda b, i, j, off: (b, j, 0))
    )
    kvspec = pl.BlockSpec((1, bk, d), kvdx)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nkv=nkv, causal=causal, scale=scale,
                          prec=prec, bq=bq, bk=bk),
        grid_spec=_grid_spec(
            (bh, nq, nkv),
            [qspec, kvspec, kvspec],
            [qspec, pl.BlockSpec((1, bq, 1), lambda b, i, j, off: (b, i, 0))],
            [
                pltpu.VMEM((bq, d), jnp.float32),    # o accumulator
                pltpu.VMEM((bq, 128), jnp.float32),  # running max (col 0)
                pltpu.VMEM((bq, 128), jnp.float32),  # running sum-exp (col 0)
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
    )(off, q3, k3, v3)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash3(q3, k3, v3, off, causal: bool, scale: float, vma=None, prec=_HI,
            aligned: bool = False, bq: int = _BQ, bk: int = _BK,
            cast16: bool = False):
    return _fwd(q3, k3, v3, off, causal, scale, vma, prec, aligned, bq, bk,
                cast16)


def _flash3_fwd(q3, k3, v3, off, causal, scale, vma, prec, aligned, bq, bk,
                cast16):
    o, lse = _fwd(q3, k3, v3, off, causal, scale, vma, prec, aligned, bq, bk,
                  cast16)
    return (o, lse), (q3, k3, v3, off, o, lse)


def _bwd_tri(q3, k3, v3, do, lse, delta, scale: float, vma, prec, bq: int,
             cast16: bool):
    """Aligned-causal backward on the triangular pair grids."""
    bh, s_q, d = q3.shape
    nq = s_q // bq
    q3s = _prescale_q(q3, scale)  # kernels recompute base-2 scores
    if cast16:
        # one HBM-level cast instead of per-tile dtype promotions: with
        # bf16 residuals, a f32 dO tile would force _dot to promote the
        # V/P sides back to f32 inside every recompute tile (measured:
        # the whole bf16 backward advantage disappeared into those casts)
        do = do.astype(jnp.bfloat16)

    itab, jtab = _tri_tables_qmajor(nq)
    qspec = pl.BlockSpec((1, bq, d), lambda b, p, it, jt: (b, it[p], 0))
    q1spec = pl.BlockSpec((1, bq, 1), lambda b, p, it, jt: (b, it[p], 0))
    kvspec = pl.BlockSpec((1, bq, d), lambda b, p, it, jt: (b, jt[p], 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_tri, bq=bq, scale=scale,
                          cast16=cast16, prec=prec),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, itab.shape[0]),
            in_specs=[qspec, kvspec, kvspec, qspec, q1spec, q1spec],
            out_specs=qspec,
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
        interpret=_interpret(),
    )(jnp.asarray(itab), jnp.asarray(jtab), q3s, k3, v3, do, lse, delta)

    jtab2, itab2 = _tri_tables_kmajor(nq)
    kspec = pl.BlockSpec((1, bq, d), lambda b, p, jt, it: (b, jt[p], 0))
    qstream = pl.BlockSpec((1, bq, d), lambda b, p, jt, it: (b, it[p], 0))
    q1stream = pl.BlockSpec((1, bq, 1), lambda b, p, jt, it: (b, it[p], 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_tri, nq=nq, bq=bq, cast16=cast16,
                          prec=prec),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, jtab2.shape[0]),
            in_specs=[qstream, kspec, kspec, qstream, q1stream, q1stream],
            out_specs=[kspec, kspec],
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
    )(jnp.asarray(jtab2), jnp.asarray(itab2), q3s, k3, v3, do, lse, delta)
    return dq, dk, dv


def _flash3_bwd(causal, scale, vma, prec, aligned, bq, bk, cast16, res, cts):
    q3, k3, v3, off, o, lse = res
    do, dlse = cts
    bh, s_q, d = q3.shape
    s_kv = k3.shape[1]
    nq, nkv = s_q // bq, s_kv // bk
    do = do.astype(jnp.float32)
    # d lse/d scores is the softmax P itself, so the lse cotangent enters
    # dS = P (dP - delta) as a shift of delta: delta = rowsum(dO*O) - dlse
    delta = jnp.sum(do * o, axis=-1, keepdims=True) - dlse.astype(jnp.float32)

    if causal and aligned and s_q == s_kv and bq == bk:
        dq, dk, dv = _bwd_tri(q3, k3, v3, do, lse, delta, scale, vma, prec,
                              bq, cast16)
        doff = jax.custom_derivatives.zero_from_primal(off)
        return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype), doff

    # dq: outer = Q blocks, streamed = KV blocks
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j, off: (b, i, 0))
    q1spec = pl.BlockSpec((1, bq, 1), lambda b, i, j, off: (b, i, 0))
    kvdx = (
        (lambda b, i, j, off: (b, _kv_clamp(off, i, j, nkv, bq, bk), 0))
        if causal
        else (lambda b, i, j, off: (b, j, 0))
    )
    kvspec = pl.BlockSpec((1, bk, d), kvdx)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nkv=nkv, causal=causal, scale=scale,
                          prec=prec, bq=bq, bk=bk),
        grid_spec=_grid_spec(
            (bh, nq, nkv),
            [qspec, kvspec, kvspec, qspec, q1spec, q1spec],
            qspec,
            [pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
        interpret=_interpret(),
    )(off, q3, k3, v3, do, lse, delta)

    # dk/dv: outer = KV blocks, streamed = Q blocks (causal: Q blocks
    # before the KV block see none of it — clamp onto the first useful)
    kspec = pl.BlockSpec((1, bk, d), lambda b, j, i, off: (b, j, 0))
    qdx = (
        (lambda b, j, i, off: (b, _q_clamp(off, j, i, nq, bq, bk), 0))
        if causal
        else (lambda b, j, i, off: (b, i, 0))
    )
    qstream = pl.BlockSpec((1, bq, d), qdx)
    q1stream = pl.BlockSpec((1, bq, 1), qdx)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, nq=nq, causal=causal, scale=scale,
                          prec=prec, bq=bq, bk=bk),
        grid_spec=_grid_spec(
            (bh, nkv, nq),
            [qstream, kspec, kspec, qstream, q1stream, q1stream],
            [kspec, kspec],
            [
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_kv, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_kv, d), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
    )(off, q3, k3, v3, do, lse, delta)

    doff = jax.custom_derivatives.zero_from_primal(off)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype), doff


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def _to3(x, b, h, keep_bf16: bool = False):
    s = x.shape[1]
    dt = (
        jnp.bfloat16
        if keep_bf16 and x.dtype == jnp.bfloat16
        else jnp.float32
    )
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, -1).astype(dt)


_PRECS = {
    "highest": jax.lax.Precision.HIGHEST,
    "default": jax.lax.Precision.DEFAULT,
}


def _prec_of(precision: str):
    try:
        return _PRECS[precision]
    except KeyError:
        raise ValueError(
            f"precision must be one of {sorted(_PRECS)}, got {precision!r}"
        ) from None


def _static_scale(sm_scale, d: int) -> float:
    if isinstance(sm_scale, jax.core.Tracer):
        raise TypeError(
            "sm_scale must be static (it is baked into the kernel); close "
            "over it rather than passing a traced value"
        )
    return float(sm_scale) if sm_scale is not None else 1.0 / (float(d) ** 0.5)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    precision: str = "highest",
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jnp.ndarray:
    """Exact attention, blockwise in VMEM. q,k,v: [B, S, H, D] -> same.

    Drop-in for `parallel.dense_attention` at long S (S must be a
    multiple of 128): no [S, S] score matrix ever exists in HBM, nothing
    whole-sequence-resident ever sits in VMEM, forward or backward.

    `precision` sets the MXU pass count of every tile dot: 'highest'
    (default) runs full-f32 passes; 'default' runs single bf16 passes —
    several times faster on the MXU and the standard choice for
    long-context training, with softmax statistics and accumulators
    still f32. Accuracy: the ~1e-6 agreement with the f32 dense
    reference holds for F32 INPUTS at 'highest' only. BF16 inputs are
    input-rounding-limited at ANY precision setting: q/k/v already
    carry bf16's ~8-bit mantissa, so expect ~2e-2 against an f32
    reference whatever the MXU pass count — raising `precision` on bf16
    inputs buys back only the in-kernel rounding, not the input
    quantization (tests/test_flash.py tolerances).

    `block_q`/`block_k` override the VMEM tile heights (multiples of 128
    dividing S; defaults swept on a v5e — see `_BQ`). Causal uses
    equal tiles (the triangular grid pairs them).
    """
    b, s, h, d = q.shape
    if block_q is None and block_k is None and (
        q.dtype == jnp.bfloat16 and precision == "default" and causal
        and d <= 64 and s % 1024 == 0
    ):
        # measured best tile for the configuration the sweep actually ran
        # (flash_bf16_tiles.json round 5: causal fwd+bwd, bf16 tiles at
        # 'default' precision, reference-scale head dims — 1024 beats 512
        # by ~15% at S=4k and ~33% at S=8k; bf16 halves the tile VMEM
        # that made 1024 uncompilable in round 4). Unmeasured shapes
        # (f32, 'highest' — whose f32 probability tiles carry the VMEM
        # class that fails compile at S=8k f32 — and the non-causal
        # rectangular kernels) keep the 512 default.
        block_q = block_k = 1024
    bq, bk = _resolve_blocks(s, s, d, block_q, block_k)
    if causal:
        bk = bq = min(bq, bk)  # triangular grid pairs equal tiles
    scale = _static_scale(sm_scale, d)
    off = jnp.zeros((2,), jnp.int32)
    # bf16 inputs stay bf16 through the aligned kernels (half the tile
    # DMA; accumulators and softmax statistics are f32 regardless), and
    # at 'default' precision the probability tiles feed the MXU in bf16
    # too — the same rounding class as XLA's dense softmax@V on that
    # path (measured ~10% of the step, benchmarks/flash_attrib_probe.json)
    cast16 = q.dtype == jnp.bfloat16 and precision == "default"
    # offsets are statically zero: causal takes the triangular grid
    o, _ = _flash3(_to3(q, b, h, True), _to3(k, b, h, True),
                   _to3(v, b, h, True),
                   off, causal, scale, None, _prec_of(precision), True,
                   bq, bk, cast16)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(q.dtype)


def flash_block(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_offset,
    k_offset,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    vma=None,
    precision: str = "highest",
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One (Q block, KV block) partial attention with global positions.

    q: [B, Sq, H, D] at global positions `q_offset + [0, Sq)`;
    k, v: [B, Skv, H, D] at `k_offset + [0, Skv)` (offsets may be traced,
    device-varying scalars — e.g. `ring_attention`'s block origins).
    Returns `(o, lse)`, both f32 and both in head-major layout — o
    `[B, H, Sq, D]`, lse `[B, H, Sq]` — which is what an online-softmax
    merge accumulates in (and the kernel's native layout: no transposes
    on the fold path). o is this block's normalized attention output,
    lse its per-row logsumexp — the pair needed to fold partial blocks
    exactly
    (lse = -1e30 and o = 0 for causal rows that see no key in this
    block). Differentiable in q, k, v — including through uses of lse.
    """
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    bq, bk = _resolve_blocks(s_q, s_kv, d, block_q, block_k)
    scale = _static_scale(sm_scale, d)
    off = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )
    o, lse = _flash3(_to3(q, b, h), _to3(k, b, h), _to3(v, b, h),
                     off, causal, scale,
                     frozenset(vma) if vma else None, _prec_of(precision),
                     False, bq, bk)
    # both outputs stay f32 regardless of input dtype: partials feed an
    # online-softmax accumulation (ring.py fold_flash) and rounding them
    # before the merge would waste the f32 carry
    return o.reshape(b, h, s_q, d), lse.reshape(b, h, s_q)
