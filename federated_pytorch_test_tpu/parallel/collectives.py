"""Cross-client collectives: the framework's communication backend.

The reference has no communication backend at all — its "master ↔ slave"
exchange is in-process flat-vector arithmetic with comments marking where
the wire protocol would go (reference src/consensus_admm_trio.py:501-513).
Here those exchanges are XLA collectives over the `clients` mesh axis,
riding ICI within a slice and DCN across slices.

All functions are designed to be called inside a `shard_map` whose inputs
carry a LOCAL client block as their leading axis (size K/D per device, see
`mesh.py`): reductions first collapse the local axis, then `psum` across
devices, so the result is identical for any device count D dividing K.

The ADMM z-update `z = Σ_k (y_k + ρ_k x_k) / Σ_k ρ_k` (reference
src/consensus_admm_trio.py:502) and the FedAvg mean (reference
src/federated_trio.py:357) are both `weighted_client_mean` — the API takes
`(value, weight)` pairs from day one (SURVEY.md §2.4).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.parallel.mesh import CLIENT_AXIS


def client_sum(x: jnp.ndarray, local_axis: int | None = 0, axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Sum over all K clients: local-block sum + cross-device psum.

    Pass `local_axis=None` when the value is already reduced per device.
    """
    if local_axis is not None:
        x = jnp.sum(x, axis=local_axis)
    return lax.psum(x, axis_name)


def client_count(x_local: jnp.ndarray, axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Total number of clients K, derived from the local block size."""
    return lax.psum(jnp.asarray(x_local.shape[0], jnp.float32), axis_name)


def client_mean(x: jnp.ndarray, local_axis: int = 0, axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Unweighted mean over all K clients — the FedAvg z-update
    `z = (x_1 + ... + x_K)/K` (reference src/federated_trio.py:357).

    Unlike `client_sum` there is no already-reduced form: the local client
    block must still be present so K can be derived from its size.
    """
    total = client_sum(x, local_axis, axis_name)
    k = client_sum(jnp.asarray(float(x.shape[local_axis])), None, axis_name)
    return total / k


def weighted_client_mean(
    value: jnp.ndarray,
    weight: jnp.ndarray,
    local_axis: int | None = 0,
    axis_name: str = CLIENT_AXIS,
) -> jnp.ndarray:
    """`Σ_k w_k v_k / Σ_k w_k` over all clients.

    `weight` must have the same rank as `value` with broadcastable trailing
    axes — pass per-client scalar weights as `[K_loc, 1]` against
    `[K_loc, N]` values. This is the ADMM z-update with `v = y/ρ + x`,
    `w = ρ` (reference src/consensus_admm_trio.py:502).
    """
    num = client_sum(value * weight, local_axis, axis_name)
    den = client_sum(weight, local_axis, axis_name)
    return num / den


def all_clients(x_local: jnp.ndarray, axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Gather every client's value to all devices: `[K, ...]` everywhere.

    Used by diagnostics (the `distance_of_layers` equivalent, reference
    src/federated_trio.py:170-186) and by the Byzantine-robust order
    statistics (consensus/robust.py): a coordinate-wise median/trim needs
    every client's value per coordinate, so robust-agg exchanges
    DELIBERATELY spend a full [K, N] gather on integrity. The mean path
    keeps its psum — the reference's bandwidth-saving contract holds
    exactly when `robust_agg='mean'` (the default).
    """
    return lax.all_gather(x_local, axis_name, axis=0, tiled=True)
