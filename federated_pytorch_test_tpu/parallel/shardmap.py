"""`shard_map` across JAX versions — the one import the whole engine rides.

The framework is developed against JAX >= 0.9, where `shard_map` is a
top-level export and its replication-checking knob is `check_vma`
(varying-manual-axes). Older runtimes (0.4.x) ship it under
`jax.experimental.shard_map` with the same semantics behind the
`check_rep` keyword. A hard `from jax import shard_map` made that
difference fatal at *import* time: every engine/consensus/parallel module
— and every test transitively touching them — died on older
environments before a single line ran. Robustness starts at import:
resolve the symbol and the keyword once here, and let everything else
spell `check_vma` uniformly.
"""

from __future__ import annotations

import inspect

try:  # JAX >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older JAX: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# The check keyword follows the SIGNATURE, not the import location: there
# are versions where the top-level export exists but still takes the
# legacy `check_rep` (the rename to `check_vma` landed later), and keying
# on where the symbol imported from would pass the wrong keyword there.
try:
    _PARAMS = inspect.signature(_shard_map).parameters
except (TypeError, ValueError):  # unsignaturable wrapper: assume modern
    _PARAMS = {"check_vma": None}
_CHECK_KW = "check_vma" if "check_vma" in _PARAMS else "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kwargs):
    """Version-portable `jax.shard_map` (keyword-only, like the modern API).

    `check_vma` is a static developer-time consistency check, never a
    numerics knob. The legacy `check_rep` machinery predates replication
    rules for `while`/`scan` bodies (it raises NotImplementedError on the
    L-BFGS line-search loop), so on the legacy path the check is forced
    off — the modern environment keeps it on everywhere. Extra keywords
    (e.g. `axis_names`) pass straight through to the underlying API.
    """
    if _CHECK_KW == "check_rep":
        check_vma = False
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
        **kwargs,
    )
