"""Expert-parallelism meshes and shardings (the `experts` axis).

The MoE layer itself lives with the models (models/moe.py `MoEMLP`); this
module is the axis's mesh/sharding idiom, in the same place and shape as
every other axis's: `mesh.py` (clients), `ring.py` (seq), `tensor.py`
(model), `pipeline.py` (stages). Expert weights are stacked `[E, ...]`
leaves; expert parallelism is a SHARDING of that axis (GSPMD partitions
the vmapped expert compute and inserts the combine collectives), so these
helpers only need names and shapes — they never import the model code.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from federated_pytorch_test_tpu.parallel.mesh import (
    CLIENT_AXIS,
    mesh_1d,
    mesh_2d,
    path_names,
)

EXPERT_AXIS = "experts"

PyTree = Any

# MoEMLP's stacked expert leaves (models/moe.py); the gate and every
# non-expert param stay replicated
_EXPERT_LEAVES = ("w1", "b1", "w2", "b2")


def expert_mesh(d_experts: int, devices=None) -> Mesh:
    """A 1-D mesh over `d_experts` devices with the `experts` axis."""
    return mesh_1d(EXPERT_AXIS, d_experts, devices)


def client_expert_mesh(d_clients: int, d_experts: int, devices=None) -> Mesh:
    """A 2-D `(clients, experts)` mesh: per-client expert pools."""
    return mesh_2d((CLIENT_AXIS, EXPERT_AXIS), d_clients, d_experts, devices)


def ep_param_specs(tree: PyTree, n_experts: int, client_axis: bool = False) -> PyTree:
    """`PartitionSpec` tree sharding stacked expert leaves on `experts`.

    A leaf is an expert stack iff its leading axis (after any client axis)
    equals `n_experts` AND its leaf name is one of MoEMLP's expert params
    (w1/b1/w2/b2) AND it lives in a MoE scope: a path component containing
    "moe" (TransformerLM names the layer `moe`) or a sibling `gate`
    projection (MoEMLP's own structure, which also covers a bare MoEMLP
    tree with no enclosing scope). The scope requirement keeps an
    unrelated param that happens to be named w1 with a matching leading
    axis from being silently sharded on the experts axis. With
    `client_axis=True` (stacked `[K, ...]` trees) every spec gets the
    `clients` axis prepended.
    """

    # nodes that contain a `gate` submodule: their direct children are
    # MoEMLP's params (leaf paths look like <node>/gate/kernel)
    leaf_paths = [
        path_names(p) for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]
    gate_scopes = {p[:-2] for p in leaf_paths if len(p) >= 2 and p[-2] == "gate"}

    def spec(path, leaf):
        names = path_names(path)
        in_moe = names[:-1] in gate_scopes or any(
            isinstance(n, str) and "moe" in n.lower() for n in names[:-1]
        )
        shape = leaf.shape[1:] if client_axis else leaf.shape
        s = P()
        if (
            in_moe
            and names
            and names[-1] in _EXPERT_LEAVES
            and shape
            and shape[0] == n_experts
        ):
            s = P(EXPERT_AXIS)
        if client_axis:
            s = P(CLIENT_AXIS, *tuple(s))
        return s

    return jax.tree_util.tree_map_with_path(spec, tree)


def shard_params_ep(
    tree: PyTree, mesh: Mesh, n_experts: int, client_axis: bool = False
) -> PyTree:
    """device_put expert stacks sharded on the mesh's `experts` axis.

    `n_experts` must divide by the axis size (each device owns a whole
    block of experts); everything else is replicated.
    """
    if EXPERT_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh {tuple(mesh.axis_names)} has no {EXPERT_AXIS!r} axis — "
            "build it with expert_mesh()/client_expert_mesh()"
        )
    if client_axis and CLIENT_AXIS not in mesh.shape:
        raise ValueError(
            f"client_axis=True needs a {CLIENT_AXIS!r} mesh axis — build "
            "the mesh with client_expert_mesh()"
        )
    de = mesh.shape[EXPERT_AXIS]
    if n_experts % de != 0:
        raise ValueError(
            f"n_experts={n_experts} not divisible by the mesh's experts "
            f"axis (size {de})"
        )
    specs = ep_param_specs(tree, n_experts, client_axis=client_axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )
