"""Cross-client diagnostics.

The reference defines (but never calls) `distance_of_layers`, an
interactive debugging aid computing each layer's distance-from-mean across
the three clients (reference src/federated_trio.py:170-186; SURVEY.md §4).
Here it is a first-class jittable diagnostic over the client mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.parallel.collectives import client_mean
from federated_pytorch_test_tpu.partition import Partition


def group_distances(x_local: jnp.ndarray, partition: Partition) -> jnp.ndarray:
    """Per-group mean distance from the cross-client mean.

    `x_local` is the local client block `[K_loc, N]` of FULL flat params.
    Returns `[num_groups]` replicated: for each partition group g,
    `mean_k ‖x_k[g] − mean_j x_j[g]‖` — the reference's per-layer
    `distance_of_layers` diagnostic (src/federated_trio.py:170-186), with
    the cross-client mean as the reference point instead of pairwise sums.

    Call inside `shard_map`; one `psum` per call (on the full vector),
    independent of the number of groups.
    """
    center = client_mean(x_local)  # [N] replicated
    diff = x_local - center  # [K_loc, N]
    out = []
    for g in range(partition.num_groups):
        parts = [
            jax.lax.slice(diff, (0, s.start), (diff.shape[0], s.start + s.size))
            for s in partition.groups[g]
        ]
        blk = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        out.append(client_mean(jnp.linalg.norm(blk, axis=1)))
    return jnp.stack(out)
