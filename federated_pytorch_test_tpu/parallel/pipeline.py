"""Pipeline parallelism: a `stages` mesh axis with an SPMD ppermute pipeline.

The reference has no pipeline parallelism (SURVEY.md §2.3 calls the
layer-partition round-robin "a scheduling cousin" and asks that the
partition abstraction stay orthogonal to the mesh so PP could reuse it).
This module supplies the real thing, the TPU-idiomatic way: consecutive
layer stages live on consecutive devices of a named `stages` mesh axis,
microbatches stream through inside ONE jitted `shard_map` — each cycle
every device applies its stage and hands its activation to the next device
with a single `lax.ppermute` hop over ICI, and a `lax.scan` drives the
M + S - 1 cycles. No host round-trips, no per-stage programs: the whole
pipeline (bubbles included) is one XLA program, and `jax.grad` through it
yields the reverse pipeline automatically (the transpose of `ppermute` is
the reverse permutation, the transpose of the scan is the backward sweep).

This is the standard SPMD pipelining trade: every device computes every
cycle, so S·(M+S-1) stage applications run for M·S useful ones — the
bubble fraction is (S-1)/(M+S-1); raise the microbatch count M to
amortize it.

Composition: the `stages` axis is just another mesh axis, so
`client_stage_mesh(dc, ds)` runs one pipeline per client block while
consensus collectives reduce over `clients` — the same disjoint-axes
pattern as `(clients, seq)` ring attention and `(clients, model)` tensor
parallelism (mesh.py, tensor.py).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from federated_pytorch_test_tpu.parallel.mesh import (
    CLIENT_AXIS,
    mesh_1d,
    mesh_2d,
)

STAGE_AXIS = "stages"

PyTree = Any


def stage_mesh(d_stages: int, devices=None) -> Mesh:
    """A 1-D mesh over `d_stages` devices with the `stages` axis."""
    return mesh_1d(STAGE_AXIS, d_stages, devices)


def client_stage_mesh(d_clients: int, d_stages: int, devices=None) -> Mesh:
    """A 2-D `(clients, stages)` mesh: one pipeline per client block.

    `stages` rides the inner (physically adjacent) axis — the per-cycle
    ppermute hop is the latency-critical pattern.
    """
    return mesh_2d((CLIENT_AXIS, STAGE_AXIS), d_clients, d_stages, devices)


def stack_stage_params(stage_params: Sequence[PyTree]) -> PyTree:
    """Stack S per-stage param trees into one `[S, ...]`-leaved tree.

    The stages must be structurally identical (e.g. S equal transformer
    blocks); the stacked tree is what gets sharded on the `stages` axis.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def spmd_pipeline(
    fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    params: PyTree,
    xs: jnp.ndarray,
    axis_name: str = STAGE_AXIS,
) -> jnp.ndarray:
    """Run microbatches `xs` through the stage pipeline. CALL INSIDE a
    `shard_map` that binds `axis_name` (see `pipeline_apply` for the
    self-contained entry point).

    fn:     `(one_stage_params, x_micro) -> y_micro`, output shaped like
            the input (homogeneous stages — transformer blocks qualify).
    params: this device's stage params with a leading local axis of size 1
            (the `[S, ...]` stacked tree sharded on `axis_name`).
    xs:     `[M, ...]` microbatches, replicated (only stage 0 reads them).

    Returns `[M, ...]` outputs, replicated across the axis (a psum
    broadcast of the last stage's collection buffer).
    """
    stage = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    m = xs.shape[0]
    p = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)

    def cycle(carry, t):
        state, outbuf = carry
        # stage 0 injects microbatch t (clamped once the stream runs dry;
        # those cycles' results are masked out of the collection below)
        x_t = lax.dynamic_index_in_dim(
            xs, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        inp = jnp.where(stage == 0, x_t, state)
        out = fn(p, inp)
        # last stage finishes microbatch t-(S-1) at cycle t
        idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
        take = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        prev = lax.dynamic_index_in_dim(outbuf, idx, axis=0, keepdims=False)
        outbuf = lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(take, out, prev), idx, axis=0
        )
        # hand the activation to the next stage (one ICI hop); the wrap
        # from last->first carries garbage that stage 0 overwrites
        state = _shift_forward(out, axis_name)
        return (state, outbuf), None

    # constant-initialized carries become device-varying after one cycle
    # (ppermute / stage-masked writes) — promote them up front so the
    # scan's vma fixpoint sees invariant carry types (see ring.py)
    from federated_pytorch_test_tpu.parallel.ring import mark_varying

    state0 = mark_varying(jnp.zeros_like(xs[0]), axis_name)
    outbuf0 = mark_varying(jnp.zeros_like(xs), axis_name)
    (_, outbuf), _ = lax.scan(
        cycle, (state0, outbuf0), jnp.arange(m + n_stages - 1)
    )
    # only the last stage holds real outputs; psum broadcasts them (every
    # other stage contributes zeros)
    return lax.psum(jnp.where(stage == n_stages - 1, outbuf, 0.0), axis_name)


def _static_axis_size(axis_name: str) -> int:
    return lax.psum(1, axis_name)  # static under shard_map


def _shift_forward(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    n = _static_axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def pipeline_apply(
    fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    stacked_params: PyTree,
    xs: jnp.ndarray,
    mesh: Mesh,
) -> jnp.ndarray:
    """Self-contained jittable entry point: shard `[S, ...]` params on the
    mesh's `stages` axis and stream `[M, ...]` microbatches through.

    Differentiable end-to-end; the returned `[M, ...]` outputs equal the
    sequential composition of the stages (tested in tests/test_pipeline.py).
    """
    if STAGE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh {tuple(mesh.axis_names)} has no {STAGE_AXIS!r} axis — "
            "build it with stage_mesh()/client_stage_mesh()"
        )
    s = mesh.shape[STAGE_AXIS]
    leads = {getattr(leaf, "shape", ())[0] if getattr(leaf, "ndim", 0) else None
             for leaf in jax.tree.leaves(stacked_params)}
    if len(leads) != 1 or None in leads:
        raise ValueError(
            f"stacked params have inconsistent leading dims {sorted(leads, key=str)} "
            "— every leaf must be stacked [S, ...] with the same stage count "
            "(build the tree with stack_stage_params())"
        )
    (lead,) = leads
    if lead != s:
        raise ValueError(
            f"stacked params carry {lead} stages but the mesh's "
            f"{STAGE_AXIS!r} axis has {s} devices — they must match "
            "(one stage per device)"
        )
    pspec = jax.tree.map(lambda _: P(STAGE_AXIS), stacked_params)

    from federated_pytorch_test_tpu.parallel.shardmap import shard_map

    run = shard_map(
        lambda prm, x: spmd_pipeline(fn, prm, x),
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
    )
    return run(stacked_params, xs)
