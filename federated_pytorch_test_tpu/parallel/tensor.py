"""Tensor parallelism: a `model` mesh axis with Megatron-style shardings.

The reference has no tensor parallelism (SURVEY.md §2.3: whole model per
client) and round-2 merely reserved the axis name. This module makes TP a
real capability, the TPU-idiomatic way: instead of hand-writing sharded
matmul kernels (the GPU/Megatron route), parameters are annotated with
`PartitionSpec`s over a named `model` mesh axis and XLA's SPMD partitioner
derives the per-device program and inserts the collectives (all-reduce
after row-parallel layers) — the scaling-book recipe of "pick a mesh,
annotate shardings, let XLA insert collectives".

The sharding rules are the Megatron alternation, keyed on the framework's
own layer names (models/transformer.py, models/simple.py):

  column-parallel (split output features):  qkv, fc1, head
      kernel [in, out]  -> P(None, 'model');  bias [out] -> P('model')
  row-parallel (split input features):      proj, fc2
      kernel [in, out]  -> P('model', None);  bias [out] -> P()  (replicated;
      XLA adds the psum over 'model' that completes the row-parallel matmul)
  everything else (embeddings, positions, norms, convs) stays replicated:
  P(). A column-parallel leaf whose axis does not divide by the mesh size
  is demoted to replicated when a mesh is given (`tp_param_specs`) — small
  classifier heads (ViT's 10-way `head`) stay whole while the network
  around them shards.

For `MultiHeadAttention` the `qkv` projection's output axis is HEAD-MAJOR
([h0(q,k,v), h1(q,k,v), ...] — models/transformer.py), so the contiguous
blocks of a `model`-axis split each hold whole heads with their q, k and
v together: when d_model divides num_heads, attention is head-local and
the `proj` all-reduce is the block's only cross-device traffic
(asserted against the compiled forward HLO in tests/test_tensor.py).

Composition with the federated axis: client-stacked `[K, ...]` trees get
the `clients` axis prepended to every spec (`client_axis=True`), giving a
2-D `(clients, model)` mesh — per-client TP shards ride the `model` axis
while consensus collectives reduce over `clients`, on disjoint axes just
like the `(clients, seq)` ring mesh (mesh.py).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from federated_pytorch_test_tpu.parallel.mesh import (
    CLIENT_AXIS,
    mesh_1d,
    mesh_2d,
    mesh_3d,
    path_names,
)

MODEL_AXIS = "model"

PyTree = Any

# layer name -> role in the Megatron alternation
_COLUMN_PARALLEL = ("qkv", "fc1", "head")
_ROW_PARALLEL = ("proj", "fc2")

# column/row partners: sharding only one side of a pair is correct (GSPMD
# inserts the resharding) but silently doubles the collective traffic, so
# divisibility demotion applies to the whole pair (see tp_param_specs)
_PAIR = {"qkv": "proj", "proj": "qkv", "fc1": "fc2", "fc2": "fc1"}


def model_mesh(d_model: int, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 1-D mesh over `d_model` devices with the `model` axis (pure TP)."""
    return mesh_1d(MODEL_AXIS, d_model, devices)


def client_model_mesh(
    d_clients: int, d_model: int, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """A 2-D `(clients, model)` mesh: federated parallelism composed with
    tensor parallelism.

    `model` rides the inner (physically adjacent) axis: the per-layer
    all-reduces of TP are latency-critical, while the per-round consensus
    psum over `clients` is amortized across a whole epoch
    (engine/steps.py) and can afford the longer strides.
    """
    return mesh_2d((CLIENT_AXIS, MODEL_AXIS), d_clients, d_model, devices)


def client_model_seq_mesh(
    d_clients: int,
    d_model: int,
    d_seq: int,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """A 3-D `(clients, model, seq)` mesh: federated x tensor x sequence
    parallelism composed.

    The intended use is HYBRID shard_map: manual over `clients` (per-
    client programs + consensus collectives) and `seq` (ring attention's
    ppermute), auto over `model` — inside the body GSPMD completes the
    Megatron row-parallel layers with all-reduces over `model` exactly
    as on a pure `(clients, model)` mesh (jax.shard_map's `axis_names`
    lists the manual axes; `tp_param_specs` works unchanged because it
    only requires the mesh to CONTAIN the axes it shards). Proven
    numerically identical to the per-client single-device reference in
    tests/test_ring.py::test_three_axis_mesh_composes_tp_and_ring and in
    the `triaxis` dryrun leg (__graft_entry__.py).

    `seq` rides the innermost (physically adjacent) axis: ring
    attention's per-step ppermute is bandwidth-critical and wants
    neighbor hops; TP's all-reduce takes the middle axis; the per-round
    consensus psum over `clients` is amortized across an epoch and can
    afford the longest strides.
    """
    from federated_pytorch_test_tpu.parallel.ring import SEQ_AXIS

    return mesh_3d((CLIENT_AXIS, MODEL_AXIS, SEQ_AXIS), d_clients, d_model,
                   d_seq, devices)


def _layer_of(names) -> tuple:
    """(index, name) of the first Megatron-role component in a path."""
    for i, n in enumerate(names):
        if n in _COLUMN_PARALLEL + _ROW_PARALLEL:
            return i, n
    return -1, None


def _leaf_spec(path, ndim: int) -> P:
    """Sharding spec for one param leaf, from its tree path and rank.

    `ndim` is the rank of the leaf WITHOUT any leading client axis — the
    caller strips it for client-stacked trees.
    """
    names = path_names(path)
    _, layer = _layer_of(names)
    leaf_name = names[-1] if names else None
    if layer is None:
        return P()
    if layer in _COLUMN_PARALLEL:
        if leaf_name == "kernel" and ndim >= 2:
            # [..., in, out] — split output features (conv kernels keep
            # spatial dims leading, Dense kernels are [in, out]; either
            # way the last axis is the output-feature axis)
            return P(*([None] * (ndim - 1) + [MODEL_AXIS]))
        if leaf_name == "bias" and ndim == 1:
            return P(MODEL_AXIS)
        return P()
    # row-parallel: split input features; bias stays replicated (added
    # after the all-reduce that completes the matmul)
    if leaf_name == "kernel" and ndim >= 2:
        return P(*([None] * (ndim - 2) + [MODEL_AXIS, None]))
    return P()


def tp_param_specs(
    tree: PyTree, client_axis: bool = False, mesh: Mesh | None = None
) -> PyTree:
    """`PartitionSpec` tree matching `tree` under the Megatron rules above.

    `client_axis=True` is for client-stacked `[K, ...]` trees
    (models/base.py `init_client_params`): every spec gets the `clients`
    axis prepended for the leading K dimension.

    With a `mesh`, any leaf whose sharded axis does not divide evenly by
    the mesh axis is demoted to replicated — the fallback that keeps small
    classifier heads (e.g. ViT's 10-way `head`) whole while the rest of
    the network shards. Demotion applies to a Megatron column/row PAIR as
    a unit: if `qkv` cannot split, its `proj` partner is demoted too (and
    vice versa; same for fc1/fc2), with a warning — a half-sharded pair
    would still be correct (GSPMD reshards) but silently pay extra
    collective traffic. Without a mesh the specs are the pure rule table
    (divisibility is then the caller's problem; see
    `validate_tp_divisibility`).
    """

    if mesh is not None:
        for axis, builder in (
            (MODEL_AXIS, "model_mesh()/client_model_mesh()"),
            (CLIENT_AXIS, "client_model_mesh()"),
        ):
            if axis not in mesh.shape and (axis == MODEL_AXIS or client_axis):
                raise ValueError(
                    f"mesh {tuple(mesh.axis_names)} has no {axis!r} axis — "
                    f"build it with {builder}"
                )

    # pass 1: layer scopes (path prefix up to the layer name) whose own
    # leaves cannot divide — the pair demotion set
    demoted: set[tuple] = set()
    if mesh is not None:

        def scan(path, leaf):
            names = path_names(path)
            idx, layer = _layer_of(names)
            if layer is None:
                return
            s = _leaf_spec(path, leaf.ndim - 1 if client_axis else leaf.ndim)
            if tuple(s) and not _divides(
                leaf.shape[1:] if client_axis else leaf.shape, s, mesh
            ):
                demoted.add(tuple(names[: idx + 1]))

        jax.tree_util.tree_map_with_path(scan, tree)
        # key=str: scopes can mix str and int (SequenceKey) components,
        # which plain tuple comparison cannot order
        for scope in sorted(demoted, key=str):
            partner = _PAIR.get(scope[-1])
            if partner and scope[:-1] + (partner,) not in demoted:
                import warnings

                warnings.warn(
                    f"TP: {'/'.join(map(str, scope))} cannot divide by "
                    f"d_model={mesh.shape[MODEL_AXIS]}; demoting its "
                    f"Megatron partner {partner!r} to replicated as well "
                    "so the pair stays consistent",
                    stacklevel=3,
                )

    def _pair_demoted(names) -> bool:
        idx, layer = _layer_of(names)
        if layer is None:
            return False
        scope = tuple(names[: idx + 1])
        partner = _PAIR.get(layer)
        return scope in demoted or (
            partner is not None and scope[:-1] + (partner,) in demoted
        )

    def spec(path, leaf):
        names = path_names(path)
        s = _leaf_spec(path, leaf.ndim - 1 if client_axis else leaf.ndim)
        if mesh is not None and (
            _pair_demoted(names)
            or not _divides(leaf.shape[1:] if client_axis else leaf.shape, s, mesh)
        ):
            s = P()
        if client_axis:
            if mesh is not None and leaf.shape[0] % mesh.shape[CLIENT_AXIS] != 0:
                # the K axis cannot be demoted — replicating it would turn
                # client parallelism off behind the caller's back
                raise ValueError(
                    f"leading client axis of length {leaf.shape[0]} "
                    f"(param {jax.tree_util.keystr(path)}) is not "
                    f"divisible by the mesh's clients axis "
                    f"(size {mesh.shape[CLIENT_AXIS]})"
                )
            # pad to full rank so the leading K axis maps to `clients`
            # and the layer's own axes keep their Megatron placement
            s = P(CLIENT_AXIS, *(tuple(s) + (None,) * (leaf.ndim - 1 - len(s))))
        return s

    return jax.tree_util.tree_map_with_path(spec, tree)


def _divides(shape, spec: P, mesh: Mesh) -> bool:
    return all(
        axis is None or dim % mesh.shape[axis] == 0
        for dim, axis in zip(shape, tuple(spec))
    )


def validate_tp_divisibility(tree: PyTree, specs: PyTree, mesh: Mesh) -> None:
    """Raise if any sharded axis length is not divisible by its mesh axis.

    XLA would silently pad uneven shards; for the fixed model zoo here an
    uneven split always means a wrong `d_model` choice (e.g. the qkv
    output axis is 3*dim — `d_model` must divide it), so fail loudly.
    """

    def check(path, leaf, spec):
        for dim, axis in zip(leaf.shape, tuple(spec)):
            if axis is None:
                continue
            size = mesh.shape[axis]
            if dim % size != 0:
                raise ValueError(
                    f"param {jax.tree_util.keystr(path)} axis of length "
                    f"{dim} is not divisible by mesh axis {axis!r} "
                    f"(size {size})"
                )

    jax.tree_util.tree_map_with_path(check, tree, specs)


def shard_params_tp(
    tree: PyTree, mesh: Mesh, client_axis: bool = False
) -> PyTree:
    """device_put every leaf according to its Megatron spec on `mesh`.

    Leaves that cannot split evenly (small classifier heads) stay
    replicated (see `tp_param_specs`); if NOTHING shards, `d_model` is
    simply wrong for this model and the call raises instead of silently
    running fully replicated.

    Under `jit`, computation on the result is partitioned by sharding
    propagation from these placements — no shard_map or manual collective
    is needed; the all-reduces appear where the row-parallel layers need
    them (tested against the compiled HLO in tests/test_tensor.py).
    """
    specs = tp_param_specs(tree, client_axis=client_axis, mesh=mesh)
    d_model = mesh.shape[MODEL_AXIS]
    if d_model > 1 and not any(
        MODEL_AXIS in tuple(s) for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
    ):
        raise ValueError(
            f"no parameter axis of this model divides by d_model={d_model}; "
            "every leaf would be replicated — pick a d_model that divides "
            "the hidden sizes (e.g. the qkv output axis)"
        )
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )
