"""Device meshes and shardings for the client axis.

The reference "cluster" is three model replicas stepped sequentially in one
process (reference src/federated_trio.py:336-338). Here clients are a named
mesh axis: stacked `[K, ...]` arrays are sharded across devices on that
axis and one jitted, `shard_map`ped function steps every client
simultaneously, with XLA collectives over ICI/DCN where the reference does
Python-side tensor copies (reference src/consensus_admm_trio.py:501-513).

K need not equal the device count: any D dividing K works — each device
then carries a local block of K/D clients (the single-real-chip benchmark
runs K=3 on D=1; a v4-64 runs K=64 on D=64). Per-client compute vmaps over
the local block; cross-client collectives reduce the local axis before the
`psum` (see `collectives.py`).

The mesh is built with a trailing unused `model` axis slot reserved in the
axis-name universe so tensor/sequence axes can be added later without
renaming (SURVEY.md §2.3 non-goals).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"

PyTree = Any


def path_component_name(key) -> Any:
    """The name of one tree-path component, whatever its key kind.

    Flax dict params yield `DictKey(.key)`, attribute-style trees yield
    `GetAttrKey(.name)`, and list/tuple children yield `SequenceKey(.idx)`
    — the latter has neither `.key` nor `.name`, so naive
    `getattr(k, "key", ...)` chains silently return None for them (and
    None entries make mixed path tuples unsortable). Returns the string
    name where one exists, else the integer sequence index, else None.
    """
    name = getattr(key, "key", getattr(key, "name", None))
    if name is None:
        name = getattr(key, "idx", None)
    return name


def path_names(path) -> tuple:
    """`path_component_name` over a full tree path, as a tuple."""
    return tuple(path_component_name(k) for k in path)


def mesh_1d(
    axis: str,
    n_devices: int | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """A 1-D mesh over `n_devices` devices with the given axis name."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def mesh_2d(
    axes: tuple[str, str],
    d_outer: int,
    d_inner: int,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """A 2-D `(outer, inner)` mesh.

    The inner axis is fastest-varying in device index = physically
    adjacent on most topologies — put the latency/bandwidth-critical
    collective pattern (ring `ppermute`, TP all-reduce) on it.
    """
    devs = list(devices) if devices is not None else jax.devices()
    need = d_outer * d_inner
    if need > len(devs):
        raise ValueError(f"requested {need} devices, only {len(devs)} available")
    grid = np.asarray(devs[:need]).reshape(d_outer, d_inner)
    return Mesh(grid, axes)


def mesh_3d(
    axes: tuple[str, str, str],
    d_outer: int,
    d_mid: int,
    d_inner: int,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """A 3-D `(outer, mid, inner)` mesh; inner is fastest-varying (see
    `mesh_2d` for the adjacency rationale)."""
    devs = list(devices) if devices is not None else jax.devices()
    need = d_outer * d_mid * d_inner
    if need > len(devs):
        raise ValueError(f"requested {need} devices, only {len(devs)} available")
    grid = np.asarray(devs[:need]).reshape(d_outer, d_mid, d_inner)
    return Mesh(grid, axes)


def client_mesh(
    n_devices: int | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """A 1-D mesh over `n_devices` devices with the `clients` axis."""
    return mesh_1d(CLIENT_AXIS, n_devices, devices)


def client_seq_mesh(
    d_clients: int, d_seq: int, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """A 2-D `(clients, seq)` mesh: federated parallelism composed with
    sequence/context parallelism.

    Each client block owns a `d_seq`-device ring for ring attention
    (`parallel/ring.py`) while consensus collectives still reduce over the
    `clients` axis — the two communication patterns ride disjoint mesh
    axes, so neither collective sees the other's traffic. The axis order
    puts `seq` innermost (fastest-varying device index = physically
    adjacent on most topologies), which is where the ring's per-step
    `ppermute` bandwidth matters.
    """
    from federated_pytorch_test_tpu.parallel.ring import SEQ_AXIS

    return mesh_2d((CLIENT_AXIS, SEQ_AXIS), d_clients, d_seq, devices)


def mesh_size(mesh: Mesh) -> int:
    return mesh.shape[CLIENT_AXIS]


def largest_feasible_mesh(n_clients: int, max_devices: int | None = None) -> Mesh:
    """Largest device count D ≤ available that divides K (one local block of
    K/D clients per device)."""
    avail = len(jax.devices()) if max_devices is None else min(max_devices, len(jax.devices()))
    d = max(d for d in range(1, min(n_clients, avail) + 1) if n_clients % d == 0)
    return client_mesh(d)


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding placing the leading (client) axis across the mesh."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_clients(tree: PyTree, mesh: Mesh) -> PyTree:
    """device_put every `[K, ...]` leaf sharded on the client axis."""
    sh = client_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def replicate(tree: PyTree, mesh: Mesh) -> PyTree:
    sh = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
