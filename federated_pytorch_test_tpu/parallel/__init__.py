"""Client-axis parallelism: meshes, shardings, and XLA collectives.

This package is the distributed communication backend the reference lacks
(SURVEY.md §2.4): the `clients` mesh axis replaces the reference's three
sequentially-stepped replicas, and weighted `psum` collectives replace its
in-process flat-vector copies.
"""

from federated_pytorch_test_tpu.parallel.collectives import (
    all_clients,
    client_count,
    client_mean,
    client_sum,
    weighted_client_mean,
)
from federated_pytorch_test_tpu.parallel.diagnostics import group_distances
from federated_pytorch_test_tpu.parallel.ring import (
    mark_varying,
    SEQ_AXIS,
    dense_attention,
    ring_attention,
    seq_shard,
    seq_unshard,
)
from federated_pytorch_test_tpu.parallel.multihost import (
    initialize_distributed,
    multihost_client_mesh,
)
from federated_pytorch_test_tpu.parallel.expert import (
    EXPERT_AXIS,
    client_expert_mesh,
    ep_param_specs,
    expert_mesh,
    shard_params_ep,
)
from federated_pytorch_test_tpu.parallel.pipeline import (
    STAGE_AXIS,
    client_stage_mesh,
    pipeline_apply,
    spmd_pipeline,
    stack_stage_params,
    stage_mesh,
)
from federated_pytorch_test_tpu.parallel.tensor import (
    MODEL_AXIS,
    client_model_mesh,
    client_model_seq_mesh,
    model_mesh,
    shard_params_tp,
    tp_param_specs,
)
from federated_pytorch_test_tpu.parallel.shardmap import shard_map
from federated_pytorch_test_tpu.parallel.mesh import (
    CLIENT_AXIS,
    client_mesh,
    client_seq_mesh,
    client_sharding,
    largest_feasible_mesh,
    mesh_size,
    path_component_name,
    path_names,
    replicate,
    replicated_sharding,
    shard_clients,
)

__all__ = [
    "mark_varying",
    "shard_map",
    "CLIENT_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "STAGE_AXIS",
    "client_expert_mesh",
    "ep_param_specs",
    "expert_mesh",
    "shard_params_ep",
    "client_stage_mesh",
    "pipeline_apply",
    "spmd_pipeline",
    "stack_stage_params",
    "stage_mesh",
    "client_model_mesh",
    "client_model_seq_mesh",
    "model_mesh",
    "shard_params_tp",
    "tp_param_specs",
    "all_clients",
    "dense_attention",
    "ring_attention",
    "seq_shard",
    "seq_unshard",
    "client_count",
    "client_mean",
    "client_mesh",
    "client_seq_mesh",
    "client_sharding",
    "client_sum",
    "group_distances",
    "initialize_distributed",
    "largest_feasible_mesh",
    "multihost_client_mesh",
    "mesh_size",
    "replicate",
    "replicated_sharding",
    "shard_clients",
    "path_component_name",
    "path_names",
    "weighted_client_mean",
]
