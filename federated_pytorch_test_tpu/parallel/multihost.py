"""Multi-host setup: process initialization and DCN-spanning client meshes.

The reference is strictly single-process (SURVEY.md §2.4 — no
torch.distributed, no sockets). This framework scales the `clients` axis
past one host the JAX way:

* every host runs the SAME program; `initialize_distributed()` wires the
  processes together (coordinator discovery via the standard TPU
  environment, or explicit arguments elsewhere);
* `multihost_client_mesh(K)` builds the client mesh over ALL processes'
  devices, DCN-aware: with `jax.experimental.mesh_utils`'s hybrid layout
  the client axis is ordered so that the clients of one slice are
  ICI-adjacent and the slice boundary (DCN) is crossed as few times as
  possible — consensus `psum`s then reduce within slices first and cross
  DCN once, which is exactly the weighted-mean collective's reduction
  shape (parallel/collectives.py).

Single-process (the dev box, CI's virtual CPU mesh) everything degrades
to the plain `client_mesh` — the same code runs everywhere.
"""

from __future__ import annotations

import os
import time
import warnings

import jax
import numpy as np
from jax.sharding import Mesh

from federated_pytorch_test_tpu.parallel.mesh import (
    CLIENT_AXIS,
    largest_feasible_mesh,
)

def _env_signals_multihost() -> bool:
    """True when the environment describes MORE than this one process.

    A coordinator address always does; `TPU_WORKER_HOSTNAMES` only when
    it lists several workers — single-worker setups (including tunneled
    dev chips) carry a one-entry list and are NOT multi-host.
    """
    if any(
        v in os.environ
        for v in ("COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")
    ):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    max_attempts: int = 5,
    backoff_s: float = 2.0,
) -> int:
    """Initialize JAX's multi-process runtime; returns this process' id.

    On TPU pods with standard environment variables, call with no
    arguments on every host, BEFORE any other JAX call (touching the
    backend first makes `jax.distributed.initialize` impossible — even
    `jax.devices()` counts). A no-op (returning 0) when single-process
    (nothing configured and no arguments given).

    On pods the coordinator process routinely comes up seconds after the
    workers (pod schedulers give no start-order guarantee), so the
    connection is retried with exponential backoff — `max_attempts` tries,
    `backoff_s * 2**attempt` seconds between them (capped at 30 s per
    wait). A failed `jax.distributed.initialize` leaves partial global
    state behind (the client object is created before connect()), and a
    second call against that state dies instantly on "should only be
    called once" instead of touching the network — so every failed
    attempt is followed by a best-effort `jax.distributed.shutdown()` to
    make the next connect real. When every attempt fails, the LAST error
    raises loudly: continuing would leave every host training the whole
    job independently, racing on checkpoints — worse than a crash.
    """
    # decide from env/args alone — probing jax.process_count() here would
    # itself initialize the backend and break the multi-process path
    if coordinator_address is None and num_processes is None:
        if not _env_signals_multihost():
            return 0  # single-process run
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
    last: Exception | None = None
    for attempt in range(max_attempts):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            return jax.process_index()
        except RuntimeError as e:
            msg = str(e).lower()
            if attempt == 0 and ("already" in msg or "called once" in msg):
                # the runtime was initialized before we were called:
                # benign. Only trustworthy on the FIRST attempt — after
                # our own failed connect the same message just means the
                # broken partial state was not cleared.
                return jax.process_index()
            last = e
            try:  # clear the partial init state so the retry reconnects
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt + 1 < max_attempts:
                delay = min(backoff_s * (2.0 ** attempt), 30.0)
                warnings.warn(
                    f"jax.distributed.initialize failed (attempt "
                    f"{attempt + 1}/{max_attempts}): {e}; coordinator may "
                    f"not be up yet — retrying in {delay:.1f}s"
                )
                time.sleep(delay)
    raise RuntimeError(
        f"jax.distributed.initialize failed after {max_attempts} attempts; "
        "a configured multi-host run MUST NOT fall back to independent "
        "single-process training (checkpoint races, split-brain consensus) "
        f"— last error: {last}"
    ) from last


def _dcn_islands() -> tuple[int, bool]:
    """(number of DCN islands, islands-are-processes?).

    TPU devices expose `slice_index` — ICI-connected slices are the
    islands, however many processes drive them (multi-host single-slice
    pods are ONE island). Backends without slice topology (CPU workers,
    the CI multi-process harness) have no ICI at all: every process
    boundary is the DCN analogue, so each process is its own island and
    `mesh_utils` groups by process (`process_is_granule`).
    """
    devs = jax.devices()
    slices = {getattr(d, "slice_index", None) for d in devs}
    if None not in slices and len(slices) > 1:
        return len(slices), False  # real multi-slice accelerator topology
    if devs[0].platform == "cpu":
        # no ICI anywhere (the distributed CPU backend reports a uniform
        # slice_index 0, which says nothing): every process boundary is
        # the DCN analogue
        return max(1, jax.process_count()), True
    return 1, False


def multihost_client_mesh(n_clients: int) -> Mesh:
    """A 1-D `clients` mesh over every device of every process, laid out
    DCN-aware when multiple slices are present.

    Single-process: identical to `largest_feasible_mesh` (the largest
    local device count dividing K). Multi-process: all global devices
    participate, so `n_clients` must be a multiple of the global device
    count (each device carries a K/D local client block).
    """
    if jax.process_count() == 1:
        return largest_feasible_mesh(n_clients)

    n_global = len(jax.devices())
    if n_clients % n_global != 0:
        raise ValueError(
            f"multi-process mesh uses all {n_global} global devices; "
            f"n_clients={n_clients} must be a multiple of that"
        )

    from jax.experimental import mesh_utils

    n_slices, by_process = _dcn_islands()
    per_slice = n_global // n_slices
    if n_slices > 1 and n_slices * per_slice == n_global:
        try:
            devices = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(per_slice,),
                dcn_mesh_shape=(n_slices,),
                process_is_granule=by_process,
            )
            return Mesh(np.asarray(devices).reshape(-1), (CLIENT_AXIS,))
        except (ValueError, AssertionError) as e:
            warnings.warn(
                f"hybrid mesh layout unavailable ({e}); falling back to "
                "default device order"
            )
    return Mesh(np.asarray(jax.devices()), (CLIENT_AXIS,))
