"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence dimension anywhere (CNNs on 32x32 images;
SURVEY.md §2.3 records SP/CP as absent), but long-context support is a
first-class capability of this framework, not an afterthought: the
transformer family (models/transformer.py) trains under the same
federated/consensus engine, and when a sequence no longer fits one device
it is sharded over a `seq` mesh axis and attention runs as a RING —
the TPU-native equivalent of Ring Attention with Blockwise Transformers
(Liu et al., 2023):

* each device holds a `[B, S/P, H, D]` shard of Q, K, V;
* P ring steps: attend Q_local against the resident K/V block while
  `lax.ppermute` rotates the K/V blocks one neighbour around the axis —
  compute and ICI transfer overlap, and no device ever materializes the
  full `[S, S]` score matrix or the full K/V;
* softmax is accumulated ONLINE (flash-attention style running max /
  sum-exp / output triple), so the result is exact dense attention, not
  an approximation.

Causality is handled with global position ids derived from each block's
ring origin, so the same code path serves encoder (bidirectional) and
decoder (causal) stacks.

`dense_attention` is the single-device reference implementation used by
the transformer models when the sequence axis is unsharded; the ring path
is numerically identical to it (tests/test_ring.py, 8-device CPU mesh).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # large-negative instead of -inf: keeps exp() at exact 0
# without NaNs from (-inf) - (-inf) in fully-masked blocks


def mark_varying(x, axis_name):
    """Mark `x` as varying over `axis_name` (no-op on older JAX).

    Used for constant-initialized accumulators that a loop will overwrite
    with varying values, and for replicated operands (e.g. the consensus
    vector z) that are closed over by a `lax.while_loop` — JAX's vma
    fixpoint re-applies recorded pvary insertions when loop carries get
    promoted, which errors on an unvarying closed-over constant.
    """
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):  # pre-pcast JAX
        return lax.pvary(x, (axis_name,))
    return x


_pvary = mark_varying  # internal alias used below


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Reference single-device attention. q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(float(d))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        qi = jnp.arange(s_q)[:, None]
        ki = jnp.arange(s_k)[None, :]
        scores = jnp.where(ki <= qi, scores, _NEG_BIG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    use_flash: bool = False,
    precision: Optional[str] = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded on `axis_name`.

    Must be called inside `shard_map`/`pmap` with `axis_name` bound.
    q, k, v: `[B, S_local, H, D]` shards (sequence axis 1); returns the
    `[B, S_local, H, D]` output shard. One `ppermute` per ring step moves
    each K/V block to the next neighbour, so the interconnect carries
    exactly `(P-1)/P` of K and V once — the minimum for exact attention —
    and every step's compute overlaps the next block's transfer.

    `use_flash=True` swaps each ring step's block compute from the dense
    einsum (materializes the local `[S_q, S_kv]` score tile in HBM) to the
    Pallas flash kernel (`ops/flash_attention.flash_block`): the kernel
    streams 128-row tiles through VMEM and returns this block's
    `(output, logsumexp)` partial, which the same online-softmax merge
    folds across ring steps. Two-level streaming — ring over ICI, tiles
    within the device — so LOCAL shard length is no longer score-matrix-
    bound either (requires S_local % 128 == 0).

    `precision` ('highest' | 'default' | None) applies to both folds:
    the flash kernels' MXU pass count (None = their 'highest' default,
    see `ops.flash_attention.flash_attention`) and the dense fold's
    einsum precision (None = ambient default). In Pallas interpret mode
    (CPU tests) the enclosing shard_map needs `check_vma=False`: the
    interpreter cannot propagate varying-mesh-axis metadata through its
    internal slicing (compiled TPU kernels carry it via the out_shape
    `vma` annotation).
    """
    if precision not in (None, "highest", "default"):
        raise ValueError(
            f"precision must be None, 'highest' or 'default', got "
            f"{precision!r}"
        )
    p = lax.psum(1, axis_name)  # ring size (number of sequence shards)
    my = lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(float(d))

    q_pos = my * s_q + jnp.arange(s_q)  # global positions of local queries
    # the precision knob applies to BOTH folds: kernel MXU passes for
    # flash, einsum precision for dense (None = leave each at its default)
    prec = None if precision is None else (
        jax.lax.Precision.HIGHEST if precision == "highest"
        else jax.lax.Precision.DEFAULT
    )

    def fold_dense(acc, k_blk, v_blk, i):
        """Fold one K/V block (ring step i) into the online softmax."""
        o, m, l = acc
        # the resident block started on device (my - i) mod p
        src = (my - i) % p
        k_pos = src * s_kv + jnp.arange(s_kv)

        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, precision=prec
        ) * scale
        if causal:
            keep = (k_pos[None, :] <= q_pos[:, None])[None, None]
            scores = jnp.where(keep, scores, _NEG_BIG)

        blk_max = jnp.max(scores, axis=-1)  # [B,H,Sq]
        m_new = jnp.maximum(m, blk_max)
        probs = jnp.exp(scores - m_new[..., None])  # [B,H,Sq,Skv]
        # rows with no visible key in THIS block (blk_max == _NEG_BIG)
        # must contribute zero weight even if the accumulator is still
        # empty (m == -1e30, where exp(scores - m_new) == exp(0) == 1
        # would add phantom weight) — same order-independence guard as
        # fold_flash's beta
        probs = jnp.where((blk_max > _NEG_BIG * 0.5)[..., None], probs, 0.0)
        corr = jnp.exp(m - m_new)  # [B,H,Sq]
        l_new = l * corr + jnp.sum(probs, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", probs, v_blk, precision=prec
        )
        return o_new, m_new, l_new

    def fold_flash(acc, k_blk, v_blk, i):
        """Fold one K/V block's `flash_block` partial (Pallas kernel):

            m' = max(m, lse);  l' = l*e^{m-m'} + e^{lse-m'}
            o' = o*e^{m-m'} + o_blk*e^{lse-m'}      (o_blk normalized)

        The merge is order-independent: a fully-masked partial
        (lse = -1e30) gets its block weight forced to exactly 0, so it
        contributes nothing even if it meets a still-empty accumulator
        (m = -1e30), where exp(lse - m_new) would otherwise be exp(0) = 1.
        """
        from federated_pytorch_test_tpu.ops.flash_attention import flash_block

        o, m, l = acc
        src = (my - i) % p  # ring origin of the resident block
        o_blk, lse = flash_block(
            q, k_blk, v_blk, my * s_q, src * s_kv, causal=causal,
            sm_scale=sm_scale, vma=(axis_name,),
            precision=precision or "highest",
        )  # o_blk [B,H,Sq,D]: already the accumulator layout
        m_new = jnp.maximum(m, lse)
        alpha = jnp.exp(m - m_new)
        # zero (not exp(0)=1) weight for masked partials: lse = _NEG_BIG
        # means "no visible keys in this block", regardless of m_new
        beta = jnp.where(lse > _NEG_BIG * 0.5, jnp.exp(lse - m_new), 0.0)
        o_new = o * alpha[..., None] + o_blk.astype(o.dtype) * beta[..., None]
        return o_new, m_new, l * alpha + beta

    fold = fold_flash if use_flash else fold_dense
    acc_dtype = jnp.float32 if use_flash else q.dtype
    perm = [(j, (j + 1) % p) for j in range(p)]

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        # rotate K/V to the next neighbour, then fold the received block —
        # p-1 permutes total, so the interconnect carries exactly (P-1)/P
        # of K and V once
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        o, m, l = fold((o, m, l), k_blk, v_blk, i)
        return o, m, l, k_blk, v_blk

    o0 = jnp.zeros((b, h, s_q, d), acc_dtype)
    m0 = jnp.full((b, h, s_q), _NEG_BIG, acc_dtype)
    l0 = jnp.zeros((b, h, s_q), acc_dtype)
    # constant-initialized carries are 'unvarying' over the mesh axis while
    # the loop writes varying values into them; mark them varying up front
    o0, m0, l0 = (_pvary(x, axis_name) for x in (o0, m0, l0))
    # ring step 0: the device's own resident block, no transfer needed
    acc = fold((o0, m0, l0), k, v, 0)
    o, m, l, _, _ = lax.fori_loop(1, p, step, acc + (k, v))

    # causal rows always see at least their own position, non-causal rows
    # see everything — l == 0 cannot happen; the maximum is pure paranoia
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)  # [B, Sq, H, D]


def seq_shard(x: jnp.ndarray, axis_name: str = SEQ_AXIS):
    """Inside shard_map: global [B, S, ...] -> this device's [B, S/P, ...]."""
    p = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    s = x.shape[1]
    if s % p != 0:
        raise ValueError(f"sequence length {s} not divisible by ring size {p}")
    blk = s // p
    return lax.dynamic_slice_in_dim(x, my * blk, blk, axis=1)


def seq_unshard(x_local: jnp.ndarray, axis_name: str = SEQ_AXIS):
    """Inside shard_map: [B, S/P, ...] shard -> replicated [B, S, ...]."""
    gathered = lax.all_gather(x_local, axis_name, axis=1, tiled=True)
    return gathered
