"""federated_pytorch_test_tpu — a TPU-native federated/consensus optimization framework.

A brand-new JAX/XLA framework with the capabilities of the reference
``koilgg/federated-pytorch-test`` (mounted at /root/reference): K CNN clients
training on disjoint CIFAR10/100 shards without sharing data, coordinated by
partial-parameter federated averaging or ADMM consensus (with optional
Barzilai-Borwein adaptive penalty), driven by a jittable stochastic L-BFGS
inner optimizer.

Where the reference simulates its three clients sequentially in one process
(reference src/federated_trio.py:336-338), this framework maps one client per
TPU device on a `jax.sharding.Mesh` and steps all clients simultaneously
inside a single `shard_map`ped, jitted training function. The per-partition
averaging / ADMM z- and y-updates are weighted `psum` collectives over
ICI/DCN; only the active layer/block partition crosses the interconnect,
preserving the reference's bandwidth-saving design (reference README.md:2).

Layout:
  partition/  flat codec + static layer/block partition specs
  models/     Flax models: Net/Net1/Net2, ResNet18 (ELU) + partition metadata
  data/       CIFAR pipelines: K-way disjoint shards, biased normalization
  optim/      jittable stochastic L-BFGS (two-loop recursion + line searches)
  consensus/  FedAvg / ADMM / adaptive-rho strategies as pure collective fns
  parallel/   mesh construction, client-axis collectives, sharded step builders
  fault/      replayable failure injection: dropout masks, stragglers, crashes
  ops/        numerics kernels (Pallas where warranted)
  utils/      config presets, metrics, checkpointing, tracing
"""

__version__ = "0.1.0"

from federated_pytorch_test_tpu.partition import Partition, Segment

__all__ = ["Partition", "Segment", "__version__"]
