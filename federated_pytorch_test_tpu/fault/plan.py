"""Deterministic failure-injection schedules: the chaos that replays.

The reference trio simulates three always-alive clients in one process,
so every client survives every round by construction (SURVEY.md §2.4 —
there is no transport to fail). Real federated deployments drop clients,
straggle, and crash mid-round; TAMUNA (arXiv:2302.09832) treats partial
participation as a first-class algorithmic regime and FedADMM
(arXiv:2204.03529) shows ADMM consensus absorbs system heterogeneity when
the aggregation is participation-aware.

A `FaultPlan` is the *schedule* of those failures, and nothing else: every
fault it describes is a pure function of `(plan.seed, round cursor)`,
where the round cursor is the trainer's `(nloop, gid, nadmm)` triple. Two
runs of the same plan therefore inject byte-identical faults regardless of
wall-clock, host count, or how often the run crashed and resumed — the
"resumed run replays the exact trajectory" invariant of
`utils/checkpoint.py` extends to injected faults (docs/FAULT.md).

Three fault kinds:

* **dropout** — each client independently misses a consensus round with
  probability `dropout_p` (it trains locally but its contribution is
  excluded from the masked aggregation and it does not receive the
  broadcast; see consensus/fedavg.py, consensus/admm.py);
* **stragglers** — a round stalls for `straggler_delay_s` host-side
  seconds with probability `straggler_p` (the coordinator waiting out a
  slow client before declaring it dropped);
* **crashes** — the process raises `InjectedCrash` at a named round
  boundary, exercising checkpoint/resume (`--resume auto`).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Tuple

import numpy as np


class InjectedCrash(RuntimeError):
    """A planned crash point fired (see FaultPlan.crashes)."""


@dataclasses.dataclass(frozen=True)
class CrashPoint:
    """Crash AFTER the consensus exchange of round `(nloop, gid, nadmm)`.

    The boundary is chosen so a crashed run holds exactly the state an
    outer-loop checkpoint would capture mid-flight: resume restarts the
    interrupted outer loop from the last checkpoint and deterministically
    replays the rounds before the crash point (docs/FAULT.md).
    """

    nloop: int
    gid: int
    nadmm: int

    def key(self) -> str:
        return f"{self.nloop}_{self.gid}_{self.nadmm}"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A replayable chaos schedule (all faults pure in seed + cursor)."""

    seed: int = 0
    dropout_p: float = 0.0
    straggler_p: float = 0.0
    straggler_delay_s: float = 0.0
    crashes: Tuple[CrashPoint, ...] = ()

    def __post_init__(self):
        for name in ("dropout_p", "straggler_p"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.straggler_delay_s < 0:
            raise ValueError(
                f"straggler_delay_s must be >= 0, got {self.straggler_delay_s}"
            )

    # ------------------------------------------------------------- schedule

    def _rng(self, nloop: int, gid: int, nadmm: int) -> np.random.Generator:
        # the same SeedSequence folding as the trainer's epoch shuffles
        # (engine/trainer.py _epoch_seed): deterministic in (seed, cursor),
        # independent across rounds
        return np.random.default_rng(
            [self.seed & 0x7FFFFFFF, nloop, gid, nadmm]
        )

    def participation(
        self, n_clients: int, nloop: int, gid: int, nadmm: int
    ) -> np.ndarray:
        """`[K]` float32 mask for one consensus round: 1 = alive, 0 = dropped.

        Pure in (seed, cursor) — NOT in execution history, so a resumed
        run re-derives the identical mask for a replayed round. All-dropped
        rounds are allowed; the masked aggregation degenerates to keeping
        the previous consensus state (consensus/fedavg.py).
        """
        rng = self._rng(nloop, gid, nadmm)
        if self.dropout_p <= 0.0:
            return np.ones(n_clients, np.float32)
        return (rng.random(n_clients) >= self.dropout_p).astype(np.float32)

    def straggler_delay(self, nloop: int, gid: int, nadmm: int) -> float:
        """Host-side seconds this round's consensus stalls (0 = no straggler)."""
        if self.straggler_p <= 0.0 or self.straggler_delay_s <= 0.0:
            return 0.0
        # a separate fold from participation so adding stragglers to a plan
        # does not perturb its dropout masks
        rng = np.random.default_rng(
            [(self.seed + 1) & 0x7FFFFFFF, nloop, gid, nadmm]
        )
        return self.straggler_delay_s if rng.random() < self.straggler_p else 0.0

    def crash_at(self, nloop: int, gid: int, nadmm: int) -> CrashPoint | None:
        for c in self.crashes:
            if (c.nloop, c.gid, c.nadmm) == (nloop, gid, nadmm):
                return c
        return None

    # -------------------------------------------------------- serialization

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["crashes"] = [dataclasses.asdict(c) for c in self.crashes]
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        crashes = tuple(CrashPoint(**c) for c in d.pop("crashes", []))
        return cls(crashes=crashes, **d)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Build a plan from a CLI `--fault-plan` value.

        Accepts (1) a path to a JSON file written by `to_json`, or (2) an
        inline spec of comma-separated `key=value` pairs:

            seed=1,dropout=0.3,straggler=0.1:0.5,crash=0:1:2

        where `straggler=p:delay_s` and each `crash=nloop:gid:nadmm` names
        one crash point (repeatable).
        """
        if os.path.exists(spec):
            with open(spec) as f:
                return cls.from_json(f.read())
        kw: dict = {}
        crashes = []
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"bad fault-plan item {item!r} (want key=value); "
                    f"note {spec!r} is also not an existing file path"
                )
            key, val = item.split("=", 1)
            key = key.strip()
            if key == "seed":
                kw["seed"] = int(val)
            elif key == "dropout":
                kw["dropout_p"] = float(val)
            elif key == "straggler":
                p, _, delay = val.partition(":")
                kw["straggler_p"] = float(p)
                kw["straggler_delay_s"] = float(delay) if delay else 1.0
            elif key == "crash":
                parts = val.split(":")
                if len(parts) != 3:
                    raise ValueError(
                        f"crash point {val!r} must be nloop:gid:nadmm"
                    )
                crashes.append(CrashPoint(*(int(p) for p in parts)))
            else:
                raise ValueError(
                    f"unknown fault-plan key {key!r} "
                    "(have seed, dropout, straggler, crash)"
                )
        return cls(crashes=tuple(crashes), **kw)
