"""Deterministic failure-injection schedules: the chaos that replays.

The reference trio simulates three always-alive clients in one process,
so every client survives every round by construction (SURVEY.md §2.4 —
there is no transport to fail). Real federated deployments drop clients,
straggle, and crash mid-round; TAMUNA (arXiv:2302.09832) treats partial
participation as a first-class algorithmic regime and FedADMM
(arXiv:2204.03529) shows ADMM consensus absorbs system heterogeneity when
the aggregation is participation-aware.

A `FaultPlan` is the *schedule* of those failures, and nothing else: every
fault it describes is a pure function of `(plan.seed, round cursor)`,
where the round cursor is the trainer's `(nloop, gid, nadmm)` triple. Two
runs of the same plan therefore inject byte-identical faults regardless of
wall-clock, host count, or how often the run crashed and resumed — the
"resumed run replays the exact trajectory" invariant of
`utils/checkpoint.py` extends to injected faults (docs/FAULT.md).

Four fault kinds:

* **dropout** — each client independently misses a consensus round with
  probability `dropout_p` (it trains locally but its contribution is
  excluded from the masked aggregation and it does not receive the
  broadcast; see consensus/fedavg.py, consensus/admm.py);
* **stragglers** — a round stalls for `straggler_delay_s` host-side
  seconds with probability `straggler_p` (the coordinator waiting out a
  slow client before declaring it dropped);
* **crashes** — the process raises `InjectedCrash` at a named round
  boundary, exercising checkpoint/resume (`--resume auto`);
* **corruption** — a chosen client's post-epoch update is corrupted IN
  TRANSIT before the consensus exchange (Byzantine behavior: the
  client's own local state keeps its true parameters; only the update
  the aggregation sees is garbage). Modes: `scale` (×λ), `signflip`,
  `nan_burst` (the whole update NaN), `gauss` (additive σ·N(0,1) noise).
  The schedule is emitted like the dropout masks — `[nadmm, K]`
  mode/strength/seed arrays the fused round consumes as scan inputs
  (engine/steps.py) — and the defense lives in consensus/robust.py
  (`--robust-agg median|trimmed|clip`, auto-quarantine).

Plus one SPEED axis (system heterogeneity, not a failure):

* **slow clients** — each round, chosen clients run at `slow_factor`×
  the nominal per-step time (`slow=<k-or-p>[:factor]`; exactly-k or
  Bernoulli-p victims, like corruption). A nominal inner step takes
  `step_time_s` SIMULATED seconds, so client k needs
  `steps * step_time_s * speed_k` simulated seconds for its local work.
  On its own the axis only produces tail-latency telemetry; combined
  with a round deadline (`--round-deadline`, engine/config.py) the
  injector converts each client's speed into the inner-step budget it
  can afford before the deadline — ragged local work inside the round
  program (engine/steps.py), partial updates instead of a stalled
  cohort (docs/FAULT.md §Heterogeneity).

And one CHURN axis (fleet availability, virtual-client populations):

* **churn** — virtual clients leave and rejoin the AVAILABLE POOL per
  outer loop (`churn=<p>[:mean_absence]`): each loop, every client
  independently begins an absence with probability `churn_p`, and an
  absence begun at loop s lasts a geometric number of loops with mean
  `churn_mean_absence` (the phone that goes offline for a while, not
  the one that misses a single exchange — that is `dropout`). The
  cohort sampler (clients/cohort.py) draws only from the available
  pool, so churn composes with every per-round axis: an absent client
  is simply never sampled, while a sampled client can still drop,
  straggle, lie, or run slow. `availability(n_virtual, nloop)` is pure
  in (seed, nloop) — it re-derives every in-flight absence from the
  per-loop departure draws, no state threaded across calls — so
  crashed+resumed runs see the identical pool. The axis only exists
  over a virtual population (the engine rejects churn plans without
  `--virtual-clients`: a fixed cross-silo cohort has no pool to leave).

And one STORAGE axis (the disk, not the wire):

* **storage** — each chunk/stream I/O op faults independently with
  probability `storage_p` (`storage=<p>:<mode>[:strength]`), through
  the fault-pluggable shim in fault/io.py: `bitrot` flips bits in the
  bytes a read returns, `torn` truncates them (both read-side — disk
  intact, so the checksum layer in clients/store.py detects and a
  re-read heals), `ioerror`/`enospc` raise transient OSErrors absorbed
  by bounded retry. Draws are pure in (seed fold, direction, op
  ordinal) rather than the round cursor: which I/O ops exist depends
  on cache and residency state, so the axis is deterministic per
  execution, not replay-pure like the wire axes (docs/FAULT.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Tuple

import numpy as np


class InjectedCrash(RuntimeError):
    """A planned crash point fired (see FaultPlan.crashes)."""


# Corruption-mode codes, shared with the on-device application
# (consensus/robust.py apply_corruption's lax.switch branch order).
# 0 is reserved for "no corruption this round".
CORRUPT_MODES = {"scale": 1, "signflip": 2, "nan_burst": 3, "gauss": 4}

# Storage fault modes (fault/io.py StorageFaultShim). bitrot/torn corrupt
# the bytes a READ returns (the file on disk stays intact, so a verified
# re-read heals); ioerror/enospc raise transient OSErrors on reads and
# writes, absorbed by the bounded retry in the disk-facing callers.
STORAGE_MODES = ("bitrot", "torn", "ioerror", "enospc")

# THE seed-fold registry: every independently-seeded schedule axis folds
# `base_seed + SEED_FOLDS[axis]` into its SeedSequence, so adding one
# axis to a plan perturbs none of the others' draws. These offsets used
# to be scattered magic numbers (+1 straggler, +2 corruption, +3 speed)
# across this file; any new axis MUST claim its fold here — two axes
# sharing an offset would draw correlated schedules silently (the
# distinctness is regression-tested in tests/test_clients.py). "cohort"
# is reserved for the virtual-client cohort sampler (clients/cohort.py),
# which rides the same registry even though its base seed is the
# separate `--cohort-seed`: an operator pointing both seeds at the same
# value must still get independent cohort and dropout draws.
SEED_FOLDS = {
    "dropout": 0,
    "straggler": 1,
    "corruption": 2,
    "speed": 3,
    "cohort": 4,
    "churn": 5,
    "storage": 6,
}


def fold_seed(base: int, axis: str) -> int:
    """`base` folded for one schedule axis (masked to SeedSequence range)."""
    return (base + SEED_FOLDS[axis]) & 0x7FFFFFFF


@dataclasses.dataclass(frozen=True)
class CrashPoint:
    """Crash AFTER the consensus exchange of round `(nloop, gid, nadmm)`.

    The boundary is chosen so a crashed run holds exactly the state an
    outer-loop checkpoint would capture mid-flight: resume restarts the
    interrupted outer loop from the last checkpoint and deterministically
    replays the rounds before the crash point (docs/FAULT.md).
    """

    nloop: int
    gid: int
    nadmm: int

    def key(self) -> str:
        return f"{self.nloop}_{self.gid}_{self.nadmm}"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A replayable chaos schedule (all faults pure in seed + cursor)."""

    seed: int = 0
    dropout_p: float = 0.0
    straggler_p: float = 0.0
    straggler_delay_s: float = 0.0
    crashes: Tuple[CrashPoint, ...] = ()
    # corruption: either EXACTLY `corrupt_k` clients per round (chosen by
    # the round's rng; the Byzantine-f regime the robust combiners are
    # sized against) or each client independently with `corrupt_p`.
    # `corrupt_strength` is λ for `scale`, σ for `gauss` (ignored by
    # `signflip`/`nan_burst`).
    corrupt_p: float = 0.0
    corrupt_k: int = 0
    corrupt_mode: str = "scale"
    corrupt_strength: float = 10.0
    # compute-speed heterogeneity: either EXACTLY `slow_k` clients per
    # round (chosen by the round's rng) or each client independently
    # with `slow_p` run at `slow_factor`x the nominal per-step time.
    # `step_time_s` is the SIMULATED seconds one nominal inner step
    # costs — the unit that converts a round deadline into per-client
    # step budgets (fault/injector.py step_budgets_for_round).
    slow_p: float = 0.0
    slow_k: int = 0
    slow_factor: float = 3.0
    step_time_s: float = 1.0
    # availability churn over a VIRTUAL population (module docstring):
    # each outer loop a client begins an absence with probability
    # `churn_p`; the absence lasts a geometric number of loops with mean
    # `churn_mean_absence` (>= 1 — an absence shorter than one loop
    # would be invisible to a per-loop pool).
    churn_p: float = 0.0
    churn_mean_absence: float = 2.0
    # storage faults (module docstring; fault/io.py): each chunk/stream
    # I/O op faults independently with `storage_p`. `storage_strength`
    # is the bit-flip count for `bitrot` (ignored by the other modes).
    # Unlike the round-cursor axes the draw is per-I/O-OP — pure in
    # (seed fold, direction, op ordinal), not in the round cursor,
    # because which ops exist depends on cache/residency state.
    storage_p: float = 0.0
    storage_mode: str = "bitrot"
    storage_strength: float = 1.0

    def __post_init__(self):
        # types FIRST, so a wrong-typed field (a JSON plan with
        # corrupt_k: 2.5 or dropout_p: "0.3") fails HERE naming the
        # field, not rounds later inside numpy with an opaque TypeError
        for name in ("seed", "corrupt_k", "slow_k"):
            v = getattr(self, name)
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"{name} must be an int, got {v!r}")
        for name in (
            "dropout_p", "straggler_p", "straggler_delay_s",
            "corrupt_p", "corrupt_strength",
            "slow_p", "slow_factor", "step_time_s",
            "churn_p", "churn_mean_absence",
            "storage_p", "storage_strength",
        ):
            v = getattr(self, name)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{name} must be a number, got {v!r}")
        for name in ("dropout_p", "straggler_p", "corrupt_p", "slow_p",
                     "churn_p", "storage_p"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.straggler_delay_s < 0:
            raise ValueError(
                f"straggler_delay_s must be >= 0, got {self.straggler_delay_s}"
            )
        if self.corrupt_k < 0:
            raise ValueError(
                f"corrupt_k must be >= 0, got {self.corrupt_k}"
            )
        if self.corrupt_mode not in CORRUPT_MODES:
            raise ValueError(
                f"corrupt_mode must be one of {sorted(CORRUPT_MODES)}, "
                f"got {self.corrupt_mode!r}"
            )
        if not (
            np.isfinite(self.corrupt_strength) and self.corrupt_strength >= 0
        ):
            raise ValueError(
                f"corrupt_strength must be finite and >= 0, "
                f"got {self.corrupt_strength}"
            )
        if self.slow_k < 0:
            raise ValueError(f"slow_k must be >= 0, got {self.slow_k}")
        if not (np.isfinite(self.slow_factor) and self.slow_factor >= 1.0):
            # < 1 would be a FAST client; the axis models stragglers, and
            # a sub-nominal multiplier would silently let a deadline
            # GROW a budget past the lockstep step count
            raise ValueError(
                f"slow_factor must be finite and >= 1, got {self.slow_factor}"
            )
        if not (np.isfinite(self.step_time_s) and self.step_time_s > 0):
            raise ValueError(
                f"step_time_s must be finite and > 0, got {self.step_time_s}"
            )
        if not (
            np.isfinite(self.churn_mean_absence)
            and self.churn_mean_absence >= 1.0
        ):
            # < 1 loop would be an absence the per-loop pool never sees
            raise ValueError(
                f"churn_mean_absence must be finite and >= 1, "
                f"got {self.churn_mean_absence}"
            )
        if self.storage_mode not in STORAGE_MODES:
            raise ValueError(
                f"storage_mode must be one of {sorted(STORAGE_MODES)}, "
                f"got {self.storage_mode!r}"
            )
        if not (
            np.isfinite(self.storage_strength) and self.storage_strength > 0
        ):
            raise ValueError(
                f"storage_strength must be finite and > 0, "
                f"got {self.storage_strength}"
            )

    @property
    def has_corruption(self) -> bool:
        """Whether any round of this plan can corrupt an update."""
        return self.corrupt_p > 0.0 or self.corrupt_k > 0

    @property
    def has_heterogeneity(self) -> bool:
        """Whether any round of this plan can slow a client down."""
        return self.slow_p > 0.0 or self.slow_k > 0

    @property
    def has_churn(self) -> bool:
        """Whether any loop of this plan can remove a client from the
        available pool."""
        return self.churn_p > 0.0

    @property
    def has_storage(self) -> bool:
        """Whether any I/O op of this plan can fault (fault/io.py)."""
        return self.storage_p > 0.0

    # ------------------------------------------------------------- schedule

    def _rng(self, nloop: int, gid: int, nadmm: int) -> np.random.Generator:
        # the same SeedSequence folding as the trainer's epoch shuffles
        # (engine/trainer.py _epoch_seed): deterministic in (seed, cursor),
        # independent across rounds
        return np.random.default_rng(
            [fold_seed(self.seed, "dropout"), nloop, gid, nadmm]
        )

    def participation(
        self, n_clients: int, nloop: int, gid: int, nadmm: int
    ) -> np.ndarray:
        """`[K]` float32 mask for one consensus round: 1 = alive, 0 = dropped.

        Pure in (seed, cursor) — NOT in execution history, so a resumed
        run re-derives the identical mask for a replayed round. All-dropped
        rounds are allowed; the masked aggregation degenerates to keeping
        the previous consensus state (consensus/fedavg.py).
        """
        rng = self._rng(nloop, gid, nadmm)
        if self.dropout_p <= 0.0:
            return np.ones(n_clients, np.float32)
        return (rng.random(n_clients) >= self.dropout_p).astype(np.float32)

    def straggler_delay(self, nloop: int, gid: int, nadmm: int) -> float:
        """Host-side seconds this round's consensus stalls (0 = no straggler)."""
        if self.straggler_p <= 0.0 or self.straggler_delay_s <= 0.0:
            return 0.0
        # a separate fold from participation (SEED_FOLDS) so adding
        # stragglers to a plan does not perturb its dropout masks
        rng = np.random.default_rng(
            [fold_seed(self.seed, "straggler"), nloop, gid, nadmm]
        )
        return self.straggler_delay_s if rng.random() < self.straggler_p else 0.0

    def corruption(
        self, n_clients: int, nloop: int, gid: int, nadmm: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One round's corruption schedule: `(modes, strengths, seeds)`.

        `modes [K]` int32 (0 = clean, else CORRUPT_MODES code),
        `strengths [K]` float32, `seeds [K]` int32 (the per-client PRNG
        seed the `gauss` mode folds into its on-device noise draw).
        Pure in (seed, cursor) like the dropout masks — a separate seed
        fold (SEED_FOLDS['corruption']), so adding corruption to a plan
        perturbs neither its
        dropout masks nor its straggler schedule.
        """
        modes = np.zeros(n_clients, np.int32)
        strengths = np.full(n_clients, self.corrupt_strength, np.float32)
        seeds = np.zeros(n_clients, np.int32)
        if not self.has_corruption:
            return modes, strengths, seeds
        rng = np.random.default_rng(
            [fold_seed(self.seed, "corruption"), nloop, gid, nadmm]
        )
        if self.corrupt_k > 0:
            if self.corrupt_k > n_clients:
                # same error the FaultInjector raises at construction —
                # direct plan users must not get a silent every-client
                # cap where the engine path gets a ValueError
                raise ValueError(
                    f"corrupt_k={self.corrupt_k} exceeds "
                    f"n_clients={n_clients}: cannot corrupt more clients "
                    "than exist per round"
                )
            chosen = rng.choice(n_clients, size=self.corrupt_k, replace=False)
            hit = np.zeros(n_clients, bool)
            hit[chosen] = True
        else:
            hit = rng.random(n_clients) < self.corrupt_p
        modes[hit] = CORRUPT_MODES[self.corrupt_mode]
        seeds[:] = rng.integers(0, 2**31 - 1, n_clients, dtype=np.int64)
        return modes, strengths, seeds

    def client_speeds(
        self, n_clients: int, nloop: int, gid: int, nadmm: int
    ) -> np.ndarray:
        """`[K]` float32 per-step TIME multipliers (1.0 = nominal speed).

        A slow client's inner step takes `slow_factor * step_time_s`
        simulated seconds instead of `step_time_s`. Pure in (seed,
        cursor) like every other axis — a separate seed fold
        (SEED_FOLDS['speed']), so
        adding heterogeneity to a plan perturbs none of its dropout
        masks, straggler schedule, or corruption draws.
        """
        speeds = np.ones(n_clients, np.float32)
        if not self.has_heterogeneity:
            return speeds
        rng = np.random.default_rng(
            [fold_seed(self.seed, "speed"), nloop, gid, nadmm]
        )
        if self.slow_k > 0:
            if self.slow_k > n_clients:
                # same contract as corruption: direct plan users must not
                # get a silent every-client cap where the engine path
                # (FaultInjector) gets a ValueError
                raise ValueError(
                    f"slow_k={self.slow_k} exceeds n_clients={n_clients}: "
                    "cannot slow more clients than exist per round"
                )
            chosen = rng.choice(n_clients, size=self.slow_k, replace=False)
            hit = np.zeros(n_clients, bool)
            hit[chosen] = True
        else:
            hit = rng.random(n_clients) < self.slow_p
        speeds[hit] = self.slow_factor
        return speeds

    def availability(self, n_virtual: int, nloop: int) -> np.ndarray:
        """`[N]` float32 pool mask for outer loop `nloop`: 1 = available.

        Churn is a per-LOOP renewal process: at every loop `s` each
        client independently begins an absence with probability
        `churn_p`, whose duration (in loops) is drawn geometric with
        mean `churn_mean_absence` from the SAME per-loop rng — so a
        client is absent at loop `t` iff some departure at `s <= t` is
        still in flight (`s + duration > t`). Overlapping absences
        union. Pure in (seed, nloop) like every other axis — the
        in-flight absences are RE-DERIVED from the per-loop draws on
        every call, no state across calls — on its own seed fold
        (SEED_FOLDS['churn']), so adding churn to a plan perturbs none
        of the per-round schedules. Re-deriving costs O(nloop · N);
        the trainer queries once per loop and the scoreboard once per
        experiment, both far from hot.
        """
        avail = np.ones(n_virtual, np.float32)
        if not self.has_churn:
            return avail
        absent = np.zeros(n_virtual, bool)
        for s in range(nloop + 1):
            rng = np.random.default_rng(
                [fold_seed(self.seed, "churn"), s]
            )
            departed = rng.random(n_virtual) < self.churn_p
            # geometric(p) >= 1 with mean 1/p = churn_mean_absence; the
            # duration draw happens UNCONDITIONALLY so the departure
            # mask never changes which stream positions later loops read
            durations = rng.geometric(1.0 / self.churn_mean_absence,
                                      n_virtual)
            absent |= departed & (s + durations > nloop)
        avail[absent] = 0.0
        return avail

    def crash_at(self, nloop: int, gid: int, nadmm: int) -> CrashPoint | None:
        for c in self.crashes:
            if (c.nloop, c.gid, c.nadmm) == (nloop, gid, nadmm):
                return c
        return None

    # -------------------------------------------------------- serialization

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["crashes"] = [dataclasses.asdict(c) for c in self.crashes]
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """Parse a `to_json` document — STRICTLY.

        Unknown keys are rejected by name instead of TypeError-ing (or,
        worse, silently building a plan that ignores the typo'd field a
        chaos experiment thought it configured); out-of-range values
        surface as `__post_init__`'s per-field ValueErrors.
        """
        d = json.loads(text)
        if not isinstance(d, dict):
            raise ValueError(
                f"fault-plan JSON must be an object, got {type(d).__name__}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(
                f"unknown fault-plan field(s) {unknown} in JSON plan; "
                f"valid fields: {sorted(known)}"
            )
        crash_keys = {"nloop", "gid", "nadmm"}
        crashes = []
        crash_items = d.pop("crashes", [])
        if not isinstance(crash_items, list):
            raise ValueError(
                f"crashes must be a list of crash-point objects, got "
                f"{type(crash_items).__name__}"
            )
        for i, c in enumerate(crash_items):
            if not isinstance(c, dict) or set(c) != crash_keys:
                raise ValueError(
                    f"crashes[{i}] must be an object with exactly the keys "
                    f"{sorted(crash_keys)}, got {c!r}"
                )
            for k in sorted(crash_keys):
                v = c[k]
                # strict: int(1.9) would silently crash the wrong round
                if isinstance(v, bool) or not isinstance(v, int):
                    raise ValueError(
                        f"crashes[{i}].{k} must be an int, got {v!r}"
                    )
            crashes.append(CrashPoint(**{k: c[k] for k in crash_keys}))
        return cls(crashes=tuple(crashes), **d)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Build a plan from a CLI `--fault-plan` value.

        Accepts (1) a path to a JSON file written by `to_json`, or (2) an
        inline spec of comma-separated `key=value` pairs:

            seed=1,dropout=0.3,straggler=0.1:0.5,crash=0:1:2,corrupt=1:scale:10

        where `straggler=p:delay_s`, each `crash=nloop:gid:nadmm` names
        one crash point (repeatable), and `corrupt=<k-or-p>:<mode>[:strength]`
        schedules update corruption: an INT first part corrupts exactly
        that many clients per round (`corrupt_k`), a FLOAT is the
        per-client probability (`corrupt_p`); mode is one of
        scale|signflip|nan_burst|gauss. `slow=<k-or-p>[:factor]` (same
        int-vs-float convention) schedules the compute-speed axis, and
        `step_time=<seconds>` sets the simulated nominal per-step time.
        `churn=<p>[:mean_absence]` schedules per-outer-loop availability
        churn over a virtual population (p = per-loop departure
        probability, mean_absence = mean absence length in loops).
        `storage=<p>:<bitrot|torn|ioerror|enospc>[:strength]` schedules
        per-I/O-op storage faults (fault/io.py; strength = bit-flip
        count for bitrot).
        """
        if os.path.exists(spec):
            with open(spec) as f:
                return cls.from_json(f.read())
        kw: dict = {}
        crashes = []
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"bad fault-plan item {item!r} (want key=value); "
                    f"note {spec!r} is also not an existing file path"
                )
            key, val = item.split("=", 1)
            key = key.strip()
            if key == "seed":
                kw["seed"] = int(val)
            elif key == "dropout":
                kw["dropout_p"] = float(val)
            elif key == "straggler":
                p, _, delay = val.partition(":")
                kw["straggler_p"] = float(p)
                kw["straggler_delay_s"] = float(delay) if delay else 1.0
            elif key == "crash":
                parts = val.split(":")
                if len(parts) != 3:
                    raise ValueError(
                        f"crash point {val!r} must be nloop:gid:nadmm"
                    )
                crashes.append(CrashPoint(*(int(p) for p in parts)))
            elif key == "corrupt":
                parts = val.split(":")
                if not 2 <= len(parts) <= 3:
                    raise ValueError(
                        f"corrupt spec {val!r} must be "
                        "<k-or-p>:<mode>[:strength]"
                    )
                amount = parts[0]
                if "." in amount or "e" in amount.lower():
                    kw["corrupt_p"] = float(amount)
                else:
                    kw["corrupt_k"] = int(amount)
                kw["corrupt_mode"] = parts[1]
                if len(parts) == 3:
                    kw["corrupt_strength"] = float(parts[2])
            elif key == "slow":
                parts = val.split(":")
                if not 1 <= len(parts) <= 2:
                    raise ValueError(
                        f"slow spec {val!r} must be <k-or-p>[:factor]"
                    )
                amount = parts[0]
                if "." in amount or "e" in amount.lower():
                    kw["slow_p"] = float(amount)
                else:
                    kw["slow_k"] = int(amount)
                if len(parts) == 2:
                    kw["slow_factor"] = float(parts[1])
            elif key == "step_time":
                kw["step_time_s"] = float(val)
            elif key == "churn":
                parts = val.split(":")
                if not 1 <= len(parts) <= 2:
                    raise ValueError(
                        f"churn spec {val!r} must be <p>[:mean_absence]"
                    )
                kw["churn_p"] = float(parts[0])
                if len(parts) == 2:
                    kw["churn_mean_absence"] = float(parts[1])
            elif key == "storage":
                parts = val.split(":")
                if not 2 <= len(parts) <= 3:
                    raise ValueError(
                        f"storage spec {val!r} must be "
                        "<p>:<mode>[:strength]"
                    )
                kw["storage_p"] = float(parts[0])
                kw["storage_mode"] = parts[1]
                if len(parts) == 3:
                    kw["storage_strength"] = float(parts[2])
            else:
                raise ValueError(
                    f"unknown fault-plan key {key!r} "
                    "(have seed, dropout, straggler, crash, corrupt, "
                    "slow, step_time, churn, storage)"
                )
        return cls(crashes=tuple(crashes), **kw)
