"""Runtime side of fault injection: plan lookup + fire-once crash points.

The `FaultPlan` is pure; the `FaultInjector` is the small stateful shim
between it and the `Trainer`. Masks and delays pass straight through. The
one piece of state is crash arming: a crash point must fire exactly once
per *experiment* (not once per process), or the resumed run would march
into the same planned crash again and never finish. Fired points are
recorded as sentinel files under the checkpoint directory — the same
durability domain as the checkpoints the resume path reads — so a fresh
process (`--resume auto`) skips them. Without a state dir (no
checkpointing configured) the record is process-local, which still
guarantees single-fire for in-process restarts but makes a planned crash
of a non-checkpointing run fatal — loudly, by design: there is nothing to
resume from.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Set

import numpy as np

from federated_pytorch_test_tpu.fault.plan import FaultPlan, InjectedCrash


def step_budgets(
    speeds: np.ndarray,
    step_time_s: float,
    total_steps: int,
    deadline_s: float,
) -> np.ndarray:
    """Inner-step budgets under a round deadline (int32, `speeds`' shape).

    Each client can afford `floor(deadline / (step_time_s * speed))` of
    its `total_steps` lockstep inner steps before the deadline, clipped
    to `[0, total_steps]`. THE one definition of the conversion: the
    trainer's budget rows, `step_budgets_for_round`, and the scoreboard
    (`injected_summary`) all call it — a drifted copy would let the
    compiled round run different budgets than the `step_budget` stream
    and the `deadline_misses=` scoreboard report, silently breaking the
    resume-proof same-totals guarantee.

    The quotient is computed in float64 with a tiny absolute epsilon
    before the floor: a deadline set to EXACTLY n steps' time must
    yield budget n, not n-1 — with a non-representable decimal
    step_time (0.3, 0.9/0.3 = 2.99999...) a bare floor would falsely
    flag nominal-speed clients as deadline misses and break the
    all-full-budget bitwise-identity regime (docs/FAULT.md).
    """
    q = deadline_s / (step_time_s * speeds.astype(np.float64))
    return np.clip(np.floor(q + 1e-9), 0, total_steps).astype(np.int32)


class FaultInjector:
    """Per-run fault dispenser for one `FaultPlan`."""

    def __init__(
        self,
        plan: FaultPlan,
        n_clients: int,
        state_dir: Optional[str] = None,
        storage=None,
    ):
        self.plan = plan
        self.n_clients = n_clients
        # the storage-axis shim (fault/io.py StorageFaultShim), when the
        # plan schedules one: the trainer builds it once and hands the
        # SAME instance to the ClientStore, the metrics sink, and this
        # injector — the injector only reads its `injected` counter for
        # the scoreboard (`storage_faults=`)
        self.storage = storage
        if plan.corrupt_k > n_clients:
            # the plan alone cannot know K; validated here, where it
            # meets the run — silently capping would corrupt EVERY
            # client every round and overwhelm any trimmed-f defense
            # while the operator believes k were configured (the same
            # silently-wrong-plan class the strict JSON loader rejects)
            raise ValueError(
                f"fault plan's corrupt_k={plan.corrupt_k} exceeds "
                f"n_clients={n_clients}: cannot corrupt more clients "
                "than exist per round"
            )
        if plan.slow_k > n_clients:
            raise ValueError(
                f"fault plan's slow_k={plan.slow_k} exceeds "
                f"n_clients={n_clients}: cannot slow more clients "
                "than exist per round"
            )
        self.state_dir = os.path.abspath(state_dir) if state_dir else None
        # sentinels are scoped to THIS plan: a different plan sharing the
        # checkpoint dir (new seed, new crash schedule) must not have its
        # crash points suppressed by a previous experiment's leftovers
        self._plan_tag = hashlib.md5(plan.to_json().encode()).hexdigest()[:8]
        self._fired: Set[str] = set()

    @property
    def plan_tag(self) -> str:
        """8-hex digest identifying THIS plan — the same scope the crash
        sentinels use. The JSONL metric stream stamps it into its header
        (obs/sinks.py): a resumed run may only splice onto a stream whose
        faults were drawn from the identical plan, or the replayed and
        re-run halves of the series would disagree about who dropped when.
        """
        return self._plan_tag

    def mask(self, nloop: int, gid: int, nadmm: int) -> np.ndarray:
        """`[K]` float32 participation mask for one consensus round."""
        return self.plan.participation(self.n_clients, nloop, gid, nadmm)

    def straggler_delay(self, nloop: int, gid: int, nadmm: int) -> float:
        return self.plan.straggler_delay(nloop, gid, nadmm)

    # ------------------------------------------------- fused-round batches

    def masks_for_round(self, nloop: int, gid: int, nadmm: int) -> np.ndarray:
        """`[nadmm, K]` participation masks for a whole partition round.

        The fused round program (engine/steps.py build_round_fn) consumes
        every consensus iteration's mask as scan inputs in one dispatch;
        each row is exactly `mask(nloop, gid, a)` — pure in the plan seed
        and round cursor, so fused and unfused chaos runs replay the same
        dropout schedule.
        """
        return np.stack(
            [self.mask(nloop, gid, a) for a in range(nadmm)]
        ).astype(np.float32)

    @property
    def has_corruption(self) -> bool:
        """Whether the plan schedules update corruption at all — the
        static build flag: corruption-free runs compile the exact
        pre-corruption consensus programs (engine/steps.py)."""
        return self.plan.has_corruption

    def corruption_for_round(self, nloop: int, gid: int, nadmm: int):
        """`([nadmm, K] modes, [nadmm, K] strengths, [nadmm, K] seeds)`.

        The whole round's corruption schedule, stacked like
        `masks_for_round` so the fused round program consumes each
        consensus iteration's row as scan inputs — no host round-trips,
        and fused/unfused chaos runs replay the identical corruption.
        """
        rows = [
            self.plan.corruption(self.n_clients, nloop, gid, a)
            for a in range(nadmm)
        ]
        return (
            np.stack([r[0] for r in rows]),
            np.stack([r[1] for r in rows]),
            np.stack([r[2] for r in rows]),
        )

    @property
    def has_heterogeneity(self) -> bool:
        """Whether the plan schedules slow clients at all (the
        tail-latency telemetry gate: homogeneous, deadline-free runs
        record no client_time series — engine/trainer.py)."""
        return self.plan.has_heterogeneity

    @property
    def has_churn(self) -> bool:
        """Whether the plan churns the available pool at all (virtual
        populations only — the Trainer rejects churn plans without
        `--virtual-clients`, since a fixed cross-silo cohort has no
        pool to leave)."""
        return self.plan.has_churn

    def availability(self, nloop: int) -> np.ndarray:
        """`[N]` float32 pool mask of outer loop `nloop` (1 = available)
        — fault/plan.py `availability`, pure in (plan seed, nloop).
        The last loop's mask is memoized (purity makes the cache
        transparent): re-deriving costs O(nloop · N), and the trainer
        touches each loop's pool twice (the `availability` record and
        the sampler's draw). Callers must treat the array as
        read-only."""
        cached = getattr(self, "_avail_memo", None)
        if cached is not None and cached[0] == nloop:
            return cached[1]
        avail = self.plan.availability(self.n_clients, nloop)
        self._avail_memo = (nloop, avail)
        return avail

    def speeds_for_round(self, nloop: int, gid: int, nadmm: int) -> np.ndarray:
        """`[nadmm, K]` per-step time multipliers for a whole partition
        round, stacked like `masks_for_round` — pure in (plan seed,
        cursor), so fused/unfused/resumed runs replay identical speeds.
        """
        return np.stack(
            [
                self.plan.client_speeds(self.n_clients, nloop, gid, a)
                for a in range(nadmm)
            ]
        )

    def step_budgets_for_round(
        self,
        nloop: int,
        gid: int,
        nadmm: int,
        total_steps: int,
        deadline_s: float,
    ) -> np.ndarray:
        """`[nadmm, K]` int32 inner-step budgets under a round deadline.

        Each client can afford `floor(deadline / (step_time_s * speed))`
        of its `total_steps` lockstep inner steps before the deadline —
        clipped to `[0, total_steps]`. A budget BELOW total_steps is a
        deadline miss (the client contributes a partial update); a ZERO
        budget means not even one step finished in time, so no report
        exists and the client is excluded from that exchange like a
        dropped one (engine/trainer.py, docs/FAULT.md §Heterogeneity).
        """
        return step_budgets(
            self.speeds_for_round(nloop, gid, nadmm),
            self.plan.step_time_s,
            total_steps,
            deadline_s,
        )

    def injected_summary(
        self,
        nloops: int,
        group_order,
        nadmm: int,
        exchanges: bool = True,
        total_steps: int | None = None,
        deadline_s: "float | dict | None" = None,
        cohort=None,
        visits: "dict | None" = None,
    ) -> dict:
        """Fault counts over the experiment's full round schedule.

        Pure in the plan (every fault is a function of seed + cursor), so
        a crashed-and-resumed run reports the same totals as an
        uninterrupted one — no execution-history counters to lose.
        `exchanges=False` zeroes the exchange-bound kinds — dropout,
        corruption, AND stragglers (the coordinator stalls waiting out a
        slow client's exchange, so the trainer serves no stall without
        one) — for strategy-'none' runs, which hold no consensus
        exchange to apply them to; only the crash schedule fires either
        way. Feeds the CLI's end-of-run `# faults injected:` line.

        With `deadline_s` (and the round's `total_steps`) the scoreboard
        grows the deadline rows: `deadline_misses` counts every
        (exchange, client) whose step budget fell short of the lockstep
        step count, and `capped_stalls` every straggler stall the
        deadline capped (the host serves `min(delay, deadline)` —
        engine/trainer.py). Both are pure in the plan + deadline, so a
        resumed run prints the same totals. `deadline_s` may be a float
        (fixed `--round-deadline S`) or a `{(nloop, gid): seconds}`
        mapping — the auto-deadline policy's per-round decisions
        (engine/trainer.py `_deadline_for`): pure given the recorded
        decision history, which the stream replay restores on resume.

        Churn plans add a `churned` row: total client-loop ABSENCES over
        the experiment (how many (client, loop) pairs sat out of the
        available pool) — population-level by design, since churn acts
        on the pool the sampler draws from, not on sampled clients.

        Cohort mode (clients/): `cohort` is the sampler's pure
        `nloop -> [C] virtual ids` schedule — only faults landing on a
        loop's SAMPLED clients count (an unsampled client's scheduled
        dropout was never injected into any exchange). The sampler's
        purity keeps the totals resume-proof exactly like the plan's.

        Adaptive group schedules (exchange/schedule.py): `visits` is the
        `{nloop: [visited gids]}` mapping of rounds that actually RAN —
        a fault scheduled at a group the scheduler never picked (or
        skipped) was never injected. Pure given the recorded
        `group_schedule` decision history, which the stream replay
        restores on resume — same purity story as `deadline_s` dicts.
        None keeps the fixed `group_order` schedule.
        """
        drops = stragglers = crashes = corruptions = 0
        deadline_misses = capped_stalls = churned = 0
        for nloop in range(nloops):
            ids = cohort(nloop) if cohort is not None else None
            if self.plan.has_churn:
                avail = self.plan.availability(self.n_clients, nloop)
                churned += int(avail.size - avail.sum())
            loop_gids = (
                visits.get(nloop, []) if visits is not None else group_order
            )
            for gid in loop_gids:
                dl = (
                    deadline_s.get((nloop, gid))
                    if isinstance(deadline_s, dict)
                    else deadline_s
                )
                for a in range(nadmm):
                    if exchanges:
                        mask = self.plan.participation(
                            self.n_clients, nloop, gid, a
                        )
                        if ids is not None:
                            mask = mask[ids]
                        drops += int(mask.size - mask.sum())
                        modes, _, _ = self.plan.corruption(
                            self.n_clients, nloop, gid, a
                        )
                        if ids is not None:
                            modes = modes[ids]
                        corruptions += int((modes != 0).sum())
                        delay = self.plan.straggler_delay(nloop, gid, a)
                        if delay > 0:
                            stragglers += 1
                            if dl is not None and delay > dl:
                                capped_stalls += 1
                        if dl is not None and total_steps:
                            speeds = self.plan.client_speeds(
                                self.n_clients, nloop, gid, a
                            )
                            if ids is not None:
                                speeds = speeds[ids]
                            budgets = step_budgets(
                                speeds,
                                self.plan.step_time_s,
                                total_steps,
                                dl,
                            )
                            deadline_misses += int(
                                (budgets < total_steps).sum()
                            )
                    if self.plan.crash_at(nloop, gid, a) is not None:
                        crashes += 1
        counts = {
            "drops": drops,
            "stragglers": stragglers,
            "crashes": crashes,
            "corruptions": corruptions,
        }
        if deadline_s is not None:
            counts["deadline_misses"] = deadline_misses
            counts["capped_stalls"] = capped_stalls
        if self.plan.has_churn:
            counts["churned"] = churned
        if self.storage is not None:
            # unlike every row above this one is NOT pure in the plan:
            # which I/O ops exist depends on cache/residency state, so a
            # resumed run reports the injections of ITS OWN process (the
            # per-op draws are still deterministic — fault/io.py)
            counts["storage_faults"] = int(self.storage.injected)
        return counts

    def straggler_delays_for_round(
        self, nloop: int, gid: int, nadmm: int
    ) -> list:
        """Per-consensus-iteration straggler delays `[nadmm]` (seconds).

        A fused round is one device program, so the host cannot stall
        BETWEEN consensus iterations; the trainer serves the round's
        total delay in one stall (the coordinator waiting out every slow
        client before declaring the round) while recording each
        iteration's contribution separately for the timing series.
        """
        return [self.straggler_delay(nloop, gid, a) for a in range(nadmm)]

    # ---------------------------------------------------------- crash points

    def will_crash(self, nloop: int, gid: int, nadmm: int) -> bool:
        """Whether `maybe_crash` WOULD fire at this cursor (no side effects).

        The fused round serves its straggler stalls up-front; a planned
        crash at iteration c means the unfused replay never reaches the
        stalls of iterations > c, so the fused path truncates there —
        this is the query that respects the fire-once sentinels (an
        already-fired point stalls normally on the resumed run, exactly
        like the unfused replay).
        """
        point = self.plan.crash_at(nloop, gid, nadmm)
        return point is not None and not self._already_fired(point.key())

    def _sentinel(self, key: str) -> Optional[str]:
        if self.state_dir is None:
            return None
        return os.path.join(
            self.state_dir, f".crash_fired_{self._plan_tag}_{key}"
        )

    def _already_fired(self, key: str) -> bool:
        if key in self._fired:
            return True
        path = self._sentinel(key)
        return path is not None and os.path.exists(path)

    def maybe_crash(self, nloop: int, gid: int, nadmm: int) -> None:
        """Raise `InjectedCrash` if the plan names this round — once only.

        The sentinel is written BEFORE raising: if the write itself fails,
        the crash does not fire (a chaos plan must never be able to wedge
        an experiment into a crash loop).
        """
        point = self.plan.crash_at(nloop, gid, nadmm)
        if point is None:
            return
        key = point.key()
        if self._already_fired(key):
            return
        path = self._sentinel(key)
        if path is not None:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(path, "w") as f:
                f.write("fired\n")
        self._fired.add(key)
        raise InjectedCrash(
            f"planned crash at round (nloop={nloop}, gid={gid}, "
            f"nadmm={nadmm}); restart with resume='auto' to recover from "
            "the latest checkpoint"
        )
