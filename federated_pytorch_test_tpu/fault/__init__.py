"""Fault tolerance: replayable failure injection and crash recovery.

The subsystem has three parts, stitched into the engine by `Trainer`:

* `FaultPlan` (plan.py) — a deterministic, seeded schedule of client
  dropouts, straggler delays, crash points, and update-corruption
  events; every fault is a pure function of (seed, round cursor), so
  chaos runs replay exactly;
* `FaultInjector` (injector.py) — the runtime shim: mask/delay/corruption
  lookup plus fire-once crash sentinels persisted next to the
  checkpoints;
* participation-masked aggregation lives with the consensus math it
  guards (consensus/fedavg.py, consensus/admm.py — the `mask` argument),
  and the Byzantine-robust combiners + auto-quarantine that defend
  against corruption live in consensus/robust.py.

See docs/FAULT.md for the replay/resume guarantees.
"""

from federated_pytorch_test_tpu.fault.injector import (
    FaultInjector,
    step_budgets,
)
from federated_pytorch_test_tpu.fault.plan import (
    CORRUPT_MODES,
    SEED_FOLDS,
    CrashPoint,
    FaultPlan,
    InjectedCrash,
    fold_seed,
)

__all__ = [
    "CORRUPT_MODES",
    "SEED_FOLDS",
    "CrashPoint",
    "FaultInjector",
    "FaultPlan",
    "InjectedCrash",
    "fold_seed",
    "step_budgets",
]
