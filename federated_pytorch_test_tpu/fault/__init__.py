"""Fault tolerance: replayable failure injection and crash recovery.

The subsystem has three parts, stitched into the engine by `Trainer`:

* `FaultPlan` (plan.py) — a deterministic, seeded schedule of client
  dropouts, straggler delays, crash points, and update-corruption
  events; every fault is a pure function of (seed, round cursor), so
  chaos runs replay exactly;
* `FaultInjector` (injector.py) — the runtime shim: mask/delay/corruption
  lookup plus fire-once crash sentinels persisted next to the
  checkpoints;
* participation-masked aggregation lives with the consensus math it
  guards (consensus/fedavg.py, consensus/admm.py — the `mask` argument),
  and the Byzantine-robust combiners + auto-quarantine that defend
  against corruption live in consensus/robust.py;
* the STORAGE axis (io.py) — checksums, the fault-pluggable I/O shim
  the ClientStore/checkpoint/stream byte paths route through, and the
  bounded disk retry; scrub.py is the engine-import-free `scrub` CLI
  verb that walks a store/checkpoint dir verifying and repairing;
* the CHAOS HARNESS (chaos.py) — the `chaos` CLI verb: a seeded fuzzer
  composing fault-plan axes (PLAN_DOMAINS) with engine knobs
  (engine.KNOB_DOMAINS), the crash+resume invariant oracle, and the
  delta-debugging shrinker that minimizes violating plans into
  self-contained repro bundles.

See docs/FAULT.md for the replay/resume guarantees.
"""

from federated_pytorch_test_tpu.fault.chaos import (
    AXES,
    INVARIANTS,
    KNOB_GROUPS,
    PLAN_DOMAINS,
    ChaosCase,
    ChaosPlanGenerator,
    load_repro_bundle,
    norm_stream_records,
    run_case,
    shrink,
    write_repro_bundle,
)
from federated_pytorch_test_tpu.fault.injector import (
    FaultInjector,
    step_budgets,
)
from federated_pytorch_test_tpu.fault.io import (
    CHECKSUM_ALG,
    IntegrityError,
    StorageFaultShim,
    checksum,
    retry_io,
    stamp_crc,
    storage_shim_for,
    verify_crc,
    verify_digest,
)
from federated_pytorch_test_tpu.fault.plan import (
    CORRUPT_MODES,
    SEED_FOLDS,
    STORAGE_MODES,
    CrashPoint,
    FaultPlan,
    InjectedCrash,
    fold_seed,
)

__all__ = [
    "AXES",
    "CHECKSUM_ALG",
    "CORRUPT_MODES",
    "INVARIANTS",
    "KNOB_GROUPS",
    "PLAN_DOMAINS",
    "SEED_FOLDS",
    "STORAGE_MODES",
    "ChaosCase",
    "ChaosPlanGenerator",
    "CrashPoint",
    "FaultInjector",
    "FaultPlan",
    "InjectedCrash",
    "IntegrityError",
    "StorageFaultShim",
    "checksum",
    "fold_seed",
    "load_repro_bundle",
    "norm_stream_records",
    "retry_io",
    "run_case",
    "shrink",
    "stamp_crc",
    "step_budgets",
    "storage_shim_for",
    "verify_crc",
    "verify_digest",
    "write_repro_bundle",
]
