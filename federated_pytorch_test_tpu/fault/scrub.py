"""`scrub` CLI verb: offline storage-integrity walk + repair.

    python -m federated_pytorch_test_tpu scrub <dir> [--repair] [--json PATH]

Walks a client-store / checkpoint directory, verifies every
manifest-referenced chunk file against the checksum its manifest
recorded (clients/store.py v2 manifests, fault/io.py digests), and
either REPORTS — exit 1, naming every bad file — or REPAIRS
(`--repair`), mirroring the store's runtime ladder offline:

1. an older on-disk version of the same chunk that still verifies (or,
   for legacy digest-less files, still parses) is adopted: every
   manifest referencing the corrupt file is rewritten to the prior
   version, its digest recomputed, the manifest self-CRC re-stamped;
2. otherwise the chunk id is DROPPED from the manifests — the store
   re-initializes those rows pristine by construction at next load
   (`_materialize`), which is the same rows a never-spilled run holds;
3. the corrupt file itself is renamed `<name>.corrupt` so nothing can
   ever re-adopt it.

A corrupt MANIFEST (unparsable, or a parsable v2 document failing its
self-CRC) is reported; with `--repair` it is quarantined the same way,
so the trainer's restore loop falls back to the previous intact step.
Legacy v1 manifests and digest-less chunk files are accepted read-only
(the format contract) — scrub still parse-checks the files and counts
them separately, but absence of a digest is not a problem.

Engine-import-free by the report/watch rule (__main__.py): only stdlib,
numpy, fault/io.py and clients/store.py helpers — no accelerator
backend is ever initialized, so scrubbing a dead host's store works.
"""

from __future__ import annotations

import argparse
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from federated_pytorch_test_tpu.fault.io import (
    IntegrityError,
    checksum,
    stamp_crc,
    verify_crc,
    verify_digest,
)

_MANIFEST_RE = re.compile(r"^manifest_step_(\d+)\.json$")
_CHUNK_RE = re.compile(r"^chunk_(\d{6})_v(\d{8})\.npz$")


def _parse_manifest(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """`(manifest, None)` or `(None, reason)` — a v2 manifest must pass
    its self-CRC (clients/store.py `load` applies the same gate)."""
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable manifest: {e}"
    if not isinstance(manifest, dict):
        return None, "manifest is not a JSON object"
    version = manifest.get("version")
    if int(version or 0) >= 2 and not verify_crc(manifest):
        return None, "manifest failed its self-checksum (bit rot)"
    return manifest, None


def _chunk_ok(path: str, digest: Optional[dict]) -> Optional[str]:
    """None if the chunk file is intact, else the failure reason.

    With a digest the bytes are authoritative; without one (legacy) the
    file must at least parse as the npz the store would read."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        return f"unreadable: {e}"
    if digest is not None:
        if not verify_digest(data, digest):
            return "failed checksum verification"
        return None
    from federated_pytorch_test_tpu.clients.store import _npz_from_bytes

    try:
        _npz_from_bytes(data, path)
    except IntegrityError as e:
        return f"legacy (digest-less) chunk does not parse: {e}"
    return None


def _quarantine(path: str) -> None:
    os.replace(path, path + ".corrupt")


def _rewrite_manifest(path: str, manifest: dict) -> None:
    """Atomic manifest rewrite; v2+ documents get a fresh self-CRC."""
    manifest.pop("crc", None)
    if int(manifest.get("version") or 0) >= 2:
        text = stamp_crc(manifest)
    else:
        text = json.dumps(manifest)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _find_prior(root: str, fname: str) -> Optional[str]:
    """The newest OLDER on-disk version of `fname`'s chunk id, or None."""
    m = _CHUNK_RE.match(fname)
    if m is None:
        return None
    cid, seq = int(m.group(1)), int(m.group(2))
    priors: List[Tuple[int, str]] = []
    for entry in os.listdir(root):
        pm = _CHUNK_RE.match(entry)
        if pm and int(pm.group(1)) == cid and int(pm.group(2)) != seq:
            priors.append((int(pm.group(2)), entry))
    for _, prior in sorted(priors, reverse=True):
        if _chunk_ok(os.path.join(root, prior), None) is None:
            return prior
    return None


def scrub_dir(root: str, repair: bool = False) -> dict:
    """Scrub one store/checkpoint directory; returns the report dict
    (`problems` lists what is still wrong AFTER any repairs)."""
    entries = sorted(os.listdir(root))
    manifest_names = [e for e in entries if _MANIFEST_RE.match(e)]
    manifests: Dict[str, dict] = {}
    problems: List[str] = []
    repaired: List[str] = []
    # per-file verdicts (the `--json` machine face, ISSUE 20): every
    # manifest and referenced chunk file gets exactly one verdict string
    # — 'verified', 'legacy_no_digest', 'repaired: <how>' or the
    # failure reason — so the chaos oracle and CI consume scrub results
    # without scraping the human lines
    files: Dict[str, str] = {}

    for name in manifest_names:
        path = os.path.join(root, name)
        manifest, reason = _parse_manifest(path)
        if manifest is None:
            if repair:
                _quarantine(path)
                repaired.append(f"{name}: {reason} -> quarantined .corrupt")
                files[name] = f"repaired: {reason} -> quarantined .corrupt"
            else:
                problems.append(f"{name}: {reason}")
                files[name] = reason
            continue
        manifests[name] = manifest
        files[name] = "verified"

    # per chunk file: the referencing manifests and the digest the
    # NEWEST manifest recorded for it (newer saves re-stamp digests)
    refs: Dict[str, List[str]] = {}
    digests: Dict[str, dict] = {}
    for name in sorted(manifests, key=lambda n: int(_MANIFEST_RE.match(n).group(1))):
        manifest = manifests[name]
        for _, fname in manifest.get("chunks", {}).items():
            refs.setdefault(fname, []).append(name)
        for fname, digest in (manifest.get("digests") or {}).items():
            digests[fname] = digest

    verified = 0
    legacy = 0
    for fname in sorted(refs):
        path = os.path.join(root, fname)
        digest = digests.get(fname)
        if not os.path.exists(path):
            reason = "missing from disk"
        else:
            reason = _chunk_ok(path, digest)
        if reason is None:
            verified += 1
            if digest is None:
                legacy += 1
                files[fname] = "legacy_no_digest"
            else:
                files[fname] = "verified"
            continue
        if not repair:
            problems.append(f"{fname}: {reason}")
            files[fname] = reason
            continue
        # the offline repair ladder (module docstring): prior version,
        # else drop the chunk id so rows re-init pristine at next load
        prior = _find_prior(root, fname)
        m = _CHUNK_RE.match(fname)
        cid = int(m.group(1)) if m else None
        for mname in refs[fname]:
            manifest = manifests[mname]
            chunks = manifest.get("chunks", {})
            hit = [c for c, f in chunks.items() if f == fname]
            for c in hit:
                if prior is not None:
                    chunks[c] = prior
                else:
                    del chunks[c]
            dig = manifest.get("digests")
            if isinstance(dig, dict):
                dig.pop(fname, None)
                if prior is not None:
                    with open(os.path.join(root, prior), "rb") as f:
                        dig[prior] = checksum(f.read())
            _rewrite_manifest(os.path.join(root, mname), manifest)
        if os.path.exists(path):
            _quarantine(path)
        if prior is not None:
            repaired.append(
                f"{fname}: {reason} -> adopted prior version {prior} "
                f"in {len(refs[fname])} manifest(s)"
            )
            files[fname] = f"repaired: {reason} -> adopted prior {prior}"
        else:
            repaired.append(
                f"{fname}: {reason} -> no intact prior version; chunk "
                f"{cid} dropped ({len(refs[fname])} manifest(s)) — rows "
                "re-initialize pristine at next load"
            )
            files[fname] = f"repaired: {reason} -> chunk dropped"

    return {
        "root": root,
        "manifests": len(manifest_names),
        "chunks": len(refs),
        "verified": verified,
        "legacy_no_digest": legacy,
        "problems": problems,
        "repaired": repaired,
        "files": files,
    }


def scrub_main(argv=None) -> int:
    """`python -m federated_pytorch_test_tpu scrub <dir>` — exit 0 when
    every checksum verifies (or every problem was repaired), 1
    otherwise, naming each offending file on stdout."""
    ap = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu scrub",
        description=(
            "Walk a client-store / checkpoint directory, verify every "
            "manifest-referenced chunk file's checksum, and report or "
            "(--repair) repair (docs/FAULT.md §Storage-integrity axis)."
        ),
    )
    ap.add_argument("dir", help="store / checkpoint directory to scrub")
    ap.add_argument(
        "--repair",
        action="store_true",
        help="repair instead of report: adopt an intact prior chunk "
        "version where one exists, drop the chunk (rows re-init "
        "pristine) where none does, quarantine corrupt files as "
        "<name>.corrupt",
    )
    ap.add_argument(
        "--json",
        dest="json_out",
        default=None,
        metavar="PATH",
        help="also write the machine-readable report here ('-' for "
        "stdout): per-root per-file verdicts, totals, ok flag, and a "
        "self-integrity crc over the document (fault/io.py stamp_crc) "
        "— the form the chaos oracle and CI consume",
    )
    args = ap.parse_args(argv)
    if not os.path.isdir(args.dir):
        if args.json_out:
            _emit_json(args.json_out, {
                "dir": args.dir, "ok": False, "roots": [],
                "totals": {}, "error": "not a directory",
            })
        print(f"scrub: {args.dir!r} is not a directory")
        return 1

    # walk: a checkpoint dir keeps its store under `client_store/`
    # (clients/store.py `save`), so scrub every nested dir that holds
    # manifests rather than requiring the exact store root
    roots = [
        dirpath
        for dirpath, _, filenames in sorted(os.walk(args.dir))
        if any(_MANIFEST_RE.match(f) for f in filenames)
    ]
    if not roots:
        if args.json_out:
            _emit_json(args.json_out, {
                "dir": args.dir, "ok": True, "roots": [],
                "totals": {"manifests": 0, "chunks": 0, "verified": 0,
                           "legacy_no_digest": 0, "problems": 0,
                           "repaired": 0},
            })
        print(f"# scrub: no store manifests under {args.dir!r}; nothing to do")
        return 0

    totals = {"manifests": 0, "chunks": 0, "verified": 0,
              "legacy_no_digest": 0, "problems": 0, "repaired": 0}
    root_reports = []
    for root in roots:
        report = scrub_dir(root, repair=args.repair)
        rel = os.path.relpath(root, args.dir)
        for line in report["repaired"]:
            print(f"scrub: {rel}: repaired {line}")
        for line in report["problems"]:
            print(f"scrub: {rel}: CORRUPT {line}")
        totals["manifests"] += report["manifests"]
        totals["chunks"] += report["chunks"]
        totals["verified"] += report["verified"]
        totals["legacy_no_digest"] += report["legacy_no_digest"]
        totals["problems"] += len(report["problems"])
        totals["repaired"] += len(report["repaired"])
        root_reports.append({**report, "root": rel})
    ok = totals["problems"] == 0
    if args.json_out:
        _emit_json(args.json_out, {
            "dir": args.dir,
            "repair": bool(args.repair),
            "ok": ok,
            "totals": totals,
            "roots": root_reports,
        })
    print(
        f"# scrub: {len(roots)} store root(s), "
        f"{totals['manifests']} manifest(s), "
        f"{totals['chunks']} chunk file(s), {totals['verified']} "
        f"verified ({totals['legacy_no_digest']} legacy without digest), "
        f"{totals['problems']} problem(s), "
        f"{totals['repaired']} repaired"
    )
    return 0 if ok else 1


def _emit_json(dest: str, doc: dict) -> None:
    """Write the machine report, self-stamped: the document carries a
    trailing `crc` over every other field (fault/io.py stamp_crc — the
    same definition the stream lines and store manifests use), so a
    torn or hand-edited report fails `verify_crc` instead of being
    silently trusted by the chaos oracle."""
    text = stamp_crc(doc)
    if dest == "-":
        print(text)
        return
    tmp = dest + ".tmp"
    with open(tmp, "w") as f:
        f.write(text + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dest)


if __name__ == "__main__":
    import sys

    sys.exit(scrub_main())
