"""Storage-integrity primitives: checksums, the fault-pluggable I/O shim,
and the bounded retry the disk-facing paths share.

PR 15 put the disk in the training data path — spilled chunk files,
versioned manifests, the metrics stream — but every fault axis so far
watches the *wire*. This module is the storage counterpart, in three
parts:

* **Checksums.** `checksum(data)` stamps a small digest dict
  (`{"alg", "crc", "size"}`) over a byte buffer; `verify_digest` checks
  one, following the algorithm THE DIGEST declares (crc32c when the
  native library is importable, stdlib crc32 — zlib's C implementation —
  otherwise; a digest written under an algorithm this host cannot
  compute is accepted with a one-time warning rather than bricking a
  cross-host restore). `stamp_crc`/`verify_crc` are the JSON-document
  face of the same idea: a `"crc"` field spliced into the serialized
  object, covering every OTHER field — the per-line stream checksum
  (obs/sinks.py STREAM_VERSION 2) and the store-manifest self-check
  (clients/store.py) share this one definition, so the two formats
  cannot drift. Document CRCs are pinned to stdlib crc32: they are part
  of the versioned formats, not host-dependent.

* **The fault shim.** `StorageFaultShim` injects the `storage` axis of a
  `FaultPlan` (fault/plan.py: `storage=<p>:<mode>[:strength]`) into the
  byte paths that opt in: the ClientStore's chunk reads/writes and the
  metrics sink's line writes. `bitrot` flips `strength` bits in a read
  buffer and `torn` truncates it — READ-side faults (disk rot manifests
  at read time; the file itself stays intact, so a verified re-read
  heals and the trajectory is untouched). `ioerror`/`enospc` raise
  transient OSErrors on reads and/or writes, absorbed by the bounded
  retry below. Each decision draws from
  `default_rng([fold_seed(seed, "storage"), direction, op_ordinal])` —
  deterministic given the op sequence, independent of every other axis'
  draws — and the shim counts what it injected for the `# faults
  injected:` scoreboard (`storage_faults=`). Unlike the pure-in-plan
  axes the count is process-local: which ops exist depends on cache and
  residency state, so a resumed run reports its own process' injections.

* **Retry.** `retry_io` is the PR-1 multihost retry shape
  (parallel/multihost.py initialize_distributed) for disk I/O: bounded
  attempts, `backoff_s * 2**attempt` sleeps capped at 30 s and scaled by
  a DETERMINISTIC seeded jitter in [0.5, 1.5) — pure in (`what`,
  attempt), so concurrent prefetch/scatter retries under an ioerror
  storm desynchronize instead of stampeding the disk in lockstep while
  any single caller's schedule stays exactly reproducible
  (`retry_schedule` is the pinned contract) — a warning per failed
  attempt, and the LAST error re-raised loudly when every attempt
  fails.

`IntegrityError` is the loud refusal: raised when a checksum mismatch
survives the retry and the caller has no repair left, always naming the
file so the operator can `scrub` (fault/scrub.py) or delete it.
"""

from __future__ import annotations

import errno
import json
import threading
import time
import warnings
import zlib
from typing import Callable, Optional, Tuple

import numpy as np

from federated_pytorch_test_tpu.fault.plan import FaultPlan, fold_seed

# ---------------------------------------------------------------- checksums

# buffer-digest algorithms this host can compute. crc32c is the industry
# storage checksum (and what real chunk stores stamp); the pure-stdlib
# fallback is zlib's C crc32 — same 32-bit detection strength for the
# single-bit-flip/truncation faults this layer defends against.
_ALGS = {"crc32": zlib.crc32}
try:  # pragma: no cover - absent from the CI image
    from crc32c import crc32c as _crc32c

    _ALGS["crc32c"] = _crc32c
    CHECKSUM_ALG = "crc32c"
except ImportError:
    CHECKSUM_ALG = "crc32"

_warned_algs: set = set()


class IntegrityError(RuntimeError):
    """A checksum mismatch no retry or repair could resolve; `path`
    names the offending file (the repair ladder and `scrub` key on it)."""

    def __init__(self, message: str, path: str = ""):
        super().__init__(message)
        self.path = path


def crc_hex(data) -> str:
    """Lower-hex crc32 of a byte buffer (bytes/bytearray/memoryview/mmap)."""
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def checksum(data) -> dict:
    """Digest dict for a byte buffer: `{"alg", "crc", "size"}`.

    `alg` records WHICH checksum was computed so verification follows
    the digest, not the verifying host's preference — a chunk written
    where native crc32c was available still verifies on a host without
    it (and vice versa, with a warning).
    """
    fn = _ALGS[CHECKSUM_ALG]
    return {
        "alg": CHECKSUM_ALG,
        "crc": f"{fn(data) & 0xFFFFFFFF:08x}",
        "size": int(len(data)),
    }


def verify_digest(data, digest: Optional[dict]) -> bool:
    """True when `data` matches `digest` (None = nothing to check: a
    legacy pre-checksum file, accepted read-only by construction)."""
    if digest is None:
        return True
    alg = digest.get("alg")
    fn = _ALGS.get(alg)
    if fn is None:
        # written under an algorithm this host cannot compute: accept
        # like a legacy file rather than refusing a cross-host restore,
        # but say so once — the operator is running unverified
        if alg not in _warned_algs:
            _warned_algs.add(alg)
            warnings.warn(
                f"cannot verify {alg!r} checksums on this host (no "
                "implementation available); accepting unverified"
            )
        return True
    if digest.get("size") is not None and int(digest["size"]) != len(data):
        return False
    return f"{fn(data) & 0xFFFFFFFF:08x}" == digest.get("crc")


def stamp_crc(d: dict, default: Optional[Callable] = None) -> str:
    """Serialize `d` as a JSON object with a trailing `"crc"` field
    covering every other field's serialized bytes.

    The crc is spliced into the dumped text, so
    `verify_crc(json.loads(stamp_crc(d)))` holds by construction: the
    reader pops `"crc"` and re-dumps the remaining (order-preserved)
    dict — json round-trips are byte-stable for the types the stream
    and manifest carry (shortest-repr floats, ints, strings, lists,
    dicts). Document CRCs are pinned to stdlib crc32 (module docstring).
    """
    body = json.dumps(d, default=default)
    crc = crc_hex(body.encode())
    if body == "{}":
        return f'{{"crc": "{crc}"}}'
    return f'{body[:-1]}, "crc": "{crc}"}}'


def verify_crc(d: dict) -> bool:
    """True when a parsed `stamp_crc` document's `"crc"` matches the
    other fields. A document WITHOUT a crc field fails: the caller
    checks format version first and only verifies stamped documents."""
    crc = d.get("crc")
    if not isinstance(crc, str):
        return False
    body = json.dumps({k: v for k, v in d.items() if k != "crc"})
    return crc == crc_hex(body.encode())


# -------------------------------------------------------------------- retry


def retry_delay(
    what: str, attempt: int, backoff_s: float = 0.05, cap_s: float = 30.0
) -> float:
    """The seconds `retry_io` sleeps after failed attempt `attempt`
    (0-based): the capped exponential base `min(backoff_s * 2**attempt,
    cap_s)` scaled by a seeded jitter factor in [0.5, 1.5).

    The jitter is DETERMINISTIC — pure in (`what`, attempt), seeded by
    crc32 of the `what` label — so any single caller's retry schedule
    is exactly reproducible (and unit-pinnable), while DIFFERENT
    callers (the cohort prefetcher's chunk reads, the scatter path's
    chunk writes, the stream sink — each names itself differently)
    desynchronize under a shared ioerror storm instead of re-hitting
    the disk in lockstep at every power-of-two boundary.
    """
    base = min(backoff_s * (2.0**attempt), cap_s)
    rng = np.random.default_rng(
        [zlib.crc32(what.encode()) & 0x7FFFFFFF, attempt]
    )
    return base * (0.5 + rng.random())


def retry_schedule(
    what: str,
    attempts: int = 3,
    backoff_s: float = 0.05,
    cap_s: float = 30.0,
) -> list:
    """The full sleep schedule one `retry_io(what=...)` call would serve
    if every attempt failed — `attempts - 1` delays (no sleep follows
    the last attempt). Pure in its arguments; tests pin it."""
    return [
        retry_delay(what, a, backoff_s, cap_s) for a in range(attempts - 1)
    ]


def retry_io(
    fn: Callable,
    *,
    what: str,
    attempts: int = 3,
    backoff_s: float = 0.05,
    retry_on: Tuple[type, ...] = (OSError,),
):
    """Run `fn()` with bounded retry + jittered exponential backoff (the
    PR-1 multihost retry shape): `attempts` tries, `retry_delay(what,
    attempt)` seconds between them — `backoff_s * 2**attempt` capped at
    30 s, scaled by the deterministic seeded jitter — a warning per
    failed attempt, and the LAST error re-raised when every attempt
    fails — transient injected `ioerror`/`enospc` (and real flaky
    disks) are absorbed with zero trajectory change, persistent
    failures stay loud."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            last = e
            if attempt + 1 < attempts:
                delay = retry_delay(what, attempt, backoff_s)
                warnings.warn(
                    f"{what} failed (attempt {attempt + 1}/{attempts}): "
                    f"{e}; retrying in {delay:.2f}s"
                )
                time.sleep(delay)
    assert last is not None
    raise last


# --------------------------------------------------------------- fault shim


class StorageFaultShim:
    """Chaos injection for the byte paths (module docstring).

    Thread-safe: the op counters sit behind a lock because the cohort
    prefetcher reads chunks on a background thread while the main
    thread writes the stream. The DRAW for op k is pure in
    (plan seed, direction, k); only the op ordering itself is
    execution-dependent.
    """

    READ, WRITE = 0, 1

    def __init__(self, plan: FaultPlan):
        if plan.storage_p <= 0.0:
            raise ValueError(
                "StorageFaultShim needs a plan with storage_p > 0 "
                "(build one only when the storage axis is scheduled)"
            )
        self.plan = plan
        self._seed = fold_seed(plan.seed, "storage")
        self._ops = [0, 0]  # read / write ordinals
        self.injected = 0  # scoreboard: faults actually fired
        self._lock = threading.Lock()

    def _draw(self, direction: int) -> Optional[np.random.Generator]:
        """The per-op rng when this op is scheduled to fault, else None."""
        with self._lock:
            op = self._ops[direction]
            self._ops[direction] += 1
        rng = np.random.default_rng([self._seed, direction, op])
        if rng.random() >= self.plan.storage_p:
            return None
        with self._lock:
            self.injected += 1
        return rng

    def read_bytes(self, path: str) -> bytes:
        """The file's bytes, possibly corrupted (bitrot/torn) or refused
        (ioerror) by the schedule. The file on disk is never touched —
        a clean re-read is always possible, which is exactly what the
        caller's verified retry exploits."""
        mode = self.plan.storage_mode
        rng = self._draw(self.READ)
        if rng is not None and mode == "ioerror":
            raise OSError(
                errno.EIO, f"injected storage I/O error reading {path}"
            )
        with open(path, "rb") as f:
            data = bytearray(f.read())
        if rng is None or not data:
            return bytes(data)
        if mode == "bitrot":
            for _ in range(max(1, int(self.plan.storage_strength))):
                pos = int(rng.integers(len(data)))
                data[pos] ^= 1 << int(rng.integers(8))
        elif mode == "torn":
            del data[int(rng.integers(len(data))):]
        return bytes(data)

    def before_write(self, what: str) -> None:
        """Raise the scheduled transient write fault, BEFORE any bytes
        move (so a refused write never half-lands; the caller retries
        and the eventual write is whole). Only the error modes fire on
        writes — bitrot/torn are read-side (module docstring)."""
        if self.plan.storage_mode not in ("ioerror", "enospc"):
            return
        if self._draw(self.WRITE) is None:
            return
        if self.plan.storage_mode == "enospc":
            raise OSError(
                errno.ENOSPC, f"injected ENOSPC writing {what}"
            )
        raise OSError(errno.EIO, f"injected I/O error writing {what}")


def storage_shim_for(plan: Optional[FaultPlan]) -> Optional[StorageFaultShim]:
    """The shim for a plan's storage axis, or None when none is
    scheduled (the no-shim fast path: mmap reads, un-intercepted
    writes)."""
    if plan is None or not plan.has_storage:
        return None
    return StorageFaultShim(plan)
