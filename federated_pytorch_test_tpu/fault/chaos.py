"""Chaos harness: composed fault-plan fuzzer, invariant oracle, shrinker.

Every robustness guarantee in the repo is proven one axis or one
hand-picked combination at a time (tests/test_robust.py,
tests/test_hetero.py, the tier-2 *_smoke legs). This module is the
repo's first tool that SEARCHES the cross-product instead of pinning
known points — a quarantined straggler under a lossy codec during a
storage fault is exactly the composed condition FedADMM-style system
heterogeneity (arXiv:2204.03529) and partial-participation regimes
(TAMUNA, arXiv:2302.09832) fail in. Four parts:

* `ChaosPlanGenerator` — a seeded, validity-aware fuzzer: case `i` of
  generator seed `S` is a pure function of `(S, i)` and composes random
  fault-plan axes (PLAN_DOMAINS) with a random knob lattice drawn from
  the engine's exported `KNOB_DOMAINS` table, respecting the strict
  config validators BY CONSTRUCTION (n > 2f for trimmed, lossy-codec
  for error feedback, churn-requires-cohort, nan_burst-requires-robust
  — `_COUPLINGS` below). A deterministic coverage rotation forces axis
  `i % 7` and knob group `i % 8` into case `i`, so every axis and every
  lattice knob is exercised within the first dozen cases of any soak.
* the invariant ORACLE (`run_case`) — runs each drawn config through
  the real `Trainer` with its planned mid-run crash, auto-resumes it,
  runs the uninterrupted twin, and checks machine-readable properties
  harvested from the stream / sidecar / store (`INVARIANTS` below).
* the delta-debugging SHRINKER (`shrink`) — greedily removes one
  component at a time (axes → knob groups → crash → rounds → clients)
  while the violation reproduces, to a 1-minimal fixpoint: no single
  remaining component can be dropped without losing the violation. The
  result is dumped as a self-contained repro bundle (plan JSON + full
  config overrides + seeds + any flight-recorder incidents) runnable
  via `chaos --repro FILE`.
* SOAK mode (`chaos --budget-s N --seed S`) — streams one verdict per
  plan as JSONL with provenance stamps and cumulative axis/knob
  coverage, and writes a `trend`-ingestible `chaos_soak.json` workload
  summary, so chaos coverage is a first-class perf-trend trajectory.

The `chaos` verb dispatches ENGINE-IMPORT-FREE from `__main__` (like
`report`/`scrub`/`trend`): this module imports no engine code at import
time, pins the backend to host CPU itself (`force_host_cpu`, the
conftest contract — the ambient TPU plugin blocks on init), and only
then lazily imports the Trainer inside the oracle.

Planted-bug self-test: `CHAOS_PLANT_BUG=combiner` monkeypatches the
Byzantine-robust combiner with a naive masked mean that averages NaNs
straight in (`_apply_planted_bug`). The CI leg asserts the harness
CATCHES that violation (the `robust_finite` invariant), SHRINKS it to
<= 2 axes, and that `chaos --repro` reproduces it from the bundle —
the oracle's own false-negative test.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from federated_pytorch_test_tpu.fault.io import stamp_crc, verify_crc
from federated_pytorch_test_tpu.fault.plan import CrashPoint, FaultPlan

# --------------------------------------------------------- plan domains
#
# THE machine-readable fault-axis table: the plan-side mirror of
# `engine.KNOB_DOMAINS` (ISSUE 20). One entry per composable FaultPlan
# axis, declaring the fields the axis binds and the ranges the fuzzer
# draws within. Ranges are chosen for the CPU-twin oracle: sleeps stay
# sub-10ms (straggler_delay_s, step_time_s) so a 50-case soak clears in
# minutes, and rates sit where faults actually FIRE in a 2-loop run.
#
# 'crash' binds no scalar fields: its schedule is structural (a
# CrashPoint drawn against the round cursor) and EVERY oracle case
# carries one anyway — the crash+resume+twin comparison is the oracle's
# spine, so 'crash' membership in `axes` only marks shrinkability.
#
# 'storage' deliberately draws from the TRANSIENT modes only: the
# zero-repairs invariant (`storage_clean`) holds for faults the bounded
# retry can out-wait (bitrot/torn/ioerror garble one read/write
# attempt); a persistent `enospc` disk legitimately ends in the repair
# ladder, outside that invariant's domain (docs/FAULT.md).
PLAN_DOMAINS: dict = {
    "dropout": {
        "dropout_p": {"kind": "float", "lo": 0.1, "hi": 0.6},
    },
    "straggler": {
        "straggler_p": {"kind": "float", "lo": 0.2, "hi": 0.8},
        "straggler_delay_s": {"kind": "float", "lo": 0.001, "hi": 0.008},
    },
    "crash": {},
    "corruption": {
        "corrupt_k": {"kind": "int", "lo": 1, "hi": 2},
        "corrupt_mode": {
            "kind": "choice",
            "choices": ["scale", "signflip", "nan_burst", "gauss"],
        },
        "corrupt_strength": {"kind": "float", "lo": 1.5, "hi": 8.0},
    },
    "speed": {
        "slow_k": {"kind": "int", "lo": 1, "hi": 2},
        "slow_factor": {"kind": "float", "lo": 1.5, "hi": 4.0},
        "step_time_s": {"kind": "float", "lo": 0.0005, "hi": 0.002},
    },
    "churn": {
        "churn_p": {"kind": "float", "lo": 0.1, "hi": 0.4},
        "churn_mean_absence": {"kind": "float", "lo": 1.0, "hi": 3.0},
    },
    "storage": {
        "storage_p": {"kind": "float", "lo": 0.05, "hi": 0.25},
        "storage_mode": {
            "kind": "choice", "choices": ["bitrot", "torn", "ioerror"],
        },
        "storage_strength": {"kind": "float", "lo": 1.0, "hi": 2.0},
    },
}

AXES: Tuple[str, ...] = tuple(PLAN_DOMAINS)

# the fields each axis binds (used by the shrinker to reset a removed
# axis back to the FaultPlan dataclass defaults)
AXIS_FIELDS: Dict[str, Tuple[str, ...]] = {
    ax: tuple(spec) for ax, spec in PLAN_DOMAINS.items()
}
AXIS_FIELDS["crash"] = ("crashes",)
AXIS_FIELDS["corruption"] += ("corrupt_p",)
AXIS_FIELDS["speed"] += ("slow_p",)

# the knob-lattice groups the fuzzer composes on top of the plan. Each
# group is a COHERENT set of ExperimentConfig fields (drawn from
# engine.KNOB_DOMAINS ranges) that must be added or removed together —
# a codec's fraction without its codec is invalid, a cohort's shards
# without its population is invalid — which makes the group the
# shrinker's unit of removal.
KNOB_GROUPS: Tuple[str, ...] = (
    "robust", "quarantine", "codec", "schedule",
    "deadline", "cohort", "fold", "probes",
)

# validity couplings the generator enforces by construction and the
# shrinker must preserve (removing the key's requirement would turn a
# searched-for engine bug into a self-inflicted invalid config):
#   churn axis      -> cohort knob group (churn acts on the sampler pool)
#   deadline knobs  -> speed axis (budgets derive from plan step times)
#   nan_burst mode  -> robust knob group present, quarantine absent
#                      (the robust_finite invariant isolates the
#                      combiner's finite-screening; quarantine would
#                      mask a broken combiner by excluding the NaN
#                      sender upstream)
_COUPLINGS = {
    "churn": "cohort",
    "deadline": "speed",
}

# model 'net', non-shuffled, max_groups=1: the single trained group is
# gid 2 (partition train_order[0] — pinned by tests/test_fault_cli.py);
# every generated crash point targets it so the crash deterministically
# fires under the fixed schedule. Adaptive schedules may legitimately
# never visit it — the oracle's crash_fired invariant is scoped to
# fixed schedules for exactly that reason.
_NET_FIRST_GID = 2


def _draw(rng: np.random.Generator, spec: dict):
    """Draw one value from a PLAN_DOMAINS/KNOB_DOMAINS-style field spec."""
    if spec["kind"] == "choice":
        return spec["choices"][int(rng.integers(len(spec["choices"])))]
    if spec["kind"] == "int":
        return int(rng.integers(spec["lo"], spec["hi"] + 1))
    if spec["kind"] == "float":
        return round(float(rng.uniform(spec["lo"], spec["hi"])), 6)
    raise ValueError(f"undrawable spec kind {spec['kind']!r}")


# --------------------------------------------------------------- cases


@dataclasses.dataclass(frozen=True)
class ChaosCase:
    """One drawn composed configuration: a FaultPlan + a knob lattice.

    `knobs` maps knob-group name -> the ExperimentConfig field overrides
    that group contributes; `base` holds the scalar run shape (strategy,
    n_clients, nloop, nadmm). The case is fully serializable
    (`to_doc`/`from_doc` — the repro-bundle format) and its plan
    round-trips through the STRICT FaultPlan JSON loader, so a bundle
    written by one session is rejected loudly, never reinterpreted, by
    a session whose plan schema drifted.
    """

    index: int
    gen_seed: int
    axes: Tuple[str, ...]
    plan: FaultPlan
    knobs: Dict[str, Dict[str, Any]]
    base: Dict[str, Any]
    tags: Tuple[str, ...] = ()

    def config_overrides(self) -> Dict[str, Any]:
        over = dict(self.base)
        for group in sorted(self.knobs):
            over.update(self.knobs[group])
        return over

    def population(self) -> int:
        """The fault-plan population N: virtual clients in cohort mode,
        the fixed client count otherwise."""
        for g in self.knobs.values():
            if "virtual_clients" in g:
                return int(g["virtual_clients"])
        return int(self.base["n_clients"])

    def to_doc(self) -> dict:
        return {
            "index": self.index,
            "gen_seed": self.gen_seed,
            "axes": list(self.axes),
            "plan": json.loads(self.plan.to_json()),
            "knobs": {g: dict(f) for g, f in self.knobs.items()},
            "base": dict(self.base),
            "tags": list(self.tags),
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "ChaosCase":
        plan = FaultPlan.from_json(json.dumps(doc["plan"]))
        return cls(
            index=int(doc["index"]),
            gen_seed=int(doc["gen_seed"]),
            axes=tuple(doc["axes"]),
            plan=plan,
            knobs={g: dict(f) for g, f in doc["knobs"].items()},
            base=dict(doc["base"]),
            tags=tuple(doc.get("tags", ())),
        )


class ChaosPlanGenerator:
    """Seeded validity-aware fuzzer over composed fault configurations.

    `draw(i)` is pure in `(seed, i)` — `np.random.default_rng([seed, i])`
    — so any case from any soak is reconstructible from the two ints in
    its verdict line. Cases 0-2 are the deterministic invariant probes
    (robust_finite, all_dropped, transparent); from case 3 on, the
    coverage rotation forces axis `AXES[i % 7]` and knob group
    `KNOB_GROUPS[i % 8]` while every other axis/group joins with fixed
    probability, and the validity couplings (`_COUPLINGS`) are applied
    after the draw.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        # the engine's exported domain table: the SAME source the config
        # validators enforce, so a drawn knob cannot drift out of the
        # accepted range (generator/validator agreement is a lookup).
        # Imported lazily-at-init: engine.config imports no jax, but
        # keeping chaos.py importable standalone mirrors scrub/report.
        from federated_pytorch_test_tpu.engine.config import KNOB_DOMAINS

        self._kd = KNOB_DOMAINS

    # ------------------------------------------------- deterministic probes

    def _probe_robust_finite(self, i: int) -> ChaosCase:
        """Case 0: nan_burst corruption vs a robust combiner, NO
        quarantine — the honest engine keeps every streamed value
        finite (consensus/robust.py screens non-finite survivors); a
        combiner that averages NaNs in violates `robust_finite`. This
        is the planted-bug CI leg's tripwire, first in every soak."""
        plan = FaultPlan(
            seed=101, corrupt_k=1, corrupt_mode="nan_burst",
            crashes=(CrashPoint(1, _NET_FIRST_GID, 0),),
        )
        return ChaosCase(
            index=i, gen_seed=self.seed,
            axes=("corruption", "crash"), plan=plan,
            knobs={"robust": {"robust_agg": "median", "robust_f": 1}},
            base=self._base(n_clients=5),
            tags=("robust_finite",),
        )

    def _probe_all_dropped(self, i: int) -> ChaosCase:
        """Case 1: dropout_p=1.0 — every exchange loses every client.
        The engine must keep the consensus state (z) exactly, ship zero
        uplink bytes, and stay finite end to end."""
        plan = FaultPlan(
            seed=102, dropout_p=1.0,
            crashes=(CrashPoint(1, _NET_FIRST_GID, 0),),
        )
        return ChaosCase(
            index=i, gen_seed=self.seed,
            axes=("dropout", "crash"), plan=plan,
            knobs={}, base=self._base(),
            tags=("all_dropped",),
        )

    def _probe_transparent(self, i: int) -> ChaosCase:
        """Case 2: every drawn axis at its identity point — dropout 0.0,
        slow_factor x1.0, scale-corruption strength x1.0. The plan is
        ACTIVE (masks drawn, speeds assigned, corruption applied) yet
        must be bit-transparent: the twin's final parameters equal a
        plan-free run's exactly."""
        plan = FaultPlan(
            seed=103, dropout_p=0.0,
            corrupt_k=1, corrupt_mode="scale", corrupt_strength=1.0,
            slow_k=1, slow_factor=1.0, step_time_s=0.001,
            crashes=(CrashPoint(1, _NET_FIRST_GID, 0),),
        )
        return ChaosCase(
            index=i, gen_seed=self.seed,
            axes=("dropout", "corruption", "speed", "crash"), plan=plan,
            knobs={}, base=self._base(),
            tags=("transparent",),
        )

    # ------------------------------------------------------------- drawing

    def _base(self, n_clients: int = 3, strategy: str = "fedavg") -> dict:
        return {
            "n_clients": n_clients, "strategy": strategy,
            "nloop": 2, "nadmm": 2,
        }

    def draw(self, i: int) -> ChaosCase:
        if i == 0:
            return self._probe_robust_finite(i)
        if i == 1:
            return self._probe_all_dropped(i)
        if i == 2:
            return self._probe_transparent(i)
        rng = np.random.default_rng([self.seed, i])

        axes = {AXES[i % len(AXES)], "crash"}
        for ax in AXES:
            if rng.random() < 0.35:
                axes.add(ax)
        groups = {KNOB_GROUPS[i % len(KNOB_GROUPS)]}
        for g in KNOB_GROUPS:
            if rng.random() < 0.30:
                groups.add(g)
        # validity couplings (_COUPLINGS): churn acts on the sampler
        # pool, deadline budgets derive from the plan's step times
        if "churn" in axes:
            groups.add(_COUPLINGS["churn"])
        if "deadline" in groups:
            axes.add(_COUPLINGS["deadline"])

        base = self._base(
            n_clients=int(rng.integers(3, 6)),
            strategy="admm" if rng.random() < 0.4 else "fedavg",
        )
        cohort_mode = "cohort" in groups
        # the client axis the combiners see: the cohort in cohort mode
        k_axis = 4 if cohort_mode else base["n_clients"]

        tags: List[str] = []
        plan_fields = self._draw_plan(axes, rng)
        knobs = self._draw_knobs(groups, rng, k_axis, cohort_mode)

        # nan_burst coupling: force a robust defense, forbid quarantine
        if plan_fields.get("corrupt_mode") == "nan_burst":
            if "robust" not in knobs or knobs["robust"]["robust_agg"] == "clip":
                knobs["robust"] = {
                    "robust_agg": "median" if rng.random() < 0.5 else "trimmed",
                    "robust_f": max(1, plan_fields.get("corrupt_k", 1)),
                }
            knobs["robust"]["robust_f"] = max(
                knobs["robust"]["robust_f"], plan_fields.get("corrupt_k", 1)
            )
            knobs.pop("quarantine", None)
            tags.append("robust_finite")
        # trimmed needs k_axis > 2f; corruption needs corrupt_k <= N
        if knobs.get("robust", {}).get("robust_agg") == "trimmed":
            f_max = max(1, (k_axis - 1) // 2)
            knobs["robust"]["robust_f"] = min(
                knobs["robust"]["robust_f"], f_max
            )
            if "corrupt_k" in plan_fields and "robust_finite" in tags:
                plan_fields["corrupt_k"] = min(
                    plan_fields["corrupt_k"], knobs["robust"]["robust_f"]
                )
        if "corrupt_k" in plan_fields:
            plan_fields["corrupt_k"] = min(plan_fields["corrupt_k"], k_axis)
        if "slow_k" in plan_fields:
            plan_fields["slow_k"] = min(plan_fields["slow_k"], k_axis)

        crashes = [CrashPoint(1, _NET_FIRST_GID, 0)]
        if rng.random() < 0.2 and base["nadmm"] > 1:
            crashes.append(CrashPoint(1, _NET_FIRST_GID, base["nadmm"] - 1))
        plan = FaultPlan(
            seed=1000 + i, crashes=tuple(crashes), **plan_fields
        )
        return ChaosCase(
            index=i, gen_seed=self.seed,
            axes=tuple(a for a in AXES if a in axes),
            plan=plan, knobs=knobs, base=base, tags=tuple(tags),
        )

    def _draw_plan(
        self, axes: set, rng: np.random.Generator
    ) -> Dict[str, Any]:
        fields: Dict[str, Any] = {}
        for ax in AXES:
            if ax not in axes or ax == "crash":
                continue
            for name, spec in PLAN_DOMAINS[ax].items():
                fields[name] = _draw(rng, spec)
        if "corruption" in axes:
            # corrupt_p unused by the engine's k-based targeting here;
            # k clients per exchange is the composable contract
            fields["corrupt_p"] = 0.0
        return fields

    def _draw_knobs(
        self,
        groups: set,
        rng: np.random.Generator,
        k_axis: int,
        cohort_mode: bool,
    ) -> Dict[str, Dict[str, Any]]:
        kd = self._kd
        knobs: Dict[str, Dict[str, Any]] = {}
        if "robust" in groups:
            method = ("median", "trimmed", "clip")[int(rng.integers(3))]
            g: Dict[str, Any] = {"robust_agg": method}
            if method == "trimmed":
                g["robust_f"] = int(rng.integers(1, max(2, (k_axis - 1) // 2) + 1))
            else:
                g["robust_f"] = 1
            knobs["robust"] = g
        if "quarantine" in groups:
            knobs["quarantine"] = {
                "quarantine_z": round(float(rng.uniform(2.0, 4.0)), 3)
            }
        if "codec" in groups:
            pick = ("bf16", "topk", "quant")[int(rng.integers(3))]
            if pick == "bf16":
                knobs["codec"] = {"exchange_dtype": "bfloat16"}
            elif pick == "topk":
                knobs["codec"] = {
                    "exchange_codec": "topk",
                    "topk_fraction": round(float(rng.uniform(0.2, 0.6)), 3),
                    "error_feedback": bool(rng.random() < 0.5),
                }
            else:
                knobs["codec"] = {
                    "exchange_codec": "quant",
                    "quant_bits": (8, 4)[int(rng.integers(2))],
                    "error_feedback": bool(rng.random() < 0.5),
                }
        if "schedule" in groups:
            knobs["schedule"] = {
                "group_schedule": "adaptive",
                "group_skip_frac": round(float(rng.uniform(0.0, 0.5)), 3),
                "max_groups": 2,
            }
        if "deadline" in groups:
            if rng.random() < 0.5:
                knobs["deadline"] = {
                    "round_deadline": round(float(rng.uniform(0.05, 0.2)), 4)
                }
            else:
                knobs["deadline"] = {
                    "round_deadline": ("auto", "auto:p75")[int(rng.integers(2))]
                }
        if "cohort" in groups:
            g = {
                "virtual_clients": 8,
                "cohort": 4,
                "cohort_seed": int(rng.integers(0, 10)),
                "cohort_weighting": ("uniform", "samples")[int(rng.integers(2))],
                "data_shards": (1, 2, 4)[int(rng.integers(3))],
                "store_chunk_clients": 2,
                "prefetch": bool(rng.random() < 0.5),
            }
            if rng.random() < 0.5:
                g["store_resident_chunks"] = 2
            knobs["cohort"] = g
        if "fold" in groups:
            knobs["fold"] = {
                "client_fold": _draw(rng, kd["client_fold"])
            }
        if "probes" in groups:
            knobs["probes"] = {
                "linesearch_probes": int(rng.integers(2, kd["linesearch_probes"]["hi"] + 1))
            }
        return knobs


# --------------------------------------------------------------- oracle


def norm_stream_records(path: str) -> List[dict]:
    """THE twin-stream normalizer: parse a JSONL metric stream into
    records equal modulo wall-clock fields — the `t` stamp, per-line
    `crc`, `step_time` seconds — and the header tag (crashed+resumed
    twins' configs legitimately differ by the fired crash point and the
    run-dir paths baked into the tag). Single definition shared by the
    chaos oracle and tests/conftest.py's `norm_stream` fixture (the
    pytest face); scripts/ci.sh `assert_stream_identity` mirrors it for
    shell legs. A wall-clock field added to the stream format is then
    ignored (or surfaced) everywhere at once."""
    out = []
    for line in open(path):
        d = json.loads(line)
        d.pop("t", None)
        d.pop("crc", None)
        if d.get("event") == "stream_header":
            d.pop("tag", None)
        if d.get("series") == "step_time":
            d["value"] = {
                k: v for k, v in d["value"].items() if k != "seconds"
            }
        out.append(d)
    return out


_SOURCE = None


def _source():
    """One shared synthetic dataset per process (the test-suite idiom):
    the trainer shards it per client count, so every case reuses it."""
    global _SOURCE
    if _SOURCE is None:
        from federated_pytorch_test_tpu.data import synthetic_cifar

        _SOURCE = synthetic_cifar(n_train=240, n_test=60)
    return _SOURCE


def _build_cfg(case: ChaosCase, run_dir: str, plan: FaultPlan):
    from federated_pytorch_test_tpu.engine import get_preset

    os.makedirs(run_dir, exist_ok=True)
    plan_path = os.path.join(run_dir, "plan.json")
    with open(plan_path, "w") as f:
        f.write(plan.to_json())
    over = case.config_overrides()
    over.update(
        model="net", batch=40, check_results=False, synthetic_ok=True,
        shuffle_group_order=False,
        fault_plan=plan_path,
        metrics_stream=os.path.join(run_dir, "stream.jsonl"),
        checkpoint_dir=os.path.join(run_dir, "ckpt"),
        save_model=True, resume="auto",
    )
    over.setdefault("max_groups", 1)
    return get_preset("fedavg", **over)


def _final_flat(trainer) -> np.ndarray:
    return np.asarray(trainer._fetch(trainer.flat))


def _run_to_completion(cfg, src, max_crashes: int):
    """Run a config, auto-resuming through every planned crash; returns
    (trainer, crashes_fired)."""
    from federated_pytorch_test_tpu.engine import Trainer
    from federated_pytorch_test_tpu.fault import InjectedCrash

    fired = 0
    for _ in range(max_crashes + 2):
        tr = Trainer(cfg, verbose=False, source=src)
        try:
            tr.run()
            return tr, fired
        except InjectedCrash:
            fired += 1
    raise RuntimeError(
        f"run never completed after {fired} injected crashes "
        f"(planned {max_crashes}) — the resume ladder is stuck"
    )


def _injected_storage_error(exc: BaseException) -> bool:
    """True when `exc` is the storage shim's own loud failure: an OSError
    carrying the shim's "injected" marker (fault/io.py) that survived
    retry_io's bounded attempts. Each retry re-draws at storage_p (fresh
    op ordinal), so under the error modes an op aborts with probability
    storage_p**attempts — a tail that grows with the op population.
    That abort is the engine's DOCUMENTED contract for a persistent
    error-mode storm ("persistent failures stay loud"), not a bug."""
    return (
        isinstance(exc, OSError)
        and exc.errno in (errno.EIO, errno.ENOSPC)
        and "injected" in str(exc)
    )


def _tolerated_abort(case, exc, crashes_fired, t0, workdir, run_dirs):
    """Verdict for a run that aborted on a retry-exhausted injected
    storage error. The abort itself is tolerated (see
    _injected_storage_error), but the oracle still holds the engine to
    crash-consistency on the way down: error-mode faults refuse I/O
    BEFORE bytes move, so an abort may stop the run, never corrupt the
    store — every run dir must still scrub clean."""
    violations: List[dict] = []
    if case.plan.storage_mode not in ("ioerror", "enospc"):
        # bitrot/torn are read-side buffer damage — they can never
        # surface as an injected OSError, so this abort is unexplained
        violations.append({
            "invariant": "run_completes",
            "detail": (
                f"injected storage OSError under mode="
                f"{case.plan.storage_mode!r}, which never raises: {exc}"
            ),
        })
    from federated_pytorch_test_tpu.fault.scrub import scrub_main

    for i, d in enumerate(run_dirs):
        if not os.path.isdir(d):
            continue
        report_path = os.path.join(workdir, f"scrub-abort-{i}.json")
        rc = scrub_main([d, "--json", report_path])
        with open(report_path) as f:
            doc = json.load(f)
        if rc != 0 or not verify_crc(doc) or not doc.get("ok", False):
            violations.append({
                "invariant": "storage_clean",
                "detail": (
                    f"store at {d} does not scrub clean after a tolerated "
                    f"abort (rc={rc}) — error-mode faults must refuse "
                    "before bytes move, leaving the disk pristine"
                ),
            })
    v = _verdict(case, violations, crashes_fired, t0, workdir)
    v["tags"].append("storage_abort_tolerated")
    return v


# names of every oracle invariant, in check order (docs/FAULT.md
# §Chaos harness carries the catalog with the full semantics)
INVARIANTS: Tuple[str, ...] = (
    "run_completes",        # no unplanned exception escapes the Trainer
    "crash_fired",          # the planned crash actually fired (fixed schedule)
    "stream_twin_identity", # resumed stream == uninterrupted twin's, normalized
    "fused_dispatch",       # fused rounds stay {round:1, round_init:1}
    "ledger_conservation",  # comm_bytes records == pure-plan reconstruction
    "scoreboard",           # injected_faults == twin's == pure recomputation
    "all_dropped_keeps_state",  # p=1.0 dropout: zero uplink, finite, z kept
    "robust_finite",        # robust defense keeps every streamed value finite
    "transparent_axes",     # identity-strength axes are bit-transparent
    "storage_clean",        # transient storage chaos: zero repairs, clean scrub
)


def run_case(case: ChaosCase, workdir: str) -> dict:
    """Run one case under the full invariant oracle; returns the verdict
    `{ok, violations: [{invariant, detail}], crashes_fired, wall_s}`."""
    t0 = time.time()
    violations: List[dict] = []

    def fail(inv: str, detail: str) -> None:
        violations.append({"invariant": inv, "detail": detail})

    plan_crash = case.plan
    plan_twin = dataclasses.replace(plan_crash, crashes=())
    dir_b = os.path.join(workdir, "crash")
    dir_a = os.path.join(workdir, "twin")
    cfg_b = _build_cfg(case, dir_b, plan_crash)
    cfg_a = _build_cfg(case, dir_a, plan_twin)
    src = _source()
    adaptive = "schedule" in case.knobs
    cohort = "cohort" in case.knobs

    crashes_fired = 0
    try:
        tr_b, crashes_fired = _run_to_completion(
            cfg_b, src, len(plan_crash.crashes)
        )
        tr_a, _ = _run_to_completion(cfg_a, src, 0)
    except Exception as e:
        if plan_crash.has_storage and _injected_storage_error(e):
            return _tolerated_abort(
                case, e, crashes_fired, t0, workdir, (dir_b, dir_a)
            )
        fail("run_completes", traceback.format_exc(limit=8))
        return _verdict(case, violations, crashes_fired, t0, workdir)

    rec_a, rec_b = tr_a.recorder, tr_b.recorder

    # crash_fired — scoped to fixed schedules: an adaptive scheduler may
    # legitimately never visit the crash point's group
    if plan_crash.crashes and not adaptive and crashes_fired == 0:
        fail(
            "crash_fired",
            f"planned crashes {plan_crash.crashes} never fired under the "
            "fixed schedule",
        )

    # stream_twin_identity
    na = norm_stream_records(cfg_a.metrics_stream)
    nb = norm_stream_records(cfg_b.metrics_stream)
    if na != nb:
        idx = next(
            (i for i, (x, y) in enumerate(zip(na, nb)) if x != y),
            min(len(na), len(nb)),
        )
        fail(
            "stream_twin_identity",
            f"streams diverge at record {idx}: "
            f"twin={na[idx] if idx < len(na) else '<end>'} "
            f"resumed={nb[idx] if idx < len(nb) else '<end>'}",
        )

    # fused_dispatch
    if tr_a._fused_enabled():
        for r in rec_a.series.get("dispatch_count", []):
            if r["value"] != {"round": 1, "round_init": 1, "total": 2}:
                fail(
                    "fused_dispatch",
                    f"fused round dispatched {r['value']} at "
                    f"nloop={r.get('nloop')} group={r.get('group')}",
                )
                break

    # ledger_conservation: internal consistency always; pure-plan
    # reconstruction when survivors are plan-pure (no deadline budgets,
    # no adaptive visits)
    for name, tr, rec in (("twin", tr_a, rec_a), ("resumed", tr_b, rec_b)):
        records = rec.series.get("comm_bytes", [])
        total = sum(int(r["value"]) for r in records)
        summ = rec.latest("comm_summary") or {}
        if total != summ.get("bytes_total"):
            fail(
                "ledger_conservation",
                f"{name}: sum(comm_bytes records)={total} != "
                f"comm_summary bytes_total={summ.get('bytes_total')}",
            )
    if "deadline" not in case.knobs and not adaptive:
        N = case.population()
        expected = []
        for nloop in range(cfg_a.nloop):
            ids = tr_a.sampler.cohort(nloop) if cohort else None
            for gid in tr_a.group_order:
                for a in range(cfg_a.nadmm):
                    mask = plan_twin.participation(N, nloop, gid, a)
                    if ids is not None:
                        mask = mask[ids]
                    surv = int(mask.sum())
                    expected.append(
                        (nloop, gid, a, surv, tr_a._comm.round_bytes(gid, surv))
                    )
        got = [
            (r["nloop"], r["group"], r["nadmm"], r["survivors"], int(r["value"]))
            for r in rec_a.series.get("comm_bytes", [])
        ]
        if got != expected:
            fail(
                "ledger_conservation",
                f"pure-plan reconstruction mismatch: expected {expected[:6]}"
                f"... got {got[:6]}...",
            )

    # scoreboard: resumed == twin (modulo the fired crash schedule and
    # the per-op storage counter), and both match the pure recomputation
    counts_a = dict(rec_a.latest("injected_faults") or {})
    counts_b = dict(rec_b.latest("injected_faults") or {})
    if counts_b.get("crashes", 0) != len(plan_crash.crashes):
        fail(
            "scoreboard",
            f"resumed run reports crashes={counts_b.get('crashes')} but the "
            f"plan schedules {len(plan_crash.crashes)}",
        )
    drop_keys = ("crashes", "storage_faults")
    cmp_a = {k: v for k, v in counts_a.items() if k not in drop_keys}
    cmp_b = {k: v for k, v in counts_b.items() if k not in drop_keys}
    if cmp_a != cmp_b:
        fail(
            "scoreboard",
            f"resumed scoreboard {cmp_b} != twin scoreboard {cmp_a}",
        )
    if "deadline" not in case.knobs and not adaptive:
        from federated_pytorch_test_tpu.fault import FaultInjector

        inj = FaultInjector(plan_twin, case.population())
        pure = inj.injected_summary(
            cfg_a.nloop, tr_a.group_order, cfg_a.nadmm,
            exchanges=cfg_a.strategy != "none",
            cohort=tr_a.sampler.cohort if cohort else None,
        )
        for k in ("drops", "stragglers", "corruptions", "churned"):
            if k in pure and counts_a.get(k, 0) != pure[k]:
                fail(
                    "scoreboard",
                    f"twin {k}={counts_a.get(k)} != pure-plan {k}={pure[k]}",
                )

    # tag probes
    if "all_dropped" in case.tags:
        survs = [
            r["value"]["survivors"]
            for r in rec_a.series.get("participation", [])
        ]
        summ = rec_a.latest("comm_summary") or {}
        if survs and set(survs) != {0}:
            fail(
                "all_dropped_keeps_state",
                f"p=1.0 dropout left survivors {sorted(set(survs))}",
            )
        if summ.get("bytes_total"):
            fail(
                "all_dropped_keeps_state",
                f"all-dropped run shipped {summ['bytes_total']} uplink bytes",
            )
        if rec_a.first_nonfinite is not None:
            fail(
                "all_dropped_keeps_state",
                f"non-finite under full dropout: {rec_a.first_nonfinite}",
            )

    if "robust_finite" in case.tags:
        for name, rec in (("twin", rec_a), ("resumed", rec_b)):
            if rec.first_nonfinite is not None:
                fail(
                    "robust_finite",
                    f"{name}: first non-finite at {rec.first_nonfinite} — the "
                    "robust combiner let a corrupted update through",
                )
            if rec.series.get("fault"):
                fail(
                    "robust_finite",
                    f"{name}: fault records "
                    f"{[r['value'] for r in rec.series['fault']]} under a "
                    "robust defense sized for the corruption",
                )
        if not np.all(np.isfinite(_final_flat(tr_a))):
            fail("robust_finite", "twin's final parameters are non-finite")

    if "transparent" in case.tags:
        dir_c = os.path.join(workdir, "bare")
        try:
            from federated_pytorch_test_tpu.engine import get_preset

            over = case.config_overrides()
            over.update(
                model="net", batch=40, check_results=False,
                synthetic_ok=True, shuffle_group_order=False,
                metrics_stream=os.path.join(dir_c, "stream.jsonl"),
                checkpoint_dir=os.path.join(dir_c, "ckpt"),
                save_model=True, resume="auto",
            )
            over.setdefault("max_groups", 1)
            os.makedirs(dir_c, exist_ok=True)
            tr_c, _ = _run_to_completion(
                get_preset("fedavg", **over), src, 0
            )
            if not np.array_equal(_final_flat(tr_a), _final_flat(tr_c)):
                fail(
                    "transparent_axes",
                    "identity-strength plan (dropout 0.0, x1.0 scale "
                    "corruption, x1.0 slowdown) changed the final "
                    "parameters vs the plan-free run",
                )
        except Exception:
            fail("transparent_axes", traceback.format_exc(limit=8))

    # cohort data path: the twin's sidecar must show the client store
    # actually moved rows (clients/store.py traffic()) — a cohort run
    # whose gathers never fired is exchanging stale state silently
    if cohort:
        side = cfg_a.metrics_stream + ".status.json"
        try:
            with open(side) as f:
                traffic = (json.load(f).get("store") or {}).get("traffic")
        except (OSError, ValueError) as e:
            traffic = None
            fail("ledger_conservation", f"twin: unreadable sidecar {side}: {e}")
        if traffic is not None:
            bad = {
                k: v for k, v in traffic.items()
                if not isinstance(v, int) or v < 0
            }
            if bad or traffic.get("gather_rows", 0) < case.knobs["cohort"]["cohort"]:
                fail(
                    "ledger_conservation",
                    f"twin: store traffic {traffic} — cohort mode must "
                    "gather at least one full cohort of rows",
                )

    # storage_clean: transient storage chaos heals via bounded retry —
    # never the repair ladder — and the run dir scrubs clean afterwards
    if "storage" in case.axes:
        for name, cfg, rec, tr in (
            ("twin", cfg_a, rec_a, tr_a), ("resumed", cfg_b, rec_b, tr_b),
        ):
            side = cfg.metrics_stream + ".status.json"
            try:
                with open(side) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                fail("storage_clean", f"{name}: unreadable sidecar {side}: {e}")
                continue
            integ = doc.get("integrity") or {}
            repairs = int(integ.get("repairs_prior", 0)) + int(
                integ.get("repairs_reinit", 0)
            )
            if repairs:
                fail(
                    "storage_clean",
                    f"{name}: {repairs} repair(s) under transient storage "
                    f"faults (integrity={integ}) — bounded retry should "
                    "have healed every read",
                )
        from federated_pytorch_test_tpu.fault.scrub import scrub_main

        report_path = os.path.join(workdir, "scrub.json")
        rc = scrub_main([dir_b, "--json", report_path])
        with open(report_path) as f:
            doc = json.load(f)
        if not verify_crc(doc):
            fail("storage_clean", "scrub --json report failed its own crc")
        if rc not in (0,) or not doc.get("ok", False):
            fail(
                "storage_clean",
                f"post-run scrub of {dir_b} found problems: "
                f"{[r.get('problems') for r in doc.get('roots', [])]}",
            )

    return _verdict(case, violations, crashes_fired, t0, workdir)


def _verdict(case, violations, crashes_fired, t0, workdir) -> dict:
    return {
        "case": case.index,
        "seed": [case.gen_seed, case.index],
        "tags": list(case.tags),
        "axes": list(case.axes),
        "knobs": sorted(case.knobs),
        "ok": not violations,
        "violations": violations,
        "crashes_fired": crashes_fired,
        "wall_s": round(time.time() - t0, 3),
        "workdir": workdir,
    }


# -------------------------------------------------------------- shrinker


def _plan_defaults() -> Dict[str, Any]:
    return {
        f.name: f.default
        for f in dataclasses.fields(FaultPlan)
        if f.default is not dataclasses.MISSING
    }


def _drop_axis(case: ChaosCase, axis: str) -> Optional[ChaosCase]:
    """Remove one fault axis (reset its plan fields to defaults),
    preserving the validity couplings — returns None where removal
    would manufacture an invalid or semantically different case."""
    if axis not in case.axes:
        return None
    # nan_burst's defense is load-bearing for the robust_finite probe:
    # the corruption axis may be removed (taking the tag's trigger with
    # it), but never the other way around (see _drop_knob)
    defaults = _plan_defaults()
    repl = {f: defaults[f] for f in AXIS_FIELDS[axis]}
    if axis == "crash":
        repl = {"crashes": ()}
    plan = dataclasses.replace(case.plan, **repl)
    knobs = {g: dict(f) for g, f in case.knobs.items()}
    tags = tuple(
        t for t in case.tags
        if not (t == "robust_finite" and axis == "corruption")
    )
    if axis == "speed":
        knobs.pop("deadline", None)  # budgets derive from plan step times
    return dataclasses.replace(
        case, axes=tuple(a for a in case.axes if a != axis),
        plan=plan, knobs=knobs, tags=tags,
    )


def _drop_knob(case: ChaosCase, group: str) -> Optional[ChaosCase]:
    if group not in case.knobs:
        return None
    if group == "cohort" and "churn" in case.axes:
        return None  # churn requires the sampler pool — coupled removal only
    if group == "robust" and case.plan.corrupt_mode == "nan_burst" and (
        "corruption" in case.axes
    ):
        return None  # an undefended nan_burst fails honest engines too
    knobs = {g: dict(f) for g, f in case.knobs.items() if g != group}
    return dataclasses.replace(case, knobs=knobs)


def components(case: ChaosCase) -> List[Tuple[str, ChaosCase]]:
    """Every single-component reduction of `case`, in shrink order
    (axes -> knob groups -> crash schedule -> rounds -> clients)."""
    out: List[Tuple[str, ChaosCase]] = []
    for ax in case.axes:
        if ax == "crash":
            continue
        r = _drop_axis(case, ax)
        if r is not None:
            out.append((f"axis:{ax}", r))
    for g in sorted(case.knobs):
        r = _drop_knob(case, g)
        if r is not None:
            out.append((f"knob:{g}", r))
    if case.plan.crashes:
        r = _drop_axis(case, "crash")
        if r is not None:
            out.append(("crash:none", r))
    if case.base.get("nloop", 1) > 1:
        base = dict(case.base, nloop=1)
        plan = dataclasses.replace(
            case.plan,
            crashes=tuple(c for c in case.plan.crashes if c.nloop < 1),
        )
        out.append(
            ("rounds:1", dataclasses.replace(case, base=base, plan=plan))
        )
    if case.base.get("n_clients", 3) > 3 and "cohort" not in case.knobs:
        base = dict(case.base, n_clients=3)
        knobs = {g: dict(f) for g, f in case.knobs.items()}
        if "robust" in knobs:
            knobs["robust"]["robust_f"] = min(
                knobs["robust"].get("robust_f", 1), 1
            )
        repl = {}
        if case.plan.corrupt_k:
            repl["corrupt_k"] = min(case.plan.corrupt_k, 1)
        if case.plan.slow_k:
            repl["slow_k"] = min(case.plan.slow_k, 1)
        plan = dataclasses.replace(case.plan, **repl) if repl else case.plan
        out.append(
            (
                "clients:3",
                dataclasses.replace(case, base=base, knobs=knobs, plan=plan),
            )
        )
    return out


def shrink(
    case: ChaosCase,
    test_fn: Callable[[ChaosCase], bool],
    log: Optional[Callable[[str], None]] = None,
) -> ChaosCase:
    """Greedy delta-debugging: repeatedly drop the first single
    component whose removal keeps `test_fn` (\"still violates\") true,
    until no removal does. The fixpoint is 1-MINIMAL: every remaining
    component is individually necessary for the violation (removing any
    one makes it vanish) — not necessarily globally minimum, which
    would need an exponential search the repro loop doesn't."""
    cur = case
    changed = True
    while changed:
        changed = False
        for name, reduced in components(cur):
            if test_fn(reduced):
                if log:
                    log(f"shrink: dropped {name} — still violates")
                cur = reduced
                changed = True
                break
            if log:
                log(f"shrink: {name} is load-bearing")
    return cur


# ---------------------------------------------------------- repro bundle


def _collect_incidents(workdir: str, limit: int = 3) -> List[dict]:
    """Embed any flight-recorder incident bundles the failing runs
    dumped (`<stream>.incidents/incident-*.json`) — the post-mortem
    rides the repro file instead of a path that may not survive CI."""
    found: List[dict] = []
    for root, _dirs, files in os.walk(workdir):
        if not root.endswith(".incidents"):
            continue
        for fname in sorted(files):
            if len(found) >= limit:
                return found
            try:
                with open(os.path.join(root, fname)) as f:
                    found.append({"file": fname, "incident": json.load(f)})
            except (OSError, ValueError):
                found.append({"file": fname, "incident": None})
    return found


def write_repro_bundle(
    path: str, case: ChaosCase, verdict: dict, workdir: str
) -> dict:
    from federated_pytorch_test_tpu.obs.provenance import host_stamp

    doc = {
        "chaos_repro": 1,
        "case": case.to_doc(),
        "violations": verdict["violations"],
        "crashes_fired": verdict.get("crashes_fired", 0),
        "incidents": _collect_incidents(workdir),
        "provenance": host_stamp(),
    }
    text = stamp_crc(doc)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return json.loads(text)


def load_repro_bundle(path: str) -> Tuple[ChaosCase, dict]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("chaos_repro") != 1:
        raise ValueError(f"{path} is not a chaos repro bundle")
    if not verify_crc(doc):
        raise ValueError(
            f"{path}: crc mismatch — the bundle was edited or torn; "
            "re-dump it from a soak rather than hand-fixing"
        )
    return ChaosCase.from_doc(doc["case"]), doc


# ------------------------------------------------------------ planted bug


def _apply_planted_bug(name: str) -> None:
    """Deliberately break the engine (CHAOS_PLANT_BUG=<name>) so CI can
    assert the oracle catches, shrinks, and reproduces a real violation.

    'combiner': replace the Byzantine-robust combiner with a naive
    masked mean that averages non-finite updates straight in — the
    exact failure `consensus/robust.py` exists to prevent, caught by
    the `robust_finite` invariant on soak case 0."""
    if name != "combiner":
        raise SystemExit(f"unknown CHAOS_PLANT_BUG {name!r} (have: combiner)")
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.consensus import admm, fedavg
    from federated_pytorch_test_tpu.parallel import client_sum

    def broken_combine(v_local, mask, method, *, trim_f=0, prev=None,
                       axis_name=None):
        m = mask.astype(v_local.dtype)
        survivors = client_sum(m)
        safe = jnp.where(survivors > 0, survivors, 1.0)
        combined = client_sum(v_local * m[:, None]) / safe
        return combined, jnp.ones(combined.shape, bool)

    fedavg.robust_combine = broken_combine
    admm.robust_combine = broken_combine


# ------------------------------------------------------------------ CLI


def _setup_backend() -> None:
    """The conftest contract, verb-side: drop the ambient TPU plugin and
    pin jax to an 8-device host-CPU mesh BEFORE any engine import, with
    the persistent compile cache warm (a 50-case soak re-jits the same
    tiny shapes constantly)."""
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from federated_pytorch_test_tpu.utils import (
        compile_cache_dir,
        force_host_cpu,
    )

    jax = force_host_cpu(min_devices=8)
    jax.config.update("jax_enable_x64", False)
    cache = compile_cache_dir()
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _soak(args) -> int:
    from federated_pytorch_test_tpu.obs.provenance import host_stamp

    os.makedirs(args.out, exist_ok=True)
    stamp = host_stamp()
    gen = ChaosPlanGenerator(seed=args.seed)
    verdict_path = os.path.join(args.out, "verdicts.jsonl")
    t0 = time.time()
    axes_seen: Dict[str, int] = {}
    knobs_seen: Dict[str, int] = {}
    cleared = 0
    i = args.start_index
    with open(verdict_path, "a") as vf:
        while True:
            if args.cases is not None and cleared >= args.cases:
                break
            if args.budget_s is not None and time.time() - t0 > args.budget_s:
                print(f"# chaos: wall budget {args.budget_s}s exhausted")
                break
            case = gen.draw(i)
            workdir = os.path.join(args.out, f"case-{i:04d}")
            verdict = run_case(case, workdir)
            for ax in case.axes:
                axes_seen[ax] = axes_seen.get(ax, 0) + 1
            for g in case.knobs:
                knobs_seen[g] = knobs_seen.get(g, 0) + 1
            line = {
                **verdict,
                "coverage": {"axes": dict(axes_seen), "knobs": dict(knobs_seen)},
                "provenance": stamp,
            }
            vf.write(json.dumps(line, sort_keys=True) + "\n")
            vf.flush()
            status = "ok" if verdict["ok"] else "VIOLATION"
            print(
                f"# case {i}: {status} axes={','.join(case.axes)} "
                f"knobs={','.join(sorted(case.knobs)) or '-'} "
                f"tags={','.join(case.tags) or '-'} "
                f"wall={verdict['wall_s']}s"
            )
            if not verdict["ok"]:
                for v in verdict["violations"]:
                    print(f"#   {v['invariant']}: {v['detail'][:300]}")
                bundle = _shrink_and_dump(case, verdict, args)
                _write_summary(
                    args, stamp, cleared, 1, axes_seen, knobs_seen, t0
                )
                print(f"# chaos: violation shrunk -> {bundle}")
                return 2
            cleared += 1
            i += 1
    _write_summary(args, stamp, cleared, 0, axes_seen, knobs_seen, t0)
    print(
        f"# chaos: {cleared} case(s) clean, "
        f"{len(axes_seen)}/{len(AXES)} axes and "
        f"{len(knobs_seen)}/{len(KNOB_GROUPS)} knob groups covered, "
        f"{round(time.time() - t0, 1)}s"
    )
    return 0


def _shrink_and_dump(case: ChaosCase, verdict: dict, args) -> str:
    """Minimize the violating case and write the self-contained bundle."""
    bad = {v["invariant"] for v in verdict["violations"]}
    shrink_root = os.path.join(args.out, f"shrink-{case.index:04d}")
    os.makedirs(shrink_root, exist_ok=True)
    counter = {"n": 0}

    def still_violates(candidate: ChaosCase) -> bool:
        counter["n"] += 1
        wd = os.path.join(shrink_root, f"try-{counter['n']:03d}")
        v = run_case(candidate, wd)
        return bool(bad & {x["invariant"] for x in v["violations"]})

    shrunk = shrink(case, still_violates, log=lambda m: print(f"# {m}"))
    wd = os.path.join(shrink_root, "final")
    final_verdict = run_case(shrunk, wd)
    bundle_path = os.path.join(args.out, f"repro-{case.index:04d}.json")
    write_repro_bundle(bundle_path, shrunk, final_verdict, wd)
    print(
        f"# shrunk case {case.index}: axes "
        f"{list(case.axes)} -> {list(shrunk.axes)}, knobs "
        f"{sorted(case.knobs)} -> {sorted(shrunk.knobs)} "
        f"({counter['n']} oracle runs)"
    )
    return bundle_path


def _write_summary(args, stamp, cleared, violations, axes_seen, knobs_seen, t0):
    """The trend-ingestible workload artifact (obs/benchdb.py ingests
    docs with a `workload` key, numeric items namespaced by file stem +
    provenance): chaos coverage becomes a first-class trajectory next
    to the perf smokes."""
    doc = {
        "workload": "chaos_soak",
        "seed": args.seed,
        "cases_cleared": cleared,
        "violations": violations,
        "axes_covered": len(axes_seen),
        "knob_groups_covered": len(knobs_seen),
        "wall_s": round(time.time() - t0, 3),
        "provenance": stamp,
    }
    path = os.path.join(args.out, "chaos_soak.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(stamp_crc(doc) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _repro(args) -> int:
    case, doc = load_repro_bundle(args.repro)
    wanted = {v["invariant"] for v in doc.get("violations", [])}
    workdir = os.path.join(args.out, "repro")
    verdict = run_case(case, workdir)
    got = {v["invariant"] for v in verdict["violations"]}
    print(
        f"# repro {args.repro}: recorded {sorted(wanted)}, observed "
        f"{sorted(got)}"
    )
    if wanted & got:
        print("# repro: violation REPRODUCES")
        return 0
    print("# repro: violation did NOT reproduce")
    return 1


def chaos_main(argv: Optional[Sequence[str]] = None) -> int:
    """`chaos` verb entry point (engine-import-free dispatch).

    Usage:
      chaos [--budget-s S] [--cases N] [--seed S] [--out DIR]
      chaos --repro FILE [--out DIR]

    Soak mode fuzzes composed fault configurations under the invariant
    oracle until the case target or the wall budget is hit; any
    violation is shrunk to a 1-minimal repro bundle and exits 2. Repro
    mode replays a bundle and exits 0 iff the recorded violation
    reproduces. `CHAOS_PLANT_BUG=combiner` deliberately breaks the
    robust combiner first (the CI self-test).
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu chaos",
        description="composed fault-plan fuzzer + invariant oracle + shrinker",
    )
    parser.add_argument(
        "--budget-s", type=float, default=None,
        help="wall budget for the soak (seconds)",
    )
    parser.add_argument(
        "--cases", type=int, default=None,
        help="stop after this many CLEAN cases (default: budget-bound; "
        "50 with no budget either)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--start-index", type=int, default=0,
        help="first generator case index (resume a soak's sequence)",
    )
    parser.add_argument(
        "--out", default="chaos_runs",
        help="verdicts, run dirs, bundles and the soak summary land here",
    )
    parser.add_argument(
        "--repro", default=None,
        help="replay a repro bundle instead of soaking",
    )
    args = parser.parse_args(argv)
    if args.repro is None and args.budget_s is None and args.cases is None:
        args.cases = 50
    _setup_backend()
    plant = os.environ.get("CHAOS_PLANT_BUG")
    if plant:
        print(f"# chaos: PLANTED BUG active: {plant}")
        _apply_planted_bug(plant)
    if args.repro is not None:
        return _repro(args)
    return _soak(args)


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(chaos_main())
