"""Cross-run experiment registry + the `report` CLI verb.

A codec / combiner / deadline sweep produces a directory of JSONL metric
streams (obs/sinks.py, one per run). Comparing them used to be ad-hoc jq
— in particular ROADMAP item 3's convergence-vs-bytes frontier (accuracy
against cumulative `comm_bytes` per run) had no tooling at all. The
registry ingests such a directory, validates every stream, aligns the
runs on round index, and emits comparison tables plus the frontier as
JSON and markdown:

    python -m federated_pytorch_test_tpu report runs/ --json report.json

Validation mirrors the resume path's stream checks (obs/sinks.py
`_scan`): a file whose first parsable line is not a `stream_header`, or
whose header version is unsupported, is REFUSED (skipped with a warning
in directory mode) rather than half-parsed — splicing a foreign file
into a comparison would be worse than dropping it. Within an accepted
stream the same tolerance applies: a torn final line (crash mid-write)
is dropped, and nothing past the first unparsable line is trusted. For
version-2 streams every line carries a CRC (fault/io.py): a bit-rotted
but still-parsable line is dropped — with everything after it — exactly
like a torn tail, instead of being spliced into the report as truth.
Version-1 streams (pre-integrity archives) are still accepted, without
the per-line check.
`--match SUBSTR` additionally refuses streams whose header tag does not
contain the substring (the registry-side analogue of the resume tag
check, for directories that mix experiments).

Determinism contract: the report is a pure function of the streams'
RECORD CONTENT — never wall-clock `t` fields, `step_time` seconds, or
the raw header tag (crashed+resumed twins legitimately differ in all
three). Runs are keyed by file stem and labeled by the tag's
`<preset>:seed<N>` prefix, so a crashed+resumed run's report is
byte-identical to its uninterrupted twin's (the tier-2 `report_smoke`
gate, scripts/ci.sh).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import warnings
from typing import Dict, List, Optional, Tuple

from federated_pytorch_test_tpu.fault.io import verify_crc
from federated_pytorch_test_tpu.obs.sinks import STREAM_VERSION

REPORT_VERSION = 1

# stream format versions this reader accepts: v1 (no per-line CRC —
# archived pre-integrity runs) and the current checksummed v2
_READ_VERSIONS = (1, STREAM_VERSION)


class StreamRefused(ValueError):
    """A file the registry will not treat as a metric stream (missing or
    foreign header, unsupported version, tag filter mismatch)."""


class RunStream:
    """One ingested metric stream: header identity + parsed records."""

    def __init__(self, name: str, tag: str, path: str):
        self.name = name
        self.tag = tag
        self.path = path
        # the stable cross-twin label: '<preset>:seed<N>' (the config/plan
        # digests that follow legitimately differ between a crashed run
        # and its uninterrupted twin)
        self.label = ":".join(tag.split(":")[:2]) if tag else ""
        self.records: List[Tuple[str, dict]] = []  # (series, record)
        self.markers: List[int] = []  # nloop_complete values, in order


def read_stream(path: str, name: Optional[str] = None) -> RunStream:
    """Parse one JSONL metric stream; raises `StreamRefused` if the file
    does not open with a valid same-version `stream_header`."""
    with open(path, "rb") as f:
        data = f.read()
    run = None
    checked = False
    for raw in data.splitlines(keepends=True):
        if not raw.endswith(b"\n"):
            break  # torn tail from a crash mid-write
        try:
            d = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            break  # nothing past an unparsable line is trustworthy
        if run is None:
            if d.get("event") != "stream_header":
                raise StreamRefused(
                    f"{path}: first line is not a stream_header — not a "
                    "metric stream"
                )
            if d.get("version") not in _READ_VERSIONS:
                raise StreamRefused(
                    f"{path}: stream version {d.get('version')!r} not in "
                    f"{_READ_VERSIONS} — refusing to misread a foreign "
                    "format"
                )
            checked = d.get("version") >= 2  # v2+: per-line CRC stamped
            if checked and not verify_crc(d):
                raise StreamRefused(
                    f"{path}: stream_header failed its line checksum"
                )
            run = RunStream(
                name or os.path.splitext(os.path.basename(path))[0],
                str(d.get("tag", "")),
                path,
            )
            continue
        if checked:
            if not verify_crc(d):
                break  # bit-rotted line: dropped like a torn tail
            d.pop("crc", None)
        if d.get("event") == "nloop_complete":
            run.markers.append(int(d.get("nloop", -1)))
        elif "series" in d:
            series = d.pop("series")
            run.records.append((series, d))
    if run is None:
        raise StreamRefused(f"{path}: empty or unparsable file")
    return run


def _mean(xs) -> Optional[float]:
    xs = [float(x) for x in xs]
    return sum(xs) / len(xs) if xs else None


class RunRegistry:
    """Ingests validated metric streams and produces the cross-run
    report (see module docstring)."""

    def __init__(self, match: Optional[str] = None):
        self.match = match
        self.runs: Dict[str, RunStream] = {}

    def ingest(self, path: str, name: Optional[str] = None) -> RunStream:
        run = read_stream(path, name=name)
        if self.match and self.match not in run.tag:
            raise StreamRefused(
                f"{path}: header tag {run.tag!r} does not match "
                f"{self.match!r} — foreign experiment refused"
            )
        if run.name in self.runs:
            raise StreamRefused(
                f"{path}: run name {run.name!r} already ingested "
                f"(from {self.runs[run.name].path})"
            )
        self.runs[run.name] = run
        return run

    def ingest_dir(self, d: str, pattern: str = "*.jsonl") -> List[str]:
        """Ingest every matching stream under `d`; refused files are
        skipped with a warning. Returns the skipped paths."""
        skipped = []
        for path in sorted(_glob.glob(os.path.join(d, pattern))):
            try:
                self.ingest(path)
            except StreamRefused as e:
                warnings.warn(str(e))
                skipped.append(path)
        return skipped

    # ------------------------------------------------------------- analysis

    @staticmethod
    def _run_summary(run: RunStream) -> dict:
        cum_bytes = 0
        cum_sim_wall = 0.0
        curve: List[dict] = []
        comm_summary = None
        health_records = 0
        health_anomalies = 0
        health_last = None
        exchanges = 0
        deadlines: List[float] = []
        deadline_sources: Dict[str, int] = {}
        # adaptive layer-group scheduling evidence (exchange/schedule.py
        # `group_schedule` records): presence marks the run adaptive,
        # skipped slots sum into bytes_saved_by_skipping — uplink the
        # scheduler saved by sending NOTHING for drift-quiet slots
        schedule = None
        skipped_rounds = 0
        bytes_saved = 0
        # the virtual-client axis (clients/, docs/SCALE.md): per-loop
        # `cohort` membership records + the end-of-run participation
        # digest — both streamed and twin-stable, unlike the store's
        # residency/spill counters (process facts: they live in the
        # `watch` sidecar and incident bundles, never in a report)
        cohort_loops = 0
        cohort_size = None
        cohort_part = None
        for series, rec in run.records:
            if series == "comm_bytes":
                cum_bytes += int(rec["value"])
                exchanges += 1
            elif series == "group_schedule":
                schedule = "adaptive"
                v = rec.get("value")
                if isinstance(v, dict) and v.get("skipped"):
                    skipped_rounds += 1
                    bytes_saved += int(v.get("saved_bytes", 0))
            elif series == "client_time":
                # each exchange's SIMULATED round wall (the coordinator
                # closes the round at min(slowest client, deadline) —
                # engine/trainer.py _record_hetero); cumulative over the
                # run it is the deadline frontier's time axis
                v = rec.get("value")
                if isinstance(v, dict) and v.get("round") is not None:
                    cum_sim_wall += float(v["round"])
            elif series == "deadline":
                v = rec.get("value")
                if isinstance(v, dict) and v.get("seconds") is not None:
                    deadlines.append(float(v["seconds"]))
                    src = str(v.get("source", "fixed"))
                    deadline_sources[src] = deadline_sources.get(src, 0) + 1
            elif series == "test_accuracy":
                acc = _mean(rec["value"])
                curve.append(
                    {
                        "eval": len(curve),
                        "nloop": rec.get("nloop"),
                        "group": rec.get("group"),
                        "nadmm": rec.get("nadmm"),
                        "cum_bytes": cum_bytes,
                        "cum_sim_wall_s": round(cum_sim_wall, 6),
                        "accuracy": round(acc, 6) if acc is not None else None,
                    }
                )
            elif series == "cohort":
                v = rec.get("value")
                if isinstance(v, dict) and v.get("clients") is not None:
                    cohort_loops += 1
                    cohort_size = len(v["clients"])
            elif series == "cohort_participation":
                if isinstance(rec.get("value"), dict):
                    cohort_part = rec["value"]
            elif series == "comm_summary":
                comm_summary = rec["value"]
            elif series == "health":
                health_records += 1
                v = rec.get("value")
                if isinstance(v, dict):
                    health_anomalies += len(v.get("anomalies", ()))
                    health_last = v
        final_acc = curve[-1]["accuracy"] if curve else None
        # the wire identity the frontier labels points with: the codec
        # descriptor the comm summary carries (exchange/codec.py
        # describe()), falling back to the dense dtype name for streams
        # from codec-less ledgers, plus the schedule policy
        codec_label = None
        if comm_summary is not None:
            cd = comm_summary.get("codec")
            if isinstance(cd, dict) and cd.get("label"):
                codec_label = str(cd["label"])
            elif comm_summary.get("exchange_dtype") == "bfloat16":
                codec_label = "bf16"
            elif comm_summary.get("exchange_dtype"):
                codec_label = "identity"
        config_label = (
            f"{codec_label or '?'}/{schedule or 'roundrobin'}"
        )
        summary: dict = {
            "experiment": run.label,
            "stream": {
                "records": len(run.records),
                "markers": len(run.markers),
            },
            "config": {
                "codec": codec_label,
                "schedule": schedule or "roundrobin",
                "label": config_label,
            },
            "exchanges": exchanges,
            "evals": len(curve),
            "final_accuracy": final_acc,
            "total_comm_bytes": cum_bytes,
            "skipped_rounds": skipped_rounds,
            "bytes_saved_by_skipping": bytes_saved,
            "sim_round_wall_total_s": round(cum_sim_wall, 6),
            "curve": curve,
        }
        if cohort_loops:
            summary["cohort"] = {
                "loops": cohort_loops,
                "cohort_size": cohort_size,
                "n_virtual": (
                    cohort_part.get("n_virtual") if cohort_part else None
                ),
                "sampled_ever": (
                    cohort_part.get("sampled_ever") if cohort_part else None
                ),
            }
        if deadlines:
            summary["deadline"] = {
                "mean_s": round(sum(deadlines) / len(deadlines), 6),
                "rounds": len(deadlines),
                "sources": dict(sorted(deadline_sources.items())),
            }
        if comm_summary is not None:
            summary["comm"] = {
                k: comm_summary.get(k)
                for k in (
                    "exchange_dtype", "wire_bytes_per_value",
                    "bytes_per_round_mean", "savings_vs_full",
                )
            }
        summary["health"] = {
            "records": health_records,
            "anomalies": health_anomalies,
            "final_window": (
                health_last.get("window") if health_last else None
            ),
        }
        return summary

    @staticmethod
    def _pareto(points: List[Tuple[str, float, Optional[float]]],
                cost_key: str) -> List[dict]:
        """Final-point Pareto frontier over (cost ↓, accuracy ↑): a run
        is dominated if another reaches >= accuracy at <= cost (strictly
        better on at least one axis). `cost_key` names the cost field in
        the emitted rows."""
        frontier = []

        def _acc(a):
            return a if a is not None else -1.0

        for name, c, a in sorted(points, key=lambda p: (p[1], p[0])):
            dominated = any(
                other != name
                and oc <= c
                and _acc(oa) >= _acc(a)
                and (oc < c or _acc(oa) > _acc(a))
                for other, oc, oa in points
            )
            frontier.append(
                {
                    "run": name,
                    cost_key: c,
                    "final_accuracy": a,
                    "pareto": not dominated,
                }
            )
        return frontier

    def incidents(self) -> dict:
        """The cross-run incident table (`report --incidents`): every
        flight-recorder bundle under each ingested stream's
        `<stream>.incidents/` directory (obs/flight.py), schema-
        validated — an invalid bundle is skipped with a warning, the
        refused-stream rule applied to forensics. Rows carry only
        content-derived fields (kinds, triggering round, bundle
        basename, record counts) — no wall-clock, no tag — so a
        crashed+resumed twin directory tables byte-identically."""
        from federated_pytorch_test_tpu.obs.flight import (
            list_incidents,
            validate_incident,
        )

        rows = []
        for name, run in sorted(self.runs.items()):
            for fname, bundle in list_incidents(run.path):
                if bundle is None:
                    warnings.warn(
                        f"{run.path}: unreadable incident bundle {fname}"
                    )
                    continue
                try:
                    validate_incident(bundle)
                except ValueError as e:
                    warnings.warn(
                        f"{run.path}: invalid incident bundle {fname}: {e}"
                    )
                    continue
                rows.append(
                    {
                        "run": name,
                        "bundle": fname,
                        "kind": bundle["kind"],
                        "anomalies": list(bundle["anomalies"]),
                        "nloop": bundle["nloop"],
                        "round": bundle["round"],
                        "rounds_held": len(bundle["rounds"]),
                        "records": sum(
                            len(b["records"]) for b in bundle["rounds"]
                        ),
                    }
                )
        return {"count": len(rows), "bundles": rows}

    def integrity(self) -> dict:
        """The cross-run storage-integrity table (`report --integrity`):
        each ingested stream's `<stream>.status.json` sidecar carries the
        store's integrity digest (verified reads, checksum failures,
        retry heals, repairs — clients/store.py `integrity_digest`).
        These are PROCESS facts — a crashed+resumed twin legitimately
        differs from its uninterrupted twin in every one of them — so
        they live behind this explicit flag, never in the default
        report document (the determinism contract, module docstring)."""
        rows = []
        for name, run in sorted(self.runs.items()):
            path = run.path + ".status.json"
            try:
                with open(path) as f:
                    status = json.load(f)
            except (OSError, ValueError):
                continue
            dig = status.get("integrity")
            if not isinstance(dig, dict):
                continue
            row = {
                "run": name,
                "checksums": dig.get("checksums"),
                "alg": dig.get("alg"),
                "verified_reads": dig.get("verified_reads"),
                "failures": dig.get("failures"),
                "retry_heals": dig.get("retry_heals"),
                "repairs_prior": dig.get("repairs_prior"),
                "repairs_reinit": dig.get("repairs_reinit"),
                "storage_faults": status.get("storage_faults"),
            }
            prov = status.get("provenance")
            if isinstance(prov, dict):
                # who produced this run's numbers (obs/provenance.py) —
                # a process fact like the rest of this table, so it
                # rides the same behind-the-flag row, never the
                # deterministic default report
                from federated_pytorch_test_tpu.obs.provenance import (
                    provenance_class,
                )

                row["provenance"] = {
                    "class": provenance_class(prov),
                    "git_sha": prov.get("git_sha"),
                }
            rows.append(row)
        return {"count": len(rows), "runs": rows}

    def report(self) -> dict:
        """The full cross-run document: per-run summaries + curves,
        round-aligned comparison series, the convergence-vs-bytes
        frontier, and — for runs carrying the simulated-wall evidence
        (`client_time` records: any deadline or heterogeneous run) —
        the convergence-vs-deadline frontier (accuracy against total
        simulated round wall; the ROADMAP-item-3 acceptance surface).
        Deterministic (runs sorted by name, no wall-clock content) —
        twin directories produce byte-identical output."""
        if not self.runs:
            raise ValueError("no runs ingested")
        runs = {
            name: self._run_summary(run)
            for name, run in sorted(self.runs.items())
        }
        aligned_acc = {
            name: [p["accuracy"] for p in s["curve"]]
            for name, s in runs.items()
        }
        aligned_bytes = {
            name: [p["cum_bytes"] for p in s["curve"]]
            for name, s in runs.items()
        }
        frontier = self._pareto(
            [
                (name, s["total_comm_bytes"], s["final_accuracy"])
                for name, s in runs.items()
            ],
            "total_comm_bytes",
        )
        for p in frontier:
            # label every point with its codec+scheduler config (not
            # just preset:seed) and the uplink the scheduler saved by
            # sending nothing — both content-derived, so twin
            # directories stay byte-identical
            s = runs[p["run"]]
            p["config"] = s["config"]["label"]
            p["bytes_saved_by_skipping"] = s["bytes_saved_by_skipping"]
        doc = {
            "report_version": REPORT_VERSION,
            "runs": runs,
            "aligned": {
                "accuracy_by_eval": aligned_acc,
                "cum_bytes_by_eval": aligned_bytes,
            },
            "frontier": frontier,
        }
        # the deadline frontier only exists over runs that MEASURED a
        # simulated wall (deadline or heterogeneous runs); mixing in
        # wall-less runs at 0.0 would hand them the frontier for free
        timed = {
            name: s
            for name, s in runs.items()
            if s["sim_round_wall_total_s"] > 0
        }
        if timed:
            rows = []
            for name, s in timed.items():
                row = (name, s["sim_round_wall_total_s"],
                       s["final_accuracy"])
                rows.append(row)
            deadline_frontier = self._pareto(rows, "sim_round_wall_s")
            for p in deadline_frontier:
                dl = timed[p["run"]].get("deadline")
                p["deadline_mean_s"] = dl["mean_s"] if dl else None
            doc["deadline_frontier"] = deadline_frontier
        return doc


def render_markdown(doc: dict) -> str:
    """The report document as a compact markdown digest."""
    lines = ["# Experiment report", "", "## Runs", ""]
    lines.append(
        "| run | experiment | config | evals | final acc | comm bytes | "
        "exchanges | health anomalies |"
    )
    lines.append("|---|---|---|---|---|---|---|---|")
    for name, s in doc["runs"].items():
        acc = (
            f"{s['final_accuracy']:.4f}"
            if s["final_accuracy"] is not None
            else "-"
        )
        cfg_label = s.get("config", {}).get("label", "-")
        lines.append(
            f"| {name} | {s['experiment']} | {cfg_label} | {s['evals']} "
            f"| {acc} | {s['total_comm_bytes']:,} | {s['exchanges']} "
            f"| {s['health']['anomalies']} |"
        )
    if any(s.get("cohort") for s in doc["runs"].values()):
        lines += ["", "## Virtual-client fleet", ""]
        lines.append(
            "| run | population | cohort | loops | ever sampled |"
        )
        lines.append("|---|---|---|---|---|")
        for name, s in doc["runs"].items():
            c = s.get("cohort")
            if not c:
                continue
            nv = c["n_virtual"] if c["n_virtual"] is not None else "-"
            ev = c["sampled_ever"] if c["sampled_ever"] is not None else "-"
            lines.append(
                f"| {name} | {nv} | {c['cohort_size']} | {c['loops']} "
                f"| {ev} |"
            )
        lines.append("")
        lines.append(
            "Store residency/spill and prefetch walls are process "
            "facts (they differ across a crashed+resumed twin pair) — "
            "they surface in `watch`'s sidecar panel and incident "
            "bundles, never in a report."
        )
    lines += ["", "## Convergence vs bytes frontier", ""]
    lines.append(
        "| run | config | total comm bytes | bytes saved by skipping "
        "| final acc | pareto |"
    )
    lines.append("|---|---|---|---|---|---|")
    for p in doc["frontier"]:
        acc = (
            f"{p['final_accuracy']:.4f}"
            if p["final_accuracy"] is not None
            else "-"
        )
        flag = "*" if p["pareto"] else "dominated"
        lines.append(
            f"| {p['run']} | {p.get('config', '-')} "
            f"| {p['total_comm_bytes']:,} "
            f"| {p.get('bytes_saved_by_skipping', 0):,} | {acc} | {flag} |"
        )
    lines.append("")
    lines.append(
        "`*` = on the frontier: no other run reached at least this "
        "accuracy with at most these bytes; every other point is "
        "explicitly `dominated`. `bytes saved by skipping` sums the "
        "uplink the adaptive scheduler declined to spend (skipped "
        "slots' `group_schedule` records)."
    )
    if doc.get("deadline_frontier"):
        lines += ["", "## Convergence vs deadline frontier", ""]
        lines.append(
            "| run | sim round wall (s) | deadline mean (s) | final acc "
            "| pareto |"
        )
        lines.append("|---|---|---|---|---|")
        for p in doc["deadline_frontier"]:
            acc = (
                f"{p['final_accuracy']:.4f}"
                if p["final_accuracy"] is not None
                else "-"
            )
            dl = (
                f"{p['deadline_mean_s']:g}"
                if p.get("deadline_mean_s") is not None
                else "-"
            )
            star = "*" if p["pareto"] else ""
            lines.append(
                f"| {p['run']} | {p['sim_round_wall_s']:g} | {dl} "
                f"| {acc} | {star} |"
            )
        lines.append("")
        lines.append(
            "`*` = on the frontier: no other run reached at least this "
            "accuracy in at most this simulated round wall."
        )
    if doc.get("integrity") is not None:
        intg = doc["integrity"]
        lines += ["", "## Storage integrity", ""]
        if not intg["runs"]:
            lines.append(
                "No status sidecars with integrity digests next to the "
                "ingested streams."
            )
        else:
            lines.append(
                "| run | checksums | alg | verified reads | failures "
                "| retry heals | repairs (prior) | repairs (reinit) "
                "| injected storage faults |"
            )
            lines.append("|---|---|---|---|---|---|---|---|---|")
            for r in intg["runs"]:
                sf = r["storage_faults"]
                lines.append(
                    f"| {r['run']} | {'on' if r['checksums'] else 'off'} "
                    f"| {r['alg'] or '-'} | {r['verified_reads']} "
                    f"| {r['failures']} | {r['retry_heals']} "
                    f"| {r['repairs_prior']} | {r['repairs_reinit']} "
                    f"| {sf if sf is not None else '-'} |"
                )
            lines.append("")
            lines.append(
                "Integrity counters are process facts (a crashed+resumed "
                "run legitimately differs from its uninterrupted twin) — "
                "they appear only behind `--integrity`, never in the "
                "default report."
            )
    if doc.get("incidents") is not None:
        inc = doc["incidents"]
        lines += ["", "## Incidents", ""]
        if not inc["bundles"]:
            lines.append(
                "No incident bundles under the ingested streams' "
                "`.incidents/` directories."
            )
        else:
            lines.append(
                "| run | bundle | kind | anomalies | nloop | round "
                "| rounds held | records |"
            )
            lines.append("|---|---|---|---|---|---|---|---|")
            for r in inc["bundles"]:
                an = ",".join(r["anomalies"]) or "-"
                lines.append(
                    f"| {r['run']} | {r['bundle']} | {r['kind']} | {an} "
                    f"| {r['nloop']} | {r['round']} | {r['rounds_held']} "
                    f"| {r['records']} |"
                )
    lines.append("")
    return "\n".join(lines)


def report_main(argv=None) -> int:
    """`python -m federated_pytorch_test_tpu report <dir>` — pure
    host-side file analysis: no accelerator backend is ever
    initialized, so it is safe on hosts whose TPU runtime is absent
    (or would block on init)."""
    ap = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu report",
        description=(
            "Cross-run comparison over a directory of JSONL metric "
            "streams: per-run tables, round-aligned series, and the "
            "convergence-vs-bytes frontier (docs/OBSERVABILITY.md)."
        ),
    )
    ap.add_argument("dir", help="directory of --metrics-stream JSONL files")
    ap.add_argument(
        "--glob", default="*.jsonl", help="stream filename pattern"
    )
    ap.add_argument(
        "--match",
        default=None,
        help="refuse streams whose header tag lacks this substring "
        "(e.g. 'fedavg:seed0' to pin one experiment family)",
    )
    ap.add_argument("--json", default=None, help="write the JSON report here")
    ap.add_argument("--md", default=None, help="write the markdown here")
    ap.add_argument(
        "--incidents",
        action="store_true",
        help="add the cross-run incident-bundle table (flight-recorder "
        "bundles under each stream's .incidents/ dir, obs/flight.py)",
    )
    ap.add_argument(
        "--integrity",
        action="store_true",
        help="add the per-run storage-integrity table (status-sidecar "
        "digests: verified reads, checksum failures, repairs) — process "
        "facts, so excluded from the default report",
    )
    ap.add_argument(
        "--quiet", action="store_true", help="suppress the stdout markdown"
    )
    args = ap.parse_args(argv)

    reg = RunRegistry(match=args.match)
    skipped = reg.ingest_dir(args.dir, pattern=args.glob)
    if not reg.runs:
        print(
            f"report: no valid metric streams under {args.dir!r} "
            f"(pattern {args.glob!r}; {len(skipped)} file(s) refused)"
        )
        return 1
    doc = reg.report()
    if args.incidents:
        doc["incidents"] = reg.incidents()
    if args.integrity:
        doc["integrity"] = reg.integrity()
    md = render_markdown(doc)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.md:
        with open(args.md, "w") as f:
            f.write(md)
    if not args.quiet:
        print(md, end="")
    return 0
