"""Provenance stamps: every performance number says WHERE it came from.

Eight straight sessions closed with "no TPU reachable, re-measure
later", and nothing in the artifacts distinguishes a CPU-twin guess
from a real chip measurement — a stale host number can masquerade as a
TPU result the moment the filename stops saying so. This module is the
fix at the source: one small self-describing stamp attached to every
measurement artifact the repo emits —

* `bench.py` headlines (and the `benchmarks/bench_full.json` blob),
* both `benchmarks/*_tpu.py` output JSONs,
* the trainer's end-of-run `roofline` record (obs/roofline.py),
* the `<stream>.status.json` live sidecar (`watch` renders a one-line
  `backend/sha/twin` row from it).

The stamp answers: which commit (sha + dirty flag), which backend and
chip (platform, device kind and count), which host (hostname, cpu
count), which jax, whether this is the CPU twin, and how many bench
repeats stood behind the number. `provenance_class` collapses a stamp
to the ISOLATION KEY the trend layer compares within (obs/benchdb.py):
CPU-twin numbers compare against CPU-twin baselines, TPU against TPU,
never across — and an unstamped (pre-provenance) artifact is its own
class, forever unable to close a `backend==tpu` re-measurement debt
entry (DEBT.json, the `debt` verb).

Import rules: this module is accelerator-free. `provenance_stamp`
PROBES jax only when asked (`probe_jax=True` — callers that already
initialized a backend: the trainer, bench.py, the benchmark harnesses);
`host_stamp` never touches jax at all (the jax version comes from
package metadata, no import) — it is the stamp for host-side facts like
the CI tier walls, which always run the forced-CPU virtual mesh
(tests/conftest.py), so `backend: cpu` is the honest label.
"""

from __future__ import annotations

import os
import socket
import subprocess
from typing import Optional, Tuple

STAMP_SCHEMA = 1

# the stamp's full key set, in canonical order (consumers slice this,
# never invent keys)
STAMP_KEYS = (
    "schema",
    "git_sha",
    "git_dirty",
    "backend",
    "device_kind",
    "device_count",
    "host",
    "cpu_count",
    "jax_version",
    "cpu_twin",
    "bench_repeats",
)

_CACHED_STAMP: Optional[dict] = None


def git_info(root: Optional[str] = None) -> Tuple[Optional[str], Optional[bool]]:
    """`(short_sha, dirty)` of the working tree, or `(None, None)` when
    git (or the repo) is unavailable — a stamp from an exported tarball
    is still a stamp, just commit-less."""
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if sha.returncode != 0:
            return None, None
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        dirty = bool(status.stdout.strip()) if status.returncode == 0 else None
        return sha.stdout.strip() or None, dirty
    except Exception:
        return None, None


def _jax_version() -> Optional[str]:
    """The installed jax version WITHOUT importing jax (package
    metadata only) — safe in backend-free verbs."""
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:
        return None


def provenance_stamp(
    *,
    repeats: Optional[int] = None,
    probe_jax: bool = True,
    backend: Optional[str] = None,
    device_kind: Optional[str] = None,
    device_count: Optional[int] = None,
) -> dict:
    """Build one provenance stamp.

    `probe_jax=True` (default) reads backend/device facts from an
    ALREADY-IMPORTABLE jax — `jax.default_backend()` initializes the
    backend, so only call it from processes that run device work anyway
    (the trainer, bench.py, benchmarks/). Backend-free callers pass the
    facts explicitly or use `host_stamp`. Any probe failure degrades to
    nulls: a stamp is never the thing that kills a run.
    """
    if probe_jax and backend is None:
        try:
            import jax

            backend = jax.default_backend()
            devs = jax.devices()
            device_kind = devs[0].device_kind
            device_count = len(devs)
        except Exception:
            pass
    sha, dirty = git_info()
    return {
        "schema": STAMP_SCHEMA,
        "git_sha": sha,
        "git_dirty": dirty,
        "backend": backend,
        "device_kind": device_kind,
        "device_count": device_count,
        "host": socket.gethostname(),
        "cpu_count": os.cpu_count(),
        "jax_version": _jax_version(),
        "cpu_twin": (backend == "cpu") if backend is not None else None,
        "bench_repeats": repeats,
    }


def host_stamp(repeats: Optional[int] = None) -> dict:
    """A stamp for HOST-side measurements (CI tier walls, preflight
    findings): no jax probe, `backend: cpu` asserted — honest because
    the CI suite always runs the forced-CPU virtual mesh
    (tests/conftest.py `JAX_PLATFORMS=cpu`)."""
    return provenance_stamp(repeats=repeats, probe_jax=False, backend="cpu")


def cached_stamp(repeats: Optional[int] = None) -> dict:
    """One stamp per process (git subprocesses run once): the trainer
    rewrites the status sidecar every round and must not fork git each
    time. `repeats`, when given, overrides the cached stamp's field."""
    global _CACHED_STAMP
    if _CACHED_STAMP is None:
        _CACHED_STAMP = provenance_stamp()
    stamp = dict(_CACHED_STAMP)
    if repeats is not None:
        stamp["bench_repeats"] = repeats
    return stamp


def provenance_class(stamp) -> str:
    """Collapse a stamp to the trend layer's ISOLATION KEY.

    * no stamp (pre-provenance artifacts) -> `unstamped` — comparable
      only against other unstamped history, never a baseline for (or
      closer of) anything conditioned on a backend;
    * `cpu_twin` stamps -> `cpu_twin`;
    * everything else -> the backend string (`tpu`, `gpu`, ...), or
      `unstamped` when the stamp carries no backend at all.
    """
    if not isinstance(stamp, dict):
        return "unstamped"
    if stamp.get("cpu_twin"):
        return "cpu_twin"
    backend = stamp.get("backend")
    if not backend:
        return "unstamped"
    return str(backend)


def condition_satisfied(condition: str, stamp) -> bool:
    """Evaluate a DEBT.json owed-condition against a stamp.

    The grammar is deliberately tiny — conjunctions of equality tests
    over stamp keys: `backend==tpu`, `cpu_twin==false`,
    `backend==tpu and git_dirty==false`. Values compare as
    case-insensitive strings (`True` == `true`). An ABSENT stamp (or
    absent key) satisfies nothing: unstamped measurements cannot close
    debt, the provenance-class isolation rule as a parser property.
    """
    condition = (condition or "").strip()
    if not condition:
        return True
    if not isinstance(stamp, dict):
        return False
    for clause in condition.split(" and "):
        clause = clause.strip()
        if "!=" in clause:
            key, want = clause.split("!=", 1)
            negate = True
        elif "==" in clause:
            key, want = clause.split("==", 1)
            negate = False
        else:
            raise ValueError(f"unparsable debt condition clause: {clause!r}")
        key, want = key.strip(), want.strip().lower()
        have = stamp.get(key)
        if have is None:
            return False  # an unprovable clause never satisfies
        match = str(have).lower() == want
        if match == negate:
            return False
    return True
