"""Memory telemetry: host RSS + per-device allocator stats.

ROADMAP item 4's spilled-store work needs a bounded-RSS *gate*, and a
gate needs a measurement: this module is the one place host and device
memory are read, feeding the trainer's per-round `memory` series, the
`watch` console's memory panel (via the status sidecar — see below), and
bench.py's `memory_rss_peak_mb` headline.

Sources, each gracefully None where absent:

* **host** — `/proc/self/status` `VmRSS` (current) and `VmHWM` (peak)
  on Linux; `resource.getrusage` ru_maxrss as the peak fallback
  elsewhere (there is no portable *current*-RSS source without psutil,
  which this repo does not depend on).
* **device** — `device.memory_stats()` per addressable device:
  `bytes_in_use` / `peak_bytes_in_use` / `bytes_limit` / allocation
  counts where the backend's allocator exposes them (TPU and GPU BFC
  allocators do; the CPU backend typically returns nothing — recorded
  as None, never an error).

Memory numbers are facts about THIS PROCESS — a resumed run's RSS has
nothing to do with the crashed one's — so the trainer records the
`memory` series with `stream=False` (the `recompile_count` rule):
crash+resume twin metric streams stay byte-identical with the telemetry
on. The live surface for `watch` is instead the atomically-rewritten
`<stream>.status.json` sidecar (engine/trainer.py `_write_status`).

`jax` is imported inside the device functions only, so the analysis
verbs (`report`, `watch`) can import this module without initializing
an accelerator backend.
"""

from __future__ import annotations

from typing import List, Optional

# allocator keys worth recording where present (jax device.memory_stats
# vocabulary — backends report a superset or nothing at all)
_DEVICE_KEYS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "num_allocs",
    "largest_alloc_size",
)


def _proc_status_kb(key: str) -> Optional[int]:
    """One `VmXXX:  N kB` row of /proc/self/status, or None off-Linux."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(key + ":"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return None


def host_rss_bytes() -> Optional[int]:
    """Current resident set size of this process, or None where no
    current-RSS source exists (non-Linux without psutil)."""
    kb = _proc_status_kb("VmRSS")
    return kb * 1024 if kb is not None else None


def host_rss_peak_bytes() -> Optional[int]:
    """Peak resident set size of this process — the bounded-RSS gate's
    number (ROADMAP item 4)."""
    kb = _proc_status_kb("VmHWM")
    if kb is not None:
        return kb * 1024
    try:
        import resource

        # ru_maxrss is kilobytes on Linux (moot — /proc handled it) and
        # bytes on macOS; scale for the only platform that reaches here
        # with kB semantics absent
        peak = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        import sys

        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:
        return None


def device_memory_stats(devices=None) -> List[Optional[dict]]:
    """Per-device allocator stats (`_DEVICE_KEYS` where present), one
    entry per addressable device; None for backends whose allocator
    reports nothing (host CPU) — graceful, never an error."""
    import jax

    if devices is None:
        devices = jax.local_devices()
    out: List[Optional[dict]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            out.append(None)
        else:
            out.append(
                {k: int(stats[k]) for k in _DEVICE_KEYS if k in stats}
            )
    return out


def memory_record(devices=None) -> dict:
    """The `memory` series value: host RSS (current + peak) and the
    per-device allocator stats — all host-side reads, zero device
    dispatches (the folded round stays `{round: 1, round_init: 1}`
    with the telemetry on)."""
    return {
        "rss_bytes": host_rss_bytes(),
        "peak_rss_bytes": host_rss_peak_bytes(),
        "devices": device_memory_stats(devices),
    }
