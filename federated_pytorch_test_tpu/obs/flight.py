"""Flight recorder: a bounded ring over the stream + incident bundles.

The health engine (obs/health.py) DETECTS a bad round; until this module
the operator's next step was hand-reconstructing the incident from the
raw JSONL stream — find the round, scrape the surrounding records, guess
which deadline/schedule decisions and fault-plan rows were live. The
flight recorder keeps that reconstruction ALREADY DONE, bounded: a ring
buffer of the last `--flight-window` completed partition rounds' streamed
records, dumped as one self-contained `incident-<nloop>-<round>.json`
bundle the moment the health engine fires an anomaly (or when the
process dies mid-run — `Trainer.close()`'s crash dump).

Design rules:

* **The ring mirrors the SINK stream, not the observer feed.** The
  recorder notifies observers at log time, BEFORE deferred eval values
  materialize and before a rollback's `discard_pending` can drop a
  poisoned round's evals; sinks receive records post-harvest, resolved,
  in exactly the order the JSONL file persists them. So the flight
  recorder is a *sink* (record/flush/commit/close protocol): what the
  bundle holds is byte-for-byte what the stream holds — the acceptance
  contract "in-bundle series match the stream's last W rounds exactly"
  falls out of the wiring instead of being an approximation.
* **One segmentation rule, live and on replay.** The trainer logs
  `dispatch_count` as the round's FINAL streamed record in both trainer
  paths (engine/trainer.py run_round — the `health` record precedes
  it), so seeing one closes the ring's current bucket. A resumed run
  feeds the sink's replayed records through `replay()` — the same
  `record()` code path — and re-derives the identical ring the crashed
  process held at the restore point.
* **Incidents are process facts.** The `incident` series record is
  `stream=False` (like `recompile_count`/`roofline`) and the bundle is
  a separate file, so crash+resume twin stream identity is untouched.
  Bundles live in `<stream>.incidents/` — per-stream, so sweep
  directories holding several streams (the report_smoke layout) cannot
  clobber each other's forensics. On resume, bundles at or past the
  restore loop are deleted (they describe rounds that will re-run and
  re-dump identically — the stream-truncation rule applied to files);
  a fresh stream clears the directory entirely.
* **Rising-edge dedupe + budget.** A chronic anomaly (a plateaued run
  plateaus every round) dumps ONCE — a new bundle needs an anomaly
  kind the previous round did not have — and `MAX_INCIDENTS` caps the
  per-process total. The edge state derives purely from the `health`
  records passing through the sink, so a resumed recorder re-decides
  identically to its uninterrupted twin.

`report --incidents` (obs/registry.py) tables every bundle under a run
directory; `watch` (obs/console.py) surfaces the count live. Both read
bundles through `list_incidents`/`validate_incident` here — no jax at
import time, so the analysis verbs stay backend-free.
"""

from __future__ import annotations

import collections
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from federated_pytorch_test_tpu.obs.sinks import jsonable

INCIDENT_SCHEMA = 1

# per-process cap on anomaly bundles: a pathological run where every
# round surfaces a new anomaly kind must not fill the disk with
# forensics (crash dumps are outside the cap — there is at most one)
MAX_INCIDENTS = 16

# the round's FINAL streamed record in both trainer paths
# (engine/trainer.py run_round logs it after the health record): seeing
# one closes the ring's current bucket — the ONE segmentation rule,
# live and on replay
BOUNDARY_SERIES = "dispatch_count"

_BUNDLE_RE = re.compile(r"^incident-(\d+)-(\d+)\.json$")


def incidents_dir(stream_path: str) -> str:
    """Where a metric stream's incident bundles live:
    `<stream>.incidents/` — per-stream, so directories holding several
    sweep streams cannot clobber each other's bundles."""
    return stream_path + ".incidents"


def list_incidents(stream_path: str) -> List[Tuple[str, Optional[dict]]]:
    """Sorted `(filename, parsed bundle)` pairs under the stream's
    incidents directory — numeric (nloop, round) order, so tables are
    deterministic. An unreadable bundle parses to None (callers decide
    whether to warn or raise); validation is the caller's via
    `validate_incident`."""
    d = incidents_dir(stream_path)
    if not os.path.isdir(d):
        return []
    found = []
    for fname in os.listdir(d):
        m = _BUNDLE_RE.match(fname)
        if m is None:
            continue
        found.append((int(m.group(1)), int(m.group(2)), fname))
    out: List[Tuple[str, Optional[dict]]] = []
    for _, _, fname in sorted(found):
        try:
            with open(os.path.join(d, fname)) as f:
                out.append((fname, json.load(f)))
        except (OSError, ValueError):
            out.append((fname, None))
    return out


def validate_incident(doc: Any) -> None:
    """Strict incident-bundle schema check (docs/OBSERVABILITY.md):
    raises ValueError naming the offending field — the house validation
    style, shared by `report --incidents` and the tier-2 incident
    smoke."""

    def _fail(field: str, why: str):
        raise ValueError(f"incident bundle: field {field!r} {why}")

    if not isinstance(doc, dict):
        raise ValueError("incident bundle: must be a JSON object")
    if doc.get("schema") != INCIDENT_SCHEMA:
        _fail("schema", f"must be {INCIDENT_SCHEMA}, got {doc.get('schema')!r}")
    if doc.get("kind") not in ("anomaly", "crash"):
        _fail("kind", f"must be 'anomaly' or 'crash', got {doc.get('kind')!r}")
    anomalies = doc.get("anomalies")
    if not isinstance(anomalies, list) or not all(
        isinstance(a, str) for a in anomalies
    ):
        _fail("anomalies", f"must be a list of strings, got {anomalies!r}")
    for field in ("nloop", "round", "window"):
        v = doc.get(field)
        if not isinstance(v, int) or isinstance(v, bool):
            _fail(field, f"must be an int, got {v!r}")
        if v < 0:
            _fail(field, f"must be >= 0, got {v}")
    if doc["window"] < 1:
        _fail("window", f"must be >= 1, got {doc['window']}")
    g = doc.get("group")
    if g is not None and (not isinstance(g, int) or isinstance(g, bool)):
        _fail("group", f"must be an int or null, got {g!r}")
    if not isinstance(doc.get("tag"), str):
        _fail("tag", f"must be a string, got {doc.get('tag')!r}")
    rounds = doc.get("rounds")
    if not isinstance(rounds, list):
        _fail("rounds", f"must be a list, got {type(rounds).__name__}")
    if len(rounds) > doc["window"]:
        _fail(
            "rounds",
            f"holds {len(rounds)} rounds but the window is {doc['window']}",
        )
    for i, bucket in enumerate(rounds):
        if not isinstance(bucket, dict) or not isinstance(
            bucket.get("records"), list
        ):
            _fail(f"rounds[{i}]", "must be an object with a 'records' list")
        for j, rec in enumerate(bucket["records"]):
            if not isinstance(rec, dict) or "series" not in rec:
                _fail(
                    f"rounds[{i}].records[{j}]",
                    "must be a record object with a 'series' key",
                )
    if doc["kind"] == "crash" and not isinstance(
        doc.get("partial_round"), list
    ):
        _fail("partial_round", "must be a list (crash bundles carry the "
              "open round's records)")


class FlightRecorder:
    """Bounded ring over the streamed records + incident-bundle writer.

    Wired as a metric SINK (utils/metrics.py `MetricsRecorder.sinks`) so
    it sees exactly the resolved records — and order — the JSONL sink
    persists (see module docstring). Lifecycle mirrors `JsonlSink`:
    construct, `open(resume_nloops=...)` (stale-bundle cleanup), then
    `record`/`flush`/`commit`/`close` from the recorder; the trainer
    calls `incident()` at anomalous round boundaries and `crash_dump()`
    from `Trainer.close()` when a run dies mid-flight.
    """

    def __init__(self, window: int, dir: str, tag: str = ""):
        if window < 1:
            raise ValueError(f"flight window must be >= 1, got {window}")
        self.window = int(window)
        self.dir = os.path.abspath(dir)
        self.tag = tag
        self._ring: collections.deque = collections.deque(maxlen=self.window)
        self._open: List[dict] = []
        # rising-edge state: the previous / current round's anomaly sets,
        # derived purely from the health records passing through record()
        # — a resumed recorder replays them and re-decides identically
        self._anom_prev: set = set()
        self._anom_cur: set = set()
        self._dumped = 0
        self._crash_dumped = False

    # ------------------------------------------------------------ lifecycle

    def open(self, resume_nloops: Optional[int] = None) -> None:
        """Create the incidents directory and clear stale bundles: ALL of
        them for a fresh stream, those at or past the restore loop for a
        resume (their rounds re-run and re-dump identically — the
        stream-truncation rule applied to bundle files; the crashed
        process's crash dump goes with them)."""
        os.makedirs(self.dir, exist_ok=True)
        for fname in os.listdir(self.dir):
            m = _BUNDLE_RE.match(fname)
            if m is None:
                continue
            if resume_nloops is None or int(m.group(1)) >= int(resume_nloops):
                os.remove(os.path.join(self.dir, fname))

    # -------------------------------------------------------- sink protocol

    def record(self, name: str, rec: dict) -> None:
        self._open.append({"series": name, **rec})
        if name == "health":
            v = rec.get("value")
            if isinstance(v, dict):
                self._anom_prev = self._anom_cur
                self._anom_cur = set(v.get("anomalies", ()))
        if name == BOUNDARY_SERIES:
            self._ring.append(
                {
                    "nloop": rec.get("nloop"),
                    "group": rec.get("group"),
                    "records": self._open,
                }
            )
            self._open = []

    def flush(self) -> None:
        pass

    def commit(self, nloop: int) -> None:
        pass

    def close(self) -> None:
        pass

    def replay(self, records: Iterable[Tuple[str, dict]]) -> None:
        """Rebuild ring + edge state from a resumed stream's replayed
        records (obs/sinks.py `open(resume_nloops=...)` output) — the
        same `record()` path the live sink feed takes, so the resumed
        ring equals the crashed process's at the restore point."""
        for name, rec in records:
            self.record(name, rec)

    # ------------------------------------------------------------- contents

    def rounds(self) -> List[dict]:
        """The ring's closed buckets, oldest first (≤ `window`)."""
        return list(self._ring)

    def partial(self) -> List[dict]:
        """The open bucket: records streamed since the last boundary —
        what a crash dump captures of the dying round."""
        return list(self._open)

    # ---------------------------------------------------------------- dumps

    def _write(self, fname: str, doc: dict) -> str:
        path = os.path.join(self.dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=jsonable, sort_keys=True, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def _base(self, kind: str, anomalies, nloop, group, round_ix) -> dict:
        return {
            "schema": INCIDENT_SCHEMA,
            "kind": kind,
            "anomalies": [str(a) for a in anomalies],
            "nloop": int(nloop),
            "group": int(group) if group is not None else None,
            "round": int(round_ix),
            "tag": self.tag,
            "window": self.window,
            "rounds": self.rounds(),
        }

    def incident(
        self, anomalies, *, nloop: int, group: int, round_ix: int,
        extra=None,
    ) -> Optional[str]:
        """Dump an anomaly bundle for the just-closed round; returns the
        bundle path, or None when deduped (no anomaly kind the previous
        round lacked — a chronic alert dumps once, on its rising edge)
        or over the per-process `MAX_INCIDENTS` budget. `extra` may be a
        dict or a zero-arg callable returning one — a callable is only
        evaluated when the bundle actually dumps, so a chronic-anomaly
        run does not rebuild the (plan-slice, memos) extras every
        round just to throw them away."""
        if not set(anomalies) - self._anom_prev:
            return None
        if self._dumped >= MAX_INCIDENTS:
            return None
        self._dumped += 1
        doc = self._base("anomaly", anomalies, nloop, group, round_ix)
        if callable(extra):
            extra = extra()
        doc.update(extra or {})
        return self._write(
            f"incident-{int(nloop)}-{int(round_ix)}.json", doc
        )

    def crash_dump(
        self, *, nloop: int, round_ix: int, extra=None
    ) -> Optional[str]:
        """Dump the crash bundle (once): the ring plus the dying round's
        open bucket. Called from `Trainer.close()` when a started run
        never completed — an injected chaos crash included. `extra` as
        in `incident()`."""
        if self._crash_dumped:
            return None
        self._crash_dumped = True
        doc = self._base("crash", [], nloop, None, round_ix)
        doc["partial_round"] = self.partial()
        if callable(extra):
            extra = extra()
        doc.update(extra or {})
        return self._write(
            f"incident-{int(nloop)}-{int(round_ix)}.json", doc
        )
